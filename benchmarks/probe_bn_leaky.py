"""Probe: is TinyYOLO's 416² BN+leaky plateau physics or lowering?
(VERDICT r4 weak #7.)

Method: the suspect op chain is training-mode BatchNorm (per-channel
mean/var over N,H,W) followed by leaky-relu on [N, C, 416, 416]
activations. Its arithmetic intensity is ~5 flops per element against
~6 bytes of HBM traffic per element (read for stats + read for apply +
write) — deeply bandwidth-bound. So the question "can a Pallas kernel
beat XLA here?" reduces to "does XLA's lowering already run at the HBM
roofline?" — measured directly below as achieved GB/s vs the v5e's
~819 GB/s peak. If the achieved fraction is high, the plateau is
physics and no kernel can improve it; a fused Pallas kernel could only
remove the stats read (3 passes -> 2) for a <=1.5x ceiling.

Run: python benchmarks/probe_bn_leaky.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

HBM_PEAK_GBPS = 819.0       # public v5e figure (see measured stream below)


def measured_stream_gbps(x, iters=30):
    """Achievable streaming bandwidth ON THIS CHIP (read+write axpy) —
    the honest roofline; the tunneled single-chip backend measures well
    below the public 819 GB/s figure."""
    def chained(x0):
        def body(i, acc):
            return acc * 1.0000001 + 0.5
        return jnp.sum(jax.lax.fori_loop(0, iters, body, x0)
                       .astype(jnp.float32))
    g = jax.jit(chained)
    float(g(x))
    t0 = time.perf_counter()
    float(g(x))
    dt = (time.perf_counter() - t0) / iters
    return 2 * x.size * x.dtype.itemsize / dt / 1e9


def bn_leaky(x, gamma, beta, alpha=0.1, eps=1e-5):
    m = jnp.mean(x.astype(jnp.float32), axis=(0, 2, 3), keepdims=True)
    v = jnp.mean(jnp.square(x.astype(jnp.float32) - m), axis=(0, 2, 3),
                 keepdims=True)
    y = (x.astype(jnp.float32) - m) * jax.lax.rsqrt(v + eps)
    y = y * gamma[None, :, None, None] + beta[None, :, None, None]
    return jnp.where(y > 0, y, alpha * y).astype(x.dtype)


def two_pass_bytes(x):
    # stats read + apply read + write, in x's dtype
    return 3 * x.size * x.dtype.itemsize


def pallas_bn_leaky(x2d, gamma, beta, alpha=0.1, eps=1e-5,
                    rows=416, cols=1664):
    """Fused two-kernel BN+leaky over x [C, M] (M = N*H*W): per-channel
    grid with big CONTIGUOUS [rows, cols] blocks (the [C, bc] layout
    gathers C strided rows per DMA — measured 0.8x of XLA; this layout
    streams one channel's memory linearly), then an apply pass —
    exactly the 3 HBM passes the roofline allows, bf16 end-to-end."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    C, M = x2d.shape
    x3 = x2d.reshape(C, M // cols, cols)
    nb = (M // cols) // rows

    def stats_kernel(x_ref, out_ref, s_ref, q_ref):
        c, j = pl.program_id(0), pl.program_id(1)

        @pl.when(j == 0)
        def _():
            s_ref[:] = jnp.zeros_like(s_ref)
            q_ref[:] = jnp.zeros_like(q_ref)
        blk = x_ref[0].astype(jnp.float32)          # [rows, cols]
        s_ref[:] += jnp.sum(blk, axis=0, keepdims=True)
        q_ref[:] += jnp.sum(blk * blk, axis=0, keepdims=True)

        @pl.when(j == nb - 1)
        def _():
            out_ref[pl.ds(c, 1)] = jnp.full((1, 128),
                                            jnp.sum(s_ref[...]))
            out_ref[pl.ds(C + c, 1)] = jnp.full((1, 128),
                                                jnp.sum(q_ref[...]))

    sums = pl.pallas_call(
        stats_kernel,
        grid=(C, nb),
        in_specs=[pl.BlockSpec((1, rows, cols), lambda c, j: (c, j, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((2 * C, 128), lambda c, j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2 * C, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, cols), jnp.float32),
                        pltpu.VMEM((1, cols), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(x3)
    mean = sums[:C, :1] / M
    var = sums[C:, :1] / M - mean * mean
    scale = (gamma[:, None] * jax.lax.rsqrt(var + eps)).astype(jnp.float32)
    shift = (beta[:, None] - mean * scale).astype(jnp.float32)

    def apply_kernel(x_ref, sc_ref, sh_ref, o_ref):
        c = pl.program_id(0)
        sc = sc_ref[pl.ds(c, 1)][0, 0]
        sh = sh_ref[pl.ds(c, 1)][0, 0]
        y = x_ref[0].astype(jnp.float32) * sc + sh
        o_ref[0] = jnp.where(y > 0, y, alpha * y).astype(o_ref.dtype)

    y = pl.pallas_call(
        apply_kernel,
        grid=(C, nb),
        in_specs=[
            pl.BlockSpec((1, rows, cols), lambda c, j: (c, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, 128), lambda c, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, 128), lambda c, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, cols), lambda c, j: (c, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C, M // cols, cols), x2d.dtype),
    )(x3, jnp.broadcast_to(scale, (C, 128)),
      jnp.broadcast_to(shift, (C, 128)))
    return y.reshape(C, M)


def main():
    N, C, H, W = 32, 16, 416, 416
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, C, H, W), jnp.bfloat16)
    gamma = jnp.ones((C,), jnp.float32)
    beta = jnp.zeros((C,), jnp.float32)
    ITERS = 30

    def chained(x0):
        def body(i, acc):
            return bn_leaky(acc, gamma, beta)
        return jnp.sum(jax.lax.fori_loop(0, ITERS, body, x0)
                       .astype(jnp.float32))

    g = jax.jit(chained)
    float(g(x))                                   # compile
    t0 = time.perf_counter()
    r = g(x)
    float(r)
    dt = (time.perf_counter() - t0) / ITERS
    stream = measured_stream_gbps(jnp.ravel(x))
    gbps = two_pass_bytes(x) / dt / 1e9
    print(f"measured stream roofline: {stream:.0f} GB/s "
          f"(= {stream / HBM_PEAK_GBPS:.1%} of the public 819 GB/s)")
    print(f"XLA bn+leaky [32,16,416,416] bf16: {dt * 1e3:.3f} ms/iter, "
          f"{gbps:.0f} GB/s = {gbps / stream:.0%} of the measured roofline")

    # fused Pallas version over the channels-major 2-D view
    x2d = jnp.reshape(jnp.transpose(x, (1, 0, 2, 3)), (C, N * H * W))
    ref = np.asarray(bn_leaky(x, gamma, beta), np.float32)
    got = np.asarray(pallas_bn_leaky(x2d, gamma, beta), np.float32)
    got4 = got.reshape(C, N, H, W).transpose(1, 0, 2, 3)
    err = np.abs(got4 - ref).max()
    print("pallas vs XLA max|err|:", err)
    assert err < 0.05, err

    def chained_pl(x0):
        def body(i, acc):
            return pallas_bn_leaky(acc, gamma, beta)
        return jnp.sum(jax.lax.fori_loop(0, ITERS, body, x0)
                       .astype(jnp.float32))

    gp = jax.jit(chained_pl)
    float(gp(x2d))
    t0 = time.perf_counter()
    r = gp(x2d)
    float(r)
    dtp = (time.perf_counter() - t0) / ITERS
    gbpsp = two_pass_bytes(x) / dtp / 1e9
    print(f"Pallas fused:                      {dtp * 1e3:.3f} ms/iter, "
          f"{gbpsp:.0f} GB/s = {gbpsp / stream:.0%} of the measured "
          f"roofline, {dt / dtp:.2f}x vs XLA")
    xla_frac = gbps / stream
    speedup = dt / dtp
    if xla_frac > 0.7 and speedup < 1.15:
        print(f"verdict: PHYSICS — XLA's lowering runs at {xla_frac:.0%} "
              f"of this chip's measured streaming bandwidth and the fused "
              f"kernel is {speedup:.2f}x; the plateau is set by effective "
              f"HBM bandwidth, not by XLA's lowering.")
    elif speedup >= 1.15:
        print(f"verdict: LOWERING — the fused kernel is {speedup:.2f}x "
              f"over XLA here; promote it to a platform override.")
    else:
        print(f"verdict: INCONCLUSIVE — XLA at {xla_frac:.0%} of the "
              f"measured stream, kernel {speedup:.2f}x; neither is near "
              f"the roofline, so something else (dispatch, layout) "
              f"dominates at this shape.")


if __name__ == "__main__":
    main()
