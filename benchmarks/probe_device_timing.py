"""Probe: the ISSUE-14 device-timing bridge + fused-epilogue contracts.

Three asserted checks, printed as ONE JSON line (wired as
``bench.py --device-timing``):

1. **Non-empty attribution** — ``profiler.devicetime.measure`` over a
   conv fixture produces a per-layer table whose rows cover every layer,
   whose time shares sum to ~1, and whose per-layer FLOPs equal the
   analyzer's declared-shape model (the same numbers W105 reasons with).
2. **Fused epilogue, fp32** — the bias+BN+relu / BN+leaky Pallas
   epilogue path (NHWC + ``setEpilogueFusion`` + platform overrides in
   interpret mode off-TPU) is BIT-CLOSE to the reference path: forward
   max|Δ| and one-fit-step loss delta both under 1e-4.
3. **Fused epilogue, bf16** — under ``PrecisionPolicy("bf16")`` the
   fused+NHWC loss curve tracks the unfused bf16 curve within 10% of
   the curve scale (loss parity, the same guard the bench rows carry).

Run: python benchmarks/probe_device_timing.py [--quick]
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_fixture(hw: int = 16, bn: bool = True, leaky: bool = False):
    from deeplearning4j_tpu.nn.config import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (ActivationLayer,
                                              BatchNormalization,
                                              ConvolutionLayer, DenseLayer,
                                              OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    b = (NeuralNetConfiguration.Builder().seed(7).weightInit("relu").list()
         .layer(ConvolutionLayer(kernelSize=(3, 3), padding=(1, 1), nOut=16,
                                 activation="identity")))
    if bn:
        b = (b.layer(BatchNormalization())
             .layer(ActivationLayer("leakyrelu" if leaky else "relu")))
    b = (b.layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                  stride=(2, 2)))
         .layer(DenseLayer(nOut=32, activation="relu"))
         .layer(OutputLayer(nOut=5, lossFunction="mcxent",
                            activation="softmax"))
         .setInputType(InputType.convolutional(hw, hw, 3)))
    return MultiLayerNetwork(b.build()).init()


def check_attribution(out: dict, reps: int):
    from deeplearning4j_tpu.profiler import devicetime as dt
    net = build_fixture()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 3, 16, 16).astype(np.float32)
    table = dt.measure(net, x, reps=reps, mode="sync")
    assert len(table.rows) == len(net.layers), \
        f"attribution covered {len(table.rows)}/{len(net.layers)} layers"
    share = sum(r.share for r in table.rows)
    assert abs(share - 1.0) < 1e-6, f"time shares sum to {share}"
    flops = dict((name, f) for name, _op, f
                 in dt.layer_flop_model(net.conf))
    for r in table.rows:
        expect = flops[r.layer] * 8 * 3.0     # batch x train factor
        assert r.flops == expect, \
            f"{r.layer}: table {r.flops} != FLOP model {expect}"
    assert table.top_offenders(1), "no offenders ranked"
    out["table_rows"] = len(table.rows)
    out["top_offender"] = table.top_offenders(1)[0]["layer"]
    out["flop_model_match"] = True


def _optimized(net):
    from deeplearning4j_tpu.ops import pallas_kernels as pk
    pk.install_platform_overrides()     # interpret mode off-TPU
    net.setComputeLayout("NHWC")
    net.setEpilogueFusion(True)
    return net


def check_fused_fp32(out: dict, leaky: bool):
    import jax.numpy as jnp
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.RandomState(1)
    x = rng.randn(8, 3, 16, 16).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 8)]
    a = build_fixture(leaky=leaky)
    b = _optimized(build_fixture(leaky=leaky))
    oa = np.asarray(a.output(x))
    ob = np.asarray(b.output(x))
    fwd = float(np.abs(oa - ob).max())
    a.fit(DataSet(x, y))
    b.fit(DataSet(x, y))
    loss = abs(a.score() - b.score())
    assert fwd < 1e-4, f"fused fp32 forward diverged: {fwd}"
    assert loss < 1e-4, f"fused fp32 fit loss diverged: {loss}"
    key = "fused_fp32_leaky" if leaky else "fused_fp32"
    out[key] = {"fwd_max_abs": fwd, "fit_loss_delta": loss}


def check_fused_bf16(out: dict, steps: int):
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.RandomState(2)
    x = rng.randn(8, 3, 16, 16).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 8)]
    ds = DataSet(x, y)
    a = build_fixture().setPrecisionPolicy("bf16")
    b = _optimized(build_fixture()).setPrecisionPolicy("bf16")
    la, lb = [], []
    for _ in range(steps):
        a.fit(ds)
        la.append(float(a.score()))
        b.fit(ds)
        lb.append(float(b.score()))
    scale = max(abs(la[0]), 1e-6)
    rel = max(abs(p - q) / scale for p, q in zip(la, lb))
    assert rel < 0.10, f"bf16 fused loss parity broke: {rel}"
    out["bf16_parity_max_rel"] = round(rel, 6)


def check_zero_recompile(out: dict):
    """Churn pin: NHWC + fused epilogues reach steady state at ONE
    compiled signature per site (no per-step recompiles)."""
    from deeplearning4j_tpu.analysis.churn import get_churn_detector
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.RandomState(3)
    x = rng.randn(8, 3, 16, 16).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 8)]
    net = _optimized(build_fixture())
    ds = DataSet(x, y)
    det = get_churn_detector()
    for _ in range(6):
        net.fit(ds)
    sigs = det.signature_count("MultiLayerNetwork.fit", owner=net)
    assert sigs <= 1, f"fused/NHWC fit churned: {sigs} signatures"
    out["steady_state_signatures"] = sigs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    reps = 2 if args.quick else 3
    out = {"probe": "device_timing"}
    check_attribution(out, reps)
    check_fused_fp32(out, leaky=False)
    check_fused_fp32(out, leaky=True)      # the YOLO leaky-relu head
    check_fused_bf16(out, steps=4 if args.quick else 8)
    check_zero_recompile(out)
    out["ok"] = True
    print(json.dumps(out))


if __name__ == "__main__":
    main()
