"""Probe: fit-loop overhead of periodic atomic checkpointing.

The resilience layer's contract (ISSUE 5 acceptance): checkpointing at
``every_steps=200`` costs <3% fit time on the CPU-backend MLP probe —
fault tolerance must be cheap enough to leave ON. The probe trains the
same tiny MLP for a fixed number of steps at ``every_steps`` in
{0, 50, 200} (0 = resilience layer attached but never saving, the
baseline) and prints ONE JSON line:

  {"probe": "checkpoint_overhead", "baseline_sec_per_iter": ...,
   "every_50": {"sec_per_iter": ..., "overhead_ratio": ...},
   "every_200": {"sec_per_iter": ..., "overhead_ratio": ...}}

``overhead_ratio`` = mode/baseline - 1. Absolute numbers are CPU-backend
step times, not TPU ones; the regression signal is the ratio.

Run: python benchmarks/probe_checkpoint_overhead.py [--iters N]
"""

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build():
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.train import updaters
    conf = (NeuralNetConfiguration.Builder().seed(42)
            .updater(updaters.Adam(0.01)).list()
            .layer(DenseLayer(nOut=64, activation="relu"))
            .layer(DenseLayer(nOut=64, activation="relu"))
            .layer(OutputLayer(nOut=10, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(32))
            .build())
    return MultiLayerNetwork(conf).init()


def batches(n, batch=32, nin=32, nout=10, seed=0):
    from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
    rng = np.random.RandomState(seed)
    x = rng.randn(n * batch, nin).astype(np.float32)
    y = np.eye(nout, dtype=np.float32)[rng.randint(0, nout, n * batch)]
    return ListDataSetIterator(DataSet(x, y), batch_size=batch)


def run_mode(iters: int, every_steps: int, warmup: int) -> float:
    from deeplearning4j_tpu.train.resilience import CheckpointConfig
    net = build()
    net.fit(batches(warmup, seed=1), epochs=1)      # compile + warm caches
    it = batches(iters)
    with tempfile.TemporaryDirectory() as d:
        cfg = CheckpointConfig(d, every_steps=every_steps, keep_last=2)
        net.score()                                 # sync before the clock
        t0 = time.perf_counter()
        net.fit(it, epochs=1, checkpoint=cfg)
        net.score()
        return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=600,
                    help="measured training steps per mode")
    ap.add_argument("--warmup", type=int, default=20)
    args = ap.parse_args()

    base = run_mode(args.iters, 0, args.warmup)
    out = {"probe": "checkpoint_overhead", "iters": args.iters,
           "baseline_sec_per_iter": round(base, 6)}
    for every in (50, 200):
        t = run_mode(args.iters, every, args.warmup)
        out[f"every_{every}"] = {
            "sec_per_iter": round(t, 6),
            "overhead_ratio": round(t / base - 1.0, 4)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
