"""Probe: elastic-layer costs — async vs sync checkpointing, and
time-to-recover from a device loss.

Two acceptance numbers for ISSUE 6:

(a) **Async checkpoint overhead.** PR 5's synchronous writes cost 1.8%
    at ``every_steps=200``; the async writer must make ``every_steps=50``
    cost LESS than that — 4x the checkpoint frequency for less fit-time
    than the old sync path, because serialization/fsync run on the
    background writer while the fit dispatches.
(b) **Time-to-recover.** An 8-device elastic fit loses half its devices
    at a fixed step; recovery time (resume barrier + coordinated
    checkpoint + mesh rebuild + restore) is read from the
    ``dl4j_elastic_recovery_seconds`` histogram.

Prints ONE JSON line::

  {"probe": "elastic", "iters": ...,
   "baseline_sec_per_iter": ...,
   "sync_every_200": {"sec_per_iter": ..., "overhead_ratio": ...},
   "sync_every_50":  {...}, "async_every_50": {...},
   "async_beats_sync200": true,
   "recovery": {"devices": "8->4", "recover_seconds": ...,
                "fit_seconds": ...}}

``overhead_ratio`` = mode/baseline - 1. Absolute numbers are CPU-backend
step times; the regression signals are the ratios and the recovery time.

Run: python benchmarks/probe_elastic.py [--iters N]
"""

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build():
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.train import updaters
    conf = (NeuralNetConfiguration.Builder().seed(42)
            .updater(updaters.Adam(0.01)).list()
            .layer(DenseLayer(nOut=64, activation="relu"))
            .layer(DenseLayer(nOut=64, activation="relu"))
            .layer(OutputLayer(nOut=10, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(32))
            .build())
    return MultiLayerNetwork(conf).init()


def batches(n, batch=32, nin=32, nout=10, seed=0):
    from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
    rng = np.random.RandomState(seed)
    x = rng.randn(n * batch, nin).astype(np.float32)
    y = np.eye(nout, dtype=np.float32)[rng.randint(0, nout, n * batch)]
    return ListDataSetIterator(DataSet(x, y), batch_size=batch)


def run_mode(iters: int, every_steps: int, warmup: int,
             async_write: bool) -> float:
    from deeplearning4j_tpu.train.resilience import CheckpointConfig
    net = build()
    net.fit(batches(warmup, seed=1), epochs=1)      # compile + warm caches
    it = batches(iters)
    with tempfile.TemporaryDirectory() as d:
        cfg = CheckpointConfig(d, every_steps=every_steps, keep_last=2,
                               async_write=async_write)
        net.score()                                 # sync before the clock
        t0 = time.perf_counter()
        net.fit(it, epochs=1, checkpoint=cfg)
        net.score()
        return (time.perf_counter() - t0) / iters


def run_recovery():
    """8-device elastic fit, 4 devices die at step 10 of 40; recovery
    wall time comes from the dl4j_elastic_recovery_seconds histogram."""
    import jax
    from deeplearning4j_tpu.faults import FaultPlan
    from deeplearning4j_tpu.parallel import ElasticConfig, ParallelWrapper
    from deeplearning4j_tpu.parallel.elastic import RECOVERY_SECONDS
    from deeplearning4j_tpu.train.resilience import CheckpointConfig
    assert len(jax.devices()) == 8
    net = build()
    plan = FaultPlan(device_loss_at_step=10, lose_devices=[4, 5, 6, 7])
    before_sum, before_n = RECOVERY_SECONDS.sum, RECOVERY_SECONDS.count
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        ParallelWrapper(net).fit(
            batches(40), epochs=1, checkpoint=CheckpointConfig(d),
            elastic=ElasticConfig(), faults=plan)
        fit_seconds = time.perf_counter() - t0
    assert RECOVERY_SECONDS.count == before_n + 1
    return {"devices": "8->4",
            "recover_seconds": round(RECOVERY_SECONDS.sum - before_sum, 4),
            "fit_seconds": round(fit_seconds, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=600,
                    help="measured training steps per checkpoint mode")
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--repeats", type=int, default=3,
                    help="runs per mode; best-of is reported (CPU-backend "
                         "step times are noisy at the ms scale)")
    args = ap.parse_args()

    def best(every, is_async):
        return min(run_mode(args.iters, every, args.warmup, is_async)
                   for _ in range(max(args.repeats, 1)))

    base = best(0, False)
    out = {"probe": "elastic", "iters": args.iters,
           "baseline_sec_per_iter": round(base, 6)}
    for label, every, is_async in (("sync_every_200", 200, False),
                                   ("sync_every_50", 50, False),
                                   ("async_every_50", 50, True)):
        t = best(every, is_async)
        out[label] = {"sec_per_iter": round(t, 6),
                      "overhead_ratio": round(t / base - 1.0, 4)}
    out["async_beats_sync200"] = (
        out["async_every_50"]["overhead_ratio"]
        < out["sync_every_200"]["overhead_ratio"])
    out["recovery"] = run_recovery()
    print(json.dumps(out))


if __name__ == "__main__":
    main()