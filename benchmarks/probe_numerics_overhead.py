"""Probe: fit-loop overhead of the nonfinite-provenance sanitizer.

ISSUE 11 acceptance: the sanitizer rides the existing one-flag-check
instrumentation path — sanitizer OFF costs one enum read per dispatch
(~0%: the "off" mode IS the ship baseline), and provenance ON must add
< 5% on top of the panic mode it extends.  The legacy NAN_PANIC gate
already pays a per-step host sync to scan the loss (that is what a
panic mode is); provenance adds ONE fused device-side state-copy
dispatch per step, and the eager replay runs only on failure.

Four modes on the same tiny-LeNet fixture (alternating median blocks,
same discipline as probe_obs_overhead.py):

  off     — ProfilingMode.OFF: the ship state
  panic   — NAN_PANIC with enable_provenance(False): the legacy
            attribution-free gate (loss sync only)
  armed   — NAN_PANIC with provenance: + one snapshot dispatch/step
            (the <5%-over-panic assertion)
  ranges  — armed + track_value_ranges(every=10): the opt-in absmax
            walk, reported but NOT asserted (a diagnostic dial — one
            extra eager forward per sampled step is its documented
            price)

Prints ONE JSON line:

  {"probe": "numerics_overhead", "off_sec_per_iter": ...,
   "panic_sec_per_iter": ..., "armed_sec_per_iter": ...,
   "ranges_sec_per_iter": ..., "panic_overhead_ratio": ...,
   "provenance_overhead_ratio": ...}

Run: python benchmarks/probe_numerics_overhead.py [--iters N]
     [--assert-bounds]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

MODES = ("off", "panic", "armed", "ranges")


def build():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import zoo
    net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16 * 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    return net, DataSet(x, y)


def _set_mode(mode: str):
    from deeplearning4j_tpu import profiler
    from deeplearning4j_tpu.profiler import sanitizer
    if mode == "off":
        profiler.set_profiling_mode(profiler.ProfilingMode.OFF)
        sanitizer.enable_provenance(True)
        sanitizer.track_value_ranges(False)
        return
    profiler.set_profiling_mode(profiler.ProfilingMode.NAN_PANIC)
    sanitizer.enable_provenance(mode != "panic")
    sanitizer.track_value_ranges(mode == "ranges", every=10)


def _block(net, ds, iters: int) -> float:
    net.score()                   # sync before starting the clock
    t0 = time.perf_counter()
    for _ in range(iters):
        net.fit(ds)
    net.score()                   # sync before stopping it
    return (time.perf_counter() - t0) / iters


def run(iters: int, warmup: int, blocks: int) -> dict:
    """Alternating median blocks (see probe_obs_overhead.run): the
    shared-host scheduler noise a back-to-back A/B would alias into the
    ratio hits every mode equally instead."""
    from deeplearning4j_tpu import profiler
    from deeplearning4j_tpu.profiler import sanitizer
    nets = {m: build() for m in MODES}
    try:
        for mode, (net, ds) in nets.items():
            _set_mode(mode)
            for _ in range(warmup):
                net.fit(ds)
        per = max(1, iters // blocks)
        times = {m: [] for m in MODES}
        for _ in range(blocks):
            for mode, (net, ds) in nets.items():
                _set_mode(mode)
                times[mode].append(_block(net, ds, per))
        # MIN of blocks, not median: the per-mode floor is the intrinsic
        # cost — on a shared host, transient load inflates arbitrary
        # blocks and a median can land on an inflated one for one mode
        # and a quiet one for another, aliasing noise into the ratio
        return {mode: min(ts) for mode, ts in times.items()}
    finally:
        profiler.set_profiling_mode(None)
        sanitizer.enable_provenance(True)
        sanitizer.track_value_ranges(False)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=480,
                    help="total measured iterations per mode")
    ap.add_argument("--warmup", type=int, default=30)
    ap.add_argument("--blocks", type=int, default=16)
    ap.add_argument("--assert-bounds", action="store_true",
                    help="exit nonzero unless provenance adds < 5%% over "
                         "the legacy panic gate")
    args = ap.parse_args()

    res = run(args.iters, args.warmup, args.blocks)
    off, panic, armed, ranges = (res[m] for m in MODES)
    provenance_ratio = armed / panic - 1.0
    report = {
        "probe": "numerics_overhead",
        "iters": args.iters,
        "off_sec_per_iter": round(off, 6),
        "panic_sec_per_iter": round(panic, 6),
        "armed_sec_per_iter": round(armed, 6),
        "ranges_sec_per_iter": round(ranges, 6),
        "panic_overhead_ratio": round(panic / off - 1.0, 4),
        "provenance_overhead_ratio": round(provenance_ratio, 4),
        "ranges_overhead_ratio": round(ranges / off - 1.0, 4),
    }
    print(json.dumps(report))
    if args.assert_bounds:
        # "OFF ~= 0%" holds by construction (the sanitizer's OFF path is
        # one enum read — the off mode IS the baseline); the assertable
        # bound is what PROVENANCE adds on top of the panic gate.
        assert provenance_ratio < 0.05, \
            f"provenance adds {provenance_ratio:.1%} over NAN_PANIC >= 5%"


if __name__ == "__main__":
    main()
