"""Collective-volume characterization: W107's scaling model vs compiled HLO.

The W107 lint predicts each layer's per-step gradient-allreduce payload
with the ring model (``2(N-1)/N x`` the per-device gradient shard). In
the spirit of the CUDA-Aware-MPI characterization paper (PAPERS.md):
don't trust a scaling model you never measured against the real
program. This probe compiles the GSPMD train step
(:class:`~deeplearning4j_tpu.distributed.gspmd.ShardedTrainingPlan`,
one ``jax.jit`` with shardings) across mesh shapes, extracts the
all-reduce / all-gather / reduce-scatter byte counts from the
POST-SPMD-PARTITIONING HLO, and asserts the lint's estimate is within
2x of the measured all-reduce volume at every mesh shape.

Accounting note: XLA may fuse per-layer gradient all-reduces or emit
reduce-scatter + all-gather pairs; the comparison is therefore against
the TOTAL gradient-collective bytes (all-reduce + reduce-scatter +
all-gather attributable to the backward), which is what the lint's sum
models. HLO shape bytes are per-device op outputs; the ring factor is
applied to both sides identically.

Run: ``python benchmarks/probe_collectives.py [--json]`` — prints one
JSON line; non-zero exit when any mesh shape misses the 2x envelope.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# 8 virtual CPU devices, set before jax initializes (same bootstrap as
# tests/conftest.py)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("DL4J_TPU_MATMUL_PRECISION", "float32")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.analysis.distribution import (  # noqa: E402
    estimate_gradient_collectives)
from deeplearning4j_tpu.data.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.distributed import ShardedTrainingPlan  # noqa: E402
from deeplearning4j_tpu.distributed.gspmd import (  # noqa: E402
    compiled_train_step_hlo, hlo_collective_bytes)
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,  # noqa: E402
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.parallel.mesh import DeviceMesh  # noqa: E402
from deeplearning4j_tpu.train import updaters  # noqa: E402

#: backward-pass gradient collectives the ring model covers
GRAD_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather")


def build_model():
    conf = (NeuralNetConfiguration.Builder().seed(5)
            .updater(updaters.Sgd(0.1)).list()
            .layer(DenseLayer(nOut=512, activation="relu"))
            .layer(DenseLayer(nOut=256, activation="relu"))
            .layer(OutputLayer(nOut=32, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(256))
            .build())
    return MultiLayerNetwork(conf).init()


def measure(n_data: int, per_shard: int = 16) -> dict:
    model = build_model()
    mesh = DeviceMesh.create(data=n_data, model=1, seq=1,
                             devices=jax.devices()[:n_data])
    plan = ShardedTrainingPlan(mesh)
    model.setShardingPlan(plan)
    plan.apply(model)
    batch = per_shard * n_data
    rng = np.random.RandomState(0)
    X = rng.randn(batch, 256).astype(np.float32)
    Y = np.eye(32, dtype=np.float32)[rng.randint(0, 32, batch)]
    hlo = compiled_train_step_hlo(model, X, Y)
    coll = hlo_collective_bytes(hlo)
    # measured side with the same ring accounting the lint applies: an
    # HLO all-reduce op of size S moves ~2(N-1)/N * S per device
    ring = 2.0 * (n_data - 1) / n_data
    measured = ring * sum(coll.get(k, 0) for k in GRAD_COLLECTIVES)
    estimate = sum(estimate_gradient_collectives(model.conf,
                                                 mesh.spec()).values())
    ratio = (estimate / measured) if measured else float("inf")
    # one real dispatch to confirm the compiled program actually runs
    model._fit_one(DataSet(X, Y))
    ok = measured > 0 and 0.5 <= ratio <= 2.0
    return {"data_shards": n_data, "global_batch": batch,
            "hlo_collective_bytes": coll,
            "measured_ring_bytes": int(measured),
            "w107_estimate_bytes": int(estimate),
            "estimate_over_measured": round(ratio, 4),
            "within_2x": ok}


def main(argv):
    points = [measure(n) for n in (2, 4, 8)]
    ok = all(p["within_2x"] for p in points)
    print(json.dumps({"probe": "collectives",
                      "lint_model": "ring allreduce 2(N-1)/N x grad shard",
                      "points": points, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
