"""Probe: fit-loop AND serve-path overhead of the observability plane.

The profiler's contract is "near-zero cost when disabled" (ISSUE 1
acceptance: <5% fit-loop overhead with profiling OFF vs the pre-profiler
seed, proxied here by OFF vs BASIC+tracing on the same binary). ISSUE 16
extends the contract to the fleet observability plane: request tracing
(``profiler.tracecontext``), the always-on crash flight recorder
(``profiler.flightrec``) and SLO burn-rate evaluation (``profiler.slo``)
must each stay under the same <5% bound — for the fit loop AND for the
serve path — and the probe now ASSERTS it (exit 1 on breach).

Fit-side modes (tiny LeNet, fixed iterations, alternating blocks):

  off    — ProfilingMode.OFF, tracing disabled (the default ship state)
  basic  — ProfilingMode.BASIC + span tracing: per-iteration step/data-wait
           histograms and spans (what a perf investigation turns on)
  basic_devicetime — BASIC after a ``profiler.devicetime`` measurement
           exported its ``dl4j_op_device_seconds{model,layer,op}`` series
           (ISSUE 14): the bridge is PULL-based — an explicit measure()
           call, never a fit-loop hook — so a populated attribution
           registry must leave the fit loop inside the same <5% bound.
  trace  — tracing ON + an ambient TraceContext installed + one
           ``tracecontext.span()`` per iteration (what a traced
           ``fit_scope`` run stamps on every span)
  flightrec — OFF + one flight-recorder ring append per iteration (an
           upper bound: real records fire at dispatch/retry/roll seams,
           far below once-per-iteration)
  slo    — OFF + one ``SLOEngine.evaluate()`` per iteration (an upper
           bound: real evaluation runs per canary check / scrape)

Serve-side: a small MLP behind ``ModelServer`` (coalesce_ms=0 so the
compute path, not the coalesce window, dominates), serial submits three
ways — bare ship state, ship state with the full always-on obs plane
exercised per request (gated <5%), and tracing ON (report-only; the
toggle also wakes the pre-existing lock metrics, so that ratio prices
the whole diagnostic mode).

Prints ONE JSON line so BENCH rounds can track instrumentation cost
over time:

  {"probe": "obs_overhead", "off_sec_per_iter": ..., "basic_sec_per_iter":
   ..., "overhead_ratio": ..., "devicetime_overhead_ratio": ...,
   "trace_overhead_ratio": ..., "flightrec_overhead_ratio": ...,
   "slo_overhead_ratio": ..., "serve_off_sec_per_req": ...,
   "serve_obs_sec_per_req": ..., "serve_overhead_ratio": ..., "ok": true}

``overhead_ratio`` = basic/off - 1. The interesting regression signal is
a ratio growing, not the absolute numbers (CPU-backend step times are
not TPU step times). The <5% gate applies to the ISSUE 16 columns
(trace/flightrec/slo/serve); the BASIC columns stay report-only as
before.

Run: python benchmarks/probe_obs_overhead.py [--iters N] [--warmup N]
     [--no-assert]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BOUND = 0.05
NIN, NOUT = 32, 10


def build():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import zoo
    net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16 * 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    return net, DataSet(x, y)


def _set_mode(basic: bool):
    from deeplearning4j_tpu import profiler
    if basic:
        profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
        profiler.enable_tracing()
    else:
        profiler.set_profiling_mode(profiler.ProfilingMode.OFF)
        profiler.disable_tracing()


def _block(net, ds, iters: int, per_iter=None) -> float:
    net.score()                   # sync before starting the clock
    t0 = time.perf_counter()
    for i in range(iters):
        if per_iter is not None:
            per_iter(i)
        net.fit(ds)
    net.score()                   # sync before stopping it
    return (time.perf_counter() - t0) / iters


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def run(iters: int, warmup: int, blocks: int) -> dict:
    """Alternate measurement blocks on the same warm nets and take the
    per-mode MEDIAN of block times: shared-host scheduler noise swamps
    any back-to-back A/B comparison, and alternating short blocks
    exposes every mode to the same noise distribution."""
    from deeplearning4j_tpu import profiler
    from deeplearning4j_tpu.profiler import devicetime, flightrec
    from deeplearning4j_tpu.profiler import slo as slo_mod
    from deeplearning4j_tpu.profiler import tracecontext
    net_off, ds = build()
    net_basic, _ = build()
    net_dt, _ = build()
    net_trace, _ = build()
    net_fr, _ = build()
    net_slo, _ = build()
    nets = [net_off, net_basic, net_dt, net_trace, net_fr, net_slo]
    rec = flightrec.FlightRecorder(capacity=4096)
    engine = slo_mod.SLOEngine([
        slo_mod.SLOSpec("probe-train", step_time_baseline=1.0),
        slo_mod.SLOSpec("probe-serve", latency_bound=0.5),
    ])
    try:
        _set_mode(False)
        for net in nets:
            for _ in range(warmup):
                net.fit(ds)
        # devicetime net: measure + export the per-layer attribution
        # series ONCE (the bridge is pull-based; nothing hooks the fit
        # loop), then fit with BASIC on like net_basic
        devicetime.measure(net_dt, ds.features, reps=2,
                           mode="sync").export_metrics("probe")
        per = max(1, iters // blocks)
        times = {k: [] for k in ("off", "basic", "basic_devicetime",
                                 "trace", "flightrec", "slo")}
        ambient = tracecontext.TraceContext.new()

        def _span_iter(i):
            with tracecontext.span("probe:iter", i=i):
                pass

        for _ in range(blocks):
            _set_mode(False)
            times["off"].append(_block(net_off, ds, per))
            times["flightrec"].append(_block(
                net_fr, ds, per,
                per_iter=lambda i: rec.record("probe:iter", i=i)))
            times["slo"].append(_block(
                net_slo, ds, per,
                per_iter=lambda i: engine.evaluate()))
            _set_mode(True)
            times["basic"].append(_block(net_basic, ds, per))
            times["basic_devicetime"].append(_block(net_dt, ds, per))
            # trace column: profiling OFF (ship state) but the tracing
            # ring live — isolates the tracecontext plane from BASIC's
            # per-iteration histogram cost
            profiler.set_profiling_mode(profiler.ProfilingMode.OFF)
            with tracecontext.use(ambient):
                times["trace"].append(_block(net_trace, ds, per,
                                             per_iter=_span_iter))
            profiler.disable_tracing()
            # traced blocks accumulate spans; keep the tracer ring from
            # becoming its own overhead
            profiler.get_tracer().clear()
        return {k: _median(v) for k, v in times.items()}
    finally:
        profiler.set_profiling_mode(None)
        profiler.disable_tracing()
        profiler.get_tracer().clear()


def _build_server():
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.serving import ModelServer
    conf = (NeuralNetConfiguration.Builder().seed(42).list()
            .layer(DenseLayer(nOut=64, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    net = MultiLayerNetwork(conf).init()
    server = ModelServer(net, batch_limit=8, coalesce_ms=0.0,
                         name="obs-probe")
    server.warmup([(NIN,)])
    return server


def run_serve(reqs: int, warmup: int, blocks: int) -> dict:
    """Serial submits through ModelServer, three ways:

    off    — ship state: tracing off, bare ``submit(x)`` (request IDs are
             still minted; spans no-op)
    obs    — ship state + the full always-on obs plane exercised: a
             context minted and passed per request, a flight-recorder
             ring append per request, one ``SLOEngine.evaluate()`` per
             block. This is the GATED column: the disabled-cost
             guarantee the plane ships under.
    traced — tracing ON + per-request context: every admission/queue/
             coalesce/dispatch/terminal span records. Report-only, like
             the BASIC fit columns: flipping ``tracing_enabled()`` also
             activates the pre-existing lock wait/hold metrics on the
             serve path, so this ratio prices the whole diagnostic
             mode, not just the span plane.
    """
    from deeplearning4j_tpu import profiler
    from deeplearning4j_tpu.profiler import flightrec
    from deeplearning4j_tpu.profiler import slo as slo_mod
    from deeplearning4j_tpu.profiler import tracecontext
    server = _build_server()
    x = np.random.RandomState(7).randn(1, NIN).astype(np.float32)
    rec = flightrec.FlightRecorder(capacity=4096)
    engine = slo_mod.SLOEngine(
        [slo_mod.SLOSpec("probe-serve", latency_bound=0.5)])

    def _serve_block(n: int, mode: str) -> float:
        t0 = time.perf_counter()
        for i in range(n):
            if mode == "off":
                server.submit(x).get(timeout=30.0)
            else:
                ctx = tracecontext.TraceContext.new()
                if mode == "obs":
                    rec.record("probe:req", i=i)
                server.submit(x, trace=ctx).get(timeout=30.0)
        if mode == "obs":
            engine.evaluate()
        return (time.perf_counter() - t0) / n

    try:
        _set_mode(False)
        for _ in range(warmup):
            server.submit(x).get(timeout=30.0)
        per = max(1, reqs // blocks)
        t_off, t_obs, t_traced = [], [], []
        for _ in range(blocks):
            _set_mode(False)
            t_off.append(_serve_block(per, "off"))
            t_obs.append(_serve_block(per, "obs"))
            _set_mode(True)
            t_traced.append(_serve_block(per, "traced"))
            profiler.get_tracer().clear()
        return {"off": _median(t_off), "obs": _median(t_obs),
                "traced": _median(t_traced)}
    finally:
        server.close()
        profiler.set_profiling_mode(None)
        profiler.disable_tracing()
        profiler.get_tracer().clear()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=300,
                    help="total measured iterations per fit mode")
    ap.add_argument("--reqs", type=int, default=400,
                    help="total measured serve requests per mode")
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--blocks", type=int, default=10)
    ap.add_argument("--no-assert", action="store_true",
                    help="report ratios without enforcing the <5% bound")
    args = ap.parse_args()

    res = run(args.iters, args.warmup, args.blocks)
    serve = run_serve(args.reqs, args.warmup, args.blocks)
    off = res["off"]
    ratios = {
        "overhead_ratio": res["basic"] / off - 1.0,
        "devicetime_overhead_ratio": res["basic_devicetime"] / off - 1.0,
        "trace_overhead_ratio": res["trace"] / off - 1.0,
        "flightrec_overhead_ratio": res["flightrec"] / off - 1.0,
        "slo_overhead_ratio": res["slo"] / off - 1.0,
        "serve_overhead_ratio": serve["obs"] / serve["off"] - 1.0,
        "serve_traced_overhead_ratio": serve["traced"] / serve["off"] - 1.0,
    }
    gated = {k: v for k, v in ratios.items()
             if k not in ("overhead_ratio", "devicetime_overhead_ratio",
                          "serve_traced_overhead_ratio")}
    breaches = {k: round(v, 4) for k, v in gated.items() if v >= BOUND}
    out = {
        "probe": "obs_overhead",
        "iters": args.iters,
        "off_sec_per_iter": round(off, 6),
        "basic_sec_per_iter": round(res["basic"], 6),
        "basic_devicetime_sec_per_iter": round(res["basic_devicetime"], 6),
        "trace_sec_per_iter": round(res["trace"], 6),
        "flightrec_sec_per_iter": round(res["flightrec"], 6),
        "slo_sec_per_iter": round(res["slo"], 6),
        "serve_off_sec_per_req": round(serve["off"], 6),
        "serve_obs_sec_per_req": round(serve["obs"], 6),
        "serve_traced_sec_per_req": round(serve["traced"], 6),
        "bound": BOUND,
        "ok": not breaches,
    }
    out.update({k: round(v, 4) for k, v in ratios.items()})
    print(json.dumps(out))
    if breaches and not args.no_assert:
        print(f"FAIL: observability overhead over the {BOUND:.0%} bound: "
              f"{breaches}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
