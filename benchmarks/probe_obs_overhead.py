"""Probe: fit-loop overhead of the profiler subsystem (OFF vs BASIC).

The profiler's contract is "near-zero cost when disabled" (ISSUE 1
acceptance: <5% fit-loop overhead with profiling OFF vs the pre-profiler
seed, proxied here by OFF vs BASIC+tracing on the same binary). The probe
trains a tiny LeNet for a fixed number of iterations three ways:

  off    — ProfilingMode.OFF, tracing disabled (the default ship state)
  basic  — ProfilingMode.BASIC + span tracing: per-iteration step/data-wait
           histograms and spans (what a perf investigation turns on)
  basic_devicetime — BASIC after a ``profiler.devicetime`` measurement
           exported its ``dl4j_op_device_seconds{model,layer,op}`` series
           (ISSUE 14): the bridge is PULL-based — an explicit measure()
           call, never a fit-loop hook — so a populated attribution
           registry must leave the fit loop inside the same <5% bound.

and prints ONE JSON line so BENCH rounds can track instrumentation cost
over time:

  {"probe": "obs_overhead", "off_sec_per_iter": ..., "basic_sec_per_iter":
   ..., "overhead_ratio": ..., "devicetime_overhead_ratio": ...}

``overhead_ratio`` = basic/off - 1. The interesting regression signal is
this ratio growing, not the absolute numbers (CPU-backend step times are
not TPU step times).

Run: python benchmarks/probe_obs_overhead.py [--iters N] [--warmup N]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import zoo
    net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16 * 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    return net, DataSet(x, y)


def _set_mode(basic: bool):
    from deeplearning4j_tpu import profiler
    if basic:
        profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
        profiler.enable_tracing()
    else:
        profiler.set_profiling_mode(profiler.ProfilingMode.OFF)
        profiler.disable_tracing()


def _block(net, ds, iters: int) -> float:
    net.score()                   # sync before starting the clock
    t0 = time.perf_counter()
    for _ in range(iters):
        net.fit(ds)
    net.score()                   # sync before stopping it
    return (time.perf_counter() - t0) / iters


def run(iters: int, warmup: int, blocks: int) -> dict:
    """Alternate OFF/BASIC measurement blocks on the same warm nets and
    take the per-mode MEDIAN of block times: shared-host scheduler noise
    swamps any back-to-back A/B comparison, and alternating short blocks
    exposes both modes to the same noise distribution."""
    from deeplearning4j_tpu import profiler
    from deeplearning4j_tpu.profiler import devicetime
    net_off, ds = build()
    net_basic, _ = build()
    net_dt, _ = build()
    try:
        _set_mode(False)
        for _ in range(warmup):
            net_off.fit(ds)
        _set_mode(True)
        for _ in range(warmup):
            net_basic.fit(ds)
        # devicetime net: measure + export the per-layer attribution
        # series ONCE (the bridge is pull-based; nothing hooks the fit
        # loop), then fit with BASIC on like net_basic
        for _ in range(warmup):
            net_dt.fit(ds)
        devicetime.measure(net_dt, ds.features, reps=2,
                           mode="sync").export_metrics("probe")
        per = max(1, iters // blocks)
        t_off, t_basic, t_dt = [], [], []
        for _ in range(blocks):
            _set_mode(False)
            t_off.append(_block(net_off, ds, per))
            _set_mode(True)
            t_basic.append(_block(net_basic, ds, per))
            t_dt.append(_block(net_dt, ds, per))
        t_off.sort()
        t_basic.sort()
        t_dt.sort()
        return {"off": t_off[len(t_off) // 2],
                "basic": t_basic[len(t_basic) // 2],
                "basic_devicetime": t_dt[len(t_dt) // 2]}
    finally:
        profiler.set_profiling_mode(None)
        profiler.disable_tracing()
        profiler.get_tracer().clear()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=300,
                    help="total measured iterations per mode")
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--blocks", type=int, default=10)
    args = ap.parse_args()

    res = run(args.iters, args.warmup, args.blocks)
    off, basic = res["off"], res["basic"]
    dt = res["basic_devicetime"]
    print(json.dumps({
        "probe": "obs_overhead",
        "iters": args.iters,
        "off_sec_per_iter": round(off, 6),
        "basic_sec_per_iter": round(basic, 6),
        "basic_devicetime_sec_per_iter": round(dt, 6),
        "overhead_ratio": round(basic / off - 1.0, 4),
        "devicetime_overhead_ratio": round(dt / off - 1.0, 4),
    }))


if __name__ == "__main__":
    main()
