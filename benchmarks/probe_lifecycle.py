"""Probe: the lifecycle loop's serving-visible costs (ISSUE 20).

Runs a real :class:`~deeplearning4j_tpu.lifecycle.driver.LifecycleDriver`
— train -> eval gate -> canary -> promote — for a few rounds against a
warmed :class:`~deeplearning4j_tpu.serving.registry.ModelRegistry` while
a background client submits steadily, and reports what the closed loop
costs the serve path:

- **roll latency** — wall time of each promote (``registry.roll``: the
  atomic swap plus the canary clear), mean and max, from the
  ``dl4j_lifecycle_roll_seconds`` histogram;
- **gate wall time** — per-candidate eval-gate cost
  (``dl4j_lifecycle_gate_seconds``), the pre-serving work each round
  pays before a candidate may load;
- **dropped requests** — MUST be 0: every submit issued during the
  storm of rolls either resolved exactly once or was shed with a
  structured ``ServingError`` at admission. A request that vanished or
  double-resolved FAILS the probe (exit 1).

Prints ONE JSON line::

  {"probe": "lifecycle", "rounds": ..., "promotions": ...,
   "roll_ms": {"mean": ..., "max": ..., "n": ...},
   "gate_ms": {"mean": ..., "max": ..., "n": ...},
   "requests": ..., "shed": ..., "dropped_requests": 0,
   "recompiles_after_warmup": 0}

Run: python benchmarks/probe_lifecycle.py [--rounds N] [--quick]
"""

import argparse
import json
import os
import sys
import threading
import time

# 8 virtual CPU devices, set before jax import (same contract as the
# test suite's conftest)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

NIN = 8


def linear_model(delta):
    rng = np.random.RandomState(0)
    W = (rng.randn(NIN, 4).astype(np.float32)
         + np.float32(delta))
    return lambda x: np.asarray(x, np.float32) @ W


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    rounds = 3 if args.quick else args.rounds

    import jax
    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu import profiler as prof
    from deeplearning4j_tpu.lifecycle import LifecycleDriver
    from deeplearning4j_tpu.lifecycle.driver import (GATE_SECONDS,
                                                     ROLL_SECONDS)
    from deeplearning4j_tpu.serving import ServingError
    from deeplearning4j_tpu.serving.registry import (ModelNotFoundError,
                                                     ModelRegistry)

    rng = np.random.RandomState(1)
    eval_x = rng.randn(32, NIN).astype(np.float32)
    state_dir = f"/tmp/dl4j_lifecycle_probe_{os.getpid()}"

    stop = threading.Event()
    handles, shed = [], [0]

    reg = ModelRegistry(batch_limit=8, coalesce_ms=0.5)
    try:
        def traffic():
            while not stop.is_set():
                try:
                    if reg.active_version("m") is not None:
                        handles.append(reg.submit(
                            "m", rng.randn(2, NIN).astype(np.float32)))
                except ModelNotFoundError:
                    pass
                except ServingError:
                    shed[0] += 1
                time.sleep(0.005)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()

        gate0, roll0 = GATE_SECONDS.count, ROLL_SECONDS.count
        gsum0, rsum0 = GATE_SECONDS.sum, ROLL_SECONDS.sum

        import warnings
        drv = LifecycleDriver(
            reg, "m", lambda r: linear_model(0.001 * r), state_dir,
            eval_x=eval_x, shapes=[(NIN,)], canary_fraction=0.25,
            observe_ticks=2, confirm_ticks=1, tick_interval=0.02)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            summary = drv.run(rounds)
        stop.set()
        t.join(5.0)

        # drain every outstanding handle; a structured serving error is
        # a resolved outcome, silence is a drop
        dropped = 0
        for h in handles:
            try:
                h.get(15.0)
            except ServingError:
                pass
            if h.resolutions != 1:
                dropped += 1

        # per-phase roll/gate cost from the driver's own histograms
        gn, rn = GATE_SECONDS.count - gate0, ROLL_SECONDS.count - roll0
        gs, rs = GATE_SECONDS.sum - gsum0, ROLL_SECONDS.sum - rsum0
        roll_max = ROLL_SECONDS.quantile(1.0) or 0.0
        gate_max = GATE_SECONDS.quantile(1.0) or 0.0

        recompiles = sum(
            reg.server("m", v).recompiles_after_warmup()
            for v in reg.models()["m"]["versions"])

        out = {
            "probe": "lifecycle",
            "n_devices": len(jax.devices()),
            "rounds": summary["rounds"],
            "promotions": summary["promotions"],
            "rollbacks": summary["rollbacks"],
            "roll_ms": {"mean": round(rs / rn * 1e3, 2) if rn else None,
                        "max": round(roll_max * 1e3, 2), "n": rn},
            "gate_ms": {"mean": round(gs / gn * 1e3, 2) if gn else None,
                        "max": round(gate_max * 1e3, 2), "n": gn},
            "requests": len(handles),
            "shed": shed[0],
            "dropped_requests": dropped,
            "recompiles_after_warmup": recompiles,
        }
        print(json.dumps(out))
        failed = False
        if dropped != 0:
            print(f"# FAIL: {dropped} request(s) dropped (resolved != 1) "
                  "across the lifecycle rolls", file=sys.stderr)
            failed = True
        if recompiles != 0:
            print(f"# FAIL: {recompiles} steady-state recompile(s) "
                  "across the lifecycle's servers", file=sys.stderr)
            failed = True
        if summary["promotions"] < rounds:
            print(f"# FAIL: only {summary['promotions']} of {rounds} "
                  "clean rounds promoted", file=sys.stderr)
            failed = True
        if failed:
            sys.exit(1)
    finally:
        stop.set()
        reg.close()


if __name__ == "__main__":
    main()
