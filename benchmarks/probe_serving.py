"""Probe: serving throughput vs p99 at fixed traffic mixes, with shed
rate — the ISSUE 7 serving acceptance numbers.

Three seeded traffic mixes (the same ``ServingLoad`` generator the
``pytest -m chaos`` sweeps use, so a probe regression reproduces as a
test):

- **steady**: Poisson arrivals at ~0.8x measured capacity — the
  baseline throughput/latency point; shed rate should be ~0.
- **burst**: a quiet floor punctuated by zero-gap volleys — admission
  control must shed with ``ServerOverloadedError`` instead of letting
  queue latency grow unboundedly.
- **deadline**: half the requests carry a deadline tighter than one
  service time — they are shed BEFORE dispatch and must not rot p99
  for the loose-deadline traffic.

Also reports ``recompiles_after_warmup`` (the zero-steady-state-compile
pin, measured through the W201 churn detector) and the AOT warmup cost.

ISSUE 12 ingress probe (``--skip-ingress`` to disable):

- **Wire path vs in-process** — the steady mix replayed over REAL
  sockets through :class:`HttpIngress` at the same offered load:
  wire-side p50/p99 (the ingress latency histogram: body received to
  response written) and shed rate next to the in-process numbers, so
  the HTTP front door's overhead is a measured quantity.
- **Results-only D2H** — per-dispatch ``dl4j_serving_d2h_bytes_total``
  deltas for full-logits vs ``head="argmax"`` serving; the probe FAILS
  unless the results-only copy is measurably smaller (the acceptance
  assert).
- **W111 lint** — a registry roll planned without warmed buckets for
  the new version must produce ``DL4J-W111``; the probe FAILS if the
  lint stays silent.

Prints ONE JSON line::

  {"probe": "serving", "n_devices": ..., "batch_limit": ...,
   "buckets": [...], "warmup_seconds": ...,
   "uncontended": {"p50_ms": ..., "p99_ms": ...},
   "capacity_rps": ...,
   "mixes": {"steady": {"offered_rps": ..., "throughput_rps": ...,
                        "p50_ms": ..., "p99_ms": ...,
                        "shed_rate": ..., "shed_overload": ...,
                        "shed_deadline": ..., "completed": ...}, ...},
   "ingress": {"wire_p50_ms": ..., "wire_p99_ms": ...,
               "wire_shed_rate": ..., "inproc_p50_ms": ...,
               "inproc_p99_ms": ...},
   "d2h": {"full_logits_bytes_per_batch": ...,
           "results_only_bytes_per_batch": ..., "cut_ratio": ...},
   "w111_lint": "fires",
   "recompiles_after_warmup": 0}

Run: python benchmarks/probe_serving.py [--n N] [--batch-limit B]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

NIN, NOUT = 32, 10


def build():
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(42).list()
            .layer(DenseLayer(nOut=128, activation="relu"))
            .layer(DenseLayer(nOut=128, activation="relu"))
            .layer(OutputLayer(nOut=NOUT, lossFunction="mcxent",
                               activation="softmax"))
            .setInputType(InputType.feedForward(NIN))
            .build())
    return MultiLayerNetwork(conf).init()


def pct(sorted_vals, q):
    if not sorted_vals:
        return None
    return sorted_vals[min(int(len(sorted_vals) * q), len(sorted_vals) - 1)]


def run_mix(server, load, mix_name):
    from deeplearning4j_tpu.serving import (DeadlineExceededError,
                                            ServerOverloadedError,
                                            ServingRequest)
    t0 = time.perf_counter()
    results = load.replay(server.submit, (NIN,))
    lat, completed, shed_over, shed_dead, failed = [], 0, 0, 0, 0
    for _spec, h in results:
        if isinstance(h, ServerOverloadedError):
            shed_over += 1
            continue
        assert isinstance(h, ServingRequest), h
        try:
            h.get(60.0)
            completed += 1
            lat.append(h.resolved_at - h.enqueued_at)
        except DeadlineExceededError:
            shed_dead += 1
        except Exception:
            failed += 1
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(results)
    return {
        "n": n,
        "offered_rps": round(n / max(load.duration(), 1e-9), 1),
        "throughput_rps": round(completed / wall, 1),
        "p50_ms": round(pct(lat, 0.50) * 1e3, 3) if lat else None,
        "p99_ms": round(pct(lat, 0.99) * 1e3, 3) if lat else None,
        "completed": completed,
        "shed_overload": shed_over,
        "shed_deadline": shed_dead,
        "failed": failed,
        "shed_rate": round((shed_over + shed_dead) / n, 4),
    }


def probe_ingress(server, req_capacity, n):
    """The steady mix over REAL sockets: wire p50/p99 (ingress-side
    histogram) + shed rate at the same offered load as the in-process
    steady mix."""
    from deeplearning4j_tpu import profiler as prof
    from deeplearning4j_tpu.faults import ServingLoad
    from deeplearning4j_tpu.serving import HttpIngress
    hist = prof.get_registry().get("dl4j_ingress_latency_seconds")
    load = ServingLoad.seeded(4, mix="steady", n=n,
                              rps=0.6 * req_capacity, max_rows=2)
    with HttpIngress(server, port=0) as ing:
        results = load.replay_http(ing.url, "default", (NIN,))
    codes = [o[0] for _, o in results if isinstance(o, tuple)]
    transport_errors = sum(1 for _, o in results if isinstance(o, Exception))
    ok = codes.count(200)
    # server-stamped latencies from the response payloads: the same
    # admission->resolution stamp the in-process mixes report, so the
    # two columns compare apples to apples; the ingress histogram adds
    # the wire-side (recv -> response written) view on top
    stamped = sorted(o[1]["latency_ms"] for _, o in results
                     if isinstance(o, tuple) and o[0] == 200)
    return {
        "n": len(results),
        "completed": ok,
        "wire_shed_rate": round(
            (len(results) - ok) / max(len(results), 1), 4),
        "transport_errors": transport_errors,
        "wire_p50_ms": round(pct(stamped, 0.5), 3) if stamped else None,
        "wire_p99_ms": round(pct(stamped, 0.99), 3) if stamped else None,
        "http_p50_ms": round(hist.quantile(0.5) * 1e3, 3)
        if hist.count else None,
        "http_p99_ms": round(hist.quantile(0.99) * 1e3, 3)
        if hist.count else None,
    }


def probe_d2h(net, batch_limit, n_batches=10):
    """Per-dispatch D2H bytes, full logits vs results-only argmax —
    returns (stats, ok)."""
    from deeplearning4j_tpu import profiler as prof
    from deeplearning4j_tpu.serving import ModelServer
    counter = prof.get_registry().get("dl4j_serving_d2h_bytes_total")
    per_batch = {}
    for label, head in (("full_logits", None), ("results_only", "argmax")):
        sv = ModelServer(net, batch_limit=batch_limit, coalesce_ms=0.5,
                         head=head)
        sv.warmup([(NIN,)])
        before = counter.value
        for i in range(n_batches):
            sv.output(np.random.RandomState(i).randn(
                batch_limit, NIN).astype(np.float32), timeout=60)
        per_batch[label] = (counter.value - before) / n_batches
        sv.close()
    full, results = per_batch["full_logits"], per_batch["results_only"]
    return ({"full_logits_bytes_per_batch": full,
             "results_only_bytes_per_batch": results,
             "cut_ratio": round(results / full, 4) if full else None},
            0 < results < full)


def probe_w111(net):
    """A roll planned onto an unwarmed version must lint DL4J-W111."""
    import warnings
    from deeplearning4j_tpu.serving import ModelRegistry
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with ModelRegistry(batch_limit=8, coalesce_ms=0.5) as reg:
            reg.load("probe", net, shapes=[(NIN,)])
            reg.load("probe", build(), warm=False)
            codes = reg.validate_roll("probe").codes()
    return "fires" if "DL4J-W111" in codes else f"SILENT ({codes})"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=400,
                    help="requests per traffic mix")
    ap.add_argument("--batch-limit", type=int, default=32)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--skip-ingress", action="store_true")
    args = ap.parse_args()

    import jax

    from deeplearning4j_tpu.faults import ServingLoad
    from deeplearning4j_tpu.serving import ModelServer

    net = build()
    server = ModelServer(net, batch_limit=args.batch_limit,
                         max_queue=args.max_queue, coalesce_ms=1.0)
    t0 = time.perf_counter()
    server.warmup([(NIN,)])
    warmup_s = time.perf_counter() - t0

    # uncontended latency: sequential single-row requests
    unc = []
    for i in range(30):
        r = server.submit(np.random.RandomState(i).randn(
            1, NIN).astype(np.float32))
        r.get(30.0)
        unc.append(r.resolved_at - r.enqueued_at)
    unc.sort()

    # measured capacity: how fast full batches drain back to back
    t0 = time.perf_counter()
    full_batches = 20
    for i in range(full_batches):
        server.output(np.random.RandomState(i).randn(
            args.batch_limit, NIN).astype(np.float32), timeout=60)
    capacity_rps = full_batches * args.batch_limit \
        / (time.perf_counter() - t0)

    # capacity_rps is ROW throughput at full coalesced batches; convert
    # to a request rate for the generators (max_rows=2 -> 1.5 rows/req)
    avg_rows = 1.5
    req_capacity = capacity_rps / avg_rows
    service_ms = args.batch_limit / capacity_rps * 1e3
    mixes = {}
    mixes["steady"] = run_mix(server, ServingLoad.seeded(
        1, mix="steady", n=args.n, rps=0.6 * req_capacity, max_rows=2),
        "steady")
    # volleys sized to overwhelm the queue but leave a quiet floor
    # (the generator clamps n_bursts*burst_size <= n)
    mixes["burst"] = run_mix(server, ServingLoad.seeded(
        2, mix="burst", n=args.n, rps=0.3 * req_capacity,
        n_bursts=4, burst_size=min(args.max_queue * 2, args.n // 8),
        max_rows=2), "burst")
    mixes["deadline"] = run_mix(server, ServingLoad.seeded(
        3, mix="deadline", n=args.n, rps=0.6 * req_capacity, max_rows=2,
        tight_deadline=service_ms / 4e3, loose_deadline=10.0,
        deadline_frac=0.5), "deadline")

    out = {
        "probe": "serving",
        "n_devices": len(jax.devices()),
        "batch_limit": args.batch_limit,
        "max_queue": args.max_queue,
        "buckets": server.buckets(),
        "warmup_seconds": round(warmup_s, 3),
        "uncontended": {"p50_ms": round(pct(unc, 0.5) * 1e3, 3),
                        "p99_ms": round(pct(unc, 0.99) * 1e3, 3)},
        "capacity_rps": round(capacity_rps, 1),
        "mixes": mixes,
    }
    d2h_ok = True
    if not args.skip_ingress:
        ingress = probe_ingress(server, req_capacity, max(args.n // 2, 50))
        ingress["inproc_p50_ms"] = mixes["steady"]["p50_ms"]
        ingress["inproc_p99_ms"] = mixes["steady"]["p99_ms"]
        ingress["inproc_shed_rate"] = mixes["steady"]["shed_rate"]
        out["ingress"] = ingress
        out["d2h"], d2h_ok = probe_d2h(net, args.batch_limit)
        out["w111_lint"] = probe_w111(net)

    recompiles = server.recompiles_after_warmup()
    out["recompiles_after_warmup"] = recompiles
    server.close()

    print(json.dumps(out))
    failed = False
    if recompiles != 0:
        print(f"# FAIL: {recompiles} steady-state recompile(s) after "
              "warmup", file=sys.stderr)
        failed = True
    if not args.skip_ingress:
        if not d2h_ok:
            print(f"# FAIL: results-only D2H did not shrink the "
                  f"per-batch copy: {out['d2h']}", file=sys.stderr)
            failed = True
        if out["w111_lint"] != "fires":
            print(f"# FAIL: W111 registry-roll lint stayed silent: "
                  f"{out['w111_lint']}", file=sys.stderr)
            failed = True
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
