"""Probe: staged input pipeline vs the per-batch float path (ISSUE 10).

Isolates the INPUT side (no model dispatch): JPEGs on disk through the
staged pipeline into device-staged batches, three ways —

1. ``float32 per-batch`` — the r05 shape of the problem: host float
   conversion, one ``device_put`` of a float batch per step (4x the
   bytes of uint8).
2. ``uint8 per-batch`` — bytes to the device, cast on chip, still one
   transfer per batch.
3. ``uint8 megabatch (K)`` — the r06 staged path: workers fill one
   contiguous ``[K, B, C, H, W]`` slot, ONE transfer per K-step
   dispatch.

Plus decode-worker scaling (1 worker vs all cores) to verify the pool
actually parallelizes, and the H2D bytes each mode ships. One JSON
line:

  {"probe": "pipeline", "float32_per_batch_img_s": ..,
   "uint8_per_batch_img_s": .., "uint8_megabatch_img_s": ..,
   "decode_1w_img_s": .., "decode_nw_img_s": .., "workers": ..,
   "h2d_mb_float32": .., "h2d_mb_uint8": .., "speedup_vs_float": ..}

Run: python benchmarks/probe_pipeline.py [--imgs N] [--batch B] [--k K]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def make_dataset(root: str, n: int, side: int) -> str:
    from PIL import Image
    if os.path.isdir(root) and sum(
            len(fs) for _, _, fs in os.walk(root)) == n:
        return root
    rng = np.random.RandomState(42)
    per = n // 8
    for c in range(8):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per):
            arr = rng.randint(0, 255, (side, side, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"),
                                      quality=85)
    return root


def drive(root, hw, batch, workers, dtype, k):
    """One epoch through the pipeline, staging every item on device;
    returns (img/s, h2d_bytes)."""
    import jax

    from deeplearning4j_tpu.data.dataset import stage_item
    from deeplearning4j_tpu.data.pipeline import MultiWorkerImageIterator
    it = MultiWorkerImageIterator(root, hw, hw, batch_size=batch,
                                  workers=workers, dtype=dtype,
                                  drop_last=True, steps_per_dispatch=k)
    staged_bytes = 0
    try:
        # warmup epoch: worker spawn + child imports must not bill the
        # measured epoch (spawn re-runs site init per worker)
        while it.hasNext():
            it.next()
        it.reset()
        t0 = time.perf_counter()
        n = 0
        last = None
        for item in it.dispatch_stream():
            feats = item.features
            staged_bytes += feats.nbytes if hasattr(feats, "nbytes") else 0
            last = stage_item(item)
            n += feats.shape[0] * feats.shape[1] if feats.ndim == 5 \
                else feats.shape[0]
        if last is not None:            # real device sync
            jax.block_until_ready(last.features)
        dt = time.perf_counter() - t0
        return n / dt, staged_bytes
    finally:
        it.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--imgs", type=int, default=512)
    ap.add_argument("--side", type=int, default=96)
    ap.add_argument("--hw", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--k", type=int, default=4)
    args = ap.parse_args()

    root = make_dataset(f"/tmp/dl4j_probe_pipe_{args.side}_{args.imgs}",
                        args.imgs, args.side)
    cores = os.cpu_count() or 1

    # decode scaling: 1 worker vs all cores, uint8 K=1
    dec1, _ = drive(root, args.hw, args.batch, 1, "uint8", 1)
    decn, _ = drive(root, args.hw, args.batch, cores, "uint8", 1)

    f32, h2d_f32 = drive(root, args.hw, args.batch, cores, "float32", 1)
    u8, h2d_u8 = drive(root, args.hw, args.batch, cores, "uint8", 1)
    mega, h2d_m = drive(root, args.hw, args.batch, cores, "uint8", args.k)

    out = {"probe": "pipeline", "imgs": args.imgs, "hw": args.hw,
           "batch": args.batch, "k": args.k, "workers": cores,
           "decode_1w_img_s": round(dec1, 1),
           "decode_nw_img_s": round(decn, 1),
           "float32_per_batch_img_s": round(f32, 1),
           "uint8_per_batch_img_s": round(u8, 1),
           "uint8_megabatch_img_s": round(mega, 1),
           "h2d_mb_float32": round(h2d_f32 / 1e6, 1),
           "h2d_mb_uint8": round(h2d_u8 / 1e6, 1),
           "speedup_vs_float": round(mega / f32, 2)}
    print(json.dumps(out))
    # uint8 ships exactly 1/4 the float bytes — the staging discipline
    # the H2D-bound analysis (W108) assumes
    assert abs(h2d_f32 - 4 * h2d_u8) / h2d_f32 < 0.01, \
        f"uint8 staging should ship 1/4 the float bytes " \
        f"({h2d_u8} vs {h2d_f32})"
    return 0


if __name__ == "__main__":
    sys.exit(main())
