"""Probe: per-step host dispatch time vs steps_per_dispatch (K).

ISSUE 2 acceptance: the K-step lax.scan megastep amortizes per-step host
dispatch — at K=16 the host-side dispatch bill per update step must be
measurably below K=1. Measured via the ``dl4j_train_step_seconds``
histogram (the fit loops' dispatch-time seam): per_step = Δsum / Δsteps,
where a K-step dispatch contributes ONE sample covering K steps.

Workloads: the synthetic MLP and CNN(LeNet) fit loops the other probes
use. Prints ONE JSON line:

  {"probe": "multistep", "mlp": {"k1": ..., "k4": ..., "k16": ...,
   "speedup_k16": ...}, "cnn": {...}}

``k*`` = host dispatch seconds per update step; ``speedup_k16`` =
k1 / k16. Absolute numbers are CPU-backend dispatch times, not TPU step
times — the regression signal is the ratio shrinking toward 1.

Run: python benchmarks/probe_multistep.py [--batches N] [--reps N]
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def mlp_workload(n_batches):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.train import updaters

    def build():
        conf = (NeuralNetConfiguration.Builder().seed(42)
                .updater(updaters.Adam(0.01)).list()
                .layer(DenseLayer(nOut=64, activation="relu"))
                .layer(DenseLayer(nOut=64, activation="relu"))
                .layer(OutputLayer(nOut=10, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(32))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    batches = [DataSet(rng.randn(32, 32).astype(np.float32),
                       np.eye(10, dtype=np.float32)[rng.randint(0, 10, 32)])
               for _ in range(n_batches)]
    return build, batches


def cnn_workload(n_batches):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import zoo

    def build():
        return zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()

    rng = np.random.RandomState(0)
    batches = [DataSet(rng.randn(8, 16 * 16).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)])
               for _ in range(n_batches)]
    return build, batches


def measure(build, batches, k, reps):
    """Per-update-step host dispatch seconds at steps_per_dispatch=k."""
    from deeplearning4j_tpu import profiler
    reg = profiler.get_registry()
    h = reg.histogram("dl4j_train_step_seconds",
                      "Compiled train-step dispatch time per iteration")
    net = build()
    net.fit(batches, steps_per_dispatch=k)   # warmup: compile + prefetch spin-up
    net.score()
    s0, it0 = h.sum, net.getIterationCount()
    for _ in range(reps):
        net.fit(batches, steps_per_dispatch=k)
    net.score()                              # drain the dispatch pipeline
    steps = net.getIterationCount() - it0
    return (h.sum - s0) / max(steps, 1)


def run_workload(build, batches, ks, reps):
    out = {}
    for k in ks:
        out[f"k{k}"] = round(measure(build, batches, k, reps), 7)
    out["speedup_k16"] = round(out[f"k{ks[0]}"] / max(out[f"k{ks[-1]}"], 1e-12), 2)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=32,
                    help="minibatches per fit pass (divisible by 16)")
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    from deeplearning4j_tpu import profiler
    profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
    ks = (1, 4, 16)
    try:
        result = {
            "probe": "multistep",
            "batches": args.batches,
            "mlp": run_workload(*mlp_workload(args.batches), ks, args.reps),
            "cnn": run_workload(*cnn_workload(args.batches), ks, args.reps),
        }
    finally:
        profiler.set_profiling_mode(None)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
