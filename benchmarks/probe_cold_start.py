"""Cold-start probe: first-dispatch latency of a FRESH process with the
persistent compile cache off vs. populated — across fit, resume, and
serving warmup (the ISSUE-13 headline number).

Protocol (all measurements in subprocesses so every run really is a
fresh process with an empty jit cache):

1. ``prime``: run the scenario once with the cache configured at a temp
   dir — populates the on-disk store.
2. ``cold``: run it again in a fresh process with the cache OFF — the
   first dispatch pays full XLA compile. This is today's default.
3. ``warm``: fresh process, cache pointed at the primed dir — the first
   dispatch deserializes from disk.

Reported per scenario: cold vs warm first-dispatch wall seconds, the
speedup, and the warm run's cache stats (the probe FAILS if the warm
run recorded any disk miss for fit/serving — a miss means the content
key regressed). One JSON line on stdout for ``bench.py --cold-start``.

Run: ``python benchmarks/probe_cold_start.py [--quick]``.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, sys, time, warnings
warnings.simplefilter("ignore")
import numpy as np
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, InputType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn import compilecache as cc
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.data.dataset import DataSet

scenario, cache_dir, ckpt_dir, hidden = sys.argv[1:5]
hidden = int(hidden)
if cache_dir != "none":
    cc.configure(cache_dir)

def build():
    b = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-3))
         .weightInit("xavier").list())
    for _ in range(4):
        b = b.layer(DenseLayer(nOut=hidden, activation="relu"))
    conf = (b.layer(OutputLayer(nOut=16, lossFunction="mcxent",
                                activation="softmax"))
            .setInputType(InputType.feedForward(64)).build())
    return MultiLayerNetwork(conf).init()

rng = np.random.RandomState(0)
ds = DataSet(rng.randn(32, 64).astype(np.float32),
             np.eye(16, dtype=np.float32)[rng.randint(0, 16, 32)])
net = build()

if scenario == "fit":
    t0 = time.perf_counter()
    net.fit(ds, epochs=1)                 # ONE batch: first-dispatch bill
    first = time.perf_counter() - t0
elif scenario == "resume-prep":
    from deeplearning4j_tpu.train.resilience import CheckpointConfig
    net.fit([ds, ds], epochs=1,
            checkpoint=CheckpointConfig(ckpt_dir, every_steps=1))
    first = 0.0
elif scenario == "resume":
    from deeplearning4j_tpu.train.resilience import CheckpointConfig
    t0 = time.perf_counter()
    net.fit([ds, ds], epochs=2,           # restores + first dispatch
            checkpoint=CheckpointConfig(ckpt_dir, resume=True))
    first = time.perf_counter() - t0
elif scenario == "serving":
    from deeplearning4j_tpu.serving.server import ModelServer
    sv = ModelServer(net, batch_limit=8, name="coldstart")
    t0 = time.perf_counter()
    sv.warmup([(64,)])                    # the whole bucket ladder
    first = time.perf_counter() - t0
    sv.close()
else:
    raise SystemExit(f"unknown scenario {scenario}")
print(json.dumps({"first_dispatch_s": first, "cache": cc.cache_stats()}))
"""


def _run_child(scenario, cache_dir, ckpt_dir, hidden):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop(  # the child's cache is OUR argument, never ambient state
        "DL4J_TPU_COMPILE_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, scenario, cache_dir, ckpt_dir,
         str(hidden)],
        capture_output=True, text=True, timeout=600, env=env, cwd=_REPO)
    if proc.returncode != 0:
        raise RuntimeError(f"{scenario} child failed:\n"
                           f"{proc.stderr.strip()[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def probe(quick: bool = False) -> dict:
    hidden = 64 if quick else 256
    work = tempfile.mkdtemp(prefix="dl4j_coldstart_")
    cache = os.path.join(work, "cache")
    out = {"hidden": hidden}
    try:
        for scenario in ("fit", "resume", "serving"):
            ckpt_cold = os.path.join(work, f"ckpt_{scenario}_cold")
            ckpt_warm = os.path.join(work, f"ckpt_{scenario}_warm")
            if scenario == "resume":
                # separate checkpoint dirs so the cold and warm children
                # restore identical-but-independent state; the resumed
                # fit itself writes nothing (no periodic saves), so the
                # prime below leaves the checkpoint untouched
                _run_child("resume-prep", "none", ckpt_cold, hidden)
                _run_child("resume-prep", "none", ckpt_warm, hidden)
            # 1. prime the persistent store (its own timing is irrelevant)
            _run_child(scenario, cache, ckpt_warm, hidden)
            # 2. cold: fresh process, no cache
            t0 = time.perf_counter()
            cold = _run_child(scenario, "none", ckpt_cold, hidden)
            cold_wall = time.perf_counter() - t0
            # 3. warm: fresh process, populated cache
            t0 = time.perf_counter()
            warm = _run_child(scenario, cache, ckpt_warm, hidden)
            warm_wall = time.perf_counter() - t0
            cold_s = cold["first_dispatch_s"]
            warm_s = warm["first_dispatch_s"]
            stats = warm["cache"]
            row = {
                "cold_first_dispatch_s": round(cold_s, 4),
                "warm_first_dispatch_s": round(warm_s, 4),
                "speedup": round(cold_s / warm_s, 2) if warm_s > 0 else None,
                "cold_process_wall_s": round(cold_wall, 2),
                "warm_process_wall_s": round(warm_wall, 2),
                "warm_disk_hits": stats["disk"]["hits"],
                "warm_disk_misses": stats["disk"]["misses"],
                "warm_cold_compile_s": round(
                    stats["compile_seconds"]["cold"], 4),
            }
            # THE pin: a warm fresh process performs ZERO disk-miss
            # compiles for previously-seen keys (fit + serving; resume's
            # restore epoch may legitimately see a tail signature)
            if scenario in ("fit", "serving"):
                assert stats["disk"]["misses"] == 0, \
                    f"{scenario}: warm process recorded disk misses " \
                    f"({stats['disk']['misses']}) — content key regressed"
                assert stats["disk"]["hits"] > 0, \
                    f"{scenario}: warm process never touched the cache"
            assert warm_s < cold_s, \
                f"{scenario}: warm first dispatch ({warm_s:.3f}s) not " \
                f"faster than cold ({cold_s:.3f}s)"
            out[scenario] = row
        return out
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main(argv):
    quick = "--quick" in argv
    result = probe(quick)
    print(json.dumps(result))


if __name__ == "__main__":
    main(sys.argv[1:])
