"""Raw-jax chip-bound probes for the CNN BASELINE rows (TinyYOLO, VGG16).

Methodology (same discipline as the ResNet-50 probe recorded in BASELINE.md
"ResNet-50 XLA plateau"): hand-write the exact train step in minimal jax,
measure it at the bench config, and vary ONE axis at a time:

  A. backbone fwd+bwd with a trivial MSE head  — the honest conv bound
  B. A + the real YOLOv2 loss                  — loss formulation cost
  C. NCHW vs NHWC layouts                      — layout/transpose cost
  D. bf16 vs fp32                              — precision cost

The framework path (zoo.TinyYOLO / zoo.VGG16 via MultiLayerNetwork.fit) is
then compared against the best raw variant; the gap is framework overhead.

FLOP accounting: per-conv 2*K*K*Cin*Cout*oH*oW, summed over the actual
architecture (NOT the nominal 3.5/15.5 GFLOP figures, which are MAC
counts — BASELINE.md r4 note). The helpers are imported from bench.py so
the probe and the shipped bench can never disagree on the basis.
Backward = 2x forward as usual.

Run: python benchmarks/probe_cnn.py [yolo|vgg] [--steps N]
"""

import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# single source of truth for FLOP accounting: bench.py at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import PEAK_TFLOPS, darknet_tiny_flops, vgg16_flops  # noqa: E402

# darknet-tiny conv plan
DARKNET_TINY = [16, 32, 64, 128, 256, 512, 1024, 1024]
VGG16_PLAN = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]


# ------------------------------------------------------------------ raw nets
def _conv(x, w, stride=1, fmt="NHWC"):
    dims = (fmt, "HWIO" if fmt == "NHWC" else "OIHW", fmt)
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME", dimension_numbers=dims)


def _maxpool(x, k=2, s=2, fmt="NHWC", same=False, via="reduce_window"):
    if via == "slices" and k == 2 and s == 2 and not same:
        # 2x2/2 maxpool as elementwise max of 4 strided slices: the backward
        # is a fused select chain instead of XLA SelectAndScatter
        if fmt == "NHWC":
            return jnp.maximum(
                jnp.maximum(x[:, ::2, ::2], x[:, 1::2, ::2]),
                jnp.maximum(x[:, ::2, 1::2], x[:, 1::2, 1::2]))
        return jnp.maximum(
            jnp.maximum(x[:, :, ::2, ::2], x[:, :, 1::2, ::2]),
            jnp.maximum(x[:, :, ::2, 1::2], x[:, :, 1::2, 1::2]))
    if via == "slices" and k == 2 and s == 1 and same:
        # stride-1 SAME 2x2 maxpool = max of x and its +1 shifts (edge-pad)
        if fmt == "NHWC":
            xp = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)), mode="edge")
            return jnp.maximum(
                jnp.maximum(xp[:, :-1, :-1], xp[:, 1:, :-1]),
                jnp.maximum(xp[:, :-1, 1:], xp[:, 1:, 1:]))
        xp = jnp.pad(x, ((0, 0), (0, 0), (0, 1), (0, 1)), mode="edge")
        return jnp.maximum(
            jnp.maximum(xp[:, :, :-1, :-1], xp[:, :, 1:, :-1]),
            jnp.maximum(xp[:, :, :-1, 1:], xp[:, :, 1:, 1:]))
    if fmt == "NHWC":
        window, strides = (1, k, k, 1), (1, s, s, 1)
    else:
        window, strides = (1, 1, k, k), (1, 1, s, s)
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides,
                                 "SAME" if same else "VALID")


def init_darknet(key, n_classes=20, n_boxes=5, fmt="NHWC", dtype=jnp.bfloat16):
    params = []
    c_in = 3
    for c_out in DARKNET_TINY:
        key, k1 = jax.random.split(key)
        shape = (3, 3, c_in, c_out) if fmt == "NHWC" else (c_out, c_in, 3, 3)
        w = jax.random.normal(k1, shape, dtype) * float(2.0 / np.sqrt(9 * c_in))
        scale = jnp.ones((c_out,), dtype)
        bias = jnp.zeros((c_out,), dtype)
        params.append((w, scale, bias))
        c_in = c_out
    key, k1 = jax.random.split(key)
    head_c = n_boxes * (5 + n_classes)
    shape = (1, 1, c_in, head_c) if fmt == "NHWC" else (head_c, c_in, 1, 1)
    params.append((jax.random.normal(k1, shape, dtype) / float(np.sqrt(c_in)),))
    return params


def darknet_fwd(params, x, fmt="NHWC", pool_via="reduce_window",
                bn_fp32=True):
    """conv+BN(inference-form scale/bias)+leaky, pools per darknet-tiny."""
    for i, (w, scale, bias) in enumerate(params[:-1]):
        x = _conv(x, w, 1, fmt)
        # batch-norm in the fused mean/var formulation (the 26% ResNet
        # finding): normalize with batch stats computed in fp32
        axes = (0, 1, 2) if fmt == "NHWC" else (0, 2, 3)
        xf = x.astype(jnp.float32) if bn_fp32 else x
        mean = jnp.mean(xf, axes, keepdims=True)
        var = jnp.mean(jnp.square(xf), axes, keepdims=True) - jnp.square(mean)
        sh = (1, 1, 1, -1) if fmt == "NHWC" else (1, -1, 1, 1)
        x = ((xf - mean) * jax.lax.rsqrt(var + 1e-5)).astype(x.dtype)
        x = x * scale.reshape(sh) + bias.reshape(sh)
        x = jnp.where(x > 0, x, 0.1 * x)
        if i < 5:
            x = _maxpool(x, 2, 2, fmt, via=pool_via)
        elif i == 5:
            x = _maxpool(x, 2, 1, fmt, same=True, via=pool_via)
    return _conv(x, params[-1][0], 1, fmt)


def yolo_loss(out, labels, anchors, fmt="NHWC", n_classes=20):
    """Same formulation as nn/objdetect.py compute_loss, on [N,H,W,B,5+C]."""
    if fmt == "NHWC":
        N, H, W, ch = out.shape
        B = anchors.shape[0]
        p = out.reshape(N, H, W, B, 5 + n_classes).astype(jnp.float32)
        p = jnp.moveaxis(p, 3, 1)  # [N,B,H,W,5+C] -> match NCHW math below
        p = jnp.moveaxis(p, 4, 2)  # [N,B,5+C,H,W]
    else:
        N, ch, H, W = out.shape
        B = anchors.shape[0]
        p = out.reshape(N, B, 5 + n_classes, H, W).astype(jnp.float32)
    pred_xy = jax.nn.sigmoid(p[:, :, 0:2])
    pred_wh = anchors[None, :, :, None, None] * jnp.exp(p[:, :, 2:4])
    pred_conf = jax.nn.sigmoid(p[:, :, 4])
    pred_cls = jax.nn.softmax(p[:, :, 5:], axis=2)

    lab_box = labels[:, 0:4]
    lab_cls = labels[:, 4:]
    obj_mask = (jnp.sum(lab_cls, axis=1) > 0).astype(jnp.float32)
    gx1, gy1, gx2, gy2 = (lab_box[:, i] for i in range(4))
    gt_w = jnp.maximum(gx2 - gx1, 1e-6)
    gt_h = jnp.maximum(gy2 - gy1, 1e-6)
    cell_x = jnp.arange(W)[None, None, :]
    cell_y = jnp.arange(H)[None, :, None]
    gt_cx = (gx1 + gx2) / 2 - cell_x
    gt_cy = (gy1 + gy2) / 2 - cell_y
    inter = jnp.minimum(anchors[:, 0][None, :, None, None], gt_w[:, None]) * \
        jnp.minimum(anchors[:, 1][None, :, None, None], gt_h[:, None])
    union = anchors[:, 0][None, :, None, None] * anchors[:, 1][None, :, None, None] \
        + (gt_w * gt_h)[:, None] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=1)
    resp = jax.nn.one_hot(best, B, axis=1) * obj_mask[:, None]
    xy_loss = jnp.sum(resp[:, :, None] * jnp.square(
        pred_xy - jnp.stack([gt_cx, gt_cy], axis=1)[:, None]), axis=2)
    wh_loss = jnp.sum(resp[:, :, None] * jnp.square(
        jnp.sqrt(jnp.maximum(pred_wh, 1e-9)) -
        jnp.sqrt(jnp.stack([gt_w, gt_h], axis=1)[:, None])), axis=2)
    pcx = pred_xy[:, :, 0] + cell_x[None]
    pcy = pred_xy[:, :, 1] + cell_y[None]
    px1, px2 = pcx - pred_wh[:, :, 0] / 2, pcx + pred_wh[:, :, 0] / 2
    py1, py2 = pcy - pred_wh[:, :, 1] / 2, pcy + pred_wh[:, :, 1] / 2
    ix = jnp.maximum(0.0, jnp.minimum(px2, gx2[:, None]) - jnp.maximum(px1, gx1[:, None]))
    iy = jnp.maximum(0.0, jnp.minimum(py2, gy2[:, None]) - jnp.maximum(py1, gy1[:, None]))
    inter_a = ix * iy
    area_p = jnp.maximum(px2 - px1, 0) * jnp.maximum(py2 - py1, 0)
    iou = inter_a / jnp.maximum(area_p + (gt_w * gt_h)[:, None] - inter_a, 1e-9)
    conf_obj = jnp.square(pred_conf - jax.lax.stop_gradient(iou)) * resp
    conf_noobj = jnp.square(pred_conf) * (1.0 - resp)
    cls_loss = -jnp.sum(lab_cls[:, None] * jnp.log(jnp.maximum(pred_cls, 1e-9)),
                        axis=2) * resp
    return (5.0 * jnp.sum(xy_loss + wh_loss) + jnp.sum(conf_obj)
            + 0.5 * jnp.sum(conf_noobj) + jnp.sum(cls_loss)) / N


def _sync(out):
    """True device sync: materialize a scalar that depends on the result
    (block_until_ready alone under-measures through the async relay on this
    environment's experimental TPU backend — same finding as bench.py)."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def time_step(step, args, steps, warmup=2):
    out = None
    for _ in range(warmup):
        out = step(*args)
        args = (out[0],) + args[1:]
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = step(*args)
        args = (out[0],) + args[1:]
    _sync(out)
    return (time.perf_counter() - t0) / steps


def probe_yolo(steps=20, batch=32, hw=416):
    anchors_np = np.asarray([[1.08, 1.19], [3.42, 4.41], [6.63, 11.38],
                             [9.42, 5.11], [16.62, 10.52]], np.float32)
    fwd_flops = darknet_tiny_flops(hw)
    print(f"darknet-tiny actual fwd GFLOP/img @ {hw}: {fwd_flops/1e9:.2f}")
    grid = hw // 32
    rng = np.random.RandomState(0)
    labels = jnp.zeros((batch, 24, grid, grid), jnp.float32)
    results = {}
    for fmt in ("NHWC", "NCHW"):
        xs = (batch, hw, hw, 3) if fmt == "NHWC" else (batch, 3, hw, hw)
        x = jnp.asarray(rng.randn(*xs).astype(np.float32)).astype(jnp.bfloat16)
        params = init_darknet(jax.random.PRNGKey(0), fmt=fmt)
        anchors = jnp.asarray(anchors_np)

        def mk_loss(kind, pool_via, bn_fp32):
            def lossfn(p, x, *extra):
                out = darknet_fwd(p, x, fmt, pool_via=pool_via, bn_fp32=bn_fp32)
                if kind == "mse":
                    return jnp.mean(jnp.square(out.astype(jnp.float32)))
                return yolo_loss(out, extra[0], anchors, fmt)
            return lossfn

        variants = [
            ("mse/rw", mk_loss("mse", "reduce_window", True), ()),
            ("mse/slices", mk_loss("mse", "slices", True), ()),
            ("mse/slices/bf16bn", mk_loss("mse", "slices", False), ()),
            ("yolo/rw", mk_loss("yolo", "reduce_window", True), (labels,)),
            ("yolo/slices", mk_loss("yolo", "slices", True), (labels,)),
        ]
        for name, lossfn, extra in variants:
            # donate params: matches the framework step (and is required for
            # dependent dispatches to pipeline on relayed backends)
            @partial(jax.jit, donate_argnums=0)
            def step(p, x, *e, _f=lossfn):
                g = jax.grad(_f)(p, x, *e)
                return jax.tree_util.tree_map(lambda a, b: a - 1e-6 * b.astype(a.dtype), p, g), 0

            fresh = jax.tree_util.tree_map(jnp.copy, params)
            dt = time_step(step, (fresh, x) + extra, steps)
            ips = batch / dt
            mfu = ips * 3 * fwd_flops / PEAK_TFLOPS
            results[f"{fmt}_{name}"] = (ips, mfu)
            print(f"  {fmt} {name:18s}: {ips:8.1f} img/s  MFU {mfu:.4f}")

        # fwd-only bound (inference-shaped): how much is backward?
        @jax.jit
        def fwd_only(p, x):
            return jnp.sum(darknet_fwd(p, x, fmt, pool_via="slices")
                           .astype(jnp.float32))
        dt = time_step(lambda p, x: (p, fwd_only(p, x)), (params, x), steps)
        ips = batch / dt
        print(f"  {fmt} {'fwd-only/slices':18s}: {ips:8.1f} img/s  "
              f"(fwd MFU {ips * fwd_flops / PEAK_TFLOPS:.4f})")
    return results


def probe_vgg(steps=12, batch=64, hw=224, n_classes=1000):
    fwd_flops = vgg16_flops(hw, n_classes)
    print(f"vgg16 actual fwd GFLOP/img @ {hw}: {fwd_flops/1e9:.2f}")
    rng = np.random.RandomState(0)
    y = jnp.asarray(np.eye(n_classes, dtype=np.float32)[
        rng.randint(0, n_classes, batch)])
    results = {}
    for fmt in ("NHWC", "NCHW"):
        xs = (batch, hw, hw, 3) if fmt == "NHWC" else (batch, 3, hw, hw)
        x = jnp.asarray(rng.randn(*xs).astype(np.float32)).astype(jnp.bfloat16)
        key = jax.random.PRNGKey(0)
        params = []
        c_in = 3
        for n_convs, c_out in VGG16_PLAN:
            for _ in range(n_convs):
                key, k1 = jax.random.split(key)
                shape = (3, 3, c_in, c_out) if fmt == "NHWC" else (c_out, c_in, 3, 3)
                params.append((jax.random.normal(k1, shape, jnp.bfloat16)
                               * float(2.0 / np.sqrt(9 * c_in)),
                               jnp.zeros((c_out,), jnp.bfloat16)))
                c_in = c_out
        size = hw // 32
        feat = c_in * size * size
        for i, (a, b) in enumerate([(feat, 4096), (4096, 4096), (4096, n_classes)]):
            key, k1 = jax.random.split(key)
            params.append((jax.random.normal(k1, (a, b), jnp.bfloat16) / float(np.sqrt(a)),
                           jnp.zeros((b,), jnp.bfloat16)))

        def fwd(p, x):
            i = 0
            for n_convs, c_out in VGG16_PLAN:
                for _ in range(n_convs):
                    w, bi = p[i]
                    i += 1
                    sh = (1, 1, 1, -1) if fmt == "NHWC" else (1, -1, 1, 1)
                    x = jnp.maximum(_conv(x, w, 1, fmt) + bi.reshape(sh), 0)
                x = _maxpool(x, 2, 2, fmt)
            if fmt == "NCHW":
                x = x.reshape(x.shape[0], -1)
            else:
                x = jnp.moveaxis(x, -1, 1).reshape(x.shape[0], -1)
            for j in range(3):
                w, bi = p[i]
                i += 1
                x = x @ w + bi
                if j < 2:
                    x = jnp.maximum(x, 0)
            return x

        def lossfn(p, x, y):
            logits = fwd(p, x).astype(jnp.float32)
            return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(logits), -1))

        @partial(jax.jit, donate_argnums=0)
        def step(p, x, y):
            g = jax.grad(lossfn)(p, x, y)
            return jax.tree_util.tree_map(
                lambda a, b: a - 1e-6 * b.astype(a.dtype), p, g), 0

        dt = time_step(step, (params, x, y), steps)
        ips = batch / dt
        mfu = ips * 3 * fwd_flops / PEAK_TFLOPS
        results[fmt] = (ips, mfu)
        print(f"  {fmt}: {ips:8.1f} img/s  MFU {mfu:.4f}")
    return results


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "yolo"
    if which in ("yolo", "all"):
        probe_yolo()
    if which in ("vgg", "all"):
        probe_vgg()
