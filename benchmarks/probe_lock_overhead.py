"""Probe: cost of the instrumented-lock layer (ISSUE 8).

Two measurements, one JSON line:

1. **Microbench** — acquire/release cost of a raw ``threading.Lock``
   vs an ``InstrumentedLock`` with instrumentation OFF (the ship
   state: one module-flag check of overhead) and ON (wait/hold
   histograms + contention counter per op).
2. **Fit overhead** — a tiny-LeNet fit under ProfilingMode BASIC with a
   plain ``threading.Lock`` vs an ``InstrumentedLock`` on the
   per-iteration path (one critical section per step, the bookkeeping
   pattern the serving/elastic layers use). Both runs pay the same
   PR-1 profiler cost (pinned separately by probe_obs_overhead), so
   the ratio isolates THIS PR's lock layer. The ISSUE 8 acceptance
   bound is ``fit_overhead_ratio < 0.05`` (<5% with instrumentation
   ON); the probe exits non-zero past it.

  {"probe": "lock_overhead", "raw_ns_per_op": ..., "off_ns_per_op": ...,
   "on_ns_per_op": ..., "fit_plain_sec_per_iter": ...,
   "fit_inst_sec_per_iter": ..., "fit_overhead_ratio": ...}

Run: python benchmarks/probe_lock_overhead.py [--iters N] [--ops N]
"""

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FIT_OVERHEAD_BOUND = 0.05


def _lock_ns_per_op(lock, ops: int) -> float:
    t0 = time.perf_counter()
    for _ in range(ops):
        with lock:
            pass
    return (time.perf_counter() - t0) / ops * 1e9


def microbench(ops: int) -> dict:
    from deeplearning4j_tpu import profiler
    raw = threading.Lock()
    inst = profiler.InstrumentedLock("probe:micro")
    profiler.set_profiling_mode(profiler.ProfilingMode.OFF)
    out = {"raw_ns_per_op": _lock_ns_per_op(raw, ops),
           "off_ns_per_op": _lock_ns_per_op(inst, ops)}
    profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
    out["on_ns_per_op"] = _lock_ns_per_op(inst, ops)
    profiler.set_profiling_mode(None)
    return out


def _queue_ns_per_op(q, ops: int) -> float:
    t0 = time.perf_counter()
    for _ in range(ops):
        q.put(1)
        q.get()
    return (time.perf_counter() - t0) / ops * 1e9


def queue_microbench(ops: int) -> dict:
    """plain queue.Queue vs InstrumentedQueue put+get — the PR-10
    prefetcher-queue adoption rides on this being ~free with
    instrumentation OFF."""
    import queue as _q

    from deeplearning4j_tpu import profiler
    raw = _q.Queue(maxsize=4)
    inst = profiler.InstrumentedQueue(maxsize=4, name="probe:queue")
    profiler.set_profiling_mode(profiler.ProfilingMode.OFF)
    out = {"queue_raw_ns_per_op": _queue_ns_per_op(raw, ops),
           "queue_off_ns_per_op": _queue_ns_per_op(inst, ops)}
    profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
    out["queue_on_ns_per_op"] = _queue_ns_per_op(inst, ops)
    profiler.set_profiling_mode(None)
    return out


def build():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import zoo
    net = zoo.LeNet(num_classes=3, input_shape=(1, 16, 16)).init()
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16 * 16).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]
    return net, DataSet(x, y)


def _block(net, ds, lock, iters: int) -> float:
    net.score()
    t0 = time.perf_counter()
    for _ in range(iters):
        # one instrumented critical section per iteration: the per-step
        # bookkeeping pattern the serving/elastic layers now use
        with lock:
            net.fit(ds)
    net.score()
    return (time.perf_counter() - t0) / iters


def fit_overhead(iters: int, warmup: int, blocks: int) -> dict:
    """Plain lock vs InstrumentedLock wrapping each fit call, both
    under ProfilingMode BASIC — alternating median blocks, same shape
    as probe_obs_overhead (scheduler noise swamps back-to-back A/B)."""
    from deeplearning4j_tpu import profiler
    plain = threading.Lock()
    inst = profiler.InstrumentedLock("probe:fit")
    net_plain, ds = build()
    net_inst, _ = build()
    try:
        profiler.set_profiling_mode(profiler.ProfilingMode.BASIC)
        for _ in range(warmup):
            net_plain.fit(ds)
            net_inst.fit(ds)
        per = max(1, iters // blocks)
        t_plain, t_inst = [], []
        for b in range(blocks):
            # alternate which variant runs first: a fixed order biases
            # the second slot with the first one's cache/thermal wake
            order = [(t_plain, net_plain, plain), (t_inst, net_inst, inst)]
            for out, net, lk in (order if b % 2 == 0 else order[::-1]):
                out.append(_block(net, ds, lk, per))
        t_plain.sort()
        t_inst.sort()
        return {"fit_plain_sec_per_iter": t_plain[len(t_plain) // 2],
                "fit_inst_sec_per_iter": t_inst[len(t_inst) // 2]}
    finally:
        profiler.set_profiling_mode(None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200,
                    help="total measured fit iterations per mode")
    ap.add_argument("--warmup", type=int, default=15)
    ap.add_argument("--blocks", type=int, default=10)
    ap.add_argument("--ops", type=int, default=200_000,
                    help="microbench acquire/release ops per variant")
    args = ap.parse_args()

    res = microbench(args.ops)
    res.update(queue_microbench(max(1, args.ops // 10)))
    res.update(fit_overhead(args.iters, args.warmup, args.blocks))
    ratio = res["fit_inst_sec_per_iter"] / res["fit_plain_sec_per_iter"] \
        - 1.0
    print(json.dumps({"probe": "lock_overhead", "iters": args.iters,
                      **{k: round(v, 9) for k, v in res.items()},
                      "fit_overhead_ratio": round(ratio, 4)}))
    if ratio >= FIT_OVERHEAD_BOUND:
        print(f"FAIL: instrumented fit overhead {ratio:.1%} >= "
              f"{FIT_OVERHEAD_BOUND:.0%}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
