#!/usr/bin/env python
"""Repo linter — the tier-1 flow's "repo lints itself" gate.

Prefers ``ruff`` (config in pyproject.toml: pyflakes + bugbear) when the
binary is installed; this container ships no linter, so the default path
is a dependency-free AST fallback implementing the highest-signal subset
of the same rules:

- ``F401``  module-level import bound but never used (skipped in
  ``__init__.py`` re-export surfaces)
- ``F632``  ``is``/``is not`` comparison against a str/int/tuple literal
- ``F811``  module-level def/class silently redefining an earlier one
- ``F841``  local variable assigned but never used (plain single-name
  assignments only; ``_``-prefixed names exempt; skipped under tests/
  to match the ruff per-file-ignores)
- ``B006``  mutable default argument ([], {}, set()/list()/dict())
- ``E722``  bare ``except:``
- ``W605``  invalid escape sequence in a non-raw string literal

``# noqa`` (bare, or ``# noqa: F401,...``) on the flagged line suppresses
a finding, matching ruff semantics, so both linters agree on the same
annotations. Exit status 0 = clean.

On top of the style/correctness rules, the gate runs the repo's own
**concurrency self-lint** (``deeplearning4j_tpu.analysis.concurrency``,
the DL4J-E2xx/W21x thread-safety codes) over the package with
warnings-as-errors — per-code suppressions live in pyproject.toml under
``[tool.dl4j.concurrency]`` and per-line ones as ``# dl4j: noqa=E201``
comments. Ruff has no equivalent rule set, so this half always runs.

The gate also re-imports every graph in the persisted TF conformance
corpus (``tests/fixtures/tfgraphs``) and requires a clean
``import_report`` (the DL4J-E16x/W16x import lints) with
warnings-as-errors — suppressions live in pyproject.toml under
``[tool.dl4j.imports]``.

Usage: ``python tools/lint.py [paths...]`` (default: the package, tests,
tools, benchmarks). ``--fallback`` forces the AST linter even when ruff
exists (what the test suite pins); ``--no-concurrency`` skips the
thread-safety pass (style-only run); ``--no-imports`` skips the
imported-fixture gate.
"""

from __future__ import annotations

import argparse
import ast
import io
import re
import shutil
import subprocess
import sys
import tokenize
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = ["deeplearning4j_tpu", "tests", "tools", "benchmarks",
                 "bench.py"]

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?", re.I)
_WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


class Finding:
    def __init__(self, path: Path, line: int, code: str, message: str):
        self.path, self.line, self.code, self.message = path, line, code, message

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _noqa_lines(source: str):
    """line number -> set of suppressed codes (empty set = suppress all)."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _NOQA_RE.search(line)
        if m:
            codes = m.group("codes")
            out[i] = {c.strip().upper() for c in codes.split(",")} \
                if codes else set()
    return out


def _used_names(nodes):
    """Every identifier the module can plausibly reference: Name loads,
    plus word tokens inside string constants (quoted annotations,
    __all__ entries, forward references)."""
    used = set()
    for node in nodes:
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and len(node.value) < 200:
            used.update(_WORD_RE.findall(node.value))
        elif isinstance(node, ast.Global):
            used.update(node.names)
    return used


def _check_f401(tree, nodes, path: Path, findings):
    if path.name == "__init__.py":
        return
    used = _used_names(nodes)
    for node in tree.body:                       # module level only
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if bound not in used:
                    findings.append(Finding(
                        path, node.lineno, "F401",
                        f"'{alias.name}' imported but unused"))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                if bound not in used:
                    findings.append(Finding(
                        path, node.lineno, "F401",
                        f"'{node.module}.{alias.name}' imported but unused"))


def _check_f811(tree, path: Path, findings):
    seen = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen:
                findings.append(Finding(
                    path, node.lineno, "F811",
                    f"redefinition of '{node.name}' from line "
                    f"{seen[node.name]}"))
            seen[node.name] = node.lineno


def _check_f632(tree, nodes, path: Path, findings):
    for node in nodes:
        if not isinstance(node, ast.Compare):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Is, ast.IsNot)) and \
                    isinstance(comp, ast.Constant) and \
                    isinstance(comp.value, (str, int, bytes)) and \
                    not isinstance(comp.value, bool):
                findings.append(Finding(
                    path, node.lineno, "F632",
                    "use == / != to compare with literals, not 'is'"))


def _check_b006(tree, nodes, path: Path, findings):
    for node in nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + \
            [d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set") and not d.args
                and not d.keywords)
            if mutable:
                findings.append(Finding(
                    path, d.lineno, "B006",
                    f"mutable default argument in '{node.name}' — use "
                    f"None and create inside the function"))


def _check_e722(tree, nodes, path: Path, findings):
    for node in nodes:
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(Finding(path, node.lineno, "E722",
                                    "bare 'except:' — name the exception"))


def _scope_statements(fn):
    """Nodes belonging to ``fn``'s own scope — descends everything except
    nested function/class/lambda bodies (their assignments are THEIR
    locals, and each nested def is linted as its own scope)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _check_f841(tree, nodes, path: Path, findings):
    """Local assigned but never used. Conservative subset of ruff's F841:
    plain single-Name ``x = ...`` / annotated assignments only (tuple
    unpacking, loop targets, and aug-assigns are deliberate far too often
    to flag), ``_``-prefixed names exempt, and a name counts as used if it
    is loaded ANYWHERE inside the function — including nested closures
    and short string constants (quoted forward refs)."""
    for fn in nodes:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        used = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and not isinstance(node.ctx,
                                                             ast.Store):
                used.add(node.id)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                used.update(node.names)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) and len(node.value) < 200:
                used.update(_WORD_RE.findall(node.value))
        first_assign = {}
        for node in _scope_statements(fn):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target = node.target.id
            if target and not target.startswith("_") \
                    and target not in used:
                first_assign.setdefault(target, node.lineno)
        for name, lineno in sorted(first_assign.items(),
                                   key=lambda kv: kv[1]):
            findings.append(Finding(
                path, lineno, "F841",
                f"local variable '{name}' is assigned to but never used"))


#: every escape the language defines for str literals (bytes' stricter
#: set is not distinguished — conservative)
_VALID_ESCAPES = frozenset("\n\\'\"abfnrtv01234567xNuU")


def _check_w605(source: str, path: Path, findings):
    """Invalid escape sequences in non-raw string literals — today a
    DeprecationWarning, eventually a SyntaxError, always a latent regex
    or path bug. Token-level (not AST) so every literal is seen exactly
    where it is written."""
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return
    for tok in tokens:
        if tok.type != tokenize.STRING:
            continue
        text = tok.string
        prefix = re.match(r"[A-Za-z]*", text).group(0)
        if "r" in prefix.lower():
            continue
        rest = text[len(prefix):]
        qlen = 3 if rest[:3] in ('"""', "'''") else 1
        body = rest[qlen:-qlen]
        line = tok.start[0]
        i = 0
        while i < len(body) - 1:
            if body[i] == "\\":
                nxt = body[i + 1]
                if nxt not in _VALID_ESCAPES:
                    findings.append(Finding(
                        path, line + body[:i].count("\n"), "W605",
                        f"invalid escape sequence '\\{nxt}' — use a raw "
                        f"string (r'...') or double the backslash"))
                i += 2
            else:
                i += 1


def lint_file(path: Path):
    source = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")]
    findings = []
    nodes = list(ast.walk(tree))    # ONE tree walk shared by every check
    _check_f811(tree, path, findings)
    for check in (_check_f401, _check_f632, _check_b006, _check_e722):
        check(tree, nodes, path, findings)
    # tests/* keep F841 probes (mirrors the pyproject per-file-ignores)
    if "tests" not in path.parts:
        _check_f841(tree, nodes, path, findings)
    _check_w605(source, path, findings)
    noqa = _noqa_lines(source)
    return [f for f in findings
            if not (f.line in noqa and
                    (not noqa[f.line] or f.code in noqa[f.line]))]


def iter_py_files(paths):
    for p in paths:
        p = (REPO / p) if not Path(p).is_absolute() else Path(p)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.py"))


def run_fallback(paths) -> int:
    findings = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f))
    for f in findings:
        print(f)
    n = len(findings)
    print(f"lint (ast fallback): {n} finding(s)" if n
          else "lint (ast fallback): clean")
    return 1 if findings else 0


#: what the concurrency self-lint covers: the shipped package only —
#: tests keep deliberately-racy fixtures, benchmarks are single-threaded
CONCURRENCY_PATHS = ["deeplearning4j_tpu"]


def _pyproject_suppress(section: str) -> list:
    """``[tool.dl4j.<section>] suppress = ["W212", ...]`` from
    pyproject.toml (line-scoped parse: this container is py3.10, no
    tomllib, and the gate must stay dependency-free). Scans the section
    line by line until the next ``[section]`` header, so other keys,
    comments, or '[' characters inside the section cannot silently
    defeat the parse."""
    try:
        text = (REPO / "pyproject.toml").read_text(encoding="utf-8")
    except OSError:
        return []
    header = re.escape(f"[tool.dl4j.{section}]")
    in_section = in_array = False
    body: list = []
    for line in text.splitlines():
        # strip TOML comments first: a ']' or quoted word inside one
        # must not end (or pollute) the array parse — codes never
        # contain '#'
        stripped = line.split("#", 1)[0].strip()
        if in_array:
            head = stripped.split("]", 1)[0]
            body.append(head)
            if "]" in stripped:
                return re.findall(r'"([^"]+)"', " ".join(body))
            continue
        if re.fullmatch(header, stripped):
            in_section = True
            continue
        if in_section and re.fullmatch(r"\[[^\]]+\]", stripped):
            break                       # next section header
        if in_section:
            m = re.match(r"suppress\s*=\s*\[(?P<rest>.*)", stripped)
            if m:
                rest = m.group("rest")
                if "]" in rest:         # single-line array
                    return re.findall(r'"([^"]+)"',
                                      rest.split("]", 1)[0])
                body.append(rest)       # multi-line array: keep reading
                in_array = True
    return []


def _pyproject_concurrency_suppress() -> list:
    return _pyproject_suppress("concurrency")


def _pyproject_imports_suppress() -> list:
    return _pyproject_suppress("imports")


def run_concurrency(paths=None) -> int:
    """The DL4J-E2xx/W21x thread-safety self-lint, warnings-as-errors.
    Returns 0 when every path is clean."""
    sys.path.insert(0, str(REPO))
    try:
        from deeplearning4j_tpu.analysis.concurrency import \
            analyze_concurrency
    finally:
        sys.path.pop(0)
    suppress = _pyproject_concurrency_suppress()
    failed = 0
    for p in (paths or CONCURRENCY_PATHS):
        try:
            report = analyze_concurrency(str(REPO / p), suppress=suppress)
        except ValueError as e:
            # a typo'd code in [tool.dl4j.concurrency] suppress must be
            # a clean usage error, not a traceback
            print(f"concurrency self-lint: bad suppress config in "
                  f"pyproject.toml: {e}")
            return 1
        print(report.format())
        if not report.ok(warnings_as_errors=True):
            failed = 1
    return failed


#: what the imported-fixture gate covers: the persisted TF conformance
#: corpus — every graph must re-import with a clean ``import_report``
IMPORT_FIXTURE_DIR = "tests/fixtures/tfgraphs"


def run_imports(fixture_dir=None) -> int:
    """Imported-fixture lint gate: re-import every graph in the persisted
    conformance corpus and require a clean ``import_report`` (the
    DL4J-E16x/W16x import lints), warnings-as-errors. Per-code
    suppressions live in pyproject.toml under ``[tool.dl4j.imports]``.
    Returns 0 when every fixture is clean; skips (0) when the corpus or
    the TF proto stubs are absent — the gate audits shipped fixtures, it
    does not require a TF install."""
    fdir = Path(fixture_dir) if fixture_dir else REPO / IMPORT_FIXTURE_DIR
    files = sorted(fdir.glob("*.npz")) if fdir.is_dir() else []
    if not files:
        print("imports lint: no import fixtures found — skipped")
        return 0
    try:
        from tensorflow.core.framework import graph_pb2
    except ImportError:
        print("imports lint: tensorflow protos unavailable — skipped")
        return 0
    import numpy as np
    sys.path.insert(0, str(REPO))
    try:
        from deeplearning4j_tpu.modelimport.tensorflow import TFGraphImport
    finally:
        sys.path.pop(0)
    suppress = _pyproject_imports_suppress()
    failed = checked = 0
    for path in files:
        data = np.load(path, allow_pickle=False)
        gd = graph_pb2.GraphDef()
        gd.ParseFromString(data["graph_def"].tobytes())
        try:
            sd = TFGraphImport.importGraphDef(gd)
        except ValueError as e:
            print(f"imports lint: {path.name}: import failed: {e}")
            failed = 1
            continue
        try:
            report = sd.import_report.apply_config(suppress=suppress)
        except ValueError as e:
            # a typo'd code in [tool.dl4j.imports] suppress must be a
            # clean usage error, not a traceback
            print(f"imports lint: bad suppress config in "
                  f"pyproject.toml: {e}")
            return 1
        checked += 1
        if not report.ok(warnings_as_errors=True):
            report.subject = path.name
            print(report.format())
            failed = 1
    print(f"imports lint: {checked} fixture(s) checked"
          + ("" if failed else " — clean"))
    return failed


def run_cost(chip: str = "tpu-v4") -> int:
    """Cost-model gate: every zoo architecture through the DL4J-E12x/W12x
    whole-program cost lints on the default chip, warnings-as-errors — a
    config change that statically OOMs (or regresses the predicted plan
    on) the reference chip fails the gate before any hardware sees it.
    Per-code suppressions live under ``[tool.dl4j.cost]``. Skips (0)
    when the model stack cannot import (the gate needs the layer
    definitions, not jax — analysis itself is jax-free)."""
    sys.path.insert(0, str(REPO))
    try:
        from deeplearning4j_tpu.analysis import analyze
        from deeplearning4j_tpu.analysis.cost import CostSpec
        from deeplearning4j_tpu.models import zoo
    except ImportError as e:
        print(f"cost lint: model stack unavailable ({e}) — skipped")
        return 0
    finally:
        sys.path.pop(0)
    suppress = _pyproject_suppress("cost")
    failed = checked = 0
    for name, cls in zoo.ZOO_MODELS.items():
        try:
            report = analyze(cls().conf_builder(), cost=CostSpec(chip=chip),
                             suppress=suppress)
        except ValueError as e:
            # a typo'd code in [tool.dl4j.cost] suppress must be a clean
            # usage error, not a traceback
            print(f"cost lint: bad suppress config in pyproject.toml: {e}")
            return 1
        report.diagnostics = [d for d in report.diagnostics
                              if d.code.startswith(("DL4J-E12", "DL4J-W12"))]
        checked += 1
        if not report.ok(warnings_as_errors=True):
            report.subject = name
            print(report.format())
            failed = 1
    print(f"cost lint: {checked} zoo model(s) checked on {chip}"
          + ("" if failed else " — clean"))
    return failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--fallback", action="store_true",
                    help="force the AST fallback even when ruff is on PATH")
    ap.add_argument("--no-concurrency", action="store_true",
                    help="skip the DL4J-E2xx/W21x thread-safety self-lint")
    ap.add_argument("--no-imports", action="store_true",
                    help="skip the DL4J-E16x/W16x imported-fixture gate")
    ap.add_argument("--no-cost", action="store_true",
                    help="skip the DL4J-E12x/W12x zoo cost-model gate")
    args = ap.parse_args(argv)
    paths = args.paths or DEFAULT_PATHS
    if not args.fallback and shutil.which("ruff"):
        rc = subprocess.call(["ruff", "check", *paths], cwd=REPO)
    else:
        rc = run_fallback(paths)
    if not args.no_concurrency:
        rc = run_concurrency() or rc
    if not args.no_imports:
        rc = run_imports() or rc
    if not args.no_cost:
        rc = run_cost() or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
