"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: BERT-style transformer training throughput on one chip
(the reference's BASELINE config #4 / SameDiff-BERT metric, SURVEY.md §6).
``value`` = training samples/sec at seq-len 128; ``vs_baseline`` = model
FLOPs utilization achieved divided by the 0.35 MFU target BASELINE.md
derives (the reference publishes no in-repo number — see BASELINE.md).

Run: ``python bench.py`` (add ``--quick`` for a smaller config in CI).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# public v5e per-chip peak (BASELINE.md): 197 bf16 TFLOP/s
PEAK_TFLOPS = 197e12
TARGET_MFU = 0.35


def main(quick: bool = False):
    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.train import updaters

    if quick:
        cfg = tfm.TransformerConfig(vocab_size=8192, d_model=256, n_heads=4,
                                    n_layers=4, d_ff=1024, max_len=128,
                                    causal=False, dtype=jnp.bfloat16)
        batch, steps = 16, 10
    else:
        cfg = tfm.TransformerConfig.bert_base(dtype=jnp.bfloat16)  # 110M params
        batch, steps = 32, 20
    seq = 128

    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    updater = updaters.Adam(1e-4)
    opt = tfm.init_opt_state(params, updater)
    step = tfm.make_train_step(cfg, updater, mesh=None)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)

    # param count for the 6*N*T FLOPs estimate (fwd+bwd)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))

    # warmup / compile; float() forces a real device->host materialization
    # (block_until_ready alone under-measures through the async relay on
    # this environment's experimental TPU backend)
    params, opt, loss = step(params, opt, jnp.asarray(0.0), tokens, targets, mask)
    float(loss)

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, loss = step(params, opt, jnp.asarray(float(i + 1)),
                                 tokens, targets, mask)
    final_loss = float(loss)  # true sync: the value depends on every step
    dt = time.perf_counter() - t0

    samples_per_sec = steps * batch / dt
    tokens_per_sec = samples_per_sec * seq
    flops_per_token = 6.0 * n_params  # fwd + bwd transformer estimate
    mfu = tokens_per_sec * flops_per_token / PEAK_TFLOPS

    print(json.dumps({
        "metric": "bert_base_seq128_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec",
        "vs_baseline": round(mfu / TARGET_MFU, 4),
        "detail": {"mfu": round(mfu, 4), "n_params": n_params,
                   "batch": batch, "seq": seq, "steps": steps,
                   "final_loss": final_loss,
                   "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main(quick="--quick" in sys.argv)
