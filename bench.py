"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: BERT-style transformer training throughput on one chip
(the reference's BASELINE config #4 / SameDiff-BERT metric, SURVEY.md §6).
``value`` = training samples/sec at seq-len 128; ``vs_baseline`` = model
FLOPs utilization achieved divided by the 0.35 MFU target BASELINE.md
derives (the reference publishes no in-repo number — see BASELINE.md).

MFU accounting is per-matmul (VERDICT r1 weak #3): embedding gathers and
positional adds contribute zero FLOPs; attention score/value matmuls are
counted; backward = 2x forward.

The ``detail`` field carries the full BASELINE.md metric set:
- ``gemm``: large square bf16 matmul, TFLOP/s and % of MXU peak
- ``resnet50``: fwd+bwd img/s/chip through the ComputationGraph train
  step + MFU on the 3 x 4.1 GFLOP/img basis (BASELINE.md)
- ``vgg16`` / ``tiny_yolo``: same protocol over the other BASELINE CNN
  rows (15.5 / 3.5 GFLOP-fwd bases)
- ``dp_scaling``: measured only when >1 real device is attached (a
  virtual CPU mesh on one host measures host contention, not scaling)

Run: ``python bench.py`` (``--quick`` = small configs for CI;
``--skip-resnet`` / ``--skip-gemm`` / ``--skip-extra-cnn`` /
``--skip-scaling`` to bisect).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# public v5e per-chip peak (BASELINE.md): 197 bf16 TFLOP/s
PEAK_TFLOPS = 197e12
TARGET_MFU = 0.35


def transformer_train_flops_per_token(cfg, seq_len: int) -> float:
    """Per-matmul FLOP accounting for one training step, per token.

    Counts, per layer: QKV projection (2*E*3E), attention scores + weighted
    values (2 * 2*T*E per token), output projection (2*E*E), and the two
    FFN matmuls (2 * 2*E*F); plus the LM head (2*E*V — the tied-embedding
    head matmul is real compute, the embedding *lookup* is a gather and
    counts zero). Backward = 2x forward.
    """
    L, E, F, V = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
    proj = 2 * E * (3 * E) + 2 * E * E + 2 * (2 * E * F)
    attn = 2 * (2 * seq_len * E)
    head = 2 * E * V
    fwd = L * (proj + attn) + head
    return 3.0 * fwd


def bench_gemm(quick: bool = False):
    """Large square bf16 GEMM -> TFLOP/s and fraction of MXU peak
    (BASELINE.md 'GEMM TFLOPS' row; target >=80% of peak)."""
    n = 2048 if quick else 16384
    iters = 10 if quick else 30
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(key, (n, n), jnp.bfloat16)
    # One compiled program containing the whole chain: measures the MXU, not
    # per-dispatch latency through the tunneled backend. The chain c = c @ b
    # serializes the matmuls so none can be elided or overlapped unfairly.
    loop = jax.jit(lambda c, y: jax.lax.fori_loop(0, iters, lambda i, x: x @ y, c))
    sync = jax.jit(lambda x: x[0, 0].astype(jnp.float32))
    c = loop(a, b)
    float(sync(c))  # warmup: compile both the loop AND the sync program
    t0 = time.perf_counter()
    c = loop(a, b)
    float(sync(c))  # true device sync
    dt = time.perf_counter() - t0
    tflops = iters * 2.0 * n ** 3 / dt
    return {"n": n, "tflops": round(tflops / 1e12, 2),
            "pct_peak": round(tflops / PEAK_TFLOPS, 4)}


def bench_bert(quick: bool = False):
    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.train import updaters

    if quick:
        cfg = tfm.TransformerConfig(vocab_size=8192, d_model=256, n_heads=4,
                                    n_layers=4, d_ff=1024, max_len=128,
                                    causal=False, dtype=jnp.bfloat16)
        batch, steps = 16, 10
    else:
        cfg = tfm.TransformerConfig.bert_base(dtype=jnp.bfloat16)  # 110M params
        batch, steps = 32, 20
    seq = 128

    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    updater = updaters.Adam(1e-4)
    opt = tfm.init_opt_state(params, updater)
    step = tfm.make_train_step(cfg, updater, mesh=None)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))

    # warmup / compile; float() forces a real device->host materialization
    # (block_until_ready alone under-measures through the async relay on
    # this environment's experimental TPU backend)
    params, opt, loss = step(params, opt, jnp.asarray(0.0), tokens, targets, mask)
    float(loss)

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, loss = step(params, opt, jnp.asarray(float(i + 1)),
                                 tokens, targets, mask)
    final_loss = float(loss)  # true sync: the value depends on every step
    dt = time.perf_counter() - t0

    samples_per_sec = steps * batch / dt
    tokens_per_sec = samples_per_sec * seq
    mfu = tokens_per_sec * transformer_train_flops_per_token(cfg, seq) / PEAK_TFLOPS
    return {"samples_per_sec": round(samples_per_sec, 2),
            "mfu": round(mfu, 4), "n_params": n_params, "batch": batch,
            "seq": seq, "steps": steps, "final_loss": round(final_loss, 4)}


def bench_resnet50(quick: bool = False):
    """ResNet-50 fwd+bwd through the ComputationGraph compiled train step
    (BASELINE.md north-star row; img/s/chip + MFU on 3 x 4.1 GFLOP/img)."""
    from deeplearning4j_tpu.models import zoo

    if quick:
        batch, hw, steps = 8, 64, 3
    else:
        batch, hw, steps = 256, 224, 8
    # bf16 dtype policy (BASELINE.md: the reference's TPU-basis MFU target
    # assumes MXU-native precision; BN stats/loss/updater stay fp32)
    net = zoo.ResNet50(num_classes=1000, input_shape=(3, hw, hw),
                       dtype="bfloat16").init()
    # 4.1 GFLOP fwd per 224^2 image; scale by resolution for --quick
    return _bench_cnn_train(net, batch, hw, steps,
                            4.1e9 * (hw / 224.0) ** 2)


def _bench_cnn_train(net, batch, hw, steps, fwd_flops_per_img, n_classes=1000,
                     label_grid=None):
    """Shared fwd+bwd timing loop for CNN zoo models."""
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 3, hw, hw).astype(np.float32))
    if label_grid is not None:
        # empty-object YOLO label grid: numerically safe, same FLOPs
        y = jnp.zeros((batch,) + tuple(label_grid), jnp.float32)
    else:
        y = jnp.asarray(np.eye(n_classes, dtype=np.float32)[
            rng.randint(0, n_classes, batch)])
    ds = DataSet(x, y)
    net.fit(ds)
    float(net.score())
    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit(ds)
    float(net.score())
    dt = time.perf_counter() - t0
    img_per_sec = steps * batch / dt
    mfu = img_per_sec * 3.0 * fwd_flops_per_img / PEAK_TFLOPS
    return {"img_per_sec": round(img_per_sec, 2), "mfu": round(mfu, 4),
            "batch": batch, "hw": hw, "steps": steps}


def bench_vgg16(quick: bool = False):
    """VGG16 train img/s (the BASELINE 'not yet benchmarked' row).
    ~15.5 GFLOP fwd per 224^2 image."""
    from deeplearning4j_tpu.models import zoo
    batch, hw, steps = (4, 64, 2) if quick else (64, 224, 4)
    net = zoo.VGG16(num_classes=1000, input_shape=(3, hw, hw),
                    dtype="bfloat16").init()
    return _bench_cnn_train(net, batch, hw, steps,
                            15.5e9 * (hw / 224.0) ** 2)


def bench_tinyyolo(quick: bool = False):
    """TinyYOLO train img/s (the BASELINE 'not yet benchmarked' row).
    ~3.5 GFLOP fwd per 416^2 image (darknet-tiny backbone)."""
    from deeplearning4j_tpu.models import zoo
    batch, hw, steps = (4, 64, 2) if quick else (32, 416, 4)
    net = zoo.TinyYOLO(num_classes=20, input_shape=(3, hw, hw),
                       dtype="bfloat16").init()
    grid = hw // 32
    return _bench_cnn_train(net, batch, hw, steps,
                            3.5e9 * (hw / 416.0) ** 2,
                            label_grid=(24, grid, grid))


def bench_dp_scaling(bert_1chip_samples_per_sec, quick: bool = False):
    """DP scaling across real devices only (BASELINE.md scaling row)."""
    n = len(jax.devices())
    if n < 2:
        return {"skipped": f"single-device host (n={n}); scaling on a "
                           f"virtual CPU mesh measures host contention, "
                           f"not ICI — run on a multi-chip slice"}
    if quick:
        # the 1-chip baseline from --quick is a tiny config; an efficiency
        # ratio against full bert_base would be meaningless
        return {"skipped": "quick mode: baseline config differs"}
    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.parallel.mesh import DeviceMesh
    from deeplearning4j_tpu.train import updaters

    cfg = tfm.TransformerConfig.bert_base(dtype=jnp.bfloat16)
    mesh = DeviceMesh.create(data=n, model=1, seq=1)
    updater = updaters.Adam(1e-4)
    with mesh:
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        opt = tfm.init_opt_state(params, updater)
        step = tfm.make_train_step(cfg, updater, mesh)
        batch, seq, steps = 32 * n, 128, 20
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        mask = jnp.ones((batch, seq), jnp.float32)
        params, opt, loss = step(params, opt, jnp.asarray(0.0), tokens, targets, mask)
        float(loss)
        t0 = time.perf_counter()
        for i in range(steps):
            params, opt, loss = step(params, opt, jnp.asarray(float(i + 1)),
                                     tokens, targets, mask)
        float(loss)
        dt = time.perf_counter() - t0
    sps = steps * batch / dt
    eff = sps / (n * bert_1chip_samples_per_sec)
    return {"n_devices": n, "samples_per_sec": round(sps, 2),
            "scaling_efficiency": round(eff, 4)}


def main(argv):
    quick = "--quick" in argv
    detail = {"backend": jax.default_backend(),
              "n_devices": len(jax.devices())}

    if "--skip-gemm" not in argv:
        detail["gemm"] = bench_gemm(quick)
    bert = bench_bert(quick)
    detail["bert"] = bert
    if "--skip-resnet" not in argv:
        detail["resnet50"] = bench_resnet50(quick)
    if "--skip-extra-cnn" not in argv:
        detail["vgg16"] = bench_vgg16(quick)
        detail["tiny_yolo"] = bench_tinyyolo(quick)
    if "--skip-scaling" not in argv:
        detail["dp_scaling"] = bench_dp_scaling(bert["samples_per_sec"], quick)

    print(json.dumps({
        "metric": "bert_base_seq128_train_samples_per_sec_per_chip",
        "value": bert["samples_per_sec"],
        "unit": "samples/sec",
        "vs_baseline": round(bert["mfu"] / TARGET_MFU, 4),
        "detail": detail,
    }))


if __name__ == "__main__":
    main(sys.argv[1:])
