"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric: BERT-style transformer training throughput on one chip
(the reference's BASELINE config #4 / SameDiff-BERT metric, SURVEY.md §6).
``value`` = training samples/sec at seq-len 128; ``vs_baseline`` = model
FLOPs utilization achieved divided by the 0.35 MFU target BASELINE.md
derives (the reference publishes no in-repo number — see BASELINE.md).

Variance protocol (VERDICT r3 weak #2): every metric is measured as
``REPS`` (default 3) interleaved draws — round-robin across benchmarks so
tunnel drift decorrelates from any one metric — and ``value`` is the
MEDIAN draw; per-metric ``detail`` carries {median, min, max, n}.

MFU accounting is per-matmul (VERDICT r1 weak #3): embedding gathers and
positional adds contribute zero FLOPs; attention score/value matmuls are
counted; backward = 2x forward. CNN FLOP bases are the TRUE per-conv
2*K*K*Cin*Cout*oH*oW sums from ``benchmarks/probe_cnn.py`` (r4 fix: the
previous 4.1/15.5/3.5 "GFLOP" figures were MAC counts — a 2x undercount;
resnet50 uses the same per-conv accounting below).

The ``detail`` field carries the full BASELINE.md metric set:
- ``gemm``: large square bf16 matmul, TFLOP/s and % of MXU peak
- ``resnet50``: fwd+bwd img/s/chip through the ComputationGraph train step
- ``vgg16`` / ``tiny_yolo``: same protocol over the other BASELINE CNN rows
- ``dp_scaling``: measured when >1 real device is attached, or under
  ``--virtual-mesh`` (ISSUE 15): the GSPMD fit path on the 8-virtual-
  device CPU mesh, 1->2->4->8 data shards, samples/s + scaling
  efficiency + exact compiled-HLO collective bytes per point next to
  the W107 lint's ring-allreduce estimate (host-contention caveat on
  absolute rates noted in the row)

Run: ``python bench.py`` (``--quick`` = small configs for CI;
``--skip-resnet`` / ``--skip-gemm`` / ``--skip-extra-cnn`` /
``--skip-scaling`` to bisect; ``--reps N`` to change the draw count;
``--serving`` folds the ``benchmarks/probe_serving.py`` traffic-mix
probe — throughput vs p99 + shed rates, plus the ISSUE-12 ingress
section: wire-path p50/p99 + shed rate vs in-process submit at the
same load, per-batch D2H bytes full-logits vs results-only (asserted),
and the W111 registry-roll lint check — into ``detail.serving``;
``--cold-start`` folds ``benchmarks/probe_cold_start.py`` — fresh-
process first-dispatch seconds with the persistent compile cache off
vs. populated for fit / resume / serving warmup, with the
zero-disk-miss warm pin asserted — into ``detail.cold_start``;
``--device-timing`` folds ``benchmarks/probe_device_timing.py`` — the
ISSUE-14 bridge checks: non-empty per-layer device-time MFU attribution
matching the analyzer FLOP model, fused-epilogue bit-closeness (fp32)
and loss parity (bf16) — into ``detail.device_timing``;
``--obs`` folds ``benchmarks/probe_obs_overhead.py`` — the ISSUE-16
observability-plane cost gate: tracecontext / flightrec / SLO-engine
fit columns plus the serve-path always-on column, each asserted <5%
over the all-off baseline (tracing-ON serve ratio report-only) — into
``detail.obs_overhead``;
``--lifecycle`` folds ``benchmarks/probe_lifecycle.py`` — the ISSUE-20
continuous-training loop under live traffic: per-promote roll latency
and per-candidate gate wall time from the driver's own histograms,
with the zero-dropped-request and zero-steady-state-recompile pins
asserted by the probe itself — into ``detail.lifecycle``).

BENCH_r06 (ISSUE 14): the CNN rows measure the OPTIMIZED conv path —
``precision: "bf16"`` (explicit PrecisionPolicy), NHWC compute layout,
fused bias+BN+activation epilogues — with an ``fp32_comparison``
sub-row (legacy path, kept one release), a ``loss_parity`` guard row,
and per-layer device-time attribution (``device_time.per_layer`` +
``top_offenders``) in every detail row. All fields are additive:
BENCH_r01–r05 readers keep working.
"""

import json
import os
import sys
import time

# --virtual-mesh (ISSUE 15): the dp_scaling row measures the GSPMD path
# on an 8-virtual-device CPU mesh — the device count must be forced
# BEFORE jax initializes its backend.
if "--virtual-mesh" in sys.argv:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

if "--virtual-mesh" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

# public v5e per-chip peak (BASELINE.md): 197 bf16 TFLOP/s
PEAK_TFLOPS = 197e12
TARGET_MFU = 0.35
REPS = 3


def transformer_train_flops_per_token(cfg, seq_len: int) -> float:
    """Per-matmul FLOP accounting for one training step, per token.

    Counts, per layer: QKV projection (2*E*3E), attention scores + weighted
    values (2 * 2*T*E per token), output projection (2*E*E), and the two
    FFN matmuls (2 * 2*E*F); plus the LM head (2*E*V — the tied-embedding
    head matmul is real compute, the embedding *lookup* is a gather and
    counts zero). Backward = 2x forward.
    """
    L, E, F, V = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
    proj = 2 * E * (3 * E) + 2 * E * E + 2 * (2 * E * F)
    attn = 2 * (2 * seq_len * E)
    head = 2 * E * V
    fwd = L * (proj + attn) + head
    return 3.0 * fwd


def resnet50_flops(hw=224, n_classes=1000):
    """True fwd FLOPs/img for ResNet-50 v1 as the zoo builds it (stride on
    the first 1x1 of each stage): per-conv 2*K*K*Cin*Cout*oH*oW = 7.72
    GFLOP at 224^2 — the historical "~3.9 GFLOP" figure is MACs (the
    stride-on-3x3 v1.5 variant would be 8.26)."""
    f = 0
    size = hw // 2
    f += 2 * 49 * 3 * 64 * size * size          # 7x7/2 stem
    size //= 2                                   # stem maxpool
    c_in = 64
    for blocks, mid, out, first_stride in [(3, 64, 256, 1), (4, 128, 512, 2),
                                           (6, 256, 1024, 2), (3, 512, 2048, 2)]:
        for b in range(blocks):
            stride = first_stride if b == 0 else 1
            o = size // stride
            f += 2 * 1 * c_in * mid * o * o      # 1x1 (stride on first conv)
            f += 2 * 9 * mid * mid * o * o       # 3x3
            f += 2 * 1 * mid * out * o * o       # 1x1 expand
            if b == 0:
                f += 2 * 1 * c_in * out * o * o  # projection shortcut
            c_in, size = out, o
    f += 2 * c_in * n_classes                    # fc head
    return f


def vgg16_flops(hw=224, n_classes=1000):
    """True fwd FLOPs/img for VGG16 (~30.9 GFLOP at 224^2)."""
    f, c_in, size = 0, 3, hw
    for n_convs, c_out in [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]:
        for _ in range(n_convs):
            f += 2 * 9 * c_in * c_out * size * size
            c_in = c_out
        size //= 2
    feat = c_in * size * size
    return f + 2 * feat * 4096 + 2 * 4096 * 4096 + 2 * 4096 * n_classes


def darknet_tiny_flops(hw=416, n_classes=20, n_boxes=5):
    """True fwd FLOPs/img for darknet-tiny + 1x1 YOLO head (~6.97 GFLOP
    at 416^2)."""
    plan = [16, 32, 64, 128, 256, 512, 1024, 1024]
    f, c_in, size = 0, 3, hw
    for i, c_out in enumerate(plan[:6]):
        f += 2 * 9 * c_in * c_out * size * size
        c_in = c_out
        if i < 5:
            size //= 2
    for c_out in plan[6:]:
        f += 2 * 9 * c_in * c_out * size * size
        c_in = c_out
    return f + 2 * c_in * n_boxes * (5 + n_classes) * size * size


def cost_calibration(conf, batch, measured_step_s, chip="tpu-v5e",
                     precision=None):
    """Calibrate the static cost model (analysis/cost.py) against a
    measured step: predicted roofline step time and step-peak HBM for
    this config on ``chip`` (the 197-TFLOP chip PEAK_TFLOPS normalizes
    MFU against), plus ``cost_model_ratio = measured / predicted`` — the
    number that tells you how much to trust the model's tune/-pruning
    and capacity-planning verdicts on this hardware."""
    from deeplearning4j_tpu.analysis import cost as _cost
    spec = _cost.CostSpec(chip=chip, precision=precision)
    est = _cost.step_time(conf, cost=spec, batch_size=batch)
    mem = _cost.memory_plan(conf, cost=spec, batch_size=batch)
    ratio = measured_step_s / est.step_s if est.step_s > 0 else None
    return {"chip": chip,
            "predicted_step_ms": round(est.step_s * 1e3, 3),
            "predicted_peak_hbm_mb": round(mem.peak_bytes / 2 ** 20, 1),
            "predicted_mfu": round(est.mfu, 4),
            "predicted_bound": est.bound,
            "measured_step_ms": round(measured_step_s * 1e3, 3),
            "cost_model_ratio": None if ratio is None else round(ratio, 3)}


# --------------------------------------------------------------- benchmarks
class GemmBench:
    """Large square bf16 GEMM -> TFLOP/s and fraction of MXU peak
    (BASELINE.md 'GEMM TFLOPS' row; target >=80% of peak)."""

    name = "gemm"
    primary = "tflops"

    def __init__(self, quick):
        self.n = 2048 if quick else 16384
        self.iters = 10 if quick else 30

    def setup(self):
        key = jax.random.PRNGKey(0)
        self.a = jax.random.normal(key, (self.n, self.n), jnp.bfloat16)
        self.b = jax.random.normal(key, (self.n, self.n), jnp.bfloat16)
        # One compiled program containing the whole chain: measures the MXU,
        # not per-dispatch latency through the tunneled backend. The chain
        # c = c @ b serializes the matmuls so none can be elided.
        iters = self.iters
        self.loop = jax.jit(
            lambda c, y: jax.lax.fori_loop(0, iters, lambda i, x: x @ y, c))
        self.sync = jax.jit(lambda x: x[0, 0].astype(jnp.float32))
        c = self.loop(self.a, self.b)
        float(self.sync(c))  # compile both programs

    def measure(self):
        t0 = time.perf_counter()
        c = self.loop(self.a, self.b)
        float(self.sync(c))  # true device sync
        dt = time.perf_counter() - t0
        tflops = self.iters * 2.0 * self.n ** 3 / dt
        return {"n": self.n, "tflops": round(tflops / 1e12, 2),
                "pct_peak": round(tflops / PEAK_TFLOPS, 4)}


class BertBench:
    name = "bert"
    primary = "samples_per_sec"
    #: ``--no-tune`` sets this False (see _CnnBench.tune_enabled)
    tune_enabled = True

    def __init__(self, quick):
        self.quick = quick

    def setup(self):
        from deeplearning4j_tpu.models import transformer as tfm
        from deeplearning4j_tpu.train import updaters
        if self.quick:
            cfg = tfm.TransformerConfig(vocab_size=8192, d_model=256,
                                        n_heads=4, n_layers=4, d_ff=1024,
                                        max_len=128, causal=False,
                                        dtype=jnp.bfloat16)
            self.batch, self.steps = 16, 10
        else:
            cfg = tfm.TransformerConfig.bert_base(dtype=jnp.bfloat16)  # 110M
            # r5: batch 32 -> 64 after a same-day quiet-chip sweep measured
            # 1,275 (b32) vs 1,370 (b64) vs 1,344 (b128) samples/s — the
            # headline row reports samples/s/chip at the best batch
            self.batch, self.steps = 64, 20
        self.cfg, self.seq = cfg, 128
        self.params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        updater = updaters.Adam(1e-4)
        self.opt = tfm.init_opt_state(self.params, updater)
        self.step = tfm.make_train_step(cfg, updater, mesh=None)
        rng = np.random.RandomState(0)
        self.tokens = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (self.batch, self.seq)), jnp.int32)
        self.targets = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (self.batch, self.seq)), jnp.int32)
        self.mask = jnp.ones((self.batch, self.seq), jnp.float32)
        self.n_params = sum(int(np.prod(p.shape))
                            for p in jax.tree_util.tree_leaves(self.params))
        self.t_dev = jnp.asarray(0, jnp.int32)  # device-resident counter
        # warmup / compile; float() forces a real device->host sync
        # (block_until_ready alone under-measures through the async relay)
        self._run_steps(1)
        self.tuned = self._tuned_comparison() if self.tune_enabled else None

    def _tuned_comparison(self):
        """Restricted-space tuned-vs-default for the functional
        transformer: the layout/fusion/K seams are network-class seams,
        so the BERT row tunes the one axis its path exposes — compute
        dtype (default plan = fp32, candidate = bf16) — through the same
        driver via ``trial_fn``, reporting plan signature + MFU delta."""
        import dataclasses
        from deeplearning4j_tpu import tune as _tune
        from deeplearning4j_tpu.models import transformer as tfm
        from deeplearning4j_tpu.train import updaters
        steps = max(2, self.steps // 2)

        def trial(plan):
            if plan.precision == "bf16":
                # the headline row IS the bf16 configuration — time its
                # already-compiled step (the step donates its inputs, so
                # it must run through _run_steps, which rebinds
                # self.params rather than orphaning the donated buffers)
                self._run_steps(1)              # warm
                t0 = time.perf_counter()
                self._run_steps(steps)
                return (time.perf_counter() - t0) / steps
            cfg = dataclasses.replace(self.cfg, dtype=jnp.float32)
            params = tfm.init_params(cfg, jax.random.PRNGKey(0))
            updater = updaters.Adam(1e-4)
            opt = tfm.init_opt_state(params, updater)
            step = tfm.make_train_step(cfg, updater, mesh=None)
            t_dev = jnp.asarray(0, jnp.int32)

            def run(n):
                nonlocal params, opt, t_dev
                loss = None
                for _ in range(n):
                    params, opt, t_dev, loss = step(
                        params, opt, t_dev, self.tokens, self.targets,
                        self.mask)
                return float(loss)

            run(1)                              # warm / compile
            t0 = time.perf_counter()
            run(steps)
            return (time.perf_counter() - t0) / steps

        try:
            res = _tune.tune(
                object(), None, None, budget=3,
                space=_tune.TuningSpace({"precision": (None, "bf16")}),
                model_name=self.name, parity_guard=False, persist=False,
                trial_fn=trial)
        except Exception as e:  # noqa: BLE001 — the sub-row must never
            return {"error": f"{type(e).__name__}: {e}"}   # void a run

        def mfu_of(cost_s):
            tps = self.batch * self.seq / cost_s
            return tps * transformer_train_flops_per_token(
                self.cfg, self.seq) / PEAK_TFLOPS

        tuned_mfu = mfu_of(res.best_cost_s)
        default_mfu = mfu_of(res.default_cost_s)
        return {"plan": res.best_plan.signature(),
                "samples_per_sec": round(self.batch / res.best_cost_s, 2),
                "mfu": round(tuned_mfu, 4),
                "mfu_default": round(default_mfu, 4),
                "mfu_delta": round(tuned_mfu - default_mfu, 4),
                "speedup": round(res.speedup, 3),
                "trials": len(res.trials)}

    def _run_steps(self, n):
        for _ in range(n):
            self.params, self.opt, self.t_dev, loss = self.step(
                self.params, self.opt, self.t_dev,
                self.tokens, self.targets, self.mask)
        return float(loss)

    def measure(self):
        t0 = time.perf_counter()
        final_loss = self._run_steps(self.steps)
        dt = time.perf_counter() - t0
        sps = self.steps * self.batch / dt
        tps = sps * self.seq
        mfu = tps * transformer_train_flops_per_token(self.cfg, self.seq) \
            / PEAK_TFLOPS
        out = {"samples_per_sec": round(sps, 2), "mfu": round(mfu, 4),
               "n_params": self.n_params, "batch": self.batch,
               "seq": self.seq, "steps": self.steps,
               "precision": "bf16",    # cfg dtype — bf16 since r01
               "final_loss": round(final_loss, 4)}
        if self.tuned is not None:
            out["tuned"] = self.tuned
        return out


class _CnnBench:
    """Shared fwd+bwd timing through the zoo models' compiled train step.

    BENCH_r06 flip (ISSUE 14): the measured configuration is the
    OPTIMIZED conv path — explicit ``PrecisionPolicy("bf16")`` (the
    PR-11 seam: fp32 masters/BN stats/loss, bf16 compute), NHWC compute
    layout, and fused bias+BN+activation Pallas epilogues. Rows carry a
    ``precision`` field; an ``fp32_comparison`` sub-row (the legacy
    fp32/NCHW/unfused path, fewer steps) is kept for one release; a
    ``loss_parity`` sub-row pins the bf16-optimized loss curve against
    fp32 at small geometry (the PR-11 parity guard applied to the flip).

    Each detail row also carries the per-layer DEVICE-time MFU
    attribution (``profiler.devicetime``): a ``per_layer`` table and the
    ``top_offenders`` list, so a bench run names the worst layers
    instead of one aggregate MFU number.
    """

    primary = "img_per_sec"
    n_classes = 1000
    precision = "bf16"
    parity_hw = 64
    #: ``--no-tune`` sets this False: the tuned sub-row is additive and
    #: the opt-out keeps the r05->r06 trajectory directly comparable
    tune_enabled = True
    tune_budget = 8

    def _labels(self, rng, batch: int, hw: int):
        if getattr(self, "label_grid_for", None) is not None:
            return jnp.zeros((batch,) + tuple(self.label_grid_for(hw)),
                             jnp.float32)
        return jnp.asarray(np.eye(self.n_classes, dtype=np.float32)[
            rng.randint(0, self.n_classes, batch)])

    label_grid_for = None

    def _make_data(self, batch: int, hw: int, seed: int = 0):
        from deeplearning4j_tpu.data.dataset import DataSet
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(batch, 3, hw, hw).astype(np.float32))
        return DataSet(x, self._labels(rng, batch, hw))

    def _optimize(self, net):
        """The r06 measured configuration: bf16 policy + NHWC layout +
        fused epilogues (Pallas where shapes tile)."""
        from deeplearning4j_tpu.ops import pallas_kernels as _pk
        _pk.install_platform_overrides()
        net.setPrecisionPolicy("bf16")
        net.setComputeLayout("NHWC")
        net.setEpilogueFusion(True)
        return net

    def setup(self):
        self.ds = self._make_data(self.batch, self.hw)
        # fp32 comparison FIRST so the two full-size nets (and their
        # fp32 Adam moments) never live in HBM simultaneously
        self.fp32 = self._fp32_comparison()
        self.parity = self._loss_parity()
        self.net = self._optimize(self.build())
        self.net.fit(self.ds)
        float(self.net.score())
        from deeplearning4j_tpu.profiler import devicetime as _dt
        try:
            self.attribution = _dt.attribution_detail(
                self.net, self.ds.features, model_name=self.name,
                peak_flops=PEAK_TFLOPS, reps=2)
        except Exception as e:  # noqa: BLE001 — attribution must never
            self.attribution = {"error": f"{type(e).__name__}: {e}"}  # void a run
        self.tuned = self._tuned_comparison() if self.tune_enabled else None

    def _tuned_comparison(self):
        """ISSUE 17 tuned-vs-default sub-row: run the autotuner over the
        optimization seams at the bench geometry (restricted space, small
        budget) and report the winning plan's signature + MFU delta next
        to the hand-optimized row.  The winner persists to the
        tuning-record store, so an r06 run both REPORTS tuned-vs-default
        and SEEDS ``fit(tune="auto")`` for everything downstream.  The
        search baseline is the DEFAULT plan (fp32/NCHW/unfused/K=1) — the
        delta is search-found headroom, not a diff against the hand
        tuning above.  Numerics of the applied seams are covered by the
        ``loss_parity`` sub-row; the CLI path runs the full parity gate."""
        from deeplearning4j_tpu import tune as _tune
        space = _tune.TuningSpace({
            "compute_layout": ("NCHW", "NHWC"),
            "fuse_epilogues": (False, True),
            "precision": (None, "bf16"),
            "steps_per_dispatch": (1, 4),
        })
        try:
            res = _tune.tune(
                self.build(), self.ds.features, self.ds.labels,
                budget=self.tune_budget, reps=1,
                base_steps=max(2, self.steps), space=space,
                model_name=self.name, parity_guard=False,
                peak_flops=PEAK_TFLOPS)
        except Exception as e:  # noqa: BLE001 — the sub-row must never
            return {"error": f"{type(e).__name__}: {e}"}   # void a run

        def mfu_of(cost_s):
            return (self.batch / cost_s) * 3.0 * self.fwd_flops \
                / PEAK_TFLOPS

        tuned_mfu = mfu_of(res.best_cost_s)
        default_mfu = mfu_of(res.default_cost_s)
        return {"plan": res.best_plan.signature(),
                "img_per_sec": round(self.batch / res.best_cost_s, 2),
                "mfu": round(tuned_mfu, 4),
                "mfu_default": round(default_mfu, 4),
                "mfu_delta": round(tuned_mfu - default_mfu, 4),
                "speedup": round(res.speedup, 3),
                "trials": len(res.trials),
                "persisted": res.record is not None}

    def _fp32_comparison(self):
        """Legacy fp32/NCHW/unfused row, fewer steps — kept one release
        as the bf16 flip's before/after anchor."""
        net = self.build()
        net.fit(self.ds)
        float(net.score())
        steps = max(2, self.steps // 3)
        t0 = time.perf_counter()
        for _ in range(steps):
            net.fit(self.ds)
        float(net.score())
        dt = time.perf_counter() - t0
        ips = steps * self.batch / dt
        return {"precision": "fp32", "img_per_sec": round(ips, 2),
                "mfu": round(ips * 3.0 * self.fwd_flops / PEAK_TFLOPS, 4),
                "steps": steps}

    def _loss_parity(self, steps: int = 6):
        """Same-seed loss curves, fp32-plain vs bf16-optimized, at small
        geometry — the flip's guard. ``ok`` = every step within 10%
        relative (bf16 rounding + layout reassociation headroom; the
        tight per-op pins live in the test suite)."""
        hw, batch = self.parity_hw, 8
        ds = self._make_data(batch, hw, seed=7)
        a = self.build(hw)
        b = self._optimize(self.build(hw))
        la, lb = [], []
        for _ in range(steps):
            a.fit(ds)
            la.append(float(a.score()))
            b.fit(ds)
            lb.append(float(b.score()))
        # deltas are judged against the CURVE's scale (the initial loss),
        # not the per-step value — near-converged losses are ~0 and a
        # pointwise relative delta there is noise over noise
        scale = max(abs(la[0]), 1e-6)
        deltas = [abs(p - q) / scale for p, q in zip(la, lb)]
        return {"steps": steps, "hw": hw,
                "fp32_final_loss": round(la[-1], 5),
                "bf16_final_loss": round(lb[-1], 5),
                "max_rel_delta": round(max(deltas), 5),
                "ok": max(deltas) < 0.10}

    def measure(self):
        t0 = time.perf_counter()
        for _ in range(self.steps):
            self.net.fit(self.ds)
        float(self.net.score())
        dt = time.perf_counter() - t0
        ips = self.steps * self.batch / dt
        mfu = ips * 3.0 * self.fwd_flops / PEAK_TFLOPS
        out = {"img_per_sec": round(ips, 2), "mfu": round(mfu, 4),
               "batch": self.batch, "hw": self.hw, "steps": self.steps,
               "precision": self.precision, "compute_layout": "NHWC",
               "fused_epilogues": True,
               "fp32_comparison": self.fp32, "loss_parity": self.parity,
               "device_time": self.attribution}
        if isinstance(self.attribution, dict) \
                and "top_offenders" in self.attribution:
            out["top_offenders"] = self.attribution["top_offenders"]
        if self.tuned is not None:
            out["tuned"] = self.tuned
        try:    # static-model calibration sub-row: predicted vs measured
            out["cost_calibration"] = cost_calibration(
                self.net.conf, self.batch, dt / self.steps,
                precision=self.precision)
        except Exception as e:  # noqa: BLE001 — the sub-row must never
            out["cost_calibration"] = {                      # void a run
                "error": f"{type(e).__name__}: {e}"}
        return out


class ResNet50Bench(_CnnBench):
    """BASELINE.md north-star row; img/s/chip + true-FLOP MFU."""

    name = "resnet50"

    def __init__(self, quick):
        self.batch, self.hw, self.steps = (8, 64, 3) if quick else (256, 224, 10)
        self.fwd_flops = resnet50_flops(self.hw)

    def build(self, hw=None):
        from deeplearning4j_tpu.models import zoo
        hw = hw or self.hw
        return zoo.ResNet50(num_classes=1000,
                            input_shape=(3, hw, hw)).init()


class VGG16Bench(_CnnBench):
    name = "vgg16"

    def __init__(self, quick):
        self.batch, self.hw, self.steps = (4, 64, 2) if quick else (64, 224, 15)
        self.fwd_flops = vgg16_flops(self.hw)

    def build(self, hw=None):
        from deeplearning4j_tpu.models import zoo
        hw = hw or self.hw
        return zoo.VGG16(num_classes=1000, input_shape=(3, hw, hw)).init()


class TinyYoloBench(_CnnBench):
    name = "tiny_yolo"

    def __init__(self, quick):
        self.batch, self.hw, self.steps = (4, 64, 2) if quick else (32, 416, 20)
        self.fwd_flops = darknet_tiny_flops(self.hw)
        self.n_classes = 20

    def label_grid_for(self, hw):
        # empty-object YOLO label grid: numerically safe, same FLOPs
        return (24, hw // 32, hw // 32)

    def build(self, hw=None):
        from deeplearning4j_tpu.models import zoo
        hw = hw or self.hw
        return zoo.TinyYOLO(num_classes=20, input_shape=(3, hw, hw)).init()


class DataPipelineBench:
    """End-to-end host-decode -> device train throughput (VERDICT r4 weak
    #1 / SURVEY §7 hard-part #5): JPEGs on disk through the STAGED
    multi-worker pipeline (``data/pipeline.py``) into the ResNet-50
    compiled megastep — decode fans out across every host core, workers
    fill contiguous ``[K, B, C, H, W]`` uint8 megabatch slots, and the
    host ships ONE transfer per ``steps_per_dispatch=K`` dispatch with
    the float cast fused on chip (r06 rebuild; r05 measured the
    per-batch path at 5% of synthetic device throughput).

    Workers idle between draws (measure() re-runs the epoch) so decode
    CPU time never contaminates the other interleaved benchmarks. The
    detail row carries the host-bound analysis (per-core decode cost,
    fresh-buffer H2D bandwidth per-batch AND per-megabatch) plus the
    overlap attribution the staged pipeline exports: per-stage seconds,
    consumer-stall seconds, and the data-wait-vs-dispatch overlap ratio."""

    name = "data_pipeline"
    primary = "img_per_sec"

    def __init__(self, quick):
        self.quick = quick
        if quick:
            self.n_imgs, self.side, self.hw, self.batch = 128, 96, 64, 16
        else:
            self.n_imgs, self.side, self.hw, self.batch = 1024, 256, 224, 256
        self.k = 2                      # megabatch steps per dispatch

    def _ensure_dataset(self):
        import os
        from PIL import Image
        root = f"/tmp/dl4j_tpu_jpegs_{self.side}_{self.n_imgs}"
        if os.path.isdir(root) and sum(
                len(fs) for _, _, fs in os.walk(root)) == self.n_imgs:
            return root
        rng = np.random.RandomState(42)
        per = self.n_imgs // 8
        for c in range(8):
            d = os.path.join(root, f"class{c}")
            os.makedirs(d, exist_ok=True)
            for i in range(per):
                arr = rng.randint(0, 255, (self.side, self.side, 3),
                                  dtype=np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"),
                                          quality=85)
        return root

    def setup(self):
        import os
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.image import _list_images
        from deeplearning4j_tpu.data.pipeline import (MultiWorkerImageIterator,
                                                      _decode_one)
        from deeplearning4j_tpu.models import zoo
        root = self._ensure_dataset()
        files = _list_images(root)
        t0 = time.perf_counter()
        for f in files[:64]:
            _decode_one(f, self.hw, self.hw, 3)
        self.decode_ms = (time.perf_counter() - t0) / 64 * 1e3
        self.cores = os.cpu_count() or 1
        # measured host->device bandwidth for FRESH uint8 buffers (fresh
        # each rep: re-putting one buffer measures a cache, not the
        # link) — per-batch and per-megabatch, since on tunneled backends
        # per-transfer setup cost, not decode, can bind
        rng0 = np.random.RandomState(1)
        reps = 3

        def put_rate(shape):
            bufs = [rng0.randint(0, 255, shape, dtype=np.uint8)
                    for _ in range(reps)]
            t0 = time.perf_counter()
            for buf in bufs:
                jax.device_put(buf).block_until_ready()
            dt = (time.perf_counter() - t0) / reps
            return int(np.prod(shape)) / dt / 1e6
        self.h2d_mbps = put_rate((self.batch, 3, self.hw, self.hw))
        self.h2d_mega_mbps = put_rate((self.k, self.batch, 3, self.hw,
                                       self.hw))
        self.net = zoo.ResNet50(num_classes=8,
                                input_shape=(3, self.hw, self.hw),
                                dtype="bfloat16").init()
        self.it = MultiWorkerImageIterator(
            root, self.hw, self.hw, batch_size=self.batch,
            workers=self.cores, drop_last=True,
            steps_per_dispatch=self.k)
        # compile the uint8 megastep on synthetic same-shape batches so
        # the first measured draw pays zero XLA compiles
        rng1 = np.random.RandomState(2)
        eye = np.eye(len(self.it.labels), dtype=np.float32)
        warm = [DataSet(rng1.randint(0, 255,
                                     (self.batch, 3, self.hw, self.hw),
                                     dtype=np.uint8),
                        eye[rng1.randint(0, len(self.it.labels),
                                         self.batch)])
                for _ in range(self.k)]
        self.net.fit(warm, steps_per_dispatch=self.k)
        float(self.net.score())

    @staticmethod
    def _metric_snapshot():
        from deeplearning4j_tpu import profiler as prof
        reg = prof.get_registry()
        out = {}
        h = reg.get("dl4j_pipeline_stage_seconds")
        if h is not None:
            for (stage,), child in h.children().items():
                out[f"stage:{stage}"] = child.sum
        c = reg.get("dl4j_pipeline_stall_seconds")
        if c is not None:
            for (stage,), child in c.children().items():
                out[f"stall:{stage}"] = child.value
        for name in ("dl4j_train_step_seconds",
                     "dl4j_train_data_wait_seconds"):
            m = reg.get(name)
            out[name] = m.sum if m is not None else 0.0
        m = reg.get("dl4j_pipeline_h2d_bytes_total")
        out["h2d_bytes"] = m.value if m is not None else 0.0
        return out

    def measure(self):
        from deeplearning4j_tpu import profiler as prof
        # instrumentation ON for this draw only: the staged pipeline's
        # per-stage attribution rides on it (overhead ~ noise, pinned by
        # probe_obs_overhead; the other interleaved benches run with it
        # OFF as before)
        prev = prof.get_profiling_mode()
        prof.set_profiling_mode(prof.ProfilingMode.BASIC)
        try:
            before = self._metric_snapshot()
            t0 = time.perf_counter()
            self.net.fit(self.it, epochs=1, steps_per_dispatch=self.k,
                         prefetch=2)
            float(self.net.score())      # device sync
            dt = time.perf_counter() - t0
            after = self._metric_snapshot()
        finally:
            prof.set_profiling_mode(prev)
        delta = {key: after.get(key, 0.0) - before.get(key, 0.0)
                 for key in after}
        n = (self.n_imgs // self.batch) * self.batch
        per_core = 1e3 / self.decode_ms
        img_bytes = 3 * self.hw * self.hw
        step_s = delta["dl4j_train_step_seconds"]
        wait_s = delta["dl4j_train_data_wait_seconds"]
        overlap = step_s / (step_s + wait_s) if step_s + wait_s > 0 else None
        return {"img_per_sec": round(n / dt, 2), "n_imgs": n,
                "batch": self.batch, "hw": self.hw, "src_side": self.side,
                "steps_per_dispatch": self.k,
                "decode_ms_per_img_per_core": round(self.decode_ms, 3),
                "host_cores": self.cores,
                "host_bound_img_per_sec": round(per_core * self.cores, 1),
                "h2d_mb_per_sec": round(self.h2d_mbps, 1),
                "h2d_megabatch_mb_per_sec": round(self.h2d_mega_mbps, 1),
                "h2d_bound_img_per_sec": round(
                    self.h2d_mega_mbps * 1e6 / img_bytes, 1),
                "overlap_ratio": None if overlap is None
                else round(overlap, 4),
                "h2d_mb": round(delta["h2d_bytes"] / 1e6, 1),
                "stage_seconds": {
                    key.split(":", 1)[1]: round(v, 3)
                    for key, v in sorted(delta.items())
                    if key.startswith("stage:") and v > 0},
                "stall_seconds": {
                    key.split(":", 1)[1]: round(v, 3)
                    for key, v in sorted(delta.items())
                    if key.startswith("stall:") and v > 0}}


def _run_probe(script: str, extra_args, timeout: float):
    """Run one benchmarks/ probe in a subprocess (probes own their
    device flags / shed load / fork further children, so their jax
    state must not contaminate the training benchmarks) and parse its
    one-line JSON. A hung probe / empty output / bad JSON degrades to
    an error entry — it must not abort the benches that already ran."""
    import os
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(here, "benchmarks", script)]
    cmd += list(extra_args)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, cwd=here)
        if proc.returncode != 0:
            return {"error": (proc.stderr or proc.stdout).strip()[-500:]}
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def bench_serving(quick: bool = False):
    """Serving traffic-mix probe (benchmarks/probe_serving.py)."""
    return _run_probe(
        "probe_serving.py",
        ["--n", "100", "--batch-limit", "16"] if quick else [],
        timeout=900)


def bench_imported(quick: bool = False):
    """Imported-model serving row (ISSUE 18 satellite): an in-process
    ONNX fixture (conv -> pool -> gemm) through importOnnxModel ->
    samediff_forward -> ModelServer warmup, timing each border crossing.
    The lint counts come from the same analyzer pass warmup runs — a
    nonzero error count here means the import gate would have rejected
    the model before traffic."""
    import numpy as np
    from deeplearning4j_tpu.modelimport import onnx_proto as P
    from deeplearning4j_tpu.modelimport.onnx import OnnxGraphImport
    from deeplearning4j_tpu.serving.server import (ModelServer,
                                                   samediff_forward)
    rng = np.random.RandomState(7)
    nodes = [
        P.encode_node("Conv", ["x", "cw", "cb"], ["c1"], name="conv1",
                      strides=[2, 2], pads=[1, 1, 1, 1],
                      kernel_shape=[3, 3]),
        P.encode_node("Relu", ["c1"], ["r1"], name="relu1"),
        P.encode_node("GlobalAveragePool", ["r1"], ["gap"], name="gap"),
        P.encode_node("Flatten", ["gap"], ["fl"], name="flat", axis=1),
        P.encode_node("Gemm", ["fl", "fw", "fb"], ["out"], name="fc",
                      transB=1),
    ]
    inits = [
        P.encode_tensor("cw", rng.randn(32, 3, 3, 3).astype(np.float32)),
        P.encode_tensor("cb", np.zeros(32, np.float32)),
        P.encode_tensor("fw", rng.randn(16, 32).astype(np.float32)),
        P.encode_tensor("fb", np.zeros(16, np.float32)),
    ]
    model = P.encode_model(
        nodes,
        inputs=[P.encode_value_info("x", np.float32, (None, 3, 32, 32))],
        outputs=[P.encode_value_info("out", np.float32, (None, 16))],
        initializers=inits)

    t0 = time.perf_counter()
    sd = OnnxGraphImport.importOnnxModel(model)
    import_s = time.perf_counter() - t0
    server = ModelServer(samediff_forward(sd, ["out"]), batch_limit=8)
    t0 = time.perf_counter()
    report = server.validate(shapes=[(3, 32, 32)])
    server.warmup([(3, 32, 32)])
    warmup_s = time.perf_counter() - t0
    n = 20 if quick else 100
    feats = rng.rand(4, 3, 32, 32).astype(np.float32)
    t0 = time.perf_counter()
    for _ in range(n):
        server.submit(feats).get(30.0)
    serve_s = time.perf_counter() - t0
    server.close()
    return {
        "import_seconds": round(import_s, 4),
        "warmup_seconds": round(warmup_s, 4),
        "img_per_sec": round(n * feats.shape[0] / serve_s, 2),
        "lint_errors": len(report.errors()),
        "lint_warnings": len(report.warnings()),
    }


def bench_device_timing(quick: bool = False):
    """Device-timing probe (benchmarks/probe_device_timing.py): asserts
    the devicetime bridge produces a non-empty per-layer attribution
    table matching the analyzer's FLOP model, and that the fused Pallas
    epilogue path is bit-close (fp32) / loss-parity (bf16) against the
    reference path."""
    return _run_probe("probe_device_timing.py",
                      ["--quick"] if quick else [], timeout=900)


def bench_cold_start(quick: bool = False):
    """Cold-start probe (benchmarks/probe_cold_start.py): fresh-process
    first-dispatch latency with the persistent compile cache off vs.
    populated, across fit, resume, and serving warmup. The probe itself
    asserts zero disk-miss compiles for the warm fit/serving runs."""
    return _run_probe("probe_cold_start.py",
                      ["--quick"] if quick else [], timeout=1800)


def bench_obs(quick: bool = False):
    """Observability-plane cost probe (benchmarks/probe_obs_overhead.py):
    tracecontext / flightrec / SLO-engine fit columns and the serve-path
    always-on column, each asserted <5% over the all-off baseline by the
    probe itself (a breach surfaces here as an ``error`` entry)."""
    return _run_probe(
        "probe_obs_overhead.py",
        ["--iters", "100", "--reqs", "300", "--blocks", "5"] if quick
        else [],
        timeout=900)


def bench_lifecycle(quick: bool = False):
    """Lifecycle-loop probe (benchmarks/probe_lifecycle.py): roll
    latency + gate wall time for the continuous-training driver under
    background traffic; the probe exits nonzero (surfacing here as an
    ``error`` entry) unless dropped requests and steady-state
    recompiles are both exactly zero."""
    return _run_probe("probe_lifecycle.py",
                      ["--quick"] if quick else [], timeout=900)


def bench_dp_scaling_virtual():
    """GSPMD dp_scaling on the 8-virtual-device CPU mesh (ISSUE 15
    satellite — the row is no longer an empty dict). 1->2->4->8 data
    shards of the GSPMD fit path (ShardedTrainingPlan, per-shard batch
    held constant = weak scaling), each point carrying samples/s,
    efficiency vs 1-shard, and the compiled-HLO collective byte counts
    next to the W107 lint's ring-allreduce estimate. Host contention
    caveat applies (all 8 "devices" share one CPU): the EFFICIENCY
    numbers characterize the code path and the COLLECTIVE bytes are
    exact; absolute samples/s is not an ICI measurement."""
    from deeplearning4j_tpu.analysis.distribution import (
        estimate_gradient_collectives)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.distributed import ShardedTrainingPlan
    from deeplearning4j_tpu.distributed.gspmd import (
        compiled_train_step_hlo, hlo_collective_bytes)
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel.mesh import DeviceMesh
    from deeplearning4j_tpu.train import updaters

    devices = jax.devices()
    if len(devices) < 8:
        return {"skipped": f"--virtual-mesh needs 8 virtual devices, "
                           f"got {len(devices)}"}

    def build():
        conf = (NeuralNetConfiguration.Builder().seed(11)
                .updater(updaters.Adam(1e-3)).list()
                .layer(DenseLayer(nOut=512, activation="relu"))
                .layer(DenseLayer(nOut=512, activation="relu"))
                .layer(OutputLayer(nOut=64, lossFunction="mcxent",
                                   activation="softmax"))
                .setInputType(InputType.feedForward(256))
                .build())
        return MultiLayerNetwork(conf).init()

    per_shard = 32          # weak scaling: per-shard batch constant
    steps, warm_steps = 12, 3
    rng = np.random.RandomState(0)
    points = []
    base_sps = None
    for n in (1, 2, 4, 8):
        batch = per_shard * n
        X = rng.randn(batch, 256).astype(np.float32)
        Y = np.eye(64, dtype=np.float32)[rng.randint(0, 64, batch)]
        ds = DataSet(X, Y)
        model = build()
        mesh = DeviceMesh.create(data=n, model=1, seq=1,
                                 devices=devices[:n])
        plan = ShardedTrainingPlan(mesh)
        model.setShardingPlan(plan)
        plan.apply(model)
        for _ in range(warm_steps):
            model._fit_one(ds)
        float(model.score())            # drain the async dispatches
        t0 = time.perf_counter()
        for _ in range(steps):
            model._fit_one(ds)
        float(model.score())
        dt = time.perf_counter() - t0
        sps = steps * batch / dt
        if base_sps is None:
            base_sps = sps
        coll = hlo_collective_bytes(
            compiled_train_step_hlo(model, X, Y))
        estimate = sum(estimate_gradient_collectives(
            model.conf, mesh.spec()).values())
        # ring-scale the measured side exactly like probe_collectives:
        # an HLO all-reduce of S bytes moves ~2(N-1)/N * S per device,
        # which is what the W107 estimate models — juxtaposing the RAW
        # op bytes would make the estimate read as a 1.75x overshoot
        ring = 2.0 * (n - 1) / n if n > 1 else 0.0
        measured = int(ring * sum(
            coll.get(k, 0)
            for k in ("all-reduce", "reduce-scatter", "all-gather")))
        points.append({
            "data_shards": n,
            "global_batch": batch,
            "samples_per_sec": round(sps, 2),
            "scaling_efficiency": round(sps / (n * base_sps), 4),
            "hlo_collective_bytes": coll,
            "measured_ring_bytes": measured,
            "w107_estimate_bytes": int(estimate),
        })
    return {"mode": "virtual-mesh", "n_devices": 8,
            "weak_scaling_per_shard_batch": per_shard,
            "points": points,
            "note": "8 virtual CPU devices share one host: efficiency "
                    "characterizes the GSPMD code path, collective bytes "
                    "are exact; absolute samples/s is not an ICI number"}


def bench_dp_scaling(bert_1chip_samples_per_sec, quick: bool = False,
                     virtual: bool = False):
    """DP scaling across real devices (BASELINE.md scaling row);
    ``virtual=True`` (--virtual-mesh) measures the GSPMD path on the
    8-virtual-device CPU mesh instead of skipping."""
    n = len(jax.devices())
    if n < 2 or virtual:
        if virtual:
            return bench_dp_scaling_virtual()
        return {"skipped": f"single-device host (n={n}); scaling on a "
                           f"virtual CPU mesh measures host contention, "
                           f"not ICI — run on a multi-chip slice (or pass "
                           f"--virtual-mesh for the GSPMD-path "
                           f"characterization)"}
    if quick:
        return {"skipped": "quick mode: baseline config differs"}
    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.parallel.mesh import DeviceMesh
    from deeplearning4j_tpu.train import updaters

    cfg = tfm.TransformerConfig.bert_base(dtype=jnp.bfloat16)
    mesh = DeviceMesh.create(data=n, model=1, seq=1)
    updater = updaters.Adam(1e-4)
    with mesh:
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        opt = tfm.init_opt_state(params, updater)
        step = tfm.make_train_step(cfg, updater, mesh)
        batch, seq, steps = 32 * n, 128, 20
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        mask = jnp.ones((batch, seq), jnp.float32)
        t_dev = jnp.asarray(0, jnp.int32)
        params, opt, t_dev, loss = step(params, opt, t_dev, tokens, targets, mask)
        float(loss)
        t0 = time.perf_counter()
        for i in range(steps):
            params, opt, t_dev, loss = step(params, opt, t_dev,
                                            tokens, targets, mask)
        float(loss)
        dt = time.perf_counter() - t0
    sps = steps * batch / dt
    eff = sps / (n * bert_1chip_samples_per_sec)
    return {"n_devices": n, "samples_per_sec": round(sps, 2),
            "scaling_efficiency": round(eff, 4)}


def _with_retries(fn, tag, retries=2):
    """Retry transient tunnel/relay failures (remote_compile connection
    drops, deadline blips) — one flaky HTTP read must not void a whole
    bench run. Real errors re-raise immediately."""
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — filtered below
            msg = str(e)
            transient = ("remote_compile" in msg or "read body" in msg
                         or "DEADLINE" in msg.upper()
                         or "UNAVAILABLE" in msg.upper())
            if attempt == retries or not transient:
                raise
            print(f"# transient backend error in {tag} "
                  f"(attempt {attempt + 1}/{retries + 1}): {msg[:120]} — "
                  f"retrying", file=sys.stderr)
            time.sleep(5)


def _aggregate(draws, primary):
    """Median draw by the primary field + {median,min,max,n} spread."""
    vals = [d[primary] for d in draws]
    order = np.argsort(vals)
    med = draws[int(order[len(order) // 2])]
    out = dict(med)
    out["spread"] = {"median": vals[int(order[len(order) // 2])],
                     "min": min(vals), "max": max(vals), "n": len(vals)}
    return out


def main(argv):
    quick = "--quick" in argv
    reps = REPS
    if "--reps" in argv:
        reps = int(argv[argv.index("--reps") + 1])
    detail = {"backend": jax.default_backend(),
              "n_devices": len(jax.devices())}

    benches = []
    if "--skip-gemm" not in argv:
        benches.append(GemmBench(quick))
    benches.append(BertBench(quick))
    if "--skip-resnet" not in argv:
        benches.append(ResNet50Bench(quick))
    if "--skip-extra-cnn" not in argv:
        benches.append(VGG16Bench(quick))
        benches.append(TinyYoloBench(quick))
    if "--skip-pipeline" not in argv:
        benches.append(DataPipelineBench(quick))

    if "--no-tune" in argv:       # opt out of the ISSUE-17 tuned sub-rows
        for b in benches:
            if hasattr(b, "tune_enabled"):
                b.tune_enabled = False

    draws = {b.name: [] for b in benches}
    # NOTE on residency: interleaving keeps every benchmark's static state
    # (GEMM operands ~1.6 GB, BERT/VGG16 params + fp32 Adam moments ~2.5 GB,
    # ResNet-50/TinyYOLO ~0.4 GB) in HBM simultaneously — ~4.5 GB static +
    # the largest activation set, measured to fit a 16 GB v5e. On a smaller
    # chip run subsets via the --skip-* flags.
    for b in benches:
        _with_retries(b.setup, f"{b.name}.setup")
    # interleaved draws: round-robin so slow tunnel drift decorrelates
    # from any single metric
    for _ in range(reps):
        for b in benches:
            draws[b.name].append(_with_retries(b.measure,
                                               f"{b.name}.measure"))
    for b in benches:
        detail[b.name] = _aggregate(draws[b.name], b.primary)

    bert = detail["bert"]
    if "data_pipeline" in detail and "resnet50" in detail:
        # end-to-end rate as a fraction of the synthetic-tensor device rate
        # (the r4 "prove the pipeline can feed the chip" criterion)
        detail["data_pipeline"]["pct_of_synthetic"] = round(
            detail["data_pipeline"]["img_per_sec"]
            / detail["resnet50"]["img_per_sec"], 4)
    if "--skip-scaling" not in argv:
        detail["dp_scaling"] = bench_dp_scaling(
            bert["samples_per_sec"], quick,
            virtual="--virtual-mesh" in argv)
    if "--serving" in argv:
        detail["serving"] = bench_serving(quick)
    if "--skip-imported" not in argv:
        detail["imported_onnx"] = _with_retries(
            lambda: bench_imported(quick), "imported_onnx")
    if "--cold-start" in argv:
        detail["cold_start"] = bench_cold_start(quick)
    if "--device-timing" in argv:
        detail["device_timing"] = bench_device_timing(quick)
    if "--obs" in argv:
        detail["obs_overhead"] = bench_obs(quick)
    if "--lifecycle" in argv:
        detail["lifecycle"] = bench_lifecycle(quick)

    print(json.dumps({
        "metric": "bert_base_seq128_train_samples_per_sec_per_chip",
        "value": bert["samples_per_sec"],
        "unit": "samples/sec",
        "vs_baseline": round(bert["mfu"] / TARGET_MFU, 4),
        "detail": detail,
    }))


if __name__ == "__main__":
    main(sys.argv[1:])
