"""Deterministic fault-injection harness for the resilience layer.

Large-scale training treats failure as the common case: preemptions,
poisoned batches, flaky storage, NaN updates (Abadi et al., 2016 make
periodic checkpointing + automatic recovery a founding design point;
multi-hour data-parallel accelerator jobs hit preemption as a matter of
course). A recovery path that is not exercised by a test is a recovery
path that does not work — this module makes every failure mode the
training stack claims to survive *injectable, deterministic, and
seedable*, behind the seams the real failures would hit:

- **NaN gradients at step k** — the k-th pulled batch has its features
  poisoned with NaN, so the compiled step's loss/grads go non-finite
  exactly the way a real numerics blow-up does (through the device, not
  by monkeypatching the loss).
- **Data-pipeline errors at step k** — the iterator raises on the k-th
  ``next()`` pull; marked transient (``TransientDataError``) the retry
  path must recover, marked permanent it must propagate.
- **Checkpoint write failure / corruption at step k** — the manager's
  write raises ``OSError`` once (retry-with-backoff must succeed), or
  the finalized checkpoint has bytes flipped post-write (resume-time
  checksum validation must quarantine it).
- **Synthetic preemption at step k** — a pluggable
  :class:`~deeplearning4j_tpu.train.resilience.PreemptionSignal` that
  fires once step k completes, standing in for SIGTERM.
- **Device loss at step k** — from step k on, the planned device indices
  read as DEAD to :class:`~deeplearning4j_tpu.parallel.elastic.
  DeviceMonitor` probes (persistent, not one-shot: a dead chip stays
  dead), driving the elastic mesh-shrink path end to end.
- **Hung dispatch at step k** — the dispatch for step k stalls before
  reaching the device: ``hang_seconds`` set stalls that long (a
  straggler the watchdog's soft deadline must record), ``hang_seconds=
  None`` stalls until :meth:`FaultPlan.release_hangs` (the watchdog's
  hard deadline must fire and the dispatch reads as never-completed).
- **Slow replica at step k** — a shorter stall (``slow_seconds``)
  modelling one replica lagging the collective; the straggler
  histogram, not the timeout path, must account for it.
- **Coordinator peer death at barrier generation g**
  (``coord_peer_death={"participant": p, "generation": g}``, ISSUE 15)
  — the named participant's heartbeats stop counting at a plan-aware
  :class:`~deeplearning4j_tpu.distributed.coordinator.
  SocketCoordinatorServer` from generation g on, so every waiter in
  that barrier round deterministically receives the structured
  dead-peer error instead of N independent timeouts.

Serving fault kinds (ISSUE 7 — the model server's degradation paths):

- **Replica fault at serving batch k** (``serve_fail_at``) — the k-th
  dispatched batch's forward raises once, standing in for a transient
  XLA/runtime error; the retry-on-survivors path must recover.
- **Replica loss mid-serve** (``serve_device_loss_at_batch``) — from
  batch k on, any forward touching the planned-dead devices raises AND
  the devices read as DEAD to DeviceMonitor probes, until the serving
  mesh shrinks onto the survivors (then forwards succeed again).
- **Slow / hung forward** — reuse ``slow_replica_at`` /
  ``hung_dispatch_at`` with the index meaning *serving batch*: the
  server's DispatchWatchdog consumes them through the same
  ``dispatch_hold`` seam the training loop uses.
- **Request bursts / deadline storms** — workload-side, not
  server-side: :class:`ServingLoad` generates seeded arrival schedules
  (steady / burst / deadline-storm mixes) shared by the chaos tests and
  ``benchmarks/probe_serving.py``.

Wire-level chaos (ISSUE 12 — the HTTP ingress front door):

- **Slow clients** (``slow_frac``) — a seeded fraction of
  :meth:`ServingLoad.replay_http` requests dribble their body over
  ``slow_client_seconds`` instead of one send: a stalled upload must
  hold one handler thread, never the accept loop or another client's
  request.
- **Mid-flight disconnects** (``disconnect_frac``) — a seeded fraction
  send the request then close the socket without reading the response:
  the server still serves the work, bills
  ``dl4j_ingress_disconnects_total``, and later clients are unaffected.
- **Swap under load** — :class:`SwapSchedule` triggers seeded
  ``ModelRegistry.roll()``/``rollback()`` calls at planned offsets
  while a replay is in flight: the zero-drop hot-swap pin (every
  request resolves exactly once against exactly one version).

Lifecycle fault kinds (ISSUE 20 — the continuous-training loop's
chaos pins, consumed by :class:`~deeplearning4j_tpu.lifecycle.driver.
LifecycleDriver`):

- **Trainer death mid-roll** (``trainer_death_at_roll=k``) — as the
  driver's k-th roll (1-based) is in flight (candidate staged and
  canarying, not yet promoted), the trainer is killed: a subprocess
  trainer gets a real SIGKILL, an in-process one unwinds through
  :class:`~deeplearning4j_tpu.lifecycle.driver.TrainerKilledError`.
  The registry must keep serving a consistent version and a new driver
  over the same state dir must resume from its checkpointed state
  machine.
- **Bad candidate at round k** (``bad_candidate_at={k: "nan" |
  "regressed"}``) — the k-th training round's candidate is poisoned:
  ``"nan"`` makes its outputs non-finite, ``"regressed"`` inflates its
  eval loss past the gate's parity bound. The eval gate must quarantine
  it with a structured reason; it is never loaded.
- **SLO regression during canary** (``slo_regression_during_canary=k``)
  — the k-th roll's post-promote confirmation window reads as an SLO
  regression; the driver must ``rollback()`` automatically
  (bit-identical to the pre-roll incumbent).

Race kinds (ISSUE 8 — the concurrency analyzer's dynamic layer,
``pytest -m races``):

- **Seeded deterministic interleavings** — :class:`InterleavingHarness`
  runs N thread bodies under a cooperative scheduler: exactly one
  thread executes at a time, and at every traced line/opcode boundary
  a seeded RNG decides whether to context-switch. The schedule is a
  pure function of the seed, so a racy interleaving that loses an
  increment (the ``DL4J-E202`` class) *reproduces* instead of flaking —
  the harness is how every E201/E202 repo fix pins its regression test.
- **Preemptive stress** — :func:`preemptive_stress` drops
  ``sys.setswitchinterval`` to microseconds so the real serving /
  elastic / async-checkpoint thread pools interleave maximally while a
  seeded workload hammers them (the sweep mode: no determinism, vastly
  more schedules).

Every fault fires exactly once per planned step index (so a retried
pull succeeds, like a real transient), and :meth:`FaultPlan.seeded`
derives a whole plan from one integer seed for sweep-style chaos tests
(``pytest -m chaos``).

Step indices are **1-based global update steps** — step k poisons the
k-th batch pulled, which is the k-th update applied (pull order is
apply order through the megabatch grouping and the prefetcher).
"""

from __future__ import annotations

import contextlib
import os
import random
import sys
import threading
import time
from typing import Callable, Iterable, List, Optional, Sequence, Set

import numpy as np

from deeplearning4j_tpu.data.dataset import (DataSet, DataSetIterator,
                                             MultiDataSet, TransientDataError)


def _as_step_set(steps) -> Set[int]:
    if steps is None:
        return set()
    if isinstance(steps, int):
        return {steps}
    return {int(s) for s in steps}


class FaultPlan:
    """A deterministic schedule of injected faults.

    Parameters name the failure mode and the 1-based update step(s) it
    fires at; each planned (mode, step) fires exactly once. Pass the
    plan to ``fit(..., faults=plan)`` (or a ``CheckpointManager``) and
    the resilience layer wires it behind the real seams.
    """

    def __init__(self, seed: int = 0,
                 nan_grads_at: Iterable[int] = (),
                 data_error_at: Iterable[int] = (),
                 data_error_transient: bool = True,
                 checkpoint_write_fail_at: Iterable[int] = (),
                 checkpoint_corrupt_at: Iterable[int] = (),
                 preempt_at_step: Optional[int] = None,
                 device_loss_at_step: Optional[int] = None,
                 lose_devices: Iterable[int] = (),
                 hung_dispatch_at: Iterable[int] = (),
                 hang_seconds: Optional[float] = 0.2,
                 slow_replica_at: Iterable[int] = (),
                 slow_seconds: float = 0.1,
                 serve_fail_at: Iterable[int] = (),
                 serve_device_loss_at_batch: Optional[int] = None,
                 nan_layer_params_at: Optional[dict] = None,
                 coord_peer_death: Optional[dict] = None,
                 trainer_death_at_roll: Optional[int] = None,
                 bad_candidate_at: Optional[dict] = None,
                 slo_regression_during_canary: Optional[int] = None):
        self.seed = seed
        self.nan_grads_at = _as_step_set(nan_grads_at)
        self.data_error_at = _as_step_set(data_error_at)
        self.data_error_transient = bool(data_error_transient)
        self.checkpoint_write_fail_at = _as_step_set(checkpoint_write_fail_at)
        self.checkpoint_corrupt_at = _as_step_set(checkpoint_corrupt_at)
        self.preempt_at_step = preempt_at_step
        self.device_loss_at_step = device_loss_at_step
        self.lose_devices = frozenset(int(d) for d in lose_devices)
        self.hung_dispatch_at = _as_step_set(hung_dispatch_at)
        self.hang_seconds = hang_seconds
        self.slow_replica_at = _as_step_set(slow_replica_at)
        self.slow_seconds = float(slow_seconds)
        self.serve_fail_at = _as_step_set(serve_fail_at)
        self.serve_device_loss_at_batch = serve_device_loss_at_batch
        #: {step: layer} — poison ONE layer's params with NaN just before
        #: update step ``step`` dispatches (layer = index for sequential
        #: nets, name for graphs).  The provenance-sanitizer pin: a NaN
        #: planted at layer k must be attributed to layer k, not to
        #: whatever the loss scalar looks like K layers later.
        self.nan_layer_params_at = {int(k): v for k, v in
                                    (nan_layer_params_at or {}).items()}
        #: {"participant": name, "generation": g} — coordinator-peer-death
        #: fault kind (ISSUE 15 tier 3): from barrier generation ``g`` on,
        #: the named participant's heartbeats stop counting at a
        #: plan-aware :class:`~deeplearning4j_tpu.distributed.coordinator.
        #: SocketCoordinatorServer`, so the dead-peer detector fires
        #: deterministically for every waiter in that round.
        self.coord_peer_death = dict(coord_peer_death) \
            if coord_peer_death else None
        #: lifecycle kinds (ISSUE 20): 1-based roll index at which the
        #: trainer dies mid-roll; {round: "nan"|"regressed"} candidate
        #: poisons; 1-based roll index whose post-promote confirmation
        #: reads as an SLO regression
        self.trainer_death_at_roll = trainer_death_at_roll
        self.bad_candidate_at = {int(k): str(v) for k, v in
                                 (bad_candidate_at or {}).items()}
        for k, v in self.bad_candidate_at.items():
            if v not in ("nan", "regressed"):
                raise ValueError(
                    f"bad_candidate_at[{k}]={v!r}: kind must be "
                    "'nan' or 'regressed'")
        self.slo_regression_during_canary = slo_regression_during_canary
        # consumed-state: each fault fires once
        self._nan_pending = set(self.nan_grads_at)
        self._data_pending = set(self.data_error_at)
        self._ckpt_fail_pending = set(self.checkpoint_write_fail_at)
        self._ckpt_corrupt_pending = set(self.checkpoint_corrupt_at)
        self._hang_pending = set(self.hung_dispatch_at)
        self._slow_pending = set(self.slow_replica_at)
        self._serve_fail_pending = set(self.serve_fail_at)
        self._serve_loss_active = False
        self._layer_poison_pending = set(self.nan_layer_params_at)
        self._trainer_death_pending = trainer_death_at_roll is not None
        self._bad_candidate_pending = set(self.bad_candidate_at)
        self._slo_regression_pending = \
            slo_regression_during_canary is not None
        self._hang_release = threading.Event()
        self._pull_index = 0

    @classmethod
    def seeded(cls, seed: int, horizon: int, n_nan: int = 1,
               n_data_errors: int = 1, preempt: bool = False,
               corrupt_checkpoint: bool = False, device_loss: int = 0,
               device_pool: Iterable[int] = ()) -> "FaultPlan":
        """Derive a whole plan from one seed: fault steps are drawn
        without replacement from ``[2, horizon]`` (step 1 is left clean
        so every run performs at least one good update first). The chaos
        sweep (``pytest -m chaos``) runs this across a seed range.
        ``device_loss=n`` additionally kills n devices drawn from
        ``device_pool`` at a drawn step (elastic-shrink sweeps)."""
        rng = np.random.RandomState(seed)
        n_faults = n_nan + n_data_errors + (1 if preempt else 0) \
            + (1 if device_loss else 0)
        lo = 2
        pool = rng.permutation(np.arange(lo, max(horizon + 1, lo + n_faults)))
        picks = [int(p) for p in pool[:n_faults]]
        nan_at = picks[:n_nan]
        data_at = picks[n_nan:n_nan + n_data_errors]
        pos = n_nan + n_data_errors
        loss_at, lose = None, ()
        if device_loss:
            loss_at = picks[pos]
            pos += 1
            ids = sorted(int(d) for d in device_pool)
            if device_loss >= len(ids):
                raise ValueError(
                    f"device_loss={device_loss} would kill the whole "
                    f"device_pool ({len(ids)} devices)")
            lose = [ids[int(i)] for i in
                    rng.choice(len(ids), size=device_loss, replace=False)]
        preempt_at = picks[pos] if preempt else None
        return cls(seed=seed, nan_grads_at=nan_at, data_error_at=data_at,
                   preempt_at_step=preempt_at,
                   device_loss_at_step=loss_at, lose_devices=lose,
                   checkpoint_corrupt_at=(
                       [int(rng.randint(lo, horizon + 1))]
                       if corrupt_checkpoint else ()))

    @classmethod
    def seeded_serving(cls, seed: int, horizon: int, n_fail: int = 1,
                       n_slow: int = 0, n_hang: int = 0,
                       slow_seconds: float = 0.05,
                       hang_seconds: Optional[float] = 0.2,
                       device_loss: int = 0,
                       device_pool: Iterable[int] = ()) -> "FaultPlan":
        """A serving-side plan from one seed: fault *batch indices* are
        drawn without replacement from ``[2, horizon]`` (batch 1 is left
        clean so warmup-adjacent traffic always lands once). ``n_fail``
        injects transient replica faults, ``n_slow``/``n_hang`` stall
        forwards through the watchdog's dispatch_hold seam, and
        ``device_loss=n`` kills n devices from ``device_pool`` at a
        drawn batch (the mesh-shrink path)."""
        rng = np.random.RandomState(seed)
        n_faults = n_fail + n_slow + n_hang + (1 if device_loss else 0)
        lo = 2
        pool = rng.permutation(np.arange(lo, max(horizon + 1, lo + n_faults)))
        picks = [int(p) for p in pool[:n_faults]]
        fail_at = picks[:n_fail]
        slow_at = picks[n_fail:n_fail + n_slow]
        hang_at = picks[n_fail + n_slow:n_fail + n_slow + n_hang]
        loss_at, lose = None, ()
        if device_loss:
            loss_at = picks[n_fail + n_slow + n_hang]
            ids = sorted(int(d) for d in device_pool)
            if device_loss >= len(ids):
                raise ValueError(
                    f"device_loss={device_loss} would kill the whole "
                    f"device_pool ({len(ids)} devices)")
            lose = [ids[int(i)] for i in
                    rng.choice(len(ids), size=device_loss, replace=False)]
        return cls(seed=seed, serve_fail_at=fail_at,
                   slow_replica_at=slow_at, slow_seconds=slow_seconds,
                   hung_dispatch_at=hang_at, hang_seconds=hang_seconds,
                   serve_device_loss_at_batch=loss_at, lose_devices=lose)

    @classmethod
    def seeded_lifecycle(cls, seed: int, rounds: int, n_bad: int = 1,
                         bad_kind: Optional[str] = None,
                         trainer_death: bool = False,
                         slo_regression: bool = False) -> "FaultPlan":
        """A lifecycle plan from one seed: fault *round indices* are
        drawn without replacement from ``[2, rounds]`` (round 1 is left
        clean so every storm promotes at least one good candidate
        first). ``n_bad`` poisons that many candidates (``bad_kind``
        fixes the kind; default alternates nan/regressed per draw),
        ``trainer_death`` SIGKILLs the trainer mid-roll at a drawn roll
        index, and ``slo_regression`` plants one genuine SLO regression
        in a drawn roll's confirmation window. The chaos storm
        (``pytest -m chaos``) sweeps this across seeds."""
        rng = np.random.RandomState(seed)
        n_faults = n_bad + (1 if trainer_death else 0) \
            + (1 if slo_regression else 0)
        lo = 2
        pool = rng.permutation(np.arange(lo, max(rounds + 1, lo + n_faults)))
        picks = [int(p) for p in pool[:n_faults]]
        kinds = ("nan", "regressed")
        bad = {picks[i]: (bad_kind if bad_kind is not None
                          else kinds[i % 2]) for i in range(n_bad)}
        pos = n_bad
        death = None
        if trainer_death:
            death = picks[pos]
            pos += 1
        regression = picks[pos] if slo_regression else None
        return cls(seed=seed, bad_candidate_at=bad,
                   trainer_death_at_roll=death,
                   slo_regression_during_canary=regression)

    # ----------------------------------------------------------- data seams
    def wrap_iterator(self, iterator: DataSetIterator) -> DataSetIterator:
        """Wrap a DataSetIterator so the data-side faults (NaN batches,
        iterator errors) fire at the planned pull indices."""
        return _FaultInjectionIterator(iterator, self)

    def _on_pull(self):
        """One batch pull is about to be served: returns the poisoned
        batch transform (or raises the planned iterator error). Called
        by the injection iterator only."""
        self._pull_index += 1
        k = self._pull_index
        if k in self._data_pending:
            self._data_pending.discard(k)
            # the pull index is NOT rolled back: the retry that follows
            # delivers this same batch (the base iterator never advanced)
            self._pull_index -= 1
            if self.data_error_transient:
                raise TransientDataError(
                    f"injected transient data error at step {k} "
                    f"(FaultPlan seed={self.seed})")
            raise IOError(f"injected permanent data error at step {k} "
                          f"(FaultPlan seed={self.seed})")
        if k in self._nan_pending:
            self._nan_pending.discard(k)
            return True
        return False

    # ----------------------------------------------------- parameter seams
    def poison_layer_params(self, model, step: int) -> bool:
        """Fires once per planned layer-params poison: writes NaN into
        ONE element of the planned layer's first parameter tensor
        (through the device, like a real silent corruption / overflowed
        update would land).  Called by the resilience session's
        before-step/before-dispatch hook with the FIRST step of the
        upcoming dispatch — a poison planned for a mid-megastep step
        therefore lands at the first dispatch boundary AT OR AFTER its
        planned step (under ``steps_per_dispatch=1`` that is exactly
        the planned step)."""
        due = sorted(s for s in self._layer_poison_pending if s <= step)
        if not due:
            return False
        fire_at = due[0]
        layer = self.nan_layer_params_at[fire_at]
        self._layer_poison_pending.discard(fire_at)
        import jax.numpy as jnp
        params = model._params
        entry = params[layer]                # int index (list) or name (dict)
        for pname in sorted(entry):
            arr = entry[pname]
            if hasattr(arr, "dtype") and jnp.issubdtype(arr.dtype,
                                                        jnp.floating):
                idx = (0,) * arr.ndim
                entry[pname] = arr.at[idx].set(jnp.nan)
                # an out-of-band mutation the compiled-step replay cannot
                # reproduce: the provenance sanitizer must re-snapshot
                from deeplearning4j_tpu.profiler import sanitizer
                sanitizer.invalidate(model)
                return True
        return False

    # ------------------------------------------------------ checkpoint seams
    def checkpoint_write_error(self, step: int) -> bool:
        """True exactly once for a step planned to fail its checkpoint
        write — the manager raises OSError, and the retry-with-backoff
        path gets a clean second attempt."""
        if step in self._ckpt_fail_pending:
            self._ckpt_fail_pending.discard(step)
            return True
        return False

    def corrupt_checkpoint(self, step: int, directory: str) -> bool:
        """After a checkpoint for ``step`` is finalized: flip bytes in
        its model archive if the plan says so, leaving a checkpoint
        whose manifest checksums no longer match (resume must
        quarantine it). Returns True when corruption was applied."""
        if step not in self._ckpt_corrupt_pending:
            return False
        self._ckpt_corrupt_pending.discard(step)
        target = os.path.join(directory, "model.zip")
        if not os.path.exists(target):
            return False
        size = os.path.getsize(target)
        with open(target, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(64)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        return True

    # --------------------------------------------------------- device seams
    def dead_devices(self, step: Optional[int] = None) -> Set[int]:
        """Device indices reading as DEAD at update step ``step`` —
        persistent from ``device_loss_at_step`` on (a lost chip stays
        lost). ``step=None`` asks "as of now" (inference/serving-side
        probes): the loss applies whenever a training loss is planned at
        all, or once a planned serving loss has fired."""
        if self.device_loss_at_step is None:
            if self._serve_loss_active:
                return set(self.lose_devices)
            return set()
        if step is not None and step < self.device_loss_at_step:
            return set()
        return set(self.lose_devices)

    # --------------------------------------------------- coordination seams
    def coord_peer_dead(self, participant: str,
                        generation: int) -> bool:
        """Coordinator-peer-death fault kind: True when the planned
        participant should read as dead (heartbeats ignored) at barrier
        generation ``generation``. Persistent from the planned
        generation on — a dead peer stays dead, like a lost chip."""
        plan = self.coord_peer_death
        if not plan:
            return False
        return (str(participant) == str(plan.get("participant"))
                and int(generation) >= int(plan.get("generation", 0)))

    # -------------------------------------------------------- serving seams
    def serving_forward(self, batch_index: int, device_ids) -> None:
        """Called by the model server as serving batch ``batch_index``
        (1-based) is about to forward on ``device_ids``: raises the
        planned replica fault (once) or the planned device-loss error
        (every forward that still touches a dead device — the server
        must shrink the mesh before forwards succeed again)."""
        if batch_index in self._serve_fail_pending:
            self._serve_fail_pending.discard(batch_index)
            raise RuntimeError(
                f"injected replica fault at serving batch {batch_index} "
                f"(FaultPlan seed={self.seed})")
        if self.serve_device_loss_at_batch is not None \
                and batch_index >= self.serve_device_loss_at_batch:
            self._serve_loss_active = True
            dead = set(self.lose_devices) & {int(d) for d in device_ids}
            if dead:
                raise RuntimeError(
                    f"injected device loss at serving batch {batch_index}: "
                    f"device(s) {sorted(dead)} are dead "
                    f"(FaultPlan seed={self.seed})")

    def dispatch_hold(self, step: int) -> bool:
        """Called (in the dispatch thread) as update step ``step`` is
        about to dispatch: stalls for the planned hang/straggler delay.
        Returns False when the dispatch must be SKIPPED — a hard hang
        (``hang_seconds=None``) aborted by :meth:`release_hangs`, i.e.
        a dispatch that never completed."""
        if step in self._slow_pending:
            self._slow_pending.discard(step)
            time.sleep(self.slow_seconds)
        if step in self._hang_pending:
            self._hang_pending.discard(step)
            if self.hang_seconds is None:
                self._hang_release.wait()
                return False
            time.sleep(self.hang_seconds)
        return True

    def release_hangs(self):
        """Unblock any hard-hung dispatch (``hang_seconds=None``): the
        holder returns WITHOUT dispatching, modelling a dispatch the
        watchdog abandoned that never reaches the device."""
        self._hang_release.set()

    # ------------------------------------------------------ lifecycle seams
    def trainer_dies_at_roll(self, roll_index: int) -> bool:
        """True exactly once, when the driver's ``roll_index``-th roll
        (1-based) is the planned trainer-death point — the driver kills
        its trainer (SIGKILL for a subprocess) and unwinds; a later
        driver over the same state dir must resume."""
        if self._trainer_death_pending \
                and self.trainer_death_at_roll is not None \
                and int(roll_index) >= int(self.trainer_death_at_roll):
            self._trainer_death_pending = False
            return True
        return False

    def candidate_fault(self, round_index: int) -> Optional[str]:
        """The planned candidate poison for training round
        ``round_index`` (1-based): ``"nan"`` (non-finite outputs),
        ``"regressed"`` (eval loss inflated past the gate's parity
        bound), or None. Fires once per planned round."""
        k = int(round_index)
        if k in self._bad_candidate_pending:
            self._bad_candidate_pending.discard(k)
            return self.bad_candidate_at[k]
        return None

    def canary_regression(self, roll_index: int) -> bool:
        """True exactly once, when roll ``roll_index``'s post-promote
        confirmation window is the planned SLO-regression point — the
        driver must roll back automatically."""
        if self._slo_regression_pending \
                and self.slo_regression_during_canary is not None \
                and int(roll_index) >= int(self.slo_regression_during_canary):
            self._slo_regression_pending = False
            return True
        return False

    # ------------------------------------------------------ preemption seam
    def preemption_signal(self):
        """A StepPreemption for the planned synthetic preemption, or
        None when the plan has no preemption."""
        if self.preempt_at_step is None:
            return None
        from deeplearning4j_tpu.train.resilience import StepPreemption
        return StepPreemption(self.preempt_at_step)

    def __repr__(self):
        return (f"FaultPlan(seed={self.seed}, nan={sorted(self.nan_grads_at)}, "
                f"data={sorted(self.data_error_at)}"
                f"{' transient' if self.data_error_transient else ' permanent'}, "
                f"ckpt_fail={sorted(self.checkpoint_write_fail_at)}, "
                f"ckpt_corrupt={sorted(self.checkpoint_corrupt_at)}, "
                f"preempt={self.preempt_at_step}, "
                f"device_loss={self.device_loss_at_step}:"
                f"{sorted(self.lose_devices)}, "
                f"hung={sorted(self.hung_dispatch_at)}, "
                f"slow={sorted(self.slow_replica_at)}, "
                f"serve_fail={sorted(self.serve_fail_at)}, "
                f"serve_loss={self.serve_device_loss_at_batch}, "
                f"trainer_death_at_roll={self.trainer_death_at_roll}, "
                f"bad_candidate={sorted(self.bad_candidate_at.items())}, "
                f"slo_regression={self.slo_regression_during_canary})")


def _poison(ds):
    """NaN-poisoned copy of a batch: features become NaN so the compiled
    step's loss and gradients go non-finite through the real device
    path."""
    if isinstance(ds, MultiDataSet):
        out = MultiDataSet.__new__(MultiDataSet)
        out.features = [np.full_like(np.asarray(a), np.nan)
                        for a in ds.features]
        out.labels = list(ds.labels)
        out.features_masks = ds.features_masks
        out.labels_masks = ds.labels_masks
        return out
    out = DataSet.__new__(DataSet)
    out.features = np.full_like(np.asarray(ds.features, dtype=np.float32),
                                np.nan)
    out.labels = ds.labels
    out.features_mask = ds.features_mask
    out.labels_mask = ds.labels_mask
    return out


class _FaultInjectionIterator(DataSetIterator):
    """DataSetIterator wrapper executing a FaultPlan's data-side faults:
    raises the planned iterator errors (without advancing the base, so a
    retry delivers the batch) and NaN-poisons the planned batches."""

    def __init__(self, base: DataSetIterator, plan: FaultPlan):
        self.base = base
        self.plan = plan

    def hasNext(self) -> bool:
        return self.base.hasNext()

    def next(self):
        poison = self.plan._on_pull()          # may raise the planned error
        ds = self.base.next()
        return _poison(ds) if poison else ds

    def reset(self):
        self.base.reset()

    def batch(self):
        return self.base.batch()

    def cursor(self):
        return self.base.cursor()

    def seek(self, cursor):
        self.base.seek(cursor)


# ------------------------------------------------------------ serving load
class RequestSpec:
    """One planned serving request: ``at`` seconds after replay start,
    ``rows`` feature rows, optional ``deadline`` seconds. Wire-side
    behaviors (``replay_http`` only): ``slow_s`` dribbles the body over
    that many seconds, ``disconnect`` closes the socket without reading
    the response."""

    __slots__ = ("at", "rows", "deadline", "slow_s", "disconnect")

    def __init__(self, at: float, rows: int, deadline: Optional[float],
                 slow_s: float = 0.0, disconnect: bool = False):
        self.at = float(at)
        self.rows = int(rows)
        self.deadline = deadline
        self.slow_s = float(slow_s)
        self.disconnect = bool(disconnect)

    def __repr__(self):
        extra = ""
        if self.slow_s:
            extra += f", slow_s={self.slow_s:g}"
        if self.disconnect:
            extra += ", disconnect=True"
        return (f"RequestSpec(at={self.at:.4f}, rows={self.rows}, "
                f"deadline={self.deadline}{extra})")


class ServingLoad:
    """Seeded, deterministic request-arrival schedule for the model
    server — the workload half of the serving fault kinds. The same
    generator drives the chaos sweeps (``pytest -m chaos``) and the
    ``benchmarks/probe_serving.py`` traffic mixes, so a probe regression
    reproduces as a test.

    Mixes:

    - ``steady``: exponential inter-arrival gaps at ``rps`` (a Poisson
      process), uniform row counts in ``[1, max_rows]``.
    - ``burst``: a quiet floor at ``rps`` punctuated by ``n_bursts``
      zero-gap volleys of ``burst_size`` requests — the admission-
      control stressor (a full queue must shed, not block).
    - ``deadline``: the steady process, but ``deadline_frac`` of the
      requests carry a tight ``tight_deadline`` and the rest a loose
      one — the deadline-storm stressor (expired requests must be shed
      before dispatch without rotting the batch for the rest).
    """

    MIXES = ("steady", "burst", "deadline")

    def __init__(self, specs):
        self.specs = list(specs)

    def __len__(self):
        return len(self.specs)

    def __iter__(self):
        return iter(self.specs)

    def duration(self) -> float:
        return self.specs[-1].at if self.specs else 0.0

    @classmethod
    def seeded(cls, seed: int, mix: str = "steady", n: int = 200,
               rps: float = 500.0, max_rows: int = 4,
               n_bursts: int = 4, burst_size: int = 32,
               tight_deadline: float = 0.005, loose_deadline: float = 2.0,
               deadline_frac: float = 0.5, slow_frac: float = 0.0,
               slow_client_seconds: float = 0.05,
               disconnect_frac: float = 0.0) -> "ServingLoad":
        """``slow_frac``/``disconnect_frac`` mark a seeded fraction of
        the schedule with the wire-level client behaviors
        :meth:`replay_http` executes (the in-process :meth:`replay`
        ignores them — there is no wire to misbehave on)."""
        if mix not in cls.MIXES:
            raise ValueError(f"unknown mix {mix!r} (expected one of "
                             f"{cls.MIXES})")
        rng = np.random.RandomState(seed)
        specs = []
        t = 0.0
        if mix == "burst":
            # exactly n requests, always: an oversized volley plan is
            # clamped instead of silently generating more than n (and
            # collapsing every volley into one mega-burst at t~0)
            n_bursts = max(1, min(n_bursts, n))
            burst_size = min(burst_size, max(n // n_bursts, 1))
            floor = n - n_bursts * burst_size
            burst_at = sorted(rng.uniform(0.0, max(floor, n_bursts) / rps,
                                          size=n_bursts))
            for i in range(floor):
                t += rng.exponential(1.0 / rps)
                specs.append(RequestSpec(t, 1 + rng.randint(max_rows), None))
            for b in burst_at:
                for _ in range(burst_size):
                    specs.append(RequestSpec(
                        b, 1 + rng.randint(max_rows), None))
            specs.sort(key=lambda s: s.at)
        else:
            for i in range(n):
                t += rng.exponential(1.0 / rps)
                deadline = None
                if mix == "deadline":
                    deadline = tight_deadline \
                        if rng.uniform() < deadline_frac else loose_deadline
                specs.append(RequestSpec(t, 1 + rng.randint(max_rows),
                                         deadline))
        # wire-side behaviors drawn AFTER the arrival schedule, so a
        # given (seed, mix, n) keeps the same arrivals with or without
        # client chaos enabled
        for spec in specs:
            if slow_frac and rng.uniform() < slow_frac:
                spec.slow_s = slow_client_seconds
            if disconnect_frac and rng.uniform() < disconnect_frac:
                spec.disconnect = True
        return cls(specs)

    def replay(self, submit, feature_shape, dtype=np.float32,
               time_scale: float = 1.0, rng_seed: int = 0):
        """Drive ``submit(x, deadline=...)`` honoring the arrival
        offsets (scaled by ``time_scale``). Returns the list of
        ``(spec, handle_or_exception)`` pairs — admission rejections are
        captured, not raised, so callers can assert on the outcome
        partition. Feature values are seeded for reproducibility."""
        rng = np.random.RandomState(rng_seed)
        t0 = time.monotonic()
        out = []
        for spec in self.specs:
            delay = spec.at * time_scale - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            x = rng.randn(spec.rows, *feature_shape).astype(dtype)
            try:
                out.append((spec, submit(x, deadline=spec.deadline)))
            except Exception as e:  # admission errors are outcomes here
                out.append((spec, e))
        return out

    def replay_http(self, url: str, model: str, feature_shape,
                    dtype=np.float32, time_scale: float = 1.0,
                    rng_seed: int = 0, timeout: float = 60.0):
        """Replay the schedule over REAL sockets against an
        :class:`~deeplearning4j_tpu.serving.ingress.HttpIngress`:
        ``POST {url}/v1/models/{model}:predict`` per spec, honoring
        arrival offsets, with the wire-level client chaos the specs
        carry — ``slow_s`` dribbles the JSON body in chunks, and
        ``disconnect`` closes the socket after sending without reading
        the response (the server must absorb both).

        Each request runs on its own thread (queueing belongs on the
        server, not in the generator). Returns ``[(spec, outcome)]`` in
        schedule order: ``(status_code, payload_dict)`` for answered
        requests, the string ``"disconnected"`` for planned
        disconnects, or the raised exception for transport failures.
        Feature values are seeded identically to :meth:`replay`.
        """
        import http.client
        import json
        from urllib.parse import urlparse
        parsed = urlparse(url)
        host, port = parsed.hostname, parsed.port
        rng = np.random.RandomState(rng_seed)
        bodies = []
        for spec in self.specs:
            x = rng.randn(spec.rows, *feature_shape).astype(dtype)
            bodies.append(json.dumps({"instances": x.tolist()}).encode())
        out: list = [None] * len(self.specs)

        def one(i: int, spec: RequestSpec, body: bytes):
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            try:
                conn.putrequest("POST", f"/v1/models/{model}:predict")
                conn.putheader("Content-Type", "application/json")
                conn.putheader("Content-Length", str(len(body)))
                if spec.deadline is not None:
                    conn.putheader("deadline_ms",
                                   f"{spec.deadline * 1e3:g}")
                conn.endheaders()
                if spec.slow_s > 0:
                    # dribble: 4 chunks with stalls between them — the
                    # handler blocks on ONE thread reading this body
                    step = max(len(body) // 4, 1)
                    for pos in range(0, len(body), step):
                        conn.send(body[pos:pos + step])
                        time.sleep(spec.slow_s / 4.0)
                else:
                    conn.send(body)
                if spec.disconnect:
                    out[i] = "disconnected"
                    return          # finally closes the socket unread
                resp = conn.getresponse()
                out[i] = (resp.status, json.loads(resp.read()))
            except Exception as e:
                out[i] = e
            finally:
                conn.close()

        t0 = time.monotonic()
        threads = []
        for i, spec in enumerate(self.specs):
            delay = spec.at * time_scale - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=one, args=(i, spec, bodies[i]),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout)
        return list(zip(self.specs, out))


class SwapSchedule:
    """Seeded hot-swap-under-load schedule: planned
    ``ModelRegistry.roll()``/``rollback()`` calls fired from a
    background thread while a :class:`ServingLoad` replay is in flight
    — the workload half of the zero-drop hot-swap chaos pin.

    ``swaps`` is a list of ``(at_seconds, name, version_or_None)``;
    ``version=None`` means "roll to the newest staged version" and the
    literal string ``"rollback"`` rolls back instead.
    """

    def __init__(self, swaps):
        self.swaps = sorted(swaps, key=lambda s: s[0])
        self.performed: list = []
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def seeded(cls, seed: int, name: str, duration: float,
               n_swaps: int = 2) -> "SwapSchedule":
        """``n_swaps`` swap points drawn uniformly from the middle 70%
        of ``duration`` (the edges prove nothing — traffic must be in
        flight), alternating roll -> rollback -> roll ..."""
        rng = np.random.RandomState(seed)
        at = np.sort(rng.uniform(0.15 * duration, 0.85 * duration,
                                 size=n_swaps))
        return cls([(float(t), name, None if i % 2 == 0 else "rollback")
                    for i, t in enumerate(at)])

    def start(self, registry, time_scale: float = 1.0) -> "SwapSchedule":
        """Fire the schedule against ``registry`` on a daemon thread;
        :meth:`join` collects ``performed`` — ``(at, name, action,
        result_or_exception)`` per swap."""
        def run():
            t0 = time.monotonic()
            for at, name, version in self.swaps:
                delay = at * time_scale - (time.monotonic() - t0)
                if delay > 0:
                    time.sleep(delay)
                try:
                    if version == "rollback":
                        result = registry.rollback(name)
                        action = "rollback"
                    else:
                        result = registry.roll(name, version)
                        action = "roll"
                except Exception as e:      # surfaced via performed
                    result, action = e, "error"
                self.performed.append((at, name, action, result))
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="dl4j-swap-schedule")
        self._thread.start()
        return self

    def join(self, timeout: float = 30.0) -> list:
        if self._thread is not None:
            self._thread.join(timeout)
        return self.performed


# ------------------------------------------------- deterministic interleaving
class InterleavingHarness:
    """Seeded deterministic thread-interleaving executor.

    ``run(fn_a, fn_b, ...)`` executes the callables on real threads,
    but under a cooperative scheduler: exactly ONE thread holds the
    execution token at any time, and at every traced line (or, with
    ``opcode_level=True``, every bytecode opcode — fine enough to split
    ``self.x += 1`` between its LOAD and STORE) the running thread asks
    a seeded RNG whether to hand the token to another runnable thread.
    Because every switch decision is drawn from the seed and switch
    points execute in a total order, the interleaving — and therefore
    the outcome of any data race in the bodies — is a deterministic
    function of ``(seed, switch_prob, bodies)``.

    This is what makes the E201/E202 bug class *testable*: the
    lost-increment fixture loses the same increments on every run with
    the same seed, and the locked fix can be pinned to never lose any
    across a seed sweep (``pytest -m races``).

    Escape hatch for real blocking: if the token holder blocks in C
    (e.g. on a ``threading.Lock`` another thread holds), it cannot
    reach a switch point — a waiter that observes no scheduler progress
    for ``stall_timeout`` seconds steals the token so the run cannot
    deadlock. Bodies built purely from traced Python (the bad fixtures)
    never stall, so their schedules stay exactly deterministic; bodies
    taking real locks stay correct but may interleave through the
    (timing-based) steal path.

    Only code in the submitted bodies (their module, transitively
    called functions included) is traced; scheduler internals and the
    interpreter's ``threading`` machinery are exempt so the RNG stream
    is consumed by user code only.
    """

    def __init__(self, seed: int = 0, switch_prob: float = 0.35,
                 opcode_level: bool = True, stall_timeout: float = 0.01,
                 timeout: float = 30.0):
        self.seed = int(seed)
        self.switch_prob = float(switch_prob)
        self.opcode_level = bool(opcode_level)
        self.stall_timeout = float(stall_timeout)
        self.timeout = float(timeout)
        self._rng = random.Random(self.seed)
        self._cond = threading.Condition()
        self._active: Optional[int] = None
        self._runnable: List[int] = []
        self._progress = 0
        self._started = 0
        self._total = 0
        self._abort = False
        self._results: dict = {}
        self._errors: dict = {}
        self._tls = threading.local()

    # ------------------------------------------------------------ scheduling
    def _switch_point(self, idx: int) -> None:
        if self._abort or getattr(self._tls, "in_scheduler", False):
            return
        self._tls.in_scheduler = True
        try:
            with self._cond:
                self._progress += 1
                if self._active == idx and len(self._runnable) > 1 \
                        and self._rng.random() < self.switch_prob:
                    others = [i for i in self._runnable if i != idx]
                    self._active = self._rng.choice(others)
                    self._cond.notify_all()
                self._wait_for_token(idx)
        finally:
            self._tls.in_scheduler = False

    def _wait_for_token(self, idx: int) -> None:
        """Block (cond held) until this thread owns the token; steal it
        if the current owner is blocked outside traced code. A steal
        needs THREE consecutive empty stall windows: an owner that is
        merely descheduled (startup, a loaded box) usually progresses
        within one window, while one blocked in C on a real lock never
        does — a premature steal would diverge the seeded schedule."""
        stalls = 0
        while self._active != idx:
            if self._abort:
                return      # run() gave up: free-run to completion
            seen = self._progress
            if self._cond.wait(self.stall_timeout) \
                    or self._progress != seen:
                stalls = 0
                continue
            if idx not in self._runnable:
                stalls = 0
                continue
            stalls += 1
            if stalls >= 3:
                # owner is stuck in C (a real lock): take over.
                # every caller holds _cond around this method
                self._active = idx      # dl4j: noqa=E201
                self._cond.notify_all()
                return

    def _finish(self, idx: int) -> None:
        with self._cond:
            if idx in self._runnable:
                self._runnable.remove(idx)
            if self._runnable:
                self._active = (self._rng.choice(self._runnable)
                                if self._active == idx
                                else self._active)
            else:
                self._active = None
            self._cond.notify_all()

    # --------------------------------------------------------------- tracing
    #: exact source files never traced: the harness itself plus the
    #: stdlib modules its scheduler leans on — matched by identity, not
    #: substring, so a user file named e.g. random_search.py still gets
    #: its switch points
    _TRACE_EXCLUDED = frozenset({__file__, threading.__file__,
                                 random.__file__})

    def _tracer(self, idx: int):
        excluded = self._TRACE_EXCLUDED
        opcode_level = self.opcode_level

        def trace(frame, event, arg):
            code_file = frame.f_code.co_filename
            if code_file in excluded:
                return None
            if event == "call":
                if opcode_level:
                    frame.f_trace_opcodes = True
                return trace
            if event in ("line", "opcode"):
                self._switch_point(idx)
            return trace
        return trace

    def _body(self, idx: int, fn: Callable) -> None:
        # rendezvous: no body runs a user opcode until EVERY thread has
        # started, so a slow-to-schedule initial token owner can never
        # be stolen from before it has run at all
        with self._cond:
            self._started += 1
            self._cond.notify_all()
            while self._started < self._total:
                self._cond.wait()
            self._wait_for_token(idx)
        sys.settrace(self._tracer(idx))
        try:
            result = fn()
        except BaseException as e:
            sys.settrace(None)
            with self._cond:
                self._errors[idx] = e
            self._finish(idx)
        else:
            sys.settrace(None)
            with self._cond:
                self._results[idx] = result
            self._finish(idx)

    # ------------------------------------------------------------------- run
    def run(self, *fns: Callable) -> List:
        """Execute ``fns`` to completion under the seeded schedule;
        returns their results in order (re-raising the first body
        error). A harness instance is single-use — the RNG stream is
        part of the schedule."""
        if not fns:
            return []
        with self._cond:
            self._runnable = list(range(len(fns)))
            self._active = 0
            self._started = 0
            self._total = len(fns)
        threads = [threading.Thread(target=self._body, args=(i, fn),
                                    name=f"interleave-{i}", daemon=True)
                   for i, fn in enumerate(fns)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.timeout
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.0))
        alive = [t.name for t in threads if t.is_alive()]
        if alive:
            with self._cond:        # unwedge before reporting: parked
                self._abort = True  # threads return from _wait_for_token
                self._runnable = []  # and free-run (untraced switch
                self._active = None  # points) instead of spinning
                self._cond.notify_all()
            raise TimeoutError(
                f"interleaving harness: {alive} still running after "
                f"{self.timeout}s (seed={self.seed})")
        for i in range(len(fns)):
            if i in self._errors:
                raise self._errors[i]
        return [self._results.get(i) for i in range(len(fns))]

    @classmethod
    def sweep(cls, fns_factory: Callable[[], Sequence[Callable]],
              seeds: Iterable[int], **kw) -> List:
        """Run a fresh body set under each seed; returns the per-seed
        results list — the shape the ``-m races`` sweeps assert over."""
        out = []
        for s in seeds:
            out.append(cls(seed=s, **kw).run(*fns_factory()))
        return out


@contextlib.contextmanager
def preemptive_stress(seed: int = 0, switch_interval: float = 1e-5):
    """Maximize REAL thread preemption for the duration of the block:
    drops ``sys.setswitchinterval`` to ``switch_interval`` (the GIL
    hands off between bytecodes orders of magnitude more often) and
    yields a seeded ``random.Random`` for the workload so the request
    pattern is reproducible even though the schedule is not. The sweep
    mode for racing the *real* serving / elastic / async-checkpoint
    stacks (``pytest -m races``); :class:`InterleavingHarness` is the
    deterministic single-schedule mode."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(switch_interval)
    try:
        yield random.Random(seed)
    finally:
        sys.setswitchinterval(prev)
