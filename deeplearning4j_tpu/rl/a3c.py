"""A3C + the policy abstraction (VERDICT r3 #10).

Reference parity: ``org.deeplearning4j.rl4j.learning.async.a3c
.discrete.A3CDiscreteDense`` and the policy hierarchy ``rl4j.policy.
{Policy, ACPolicy, DQNPolicy, EpsGreedy}`` (SURVEY.md §2.2 rl4j).

TPU-native shape: the reference runs N async learner threads each
computing gradients in its own copy and applying them Hogwild-style to
shared params. Here N rollout workers (threads, one MDP instance each)
act with the CURRENT shared params and push n-step rollouts to a queue;
ONE trainer applies a single compiled advantage-actor-critic step
(policy gradient + value regression + entropy bonus, Adam) per rollout.
On a single chip this preserves A3C's decorrelated-experience property
(the point of the async design) while keeping every update inside one
XLA program — applying Hogwild to donated device buffers would serialize
on the device anyway.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.profiler.locks import InstrumentedLock
from deeplearning4j_tpu.rl.dqn import _mlp_init
from deeplearning4j_tpu.rl.mdp import MDP


# ------------------------------------------------------------------ policies
class Policy:
    """ref: rl4j.policy.Policy — maps observations to actions and can
    play an episode on an MDP."""

    def nextAction(self, obs) -> int:
        raise NotImplementedError

    def play(self, mdp: MDP, max_steps: int = 1000) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done = mdp.step(self.nextAction(obs))
            total += r
            if done:
                break
        return total


class DQNPolicy(Policy):
    """ref: rl4j.policy.DQNPolicy — greedy over a Q-network."""

    def __init__(self, q_fn: Callable, params):
        self._q_fn = q_fn
        self._params = params

    def nextAction(self, obs) -> int:
        q = self._q_fn(self._params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(q[0]))


class ACPolicy(Policy):
    """ref: rl4j.policy.ACPolicy — samples from the actor's softmax (or
    argmax when deterministic)."""

    def __init__(self, pi_fn: Callable, params, deterministic: bool = False,
                 seed: int = 0):
        self._pi_fn = pi_fn
        self._params = params
        self._det = deterministic
        self._rng = np.random.RandomState(seed)

    def nextAction(self, obs) -> int:
        logits = np.asarray(
            self._pi_fn(self._params, jnp.asarray(obs, jnp.float32)[None]))[0]
        if self._det:
            return int(np.argmax(logits))
        p = np.exp(logits.astype(np.float64) - logits.max())
        p /= p.sum()   # float64: np.random.choice rejects float32 round-off
        return int(self._rng.choice(len(p), p=p))


class EpsGreedy(Policy):
    """ref: rl4j.policy.EpsGreedy — anneals exploration around any policy."""

    def __init__(self, inner: Policy, action_space_n: int,
                 eps_start: float = 1.0, eps_end: float = 0.05,
                 anneal_steps: int = 1000, seed: int = 0):
        self.inner = inner
        self.n = action_space_n
        self.eps_start, self.eps_end = eps_start, eps_end
        self.anneal = anneal_steps
        self._t = 0
        self._rng = np.random.RandomState(seed)

    def epsilon(self) -> float:
        frac = min(self._t / max(self.anneal, 1), 1.0)
        return self.eps_start + (self.eps_end - self.eps_start) * frac

    def nextAction(self, obs) -> int:
        self._t += 1
        if self._rng.rand() < self.epsilon():
            return int(self._rng.randint(self.n))
        return self.inner.nextAction(obs)


# ----------------------------------------------------------------------- A3C
class A3CConfiguration:
    """ref: A3CConfiguration (rl4j async configs)."""

    def __init__(self, seed: int = 123, gamma: float = 0.99,
                 learning_rate: float = 7e-3, n_step: int = 16,
                 num_threads: int = 2, max_steps: int = 12000,
                 entropy_beta: float = 0.01, value_coef: float = 0.25,
                 max_episode_steps: int = 500):
        self.seed = seed
        self.gamma = gamma
        self.learning_rate = learning_rate
        self.n_step = n_step
        self.num_threads = num_threads
        self.max_steps = max_steps
        self.entropy_beta = entropy_beta
        self.value_coef = value_coef
        self.max_episode_steps = max_episode_steps


class A3CDiscreteDense:
    """ref: A3CDiscreteDense — advantage actor-critic over a dense MLP
    with shared trunk and separate policy/value heads."""

    def __init__(self, mdp_factory: Callable[[int], MDP],
                 conf: A3CConfiguration = None,
                 hidden: Tuple[int, ...] = (64,)):
        self.conf = conf or A3CConfiguration()
        self.mdp_factory = mdp_factory
        probe = mdp_factory(0)
        self.obs_dim = int(np.prod(probe.getObservationSpace().shape))
        self.n_actions = probe.getActionSpace().n
        probe.close()
        rng = np.random.RandomState(self.conf.seed)
        trunk_sizes = [self.obs_dim, *hidden]
        self._n_trunk = len(trunk_sizes) - 1
        self.params: Dict = _mlp_init(rng, trunk_sizes)
        H = trunk_sizes[-1]
        lim = float(np.sqrt(6.0 / (H + self.n_actions)))
        self.params["Wpi"] = jnp.asarray(
            rng.uniform(-lim, lim, (H, self.n_actions)).astype(np.float32))
        self.params["bpi"] = jnp.zeros((self.n_actions,), jnp.float32)
        limv = float(np.sqrt(6.0 / (H + 1)))
        self.params["Wv"] = jnp.asarray(
            rng.uniform(-limv, limv, (H, 1)).astype(np.float32))
        self.params["bv"] = jnp.zeros((1,), jnp.float32)
        self.opt_state = jax.tree_util.tree_map(
            lambda p: (jnp.zeros_like(p), jnp.zeros_like(p)), self.params)
        self._t = jnp.asarray(0, jnp.int32)
        self._step_fn = self._make_step()
        self._pi_fn = jax.jit(self._logits)
        self.episode_rewards: List[float] = []
        self._lock = InstrumentedLock("rl:a3c")

    # ---------------------------------------------------------- networks
    def _trunk(self, params, x):
        for i in range(self._n_trunk):
            x = jax.nn.relu(x @ params[f"W{i}"] + params[f"b{i}"])
        return x

    def _logits(self, params, x):
        h = self._trunk(params, x)
        return h @ params["Wpi"] + params["bpi"]

    def _value(self, params, x):
        h = self._trunk(params, x)
        return (h @ params["Wv"] + params["bv"])[..., 0]

    # ------------------------------------------------------------- update
    def _make_step(self):
        beta = self.conf.entropy_beta
        vc = self.conf.value_coef
        lr = self.conf.learning_rate
        b1, b2, eps = 0.9, 0.999, 1e-8

        def loss_fn(params, obs, actions, returns, mask):
            """Rollouts arrive PADDED to n_step with a validity mask —
            one static shape, one compiled program (a per-length retrace
            costs more than the whole rollout on small nets)."""
            n = jnp.maximum(jnp.sum(mask), 1.0)
            logits = self._logits(params, obs)
            logp = jax.nn.log_softmax(logits)
            v = self._value(params, obs)
            adv = (returns - v) * mask
            # per-rollout advantage normalization: keeps the policy
            # gradient scale independent of the (growing) return scale
            a = jax.lax.stop_gradient(adv)
            mean = jnp.sum(a) / n
            std = jnp.sqrt(jnp.sum(jnp.square((a - mean) * mask)) / n)
            a = (a - mean) * mask / (std + 1e-6)
            pg = -jnp.sum(jnp.take_along_axis(
                logp, actions[:, None], axis=1)[:, 0] * a) / n
            v_loss = jnp.sum(jnp.square(adv)) / n
            entropy = -jnp.sum(
                jnp.sum(jnp.exp(logp) * logp, axis=1) * mask) / n
            return pg + vc * v_loss - beta * entropy

        @jax.jit
        def step(params, opt_state, t, obs, actions, returns, mask):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions,
                                                      returns, mask)
            tf = t.astype(jnp.float32) + 1.0

            def adam(p, g, st):
                m, v = st
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * jnp.square(g)
                a = lr * jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
                return p - a * m / (jnp.sqrt(v) + eps), (m, v)

            flat = jax.tree_util.tree_map(adam, params, grads, opt_state,
                                          is_leaf=lambda x: isinstance(
                                              x, jax.Array))
            new_p = jax.tree_util.tree_map(
                lambda pair: pair[0], flat,
                is_leaf=lambda x: isinstance(x, tuple)
                and len(x) == 2 and isinstance(x[0], jax.Array))
            new_s = jax.tree_util.tree_map(
                lambda pair: pair[1], flat,
                is_leaf=lambda x: isinstance(x, tuple)
                and len(x) == 2 and isinstance(x[0], jax.Array))
            return new_p, new_s, t + 1, loss
        return step

    # ------------------------------------------------------------ training
    def _worker(self, wid: int, rollouts: "queue.Queue",
                stop: threading.Event):
        try:
            self._worker_body(wid, rollouts, stop)
        except BaseException as e:   # surface worker crashes to train()
            with self._lock:
                if self._worker_error is None:
                    self._worker_error = e
            stop.set()

    def _worker_body(self, wid: int, rollouts: "queue.Queue",
                     stop: threading.Event):
        mdp = self.mdp_factory(self.conf.seed + 100 + wid)
        rng = np.random.RandomState(self.conf.seed + 200 + wid)
        gamma = self.conf.gamma
        obs = mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        while not stop.is_set():
            with self._lock:
                params = self.params
            traj_o, traj_a, traj_r = [], [], []
            done = False
            for _ in range(self.conf.n_step):
                logits = np.asarray(self._pi_fn(
                    params, jnp.asarray(obs, jnp.float32)[None]))[0]
                p = np.exp(logits.astype(np.float64) - logits.max())
                p /= p.sum()
                a = int(rng.choice(self.n_actions, p=p))
                nxt, r, done = mdp.step(a)
                traj_o.append(np.asarray(obs, np.float32))
                traj_a.append(a)
                traj_r.append(r)
                ep_reward += r
                ep_steps += 1
                obs = nxt
                if done or ep_steps >= self.conf.max_episode_steps:
                    break
            # n-step discounted returns bootstrapped from V(s_T)
            if done or ep_steps >= self.conf.max_episode_steps:
                boot = 0.0
                with self._lock:    # every worker appends here
                    self.episode_rewards.append(ep_reward)
                obs = mdp.reset()
                ep_reward, ep_steps = 0.0, 0
            else:
                with self._lock:
                    params = self.params
                boot = float(self._value_jit(
                    params, jnp.asarray(obs, jnp.float32)[None])[0])
            rets = np.zeros(len(traj_r), np.float32)
            acc = boot
            for i in reversed(range(len(traj_r))):
                acc = traj_r[i] + gamma * acc
                rets[i] = acc
            T = len(traj_r)
            n = self.conf.n_step
            obs_p = np.zeros((n, self.obs_dim), np.float32)
            obs_p[:T] = np.stack(traj_o)
            act_p = np.zeros((n,), np.int32)
            act_p[:T] = traj_a
            ret_p = np.zeros((n,), np.float32)
            ret_p[:T] = rets
            mask = np.zeros((n,), np.float32)
            mask[:T] = 1.0
            rollouts.put((obs_p, act_p, ret_p, mask))
        mdp.close()

    def train(self) -> "A3CDiscreteDense":
        """Run workers + trainer until max_steps env steps are consumed."""
        with self._lock:            # BEFORE workers start: a crash during
            self._value_jit = jax.jit(self._value)  # startup must not be
            self._worker_error = None               # erased
        rollouts: "queue.Queue" = queue.Queue(maxsize=64)
        stop = threading.Event()
        workers = [threading.Thread(target=self._worker,
                                    args=(i, rollouts, stop), daemon=True)
                   for i in range(self.conf.num_threads)]
        for w in workers:
            w.start()
        consumed = 0
        while consumed < self.conf.max_steps:
            try:
                obs, actions, rets, mask = rollouts.get(timeout=60.0)
            except queue.Empty:
                if self._worker_error is not None:
                    raise RuntimeError("A3C worker died") \
                        from self._worker_error
                raise
            consumed += int(mask.sum())
            new_p, new_s, self._t, _ = self._step_fn(
                self.params, self.opt_state, self._t,
                jnp.asarray(obs), jnp.asarray(actions), jnp.asarray(rets),
                jnp.asarray(mask))
            with self._lock:
                self.params, self.opt_state = new_p, new_s
        stop.set()
        # drain so workers blocked on put() can observe stop and exit
        try:
            while True:
                rollouts.get_nowait()
        except queue.Empty:
            pass
        for w in workers:
            w.join(timeout=5.0)
        return self

    # -------------------------------------------------------------- policy
    def getPolicy(self, deterministic: bool = True) -> ACPolicy:
        """ref: A3CDiscreteDense.getPolicy -> ACPolicy."""
        return ACPolicy(self._pi_fn, self.params,
                        deterministic=deterministic, seed=self.conf.seed)

    def evaluate(self, episodes: int = 10, max_steps: int = 500) -> float:
        mdp = self.mdp_factory(self.conf.seed + 999)
        pol = self.getPolicy(deterministic=True)
        total = [pol.play(mdp, max_steps=max_steps) for _ in range(episodes)]
        mdp.close()
        return float(np.mean(total))
