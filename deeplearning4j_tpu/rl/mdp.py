"""MDP interface + built-in environments.

Reference parity: ``org.deeplearning4j.rl4j.mdp.MDP`` (+ the gym adapter
and toy MDPs the reference ships — SURVEY.md §2.2 "Aux RL4J"). The
environment runs on the HOST (tiny scalar dynamics); only the Q-network
math runs on the device.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class ObservationSpace:
    def __init__(self, shape):
        self.shape = tuple(shape)


class DiscreteActionSpace:
    def __init__(self, n: int):
        self.n = int(n)

    def randomAction(self, rng: np.random.RandomState) -> int:
        return int(rng.randint(self.n))


class MDP:
    """ref: org.deeplearning4j.rl4j.mdp.MDP."""

    def getObservationSpace(self) -> ObservationSpace:
        raise NotImplementedError

    def getActionSpace(self) -> DiscreteActionSpace:
        raise NotImplementedError

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        """-> (observation, reward, done)."""
        raise NotImplementedError

    def isDone(self) -> bool:
        raise NotImplementedError

    def close(self):
        pass


class CartPole(MDP):
    """Classic cart-pole balancing (ref: rl4j's gym CartPole-v0 usage;
    dynamics are the standard Barto-Sutton-Anderson equations)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LENGTH = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 200

    def __init__(self, seed: int = 0):
        self._rng = np.random.RandomState(seed)
        self._state = None
        self._steps = 0
        self._done = True

    def getObservationSpace(self):
        return ObservationSpace((4,))

    def getActionSpace(self):
        return DiscreteActionSpace(2)

    def reset(self):
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._steps = 0
        self._done = False
        return self._state.astype(np.float32).copy()

    def isDone(self):
        return self._done

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pm_l = self.POLE_MASS * self.POLE_HALF_LENGTH
        cos, sin = np.cos(theta), np.sin(theta)
        temp = (force + pm_l * theta_dot ** 2 * sin) / total_mass
        theta_acc = (self.GRAVITY * sin - cos * temp) / (
            self.POLE_HALF_LENGTH * (4.0 / 3.0
                                     - self.POLE_MASS * cos ** 2 / total_mass))
        x_acc = temp - pm_l * theta_acc * cos / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.asarray([x, x_dot, theta, theta_dot])
        self._steps += 1
        self._done = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT
                          or self._steps >= self.MAX_STEPS)
        return self._state.astype(np.float32).copy(), 1.0, self._done
