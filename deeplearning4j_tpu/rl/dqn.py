"""Deep Q-learning (ref: ``org.deeplearning4j.rl4j.learning.sync.qlearning.
discrete.QLearningDiscreteDense`` + ``QLearningConfiguration`` +
``ExpReplay`` — SURVEY.md §2.2 "Aux RL4J").

TPU-native shape: the replay buffer and environment stepping live on the
host; the TD update (online + target network, Bellman backup, Adam) is
ONE compiled XLA step over a sampled minibatch. Double-DQN action
selection; target network sync by period, like the reference's
``targetDqnUpdateFreq``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.rl.mdp import MDP


@dataclass
class QLearningConfiguration:
    """ref: QLearning.QLConfiguration."""
    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 15000
    exp_repeat: int = 1
    batch_size: int = 64
    target_dqn_update_freq: int = 200
    update_start: int = 500
    reward_factor: float = 1.0
    gamma: float = 0.99
    error_clamp: float = 1.0
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3000
    exp_replay_size: int = 10000
    learning_rate: float = 1e-3
    double_dqn: bool = True


class ExpReplay:
    """Uniform ring-buffer replay (ref: org.deeplearning4j.rl4j.util
    ExpReplay)."""

    def __init__(self, capacity: int, obs_dim: int, seed: int):
        self.capacity = capacity
        self._rng = np.random.RandomState(seed)
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self._n = 0
        self._pos = 0

    def store(self, s, a, r, s2, done):
        i = self._pos
        self.obs[i] = s
        self.actions[i] = a
        self.rewards[i] = r
        self.next_obs[i] = s2
        self.dones[i] = float(done)
        self._pos = (i + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)

    def __len__(self):
        return self._n

    def getBatch(self, size: int):
        idx = self._rng.randint(0, self._n, size)
        return (self.obs[idx], self.actions[idx], self.rewards[idx],
                self.next_obs[idx], self.dones[idx])


def _mlp_init(rng: np.random.RandomState, sizes: List[int]) -> Dict:
    params = {}
    for i in range(len(sizes) - 1):
        lim = np.sqrt(6.0 / (sizes[i] + sizes[i + 1]))
        params[f"W{i}"] = jnp.asarray(
            rng.uniform(-lim, lim, (sizes[i], sizes[i + 1])).astype(np.float32))
        params[f"b{i}"] = jnp.zeros(sizes[i + 1], jnp.float32)
    return params


def _mlp_apply(params: Dict, x, n_layers: int):
    for i in range(n_layers):
        x = x @ params[f"W{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x


class QLearningDiscreteDense:
    """ref: QLearningDiscreteDense — DQN over a dense MLP Q-network."""

    def __init__(self, mdp: MDP, conf: QLearningConfiguration = None,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.mdp = mdp
        self.conf = conf or QLearningConfiguration()
        self.obs_dim = int(np.prod(mdp.getObservationSpace().shape))
        self.n_actions = mdp.getActionSpace().n
        rng = np.random.RandomState(self.conf.seed)
        sizes = [self.obs_dim, *hidden, self.n_actions]
        self._n_layers = len(sizes) - 1
        self.params = _mlp_init(rng, sizes)
        self.target_params = jax.tree_util.tree_map(lambda a: a, self.params)
        self.opt_state = jax.tree_util.tree_map(
            lambda p: (jnp.zeros_like(p), jnp.zeros_like(p)), self.params)
        self.replay = ExpReplay(self.conf.exp_replay_size, self.obs_dim,
                                self.conf.seed + 1)
        self._rng = np.random.RandomState(self.conf.seed + 2)
        self._step_fn = self._make_td_step()
        self._q_fn = jax.jit(lambda p, x: _mlp_apply(p, x, self._n_layers))
        self.episode_rewards: List[float] = []

    # ------------------------------------------------------------- td step
    def _make_td_step(self):
        gamma = self.conf.gamma
        clamp = self.conf.error_clamp
        lr = self.conf.learning_rate
        nl = self._n_layers
        double = self.conf.double_dqn
        b1, b2, eps = 0.9, 0.999, 1e-8

        @jax.jit
        def step(params, target_params, opt_state, t, s, a, r, s2, done):
            q_next_t = _mlp_apply(target_params, s2, nl)
            if double:
                a_star = jnp.argmax(_mlp_apply(params, s2, nl), axis=1)
                q_next = jnp.take_along_axis(q_next_t, a_star[:, None],
                                             1)[:, 0]
            else:
                q_next = jnp.max(q_next_t, axis=1)
            y = r + gamma * (1.0 - done) * q_next

            def loss_fn(p):
                q = _mlp_apply(p, s, nl)
                q_sa = jnp.take_along_axis(q, a[:, None], 1)[:, 0]
                err = jnp.clip(q_sa - y, -clamp, clamp)   # ref: errorClamp
                return jnp.mean(err * (q_sa - y))

            loss, grads = jax.value_and_grad(loss_fn)(params)

            def adam(p, g, st):
                m, v = st
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * g * g
                mh = m / (1 - b1 ** t)
                vh = v / (1 - b2 ** t)
                return p - lr * mh / (jnp.sqrt(vh) + eps), (m, v)

            new_p, new_s = {}, {}
            for k in params:
                new_p[k], new_s[k] = adam(params[k], grads[k], opt_state[k])
            return new_p, new_s, loss

        return step

    # ------------------------------------------------------------ epsilon
    def _epsilon(self, step: int) -> float:
        c = self.conf
        frac = min(1.0, step / max(c.epsilon_nb_step, 1))
        return 1.0 + frac * (c.min_epsilon - 1.0)

    def _act(self, obs, step: int) -> int:
        if self._rng.rand() < self._epsilon(step):
            return self.mdp.getActionSpace().randomAction(self._rng)
        q = np.asarray(self._q_fn(self.params,
                                  jnp.asarray(np.ravel(obs)[None])))
        return int(q[0].argmax())

    # ------------------------------------------------------------- training
    def train(self) -> "QLearningDiscreteDense":
        c = self.conf
        total = 0
        updates = 0
        while total < c.max_step:
            obs = self.mdp.reset()
            ep_reward = 0.0
            for _ in range(c.max_epoch_step):
                a = self._act(obs, total)
                nxt, r, done = self.mdp.step(a)
                self.replay.store(np.ravel(obs), a, r * c.reward_factor,
                                  np.ravel(nxt), done)
                obs = nxt
                ep_reward += r
                total += 1
                if total >= c.update_start and len(self.replay) >= c.batch_size:
                    s, aa, rr, s2, dd = self.replay.getBatch(c.batch_size)
                    updates += 1
                    self.params, self.opt_state, _ = self._step_fn(
                        self.params, self.target_params, self.opt_state,
                        jnp.asarray(updates, jnp.float32), jnp.asarray(s),
                        jnp.asarray(aa), jnp.asarray(rr), jnp.asarray(s2),
                        jnp.asarray(dd))
                    if updates % c.target_dqn_update_freq == 0:
                        self.target_params = jax.tree_util.tree_map(
                            lambda a_: a_, self.params)
                if done or total >= c.max_step:
                    break
            self.episode_rewards.append(ep_reward)
        return self

    # ------------------------------------------------------------- policy
    def getPolicy(self):
        """Greedy policy over the trained Q-network (ref: DQNPolicy)."""
        def policy(obs) -> int:
            q = np.asarray(self._q_fn(self.params,
                                      jnp.asarray(np.ravel(obs)[None])))
            return int(q[0].argmax())
        return policy

    def evaluate(self, episodes: int = 10,
                 max_steps: Optional[int] = None) -> float:
        """Average greedy-policy return; episodes are CAPPED (an MDP with
        no internal terminal guarantee must not hang the evaluator)."""
        cap = max_steps if max_steps is not None \
            else 10 * self.conf.max_epoch_step
        policy = self.getPolicy()
        totals = []
        for _ in range(episodes):
            obs = self.mdp.reset()
            tot = 0.0
            for _ in range(cap):
                obs, r, done = self.mdp.step(policy(obs))
                tot += r
                if done:
                    break
            totals.append(tot)
        return float(np.mean(totals))
