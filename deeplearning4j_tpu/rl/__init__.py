"""RL4J equivalent (ref: the reference's rl4j module — SURVEY.md §2.2
"Aux RL4J"): MDP interface, built-in CartPole, DQN (QLearningDiscreteDense)
with experience replay, double-DQN targets, and a compiled TD step."""

from deeplearning4j_tpu.rl.mdp import (CartPole, DiscreteActionSpace, MDP,
                                       ObservationSpace)
from deeplearning4j_tpu.rl.dqn import (ExpReplay, QLearningConfiguration,
                                       QLearningDiscreteDense)

__all__ = ["MDP", "CartPole", "ObservationSpace", "DiscreteActionSpace",
           "QLearningDiscreteDense", "QLearningConfiguration", "ExpReplay"]
