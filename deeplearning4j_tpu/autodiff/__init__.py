"""Graph/autodiff engine — the SameDiff equivalent, whole-program XLA
compiled (ref: org.nd4j.autodiff.samediff; SURVEY.md §2.2, §3.3)."""

from deeplearning4j_tpu.autodiff.samediff import (  # noqa: F401
    SameDiff,
    SDVariable,
    TrainingConfig,
    History,
)
