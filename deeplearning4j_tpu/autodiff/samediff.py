"""SameDiff-equivalent graph/autodiff engine — whole-program XLA compiled.

Reference parity: ``org.nd4j.autodiff.samediff.SameDiff`` + ``SDVariable``
+ the op namespaces ``SDMath/SDNN/SDCNN/SDRNN/SDLoss/SDRandom/SDLinalg/
SDBitwise`` and the execution sessions
``internal.{AbstractSession,InferenceSession,TrainingSession}``
(SURVEY.md §2.2, call stack §3.3).

TPU-native architecture (the single biggest divergence from the reference,
deliberately — SURVEY.md §1): the reference *interprets* the graph op-by-op
in Java, crossing JNI per op. Here the recorded graph is *traced into ONE
jax program* and compiled by XLA per (outputs, placeholder-shapes)
signature — so a whole training step (forward + backward + updater) is a
single fused executable, and gradients come from program transformation
(``jax.grad``) instead of per-op ``doDiff`` chain rule bookkeeping.

Graph model:
- ``variable``  — trainable array (ref: SDVariable VARIABLE type)
- ``constant``  — non-trainable array (ref: CONSTANT)
- ``placeholder`` — fed at execution (ref: PLACEHOLDER)
- op nodes — name-addressed, created through the op namespaces; creation
  order IS topological order (the builder API can't reference a var
  before it exists, same invariant the reference exploits).

Control flow: ``sd.while_loop`` / ``sd.cond`` lower to ``lax.while_loop``
/ ``lax.cond`` instead of interpreting TF-style Enter/Exit/Merge/Switch
frames (SURVEY.md §3.3) — compiler-friendly by construction.
"""

from __future__ import annotations

import base64
import json
import os
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import registry as op_registry
from deeplearning4j_tpu.train import updaters as upd
from deeplearning4j_tpu.train.updaters import IUpdater


class _Node:
    __slots__ = ("op", "fn", "inputs", "outputs", "attrs", "rebuild")

    def __init__(self, op: str, fn: Callable, inputs: List[str],
                 outputs: List[str], attrs: Dict[str, Any],
                 rebuild: str = None):
        self.op = op
        self.fn = fn
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs
        # Key into _FN_REBUILDERS: nodes whose callable is a closure (not a
        # plain registry op) serialize by recording this key + attrs, and
        # load() rebuilds the closure — same pattern as _make_rng_fn.
        self.rebuild = rebuild


class SDVariable:
    """Symbolic handle into a SameDiff graph (ref: SDVariable)."""

    def __init__(self, sd: "SameDiff", name: str, var_type: str,
                 shape: Optional[Tuple] = None, dtype=None):
        self.sd = sd
        self.name = name
        self.var_type = var_type  # VARIABLE | CONSTANT | PLACEHOLDER | ARRAY
        self._shape = shape
        self.dtype = dtype

    # value access (eager fetch after eval)
    def eval(self, placeholders: Dict[str, Any] = None):
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def getArr(self):
        if self.var_type == "VARIABLE":
            return self.sd._variables[self.name]
        if self.var_type == "CONSTANT":
            return self.sd._constants[self.name]
        return self.eval()

    def setArray(self, arr):
        if self.var_type == "VARIABLE":
            self.sd._variables[self.name] = jnp.asarray(arr)
        elif self.var_type == "CONSTANT":
            self.sd._constants[self.name] = jnp.asarray(arr)
        else:
            raise ValueError(f"cannot set array on {self.var_type} '{self.name}'")

    @property
    def shape(self):
        return self._shape

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        self.name = new_name
        return self

    # ---- fluent op builders (each records a node) ----
    def _bin(self, other, op, reverse=False):
        o = self.sd._as_var(other)
        a, b = (o, self) if reverse else (self, o)
        return self.sd._record(op, [a.name, b.name])

    def add(self, o): return self._bin(o, "add")
    def sub(self, o): return self._bin(o, "subtract")
    def mul(self, o): return self._bin(o, "multiply")
    def div(self, o): return self._bin(o, "divide")
    def rsub(self, o): return self._bin(o, "subtract", reverse=True)
    def rdiv(self, o): return self._bin(o, "divide", reverse=True)
    def pow(self, o): return self._bin(o, "pow")
    __add__ = add
    __radd__ = add
    __sub__ = sub
    def __rsub__(self, o): return self.rsub(o)
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    def __rtruediv__(self, o): return self.rdiv(o)
    __pow__ = pow
    def __neg__(self): return self.sd._record("neg", [self.name])
    def __matmul__(self, o): return self.mmul(o)

    def mmul(self, other, transpose_a=False, transpose_b=False):
        return self.sd._record("matmul", [self.name, self.sd._as_var(other).name],
                               attrs={"transpose_a": transpose_a, "transpose_b": transpose_b})

    def gt(self, o): return self._bin(o, "greater")
    def lt(self, o): return self._bin(o, "less")
    def gte(self, o): return self._bin(o, "greater_equal")
    def lte(self, o): return self._bin(o, "less_equal")
    def eq(self, o): return self._bin(o, "equals")
    def neq(self, o): return self._bin(o, "not_equals")

    def _un(self, op, **attrs):
        return self.sd._record(op, [self.name], attrs=attrs)

    def neg(self): return self._un("neg")
    def abs(self): return self._un("abs")
    def exp(self): return self._un("exp")
    def log(self): return self._un("log")
    def sqrt(self): return self._un("sqrt")
    def square(self): return self._un("square")
    def tanh(self): return self._un("tanh")
    def sigmoid(self): return self._un("sigmoid")
    def relu(self): return self._un("relu")
    def softmax(self, axis=-1): return self._un("softmax", axis=axis)

    def sum(self, *axes, keepdims=False):
        return self._un("reduce_sum", axis=list(axes) or None, keepdims=keepdims)
    def mean(self, *axes, keepdims=False):
        return self._un("reduce_mean", axis=list(axes) or None, keepdims=keepdims)
    def max(self, *axes, keepdims=False):
        return self._un("reduce_max", axis=list(axes) or None, keepdims=keepdims)
    def min(self, *axes, keepdims=False):
        return self._un("reduce_min", axis=list(axes) or None, keepdims=keepdims)
    def std(self, *axes): return self.sd.math.std(self, *axes)
    def argmax(self, axis=None): return self._un("argmax", axis=axis)
    def norm2(self, *axes): return self._un("reduce_norm2", axis=list(axes) or None)

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self._un("reshape", shape=shape)

    def transpose(self, *perm):
        return self._un("transpose", perm=list(perm) or None)

    def castTo(self, dtype):
        return self._un("cast", dtype=np.dtype(dtype).name)

    def get(self, idx):
        # serializable when the index is basic (ints/slices/ellipsis/newaxis/
        # 1-D int lists); advanced indices (nd arrays, bool masks, traced
        # arrays) keep exact numpy semantics via a closure and are simply not
        # serializable (save() reports it)
        try:
            attrs = {"index": _encode_index(idx)}
        except TypeError:
            return self.sd._record_fn("getitem", lambda x: x[idx], [self.name])
        return self.sd._record_fn("getitem", _make_getitem_fn(attrs),
                                  [self.name], attrs=attrs, rebuild="getitem")

    __getitem__ = get

    def __repr__(self):
        return f"SDVariable(name='{self.name}', type={self.var_type}, shape={self._shape})"


class _Namespace:
    """Base for op namespaces: methods record registry ops."""

    def __init__(self, sd: "SameDiff"):
        self.sd = sd

    def _rec(self, op, inputs, name=None, n_out=1, **attrs):
        names = [v.name if isinstance(v, SDVariable) else self.sd._as_var(v).name
                 for v in inputs]
        return self.sd._record(op, names, name=name, n_out=n_out, attrs=attrs)


class SDMath(_Namespace):
    """ref: org.nd4j.autodiff.samediff.ops.SDMath."""

    def __getattr__(self, op):
        # generic passthrough for elementwise/pairwise/reduce registry ops
        if op_registry.has(op):
            def method(*inputs, name=None, **attrs):
                return self._rec(op, list(inputs), name=name, **attrs)
            return method
        raise AttributeError(op)

    def std(self, x, *axes, name=None):
        return self.sd._record_fn(
            "std", _make_std_fn({}), [x.name], name=name,
            attrs={"axis": tuple(axes) or None}, rebuild="std")

    def variance(self, x, *axes, name=None):
        return self.sd._record_fn(
            "variance", _make_variance_fn({}), [x.name], name=name,
            attrs={"axis": tuple(axes) or None}, rebuild="variance")


class SDNN(_Namespace):
    """ref: ops.SDNN."""

    def linear(self, x, w, b, name=None):
        return self._rec("xw_plus_b", [x, w, b], name=name)

    def reluLayer(self, x, w, b, name=None):
        return self._rec("relu_layer", [x, w, b], name=name)

    def softmax(self, x, axis=-1, name=None):
        return self._rec("softmax", [x], name=name, axis=axis)

    def logSoftmax(self, x, name=None):
        return self._rec("log_softmax", [x], name=name)

    def relu(self, x, name=None): return self._rec("relu", [x], name=name)
    def gelu(self, x, name=None): return self._rec("gelu", [x], name=name)
    def sigmoid(self, x, name=None): return self._rec("sigmoid", [x], name=name)
    def tanh(self, x, name=None): return self._rec("tanh", [x], name=name)
    def swish(self, x, name=None): return self._rec("swish", [x], name=name)

    def biasAdd(self, x, b, name=None): return self._rec("bias_add", [x, b], name=name)

    def layerNorm(self, x, gain, bias=None, axis=-1, name=None):
        ins = [x, gain] + ([bias] if bias is not None else [])
        return self._rec("layer_norm", ins, name=name, axis=axis)

    def batchNorm(self, x, mean, var, gamma, beta, eps=1e-5, axis=1, name=None):
        return self._rec("batchnorm_sd", [x, mean, var, gamma, beta],
                         name=name, eps=eps, axis=axis)

    def dropout(self, x, rate, name=None):
        """Dropout with the graph's per-step RNG stream (active only when
        the execution requests training mode)."""
        sd = self.sd
        return sd._record_rng("dropout", [sd._as_var(x).name], name=name,
                              params={"rate": rate})

    def multiHeadDotProductAttention(self, q, kv, wq, wk, wv, wo,
                                     num_heads, mask=None, name=None):
        ins = [q, kv, wq, wk, wv, wo] + ([mask] if mask is not None else [])
        attrs = {"num_heads": num_heads, "has_mask": mask is not None}
        return self.sd._record_fn("multi_head_dot_product_attention",
                                  _make_mha_fn(attrs),
                                  [self.sd._as_var(v).name for v in ins],
                                  name=name, attrs=attrs,
                                  rebuild="multi_head_dot_product_attention")


class SDCNN(_Namespace):
    """ref: ops.SDCNN."""

    def conv2d(self, x, w, b=None, name=None, **attrs):
        ins = [x, w] + ([b] if b is not None else [])
        return self._rec("conv2d", ins, name=name, **attrs)

    def conv1d(self, x, w, b=None, name=None, **attrs):
        ins = [x, w] + ([b] if b is not None else [])
        return self._rec("conv1d", ins, name=name, **attrs)

    def deconv2d(self, x, w, b=None, name=None, **attrs):
        ins = [x, w] + ([b] if b is not None else [])
        return self._rec("deconv2d", ins, name=name, **attrs)

    def depthWiseConv2d(self, x, w, b=None, name=None, **attrs):
        ins = [x, w] + ([b] if b is not None else [])
        return self._rec("depthwise_conv2d", ins, name=name, **attrs)

    def separableConv2d(self, x, wd, wp, b=None, name=None, **attrs):
        ins = [x, wd, wp] + ([b] if b is not None else [])
        return self._rec("sconv2d", ins, name=name, **attrs)

    def maxPooling2d(self, x, name=None, **attrs):
        return self._rec("maxpool2d", [x], name=name, **attrs)

    def avgPooling2d(self, x, name=None, **attrs):
        return self._rec("avgpool2d", [x], name=name, **attrs)

    def upsampling2d(self, x, scale=2, name=None):
        return self._rec("upsampling2d", [x], name=name, scale=scale)

    def im2Col(self, x, name=None, **attrs):
        return self._rec("im2col", [x], name=name, **attrs)

    def spaceToDepth(self, x, block, name=None):
        return self._rec("space_to_depth", [x], name=name, block_size=block)

    def depthToSpace(self, x, block, name=None):
        return self._rec("depth_to_space", [x], name=name, block_size=block)


class SDRNN(_Namespace):
    """ref: ops.SDRNN."""

    def lstmLayer(self, x_tnc, w_ih, w_hh, b, name=None):
        return self._rec("lstmLayer_out", [x_tnc, w_ih, w_hh, b], name=name)

    def gru(self, x_tnc, w_ih, w_hh, b_ih, b_hh, name=None):
        return self._rec("gru_out", [x_tnc, w_ih, w_hh, b_ih, b_hh], name=name)


class SDLoss(_Namespace):
    """ref: ops.SDLoss."""

    def mse(self, labels, preds, name=None):
        return self._rec("mean_sqerr_loss", [labels, preds], name=name)

    def meanSquaredError(self, labels, preds, name=None):
        return self._rec("mean_sqerr_loss", [labels, preds], name=name)

    def softmaxCrossEntropy(self, labels, logits, name=None):
        return self._rec("softmax_cross_entropy_loss", [labels, logits], name=name)

    def sigmoidCrossEntropy(self, labels, logits, name=None):
        return self._rec("sigmoid_cross_entropy_loss", [labels, logits], name=name)

    def sparseSoftmaxCrossEntropy(self, labels, logits, name=None):
        return self._rec("sparse_softmax_cross_entropy_loss", [labels, logits], name=name)

    def absoluteDifference(self, labels, preds, name=None):
        return self._rec("absolute_difference_loss", [labels, preds], name=name)

    def cosineDistance(self, labels, preds, name=None):
        return self._rec("cosine_distance_loss", [labels, preds], name=name)

    def hingeLoss(self, labels, preds, name=None):
        return self._rec("hinge_loss", [labels, preds], name=name)

    def huberLoss(self, labels, preds, delta=1.0, name=None):
        return self._rec("huber_loss", [labels, preds], name=name, delta=delta)

    def logLoss(self, labels, preds, name=None):
        return self._rec("log_loss", [labels, preds], name=name)

    def l2Loss(self, x, name=None):
        return self._rec("l2_loss", [x], name=name)


class SDRandom(_Namespace):
    """ref: ops.SDRandom — draws use the graph's per-execution RNG stream."""

    def _rng_op(self, opname, shape, name=None, **attrs):
        return self.sd._record_rng(opname, [], name=name,
                                   params={"shape": tuple(shape), **attrs})

    def uniform(self, low, high, shape, name=None):
        return self._rng_op("random_uniform", shape, name=name, minval=low, maxval=high)

    def normal(self, mean, stddev, shape, name=None):
        return self._rng_op("random_normal", shape, name=name, mean=mean, stddev=stddev)

    def bernoulli(self, p, shape, name=None):
        return self._rng_op("random_bernoulli", shape, name=name, p=p)


class SDLinalg(_Namespace):
    """ref: ops.SDLinalg."""

    def mmul(self, a, b, name=None):
        return self._rec("matmul", [a, b], name=name)

    def cholesky(self, a, name=None): return self._rec("cholesky", [a], name=name)
    def qr(self, a, name=None): return self._rec("qr", [a], name=name, n_out=2)
    def svd(self, a, name=None): return self._rec("svd", [a], name=name, n_out=3)
    def inverse(self, a, name=None): return self._rec("matrix_inverse", [a], name=name)
    def det(self, a, name=None): return self._rec("matrix_determinant", [a], name=name)
    def solve(self, a, b, name=None): return self._rec("solve", [a, b], name=name)


class SDBitwise(_Namespace):
    """ref: ops.SDBitwise."""

    def and_(self, a, b, name=None): return self._rec("bitwise_and", [a, b], name=name)
    def or_(self, a, b, name=None): return self._rec("bitwise_or", [a, b], name=name)
    def xor(self, a, b, name=None): return self._rec("bitwise_xor", [a, b], name=name)
    def leftShift(self, a, b, name=None): return self._rec("left_shift", [a, b], name=name)
    def rightShift(self, a, b, name=None): return self._rec("right_shift", [a, b], name=name)


class SDImage(_Namespace):
    """ref: ops.SDImage."""

    def resizeBiLinear(self, x, h, w, name=None):
        return self._rec("resize_bilinear", [x], name=name, size=(h, w))

    def resizeNearestNeighbor(self, x, h, w, name=None):
        return self._rec("resize_nearest_neighbor", [x], name=name, size=(h, w))

    def nonMaxSuppression(self, boxes, scores, max_out, iou_threshold=0.5, name=None):
        return self._rec("non_max_suppression", [boxes, scores], name=name,
                         max_out=max_out, iou_threshold=iou_threshold)


class TrainingConfig:
    """ref: org.nd4j.autodiff.samediff.TrainingConfig (builder)."""

    def __init__(self, updater: IUpdater = None, l1: float = 0.0, l2: float = 0.0,
                 data_set_feature_mapping: Sequence[str] = ("features",),
                 data_set_label_mapping: Sequence[str] = ("labels",),
                 clip_value: float = 0.0, clip_norm: float = 0.0,
                 clip_global_norm: float = 0.0):
        self.updater = updater or upd.Adam()
        self.l1 = l1
        self.l2 = l2
        self.data_set_feature_mapping = list(data_set_feature_mapping)
        self.data_set_label_mapping = list(data_set_label_mapping)
        self.clip_value = clip_value
        self.clip_norm = clip_norm
        self.clip_global_norm = clip_global_norm

    def to_config(self):
        d = dict(self.__dict__)
        d["updater"] = self.updater.to_config()
        return d

    @staticmethod
    def from_config(d):
        d = dict(d)
        d["updater"] = IUpdater.from_config(d["updater"])
        tc = TrainingConfig.__new__(TrainingConfig)
        tc.__dict__.update(d)
        return tc


class History:
    """ref: org.nd4j.autodiff.listeners.records.History."""

    def __init__(self):
        self.loss_curve: List[float] = []

    def lossCurve(self):
        return self.loss_curve


class SameDiff:
    """The graph builder + executor (ref: SameDiff, one huge class there;
    execution here delegates to XLA instead of InferenceSession)."""

    def __init__(self):
        self._variables: Dict[str, jax.Array] = {}     # trainable
        self._constants: Dict[str, jax.Array] = {}
        self._placeholders: Dict[str, Tuple] = {}      # name -> (shape, dtype)
        self._vars: Dict[str, SDVariable] = {}
        self._nodes: List[_Node] = []
        self._producers: Dict[str, _Node] = {}
        self._loss_variables: List[str] = []
        self._name_counter: Dict[str, int] = {}
        self._fn_cache: Dict[Any, Callable] = {}
        self._grad_cache: Dict[Any, Callable] = {}
        self.training_config: Optional[TrainingConfig] = None
        self._train_step_cache = None
        self._updater_state: Optional[Dict] = None
        self._step = 0
        self._listeners: List[Any] = []
        # op namespaces
        self.math = SDMath(self)
        self.nn = SDNN(self)
        self.cnn = SDCNN(self)
        self.rnn = SDRNN(self)
        self.loss = SDLoss(self)
        self.random = SDRandom(self)
        self.linalg = SDLinalg(self)
        self.bitwise = SDBitwise(self)
        self.image = SDImage(self)

    # ------------------------------------------------------------- creation
    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def _unique(self, base: str) -> str:
        if base not in self._vars and base not in self._placeholders:
            return base
        n = self._name_counter.get(base, 0)
        while True:
            n += 1
            cand = f"{base}_{n}"
            if cand not in self._vars and cand not in self._placeholders:
                self._name_counter[base] = n
                return cand

    def placeHolder(self, name: str, shape=None, dtype=jnp.float32) -> SDVariable:
        v = SDVariable(self, name, "PLACEHOLDER", tuple(shape) if shape else None, dtype)
        self._placeholders[name] = (shape, dtype)
        self._vars[name] = v
        return v

    placeholder = placeHolder

    def var(self, name: str, value=None, shape=None, init: str = "xavier",
            rng_key=None, dtype=jnp.float32) -> SDVariable:
        """Trainable variable; either an explicit value or (shape, init)."""
        if value is None:
            value = _initialize(shape, init, rng_key, dtype)
        arr = jnp.asarray(value)
        v = SDVariable(self, name, "VARIABLE", tuple(arr.shape), arr.dtype)
        self._variables[name] = arr
        self._vars[name] = v
        return v

    variable = var

    def constant(self, value, name: str = None) -> SDVariable:
        name = self._unique(name or "const")
        arr = jnp.asarray(value)
        v = SDVariable(self, name, "CONSTANT", tuple(arr.shape), arr.dtype)
        self._constants[name] = arr
        self._vars[name] = v
        return v

    def _as_var(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            return x
        return self.constant(x)

    # ------------------------------------------------------------- recording
    def _record(self, op: str, input_names: List[str], name: str = None,
                n_out: int = 1, attrs: Dict = None):
        fn = op_registry.get(op)
        return self._record_fn(op, fn, input_names, name=name, n_out=n_out,
                               attrs=attrs, registry_op=True)

    def _record_fn(self, op: str, fn: Callable, input_names: List[str],
                   name: str = None, n_out: int = 1, attrs: Dict = None,
                   registry_op: bool = False, rebuild: str = None):
        attrs = attrs or {}
        base = name or op
        out_names = [self._unique(base if n_out == 1 else f"{base}:{i}")
                     for i in range(n_out)]
        node = _Node(op, fn, list(input_names), out_names, attrs, rebuild=rebuild)
        self._nodes.append(node)
        self._invalidate()
        outs = []
        for on in out_names:
            v = SDVariable(self, on, "ARRAY")
            self._vars[on] = v
            self._producers[on] = node
            outs.append(v)
        return outs[0] if n_out == 1 else tuple(outs)

    def _record_rng(self, op: str, input_names: List[str],
                    name: str = None, params: Dict = None):
        """Record an op that consumes the per-execution RNG key and the
        train flag. The callable is rebuilt from (op, params) — both at
        record time and at load(), so RNG nodes serialize faithfully."""
        params = params or {}
        node_fn = _make_rng_fn(op, params)
        attrs = {"__rng__": True, **params}
        return self._record_fn(op, node_fn, input_names, name=name, attrs=attrs)

    # -------------------------------------------------------- shape report
    def infer_shapes(self, batch_size: int = 1) -> Dict[str, tuple]:
        """Static shape of every graph variable WITHOUT executing anything
        (ref: each DeclarableOp's shape fn feeding SameDiff.summary()).

        Abstract interpretation via jax.eval_shape per node — zero FLOPs,
        no device, no compilation. Placeholder ``None`` dims use
        ``batch_size``; those entries are reported with the substitution
        applied.
        """
        env: Dict[str, jax.ShapeDtypeStruct] = {}
        for k, v in self._variables.items():
            env[k] = jax.ShapeDtypeStruct(v.shape, v.dtype)
        for k, v in self._constants.items():
            a = jnp.asarray(v)
            env[k] = jax.ShapeDtypeStruct(a.shape, a.dtype)
        for k, (shape, dtype) in self._placeholders.items():
            if shape is None:
                # declared rank-free: shapes of everything downstream are
                # unknown (reported as None, like the reference's -1 dims)
                env[k] = None
                continue
            shape = tuple(batch_size if d in (None, -1) else int(d)
                          for d in shape)
            env[k] = jax.ShapeDtypeStruct(shape, dtype)
        key_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)
        shapes = {k: (tuple(s.shape) if s is not None else None)
                  for k, s in env.items()}
        for node in self._nodes:
            args = [env.get(n) for n in node.inputs]
            if any(a is None for a in args):
                for name in node.outputs:
                    env[name] = None
                    shapes[name] = None
                continue
            if node.attrs.get("__rng__"):
                out = jax.eval_shape(
                    lambda *a: node.fn(*a[:-1], a[-1], False),
                    *args, key_spec)
            else:
                out = jax.eval_shape(lambda *a: node.fn(*a, **node.attrs),
                                     *args)
            outs = (out,) if len(node.outputs) == 1 else tuple(out)
            for name, o in zip(node.outputs, outs):
                leaf = jax.tree_util.tree_leaves(o)[0]
                env[name] = jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
                shapes[name] = tuple(leaf.shape)
        return shapes

    def validate(self, batch_size: int = 1, **kw):
        """Static lint of the recorded op graph — shape propagation over
        the ``_Node`` list plus structural checks (E151 undefined input,
        E152 shape conflict, E153 bad loss variable, W151 dangling
        placeholder, W152 unused variable, W153 training config with no
        loss). Pure-static like ``model.validate()``: no trace, no
        compile, no device. Extra keywords pass through to
        ``analysis.analyze`` (``suppress=``, ``severity_overrides=``)."""
        from deeplearning4j_tpu.analysis import analyze
        return analyze(self, batch_size=batch_size, **kw)

    def summary(self, batch_size: int = 1) -> str:
        """Printable graph summary with per-variable shapes — computed by
        the shape functions / abstract interp, not by running the graph
        (ref: SameDiff.summary())."""
        shapes = self.infer_shapes(batch_size)
        lines = [f"SameDiff: {len(self._variables)} variables, "
                 f"{len(self._placeholders)} placeholders, "
                 f"{len(self._nodes)} ops",
                 f"{'name':<28} {'kind':<12} {'op':<28} shape",
                 "-" * 80]
        for k in self._placeholders:
            lines.append(f"{k:<28} {'PLACEHOLDER':<12} {'':<28} "
                         f"{shapes.get(k)}")
        for k in self._variables:
            lines.append(f"{k:<28} {'VARIABLE':<12} {'':<28} {shapes.get(k)}")
        for k in self._constants:
            if k in self._producers:
                continue  # folded node outputs appear as ops below
            lines.append(f"{k:<28} {'CONSTANT':<12} {'':<28} {shapes.get(k)}")
        for node in self._nodes:
            for o in node.outputs:
                lines.append(f"{o:<28} {'ARRAY':<12} {node.op:<28} "
                             f"{shapes.get(o)}")
        return "\n".join(lines)

    def _rename(self, old: str, new: str):
        for d in (self._variables, self._constants, self._placeholders, self._vars):
            if old in d:
                d[new] = d.pop(old)
        for node in self._nodes:
            node.inputs = [new if i == old else i for i in node.inputs]
            node.outputs = [new if o == old else o for o in node.outputs]
        if old in self._producers:
            self._producers[new] = self._producers.pop(old)
        self._loss_variables = [new if n == old else n for n in self._loss_variables]
        self._invalidate()

    def _invalidate(self):
        self._fn_cache.clear()
        self._grad_cache.clear()
        self._train_step_cache = None

    # ------------------------------------------------------------- execution
    def _needed_nodes(self, output_names: Sequence[str]) -> List[_Node]:
        needed = set()
        stack = list(output_names)
        seen = set()
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            node = self._producers.get(n)
            if node is not None:
                needed.add(id(node))
                stack.extend(node.inputs)
        return [nd for nd in self._nodes if id(nd) in needed]

    def _build_fn(self, output_names: Tuple[str, ...]) -> Callable:
        """Pure function (variables, constants, placeholders, rng_key, train)
        -> {name: array}; trace-compiled by jax."""
        nodes = self._needed_nodes(output_names)

        def fn(variables, constants, placeholders, rng_key, train):
            env = {}
            env.update(variables)
            env.update(constants)
            env.update(placeholders)
            key = rng_key
            for i, node in enumerate(nodes):
                args = [env[n] for n in node.inputs]
                if node.attrs.get("__rng__"):
                    key, sub = jax.random.split(key)
                    res = node.fn(*args, sub, train)
                else:
                    res = node.fn(*args, **node.attrs)
                if len(node.outputs) == 1:
                    env[node.outputs[0]] = res
                else:
                    for o, r in zip(node.outputs, res):
                        env[o] = r
            return {o: env[o] for o in output_names}
        return fn

    def _exec(self, placeholders: Dict[str, Any], output_names: Sequence[str],
              train: bool = False, rng_key=None):
        phs = {k: jnp.asarray(v) for k, v in placeholders.items()}
        key = tuple(output_names), tuple(sorted((k, v.shape, str(v.dtype))
                                                for k, v in phs.items())), train
        if rng_key is None:
            rng_key = jax.random.PRNGKey(self._step)
        if getattr(self, "_exec_backend", "jax") == "native":
            return self._exec_native(key, phs, output_names, train, rng_key)
        if key not in self._fn_cache:
            fn = self._build_fn(tuple(output_names))
            self._fn_cache[key] = jax.jit(fn, static_argnames=("train",))
        return self._fn_cache[key](self._variables, self._constants, phs,
                                   rng_key, train=train)

    # ------------------------------------------------------ native backend
    def setExecBackend(self, backend: str):
        """Execution backend for output()/eval: "jax" (default) or
        "native" — the latter lowers the SAME traced program to StableHLO
        and runs it through the C++ L0 runtime (native/pjrt_runtime.cc),
        the reference's NativeOpExecutioner seam (SURVEY.md §2.1 row 1 /
        §7 item 1). jax stays the tracer; the native client owns
        compilation + buffers + execution."""
        if backend not in ("jax", "native"):
            raise ValueError(f"unknown backend '{backend}'")
        self._exec_backend = backend
        return self

    def _exec_native(self, key, phs, output_names, train, rng_key):
        from deeplearning4j_tpu.native import runtime as native_rt
        cache = getattr(self, "_native_cache", None)
        if cache is None:
            cache = self._native_cache = {}
        args = (self._variables, self._constants, phs, rng_key)
        if key not in cache:
            from deeplearning4j_tpu.utils.environment import Environment
            fn = self._build_fn(tuple(output_names))
            prec = ("float32"
                    if Environment.get().matmul_precision == "float32"
                    else "bfloat16")
            # keep_unused: the XLA parameter list must match the flattened
            # pytree order exactly, even for inputs the program ignores;
            # default_matmul_precision: the env knob must govern the native
            # executable too (the jax path may run on a different backend)
            with jax.default_matmul_precision(prec):
                lowered = jax.jit(fn, static_argnames=("train",),
                                  keep_unused=True).lower(*args, train=train)
            exe = native_rt.get_runtime().compile(lowered.as_text())
            cache[key] = exe
        flat = [np.asarray(x) for x in jax.tree_util.tree_leaves(args)]
        outs = cache[key](*flat)
        treedef = jax.tree_util.tree_structure({n: 0 for n in output_names})
        return jax.tree_util.tree_unflatten(treedef, outs)

    def output(self, placeholders: Dict[str, Any], outputs: Sequence[str],
               train: bool = False) -> Dict[str, jax.Array]:
        """ref: SameDiff.output / batchOutput — ONE compiled program."""
        outputs = [o.name if isinstance(o, SDVariable) else o for o in outputs]
        return self._exec(placeholders or {}, outputs, train=train)

    def batchOutput(self):
        sd = self
        class _B:
            def __init__(self):
                self._phs = {}
                self._outs = []
            def input(self, name, arr):
                self._phs[name] = arr
                return self
            def output(self, *names):
                self._outs.extend(names)
                return self
            def execSingle(self):
                return sd.output(self._phs, self._outs)[self._outs[0]]
            def exec(self):
                return sd.output(self._phs, self._outs)
        return _B()

    # ------------------------------------------------------------- gradients
    def setLossVariables(self, *names):
        self._loss_variables = [n.name if isinstance(n, SDVariable) else n
                                for n in names]
        self._grad_cache.clear()
        self._train_step_cache = None

    def convertToVariables(self, *names):
        """Promote constants to trainable variables (ref:
        SameDiff.convertToVariables) — THE unfreeze step for fine-tuning
        an imported frozen graph: imported weights land as constants;
        promote them, attach a loss, and fit()."""
        for n in names:
            n = n.name if isinstance(n, SDVariable) else n
            if n in self._variables:
                continue
            if n not in self._constants:
                raise ValueError(f"'{n}' is not a constant")
            self._variables[n] = self._constants.pop(n)
            self._vars[n].var_type = "VARIABLE"
        self._updater_state = None       # shape of the state tree changed
        self._invalidate()
        return self

    def convertToConstants(self, *names):
        """Freeze variables into constants (ref: SameDiff.convertToConstants
        — transfer-learning freeze; frozen leaves get no updater state and
        no gradient computation)."""
        for n in names:
            n = n.name if isinstance(n, SDVariable) else n
            if n in self._constants:
                continue
            if n not in self._variables:
                raise ValueError(f"'{n}' is not a variable")
            self._constants[n] = self._variables.pop(n)
            self._vars[n].var_type = "CONSTANT"
        self._updater_state = None
        self._invalidate()
        return self

    def _total_loss_fn(self):
        loss_names = tuple(self._loss_variables)
        if not loss_names:
            raise ValueError("call setLossVariables first")
        base = self._build_fn(loss_names)

        def total(variables, constants, placeholders, rng_key, train):
            outs = base(variables, constants, placeholders, rng_key, train)
            return sum(jnp.sum(outs[n]) for n in loss_names)
        return total

    def calculateGradients(self, placeholders: Dict[str, Any],
                           wrt: Sequence[str] = None) -> Dict[str, jax.Array]:
        """ref: SameDiff.calculateGradients — here ONE reverse-mode program
        (jax.grad) instead of createGradFunction's doDiff graph walk.
        ``wrt`` may name variables AND placeholders (input gradients), like
        the reference."""
        wrt = list(wrt) if wrt else list(self._variables)
        phs = {k: jnp.asarray(v) for k, v in (placeholders or {}).items()}
        unknown = [k for k in wrt if k not in self._variables and k not in phs]
        if unknown:
            raise ValueError(f"calculateGradients: {unknown} are neither "
                             f"variables nor provided placeholders")
        key = ("grad", tuple(self._loss_variables), tuple(wrt),
               tuple(sorted((k, v.shape, str(v.dtype)) for k, v in phs.items())))
        if key not in self._grad_cache:
            total = self._total_loss_fn()
            gfn = jax.jit(jax.grad(total, argnums=(0, 2)),
                          static_argnames=("train",))
            self._grad_cache[key] = gfn
        var_g, ph_g = self._grad_cache[key](self._variables, self._constants, phs,
                                            jax.random.PRNGKey(self._step),
                                            train=False)
        merged = {**ph_g, **var_g}
        return {k: merged[k] for k in wrt}

    # ------------------------------------------------------------- training
    def setTrainingConfig(self, cfg: TrainingConfig):
        self.training_config = cfg
        self._train_step_cache = None

    def setListeners(self, *listeners):
        self._listeners = list(listeners)

    def _make_train_step(self):
        cfg = self.training_config
        updater = cfg.updater
        total = self._total_loss_fn()

        def step(variables, constants, opt_state, t_dev, placeholders):
            # t_dev: DONATED int32 device counter; rng derived on device from
            # it (no per-step host uploads — they serialize the dispatch
            # pipeline on relayed TPU backends)
            rng_key = jax.random.fold_in(jax.random.PRNGKey(0), t_dev)
            t = t_dev.astype(jnp.float32)
            loss, grads = jax.value_and_grad(total)(variables, constants,
                                                    placeholders, rng_key, True)
            if cfg.l1 or cfg.l2:
                grads = {k: upd.apply_regularization(variables[k], g, cfg.l1, cfg.l2)
                         for k, g in grads.items()}
            if cfg.clip_value:
                grads = upd.clip_by_value(grads, cfg.clip_value)
            if cfg.clip_norm:
                grads = upd.clip_by_norm(grads, cfg.clip_norm)
            if cfg.clip_global_norm:
                grads = upd.clip_by_global_norm(grads, cfg.clip_global_norm)
            lr = updater.lr_at(t)
            new_vars, new_state = {}, {}
            for k, g in grads.items():
                u, s = updater.apply(g, opt_state[k], lr, t)
                if (isinstance(updater, upd.AdamW) and updater.weight_decay
                        and variables[k].ndim >= 2):
                    # decoupled decay on weight matrices only — biases and
                    # norm scales (1-D) are exempt, like the loss-side L1/L2
                    u = u + updater.weight_decay_update(variables[k], lr)
                new_vars[k] = variables[k] - u
                new_state[k] = s
            return new_vars, new_state, t_dev + 1, loss
        return jax.jit(step, donate_argnums=(0, 2, 3))

    def fit(self, data=None, epochs: int = 1, batch_size: int = None,
            iterator=None) -> History:
        """ref: SameDiff.fit(MultiDataSetIterator) → TrainingSession.

        ``data``: either an iterator yielding dicts {placeholder: array}
        (re-iterable per epoch), or a dict of full arrays (optionally
        minibatched by ``batch_size``).
        """
        if self.training_config is None:
            raise ValueError("setTrainingConfig first")
        cfg = self.training_config
        if self._updater_state is None:
            self._updater_state = {k: cfg.updater.init_state(v)
                                   for k, v in self._variables.items()}
        if self._train_step_cache is None:
            self._train_step_cache = self._make_train_step()
        train_step = self._train_step_cache
        hist = History()

        def batches():
            src = iterator if iterator is not None else data
            if isinstance(src, dict):
                n = next(iter(src.values())).shape[0]
                bs = batch_size or n
                for i in range(0, n, bs):
                    yield {k: v[i:i + bs] for k, v in src.items()}
            else:
                for b in src:
                    if isinstance(b, dict):
                        yield b
                    else:  # (features, labels) pair → map via config
                        feats, labels = b
                        out = {}
                        f_list = feats if isinstance(feats, (list, tuple)) else [feats]
                        l_list = labels if isinstance(labels, (list, tuple)) else [labels]
                        for name, arr in zip(cfg.data_set_feature_mapping, f_list):
                            out[name] = arr
                        for name, arr in zip(cfg.data_set_label_mapping, l_list):
                            out[name] = arr
                        yield out

        # the compiled step DONATES the variable buffers; copy once per fit
        # so arrays the caller passed to var(...) (or grabbed via getArr()
        # before fit) survive — only framework-owned buffers get donated
        self._variables = {k: jnp.copy(v) for k, v in self._variables.items()}
        t_dev = jnp.asarray(self._step, jnp.int32)
        for epoch in range(epochs):
            for batch in batches():
                phs = {k: jnp.asarray(v) for k, v in batch.items()}
                self._variables, self._updater_state, t_dev, loss = train_step(
                    self._variables, self._constants, self._updater_state,
                    t_dev, phs)
                # keep losses on-device during the epoch; convert in bulk at
                # the end (per-step float() blocks the pipeline on every step)
                hist.loss_curve.append(loss)
                self._step += 1
                for lst in self._listeners:
                    if hasattr(lst, "iterationDone"):
                        lst.iterationDone(self, self._step, loss)
        hist.loss_curve = [float(l) for l in jax.device_get(hist.loss_curve)]
        return hist

    # ---------------------------------------------------------- control flow
    def while_loop(self, cond_fn, body_fn, init_vars: Sequence[SDVariable],
                   name: str = None):
        """Lower to lax.while_loop (ref: interpreted Enter/Exit/Merge frames).

        Two body forms:
        - Python callables over raw jax arrays — fast to write, but the
          node cannot be serialized (no data form for a closure).
        - SameDiff subgraphs — ``cond_fn``/``body_fn`` are SameDiff
          instances whose placeholders (declaration order) are the loop
          carries; the last-recorded node output (or an explicit
          ``outputs`` list via attrs) is the result. These round-trip
          through save()/load() and are what the TF importer emits for
          StatelessWhile.
        """
        names = [self._as_var(v).name for v in init_vars]
        n = len(names)
        if isinstance(cond_fn, SameDiff) and isinstance(body_fn, SameDiff):
            attrs = {"cond": subgraph_spec(cond_fn,
                                           cond_fn._default_outputs(1)),
                     "body": subgraph_spec(body_fn,
                                           body_fn._default_outputs(n))}
            if _sub_has_rng(attrs["cond"], attrs["body"]):
                attrs["__rng__"] = True
            fn = _make_subwhile_fn(attrs)
            return self._record_fn("while_loop", fn, names, name=name,
                                   n_out=n, attrs=attrs, rebuild="subwhile")

        def fn(*args):
            def body(c):
                out = body_fn(*c)
                return tuple(out) if isinstance(out, (tuple, list)) else (out,)
            res = jax.lax.while_loop(lambda c: cond_fn(*c), body, tuple(args))
            return res[0] if n == 1 else res
        return self._record_fn("while_loop", fn, names, name=name, n_out=n)

    def cond(self, pred: SDVariable, true_fn, false_fn, operands: Sequence[SDVariable],
             name: str = None, n_out: int = 1):
        """Lower to lax.cond. Branches are Python callables (not
        serializable) or SameDiff subgraphs (round-trip; see while_loop)."""
        names = [self._as_var(pred).name] + [self._as_var(v).name for v in operands]
        if isinstance(true_fn, SameDiff) and isinstance(false_fn, SameDiff):
            attrs = {"true": subgraph_spec(true_fn,
                                           true_fn._default_outputs(n_out)),
                     "false": subgraph_spec(false_fn,
                                            false_fn._default_outputs(n_out))}
            if _sub_has_rng(attrs["true"], attrs["false"]):
                attrs["__rng__"] = True
            fn = _make_subcond_fn(attrs)
            return self._record_fn("cond", fn, names, name=name, n_out=n_out,
                                   attrs=attrs, rebuild="subcond")

        def fn(p, *args):
            return jax.lax.cond(p, lambda c: true_fn(*c), lambda c: false_fn(*c),
                                tuple(args))
        return self._record_fn("cond", fn, names, name=name)

    def invoke_subgraph(self, sub: "SameDiff", inputs: Sequence[SDVariable],
                        outputs: Sequence[str] = None, name: str = None):
        """Record a whole subgraph as ONE node (function-call inlining —
        ref: the import of PartitionedCall / FunctionDef bodies).
        Differentiable and serializable."""
        names = [self._as_var(v).name for v in inputs]
        outs = list(outputs) if outputs else sub._default_outputs(1)
        attrs = {"sub": subgraph_spec(sub, outs)}
        if _sub_has_rng(attrs["sub"]):
            attrs["__rng__"] = True
        fn = _make_subcall_fn(attrs)
        return self._record_fn("subgraph", fn, names, name=name,
                               n_out=len(outs), attrs=attrs, rebuild="subcall")

    def setOutputs(self, *names):
        """Mark this graph's result variables (used when the graph serves
        as a control-flow body / called subgraph)."""
        self._marked_outputs = [n.name if isinstance(n, SDVariable) else n
                                for n in names]
        return self

    def _default_outputs(self, n: int) -> List[str]:
        """Explicitly marked outputs, else the last n recorded outputs —
        the convention for subgraph results."""
        marked = getattr(self, "_marked_outputs", None)
        if marked:
            if len(marked) != n:
                raise ValueError(f"subgraph marks {len(marked)} outputs, "
                                 f"{n} required")
            return list(marked)
        if not self._nodes:
            # identity subgraph: outputs are the last n placeholders
            phs = list(self._placeholders)
            return phs[-n:]
        outs = [o for node in self._nodes for o in node.outputs]
        return outs[-n:]

    # ------------------------------------------------------------- utilities
    def variables(self) -> List[SDVariable]:
        return [self._vars[n] for n in self._variables]

    def getVariable(self, name: str) -> SDVariable:
        return self._vars[name]

    def hasVariable(self, name: str) -> bool:
        return name in self._vars

    # ------------------------------------------------------- save / load
    def save(self, path: str, save_updater_state: bool = True):
        """ref: SameDiff.save (FlatBuffers zip). Format: zip with graph.json
        + arrays.npz (+ updater state).

        Closure-backed nodes (attention, std/variance, getitem, RNG ops)
        serialize via a rebuild key + attrs and are reconstructed at load().
        ``while_loop``/``cond`` are explicitly NOT serializable: their bodies
        are arbitrary Python callables (the reference serializes interpreted
        Enter/Exit/Merge frames; the TPU rebuild compiles bodies to
        lax.while_loop/cond, which have no data representation) — save()
        raises with this explanation, callers must rebuild such graphs from
        code."""
        graph = {"nodes": [], "placeholders": {k: [list(v[0]) if v[0] else None,
                                                   str(np.dtype(v[1]) if not isinstance(v[1], str) else v[1])]
                                               for k, v in self._placeholders.items()},
                 "loss_variables": self._loss_variables,
                 "step": self._step}
        for node in self._nodes:
            graph["nodes"].append(_node_to_spec(node))
        if self.training_config is not None:
            graph["training_config"] = self.training_config.to_config()
        arrays = {f"var::{k}": np.asarray(v) for k, v in self._variables.items()}
        arrays.update({f"const::{k}": np.asarray(v) for k, v in self._constants.items()})
        if save_updater_state and self._updater_state is not None:
            flat, treedef = jax.tree_util.tree_flatten(self._updater_state)
            for i, leaf in enumerate(flat):
                arrays[f"upd::{i}"] = np.asarray(leaf)
            graph["updater_treedef"] = _treedef_to_json(self._updater_state)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("graph.json", json.dumps(graph))
            import io
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            z.writestr("arrays.npz", buf.getvalue())

    @staticmethod
    def load(path: str) -> "SameDiff":
        sd = SameDiff()
        with zipfile.ZipFile(path) as z:
            graph = json.loads(z.read("graph.json"))
            import io
            arrays = np.load(io.BytesIO(z.read("arrays.npz")))
        for name, spec in graph["placeholders"].items():
            shape = tuple(spec[0]) if spec[0] else None
            sd.placeHolder(name, shape=shape, dtype=np.dtype(spec[1]))
        upd_leaves = {}
        for k in arrays.files:
            kind, _, name = k.partition("::")
            if kind == "var":
                sd.var(name, arrays[k])
            elif kind == "const":
                sd.constant(arrays[k], name=name)
            elif kind == "upd":
                upd_leaves[int(name)] = jnp.asarray(arrays[k])
        for nd_spec in graph["nodes"]:
            node = _node_from_spec(nd_spec)
            sd._nodes.append(node)
            for on in node.outputs:
                sd._vars[on] = SDVariable(sd, on, "ARRAY")
                sd._producers[on] = node
        sd._loss_variables = graph.get("loss_variables", [])
        sd._step = graph.get("step", 0)
        if "training_config" in graph:
            sd.training_config = TrainingConfig.from_config(graph["training_config"])
        if upd_leaves and "updater_treedef" in graph:
            leaves = [upd_leaves[i] for i in range(len(upd_leaves))]
            sd._updater_state = _treedef_from_json(graph["updater_treedef"], leaves)
        return sd


def _node_to_spec(node: _Node) -> dict:
    """JSON-able spec of one node (shared by save() and subgraph specs)."""
    spec = {"op": node.op, "inputs": node.inputs, "outputs": node.outputs,
            "attrs": {k: v for k, v in node.attrs.items() if k != "__rng__"},
            "rng": bool(node.attrs.get("__rng__"))}
    if node.rebuild is not None:
        spec["rebuild"] = node.rebuild
    elif not op_registry.has(node.op):
        raise ValueError(
            f"node '{node.op}' is not serializable: its body is an "
            f"arbitrary Python closure. while_loop/cond round-trip when "
            f"their bodies are SameDiff subgraphs (pass SameDiff instances "
            f"instead of Python callables); raw-callable bodies have no "
            f"data form and must be rebuilt from code after load.")
    return spec


def _node_from_spec(nd_spec: dict) -> _Node:
    """Rebuild a node (with executable fn) from its JSON spec."""
    attrs = dict(nd_spec["attrs"])
    attrs = {k: (tuple(v) if isinstance(v, list) and k != "index" else v)
             for k, v in attrs.items()}
    rebuild = nd_spec.get("rebuild")
    if rebuild is not None:
        if rebuild not in _FN_REBUILDERS and rebuild == "tf":
            # TF-imported graphs: the rebuilder registers on import
            import deeplearning4j_tpu.modelimport.tensorflow  # noqa: F401
        fn = _FN_REBUILDERS[rebuild](attrs)
        if nd_spec.get("rng"):
            # control-flow nodes whose subgraph bodies hold RNG ops still
            # receive (key, train) from the executor
            attrs["__rng__"] = True
    elif nd_spec.get("rng"):
        fn = _make_rng_fn(nd_spec["op"], attrs)
        attrs["__rng__"] = True
    else:
        fn = op_registry.get(nd_spec["op"])
    return _Node(nd_spec["op"], fn, nd_spec["inputs"], nd_spec["outputs"],
                 attrs, rebuild=rebuild)


# ------------------------------------------------------------- subgraphs
# A SameDiff graph can serve as the body of a control-flow node (while/
# cond) or a function call. The subgraph serializes to a fully
# self-contained JSON spec (arrays base64-inline — control-flow bodies
# are small), so control flow round-trips through save()/load() — the
# TPU-native answer to the reference's FlatBuffers'd Enter/Exit/Merge
# frames (SURVEY.md §2.2 SameDiff core).

def _arr_to_json(a) -> dict:
    a = np.asarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _arr_from_json(d) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["data"]),
                         np.dtype(d["dtype"])).reshape(d["shape"]).copy()


def subgraph_spec(sub: "SameDiff", outputs: Sequence[str]) -> dict:
    """Self-contained JSON spec of ``sub``: placeholders (in declared
    order — the call convention), variables folded to constants (subgraph
    weights are closed over, not trained), nodes, and output names."""
    return {
        "ph_order": list(sub._placeholders),
        "placeholders": {k: [list(v[0]) if v[0] else None,
                             str(np.dtype(v[1]) if not isinstance(v[1], str)
                                 else v[1])]
                         for k, v in sub._placeholders.items()},
        "consts": {k: _arr_to_json(v)
                   for k, v in {**sub._constants, **sub._variables}.items()},
        "nodes": [_node_to_spec(n) for n in sub._nodes],
        "outputs": list(outputs),
        # containing nodes thread (rng_key, train) through when True, so
        # dropout/noise inside control-flow bodies stays live in training
        "has_rng": any(n.attrs.get("__rng__") for n in sub._nodes),
    }


def subgraph_from_spec(spec: dict) -> "SameDiff":
    sub = SameDiff()
    for name in spec["ph_order"]:
        shp, dt = spec["placeholders"][name]
        sub.placeHolder(name, shape=tuple(shp) if shp else None,
                        dtype=np.dtype(dt))
    for name, d in spec["consts"].items():
        sub.constant(_arr_from_json(d), name=name)
    for nd_spec in spec["nodes"]:
        node = _node_from_spec(nd_spec)
        sub._nodes.append(node)
        for on in node.outputs:
            sub._vars[on] = SDVariable(sub, on, "ARRAY")
            sub._producers[on] = node
    return sub


def subgraph_fn(spec: dict) -> Callable:
    """Compile a subgraph spec to ``call(*args, key=None, train=False) ->
    tuple(outputs)`` with args bound to the placeholders in declared
    order. RNG nodes inside the subgraph consume ``key``/``train``."""
    sub = subgraph_from_spec(spec)
    outputs = tuple(spec["outputs"])
    ph_names = spec["ph_order"]
    base = sub._build_fn(outputs)

    def call(*args, key=None, train=False):
        k = key if key is not None else jax.random.PRNGKey(0)
        outs = base({}, sub._constants, dict(zip(ph_names, args)), k, train)
        return tuple(outs[n] for n in outputs)
    return call


def _sub_has_rng(*specs) -> bool:
    return any(s.get("has_rng") for s in specs)


def _make_subwhile_fn(attrs: dict) -> Callable:
    cond = subgraph_fn(attrs["cond"])
    body = subgraph_fn(attrs["body"])
    n = len(attrs["body"]["outputs"])

    def run(args, key, train):
        res = jax.lax.while_loop(
            lambda c: jnp.reshape(cond(*c, key=key, train=train)[0],
                                  ()).astype(bool),
            lambda c: body(*c, key=key, train=train), tuple(args))
        return res if n > 1 else res[0]

    if _sub_has_rng(attrs["cond"], attrs["body"]):
        # recorded with __rng__: _build_fn appends (key, train)
        def fn(*all_args):
            *args, key, train = all_args
            return run(args, key, train)
        return fn
    return lambda *args, **_kw: run(args, None, False)


def _make_subcond_fn(attrs: dict) -> Callable:
    tfn = subgraph_fn(attrs["true"])
    ffn = subgraph_fn(attrs["false"])
    n = len(attrs["true"]["outputs"])

    def run(p, args, key, train):
        res = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                           lambda c: tfn(*c, key=key, train=train),
                           lambda c: ffn(*c, key=key, train=train),
                           tuple(args))
        return res if n > 1 else res[0]

    if _sub_has_rng(attrs["true"], attrs["false"]):
        def fn(p, *all_args):
            *args, key, train = all_args
            return run(p, args, key, train)
        return fn
    return lambda p, *args, **_kw: run(p, args, None, False)


def _make_subcall_fn(attrs: dict) -> Callable:
    """Inline function call: one node that executes a whole subgraph
    (differentiable — jax traces straight through)."""
    sub = subgraph_fn(attrs["sub"])
    n = len(attrs["sub"]["outputs"])

    def run(args, key, train):
        res = sub(*args, key=key, train=train)
        return res if n > 1 else res[0]

    if _sub_has_rng(attrs["sub"]):
        def fn(*all_args):
            *args, key, train = all_args
            return run(args, key, train)
        return fn
    return lambda *args, **_kw: run(args, None, False)


def _make_rng_fn(op: str, params: Dict) -> Callable:
    """Build the executable closure for an RNG node from serializable
    params — used at record time AND at load() so RNG nodes round-trip."""
    inner = op_registry.get(op)
    params = {k: v for k, v in params.items() if k != "__rng__"}
    if op == "dropout":
        rate = params["rate"]
        return lambda x, key, train: inner(x, rate, key, train=train)
    shape = tuple(params.pop("shape"))
    kw = dict(params)
    return lambda key, train: inner(key, shape, **kw)


def _encode_index(idx):
    """JSON-able encoding of a numpy-style index (for serializable getitem)."""
    if isinstance(idx, tuple):
        return {"tuple": [_encode_index(i) for i in idx]}
    if isinstance(idx, slice):
        return {"slice": [idx.start, idx.stop, idx.step]}
    if idx is Ellipsis:
        return {"ellipsis": True}
    if idx is None:
        return {"newaxis": True}
    if isinstance(idx, (int, np.integer)) and not isinstance(idx, (bool, np.bool_)):
        return int(idx)
    if isinstance(idx, list) or (isinstance(idx, np.ndarray) and idx.ndim == 1
                                 and np.issubdtype(idx.dtype, np.integer)):
        return {"list": [int(i) for i in idx]}
    raise TypeError(f"unsupported index for serializable getitem: {idx!r}")


def _decode_index(spec):
    if isinstance(spec, int):
        return spec
    if "tuple" in spec:
        return tuple(_decode_index(s) for s in spec["tuple"])
    if "slice" in spec:
        return slice(*spec["slice"])
    if "ellipsis" in spec:
        return Ellipsis
    if "newaxis" in spec:
        return None
    return list(spec["list"])


def _make_getitem_fn(attrs):
    idx = _decode_index(attrs["index"])
    return lambda x, index=None: x[idx]


def _make_std_fn(attrs):
    return lambda v, axis=None: jnp.std(v, axis=axis, ddof=1)


def _make_variance_fn(attrs):
    return lambda v, axis=None: jnp.var(v, axis=axis, ddof=1)


def _make_mha_fn(attrs):
    """Rebuild the multiHeadDotProductAttention closure; the mask (when
    recorded) is a graph input, passed positionally after the six weights."""
    inner = op_registry.get("multi_head_dot_product_attention")
    if attrs.get("has_mask"):
        def fn(q, kv, wq, wk, wv, wo, m, num_heads=None, has_mask=True):
            return inner(q, kv, wq, wk, wv, wo, num_heads=num_heads, mask=m)
    else:
        def fn(q, kv, wq, wk, wv, wo, num_heads=None, has_mask=False):
            return inner(q, kv, wq, wk, wv, wo, num_heads=num_heads)
    return fn


# rebuild-key -> closure builder; save() records the key, load() calls it
_FN_REBUILDERS = {
    "getitem": _make_getitem_fn,
    "std": _make_std_fn,
    "variance": _make_variance_fn,
    "multi_head_dot_product_attention": _make_mha_fn,
    "subwhile": _make_subwhile_fn,
    "subcond": _make_subcond_fn,
    "subcall": _make_subcall_fn,
}


def _treedef_to_json(tree):
    """Structure of nested dicts (leaves -> None) for round-tripping."""
    if isinstance(tree, dict):
        return {k: _treedef_to_json(v) for k, v in sorted(tree.items())}
    return None


def _treedef_from_json(spec, leaves, _idx=None):
    if _idx is None:
        _idx = [0]
    if spec is None:
        leaf = leaves[_idx[0]]
        _idx[0] += 1
        return leaf
    return {k: _treedef_from_json(v, leaves, _idx) for k, v in sorted(spec.items())}


def _initialize(shape, init: str, rng_key=None, dtype=jnp.float32):
    """Weight init (ref: org.deeplearning4j.nn.weights.WeightInit)."""
    if rng_key is None:
        from deeplearning4j_tpu.linalg import factory
        rng_key = factory.getRandom().next_key()
    shape = tuple(shape)
    init = init.lower()
    fan_in = shape[0] if len(shape) >= 1 else 1
    fan_out = shape[-1] if len(shape) >= 2 else 1
    if len(shape) == 4:  # conv OIHW
        rf = shape[2] * shape[3]
        fan_in, fan_out = shape[1] * rf, shape[0] * rf
    if len(shape) == 5:  # conv3d OIDHW
        rf = shape[2] * shape[3] * shape[4]
        fan_in, fan_out = shape[1] * rf, shape[0] * rf
    if init == "zeros":
        return jnp.zeros(shape, dtype)
    if init == "ones":
        return jnp.ones(shape, dtype)
    if init in ("xavier", "glorot_uniform"):
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        return jax.random.uniform(rng_key, shape, dtype, -limit, limit)
    if init in ("xavier_gaussian", "glorot_normal"):
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return std * jax.random.normal(rng_key, shape, dtype)
    if init in ("relu", "he", "he_normal"):
        std = float(np.sqrt(2.0 / fan_in))
        return std * jax.random.normal(rng_key, shape, dtype)
    if init in ("he_uniform", "relu_uniform"):
        limit = float(np.sqrt(6.0 / fan_in))
        return jax.random.uniform(rng_key, shape, dtype, -limit, limit)
    if init in ("lecun_normal",):
        std = float(np.sqrt(1.0 / fan_in))
        return std * jax.random.normal(rng_key, shape, dtype)
    if init in ("uniform",):
        a = float(1.0 / np.sqrt(fan_in))
        return jax.random.uniform(rng_key, shape, dtype, -a, a)
    if init in ("normal", "gaussian"):
        return jax.random.normal(rng_key, shape, dtype) / float(np.sqrt(fan_in))
    raise ValueError(f"unknown weight init '{init}'")
