"""DataSet containers + iterator contract + normalizers.

Reference parity: ``org.nd4j.linalg.dataset.{DataSet, MultiDataSet}``,
``api.iterator.DataSetIterator``, preprocessors ``NormalizerStandardize``,
``NormalizerMinMaxScaler``, ``ImagePreProcessingScaler`` (SURVEY.md §2.2
"DataSet API"), and ``AsyncDataSetIterator`` (background prefetch,
§2.2 "Iterators").

TPU-native: host arrays stay as numpy until the train step moves a batch
to device; arrays that are ALREADY device-resident (jax.Array) are kept
as-is — coercing them to numpy would round-trip every batch through the
host link on each step. AsyncDataSetIterator double-buffers host→device
transfer behind compute.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, List, Sequence

import jax
import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.utils.concurrent import ErrorLatch as _ErrorLatch

# Registered at import so GET /metrics always exposes the input-pipeline
# series (zero until a prefetching iterator runs) — a flat-zero
# queue-depth gauge under a slow fit loop is the data-starvation signal.
_REG = _prof.get_registry()
_ASYNC_QUEUE_DEPTH = _REG.gauge(
    "dl4j_async_iterator_queue_depth",
    "Batches currently buffered by AsyncDataSetIterator (0 under load "
    "means the consumer is data-starved)")
_PREFETCH_QUEUE_DEPTH = _REG.gauge(
    "dl4j_prefetch_queue_depth",
    "Staged megabatches currently buffered by DevicePrefetcher")
_PREFETCH_H2D_BYTES = _REG.counter(
    "dl4j_prefetch_h2d_bytes_total",
    "Host bytes staged onto the device by DevicePrefetcher while prior "
    "dispatches compute (H2D/compute overlap)")
_DATA_RETRIES = _REG.counter(
    "dl4j_data_retries_total",
    "Transient data-pipeline errors retried (RetryingDataSetIterator / "
    "AsyncDataSetIterator bounded backoff)")


class TransientDataError(IOError):
    """A data-pipeline error the source declares RETRYABLE (flaky
    network filesystem, object-store 5xx, preempted reader): the bounded
    retry-with-backoff paths (RetryingDataSetIterator,
    AsyncDataSetIterator) re-pull instead of killing the fit. Any other
    exception type can opt in by setting a truthy ``transient``
    attribute."""

    transient = True


def is_transient_error(e: BaseException) -> bool:
    """True when the error is marked retryable (see TransientDataError)."""
    return bool(getattr(e, "transient", False))


def _retry_pull(pull, max_retries: int, backoff: float, sleep):
    """The one bounded transient-retry loop both data paths share
    (AsyncDataSetIterator's worker and RetryingDataSetIterator):
    exponential backoff, ``dl4j_data_retries_total`` per retry,
    immediate propagation of non-transient errors. ``sleep(seconds)``
    returns True to abort retrying (the async worker passes its stop
    event's ``wait``)."""
    attempt = 0
    while True:
        try:
            return pull()
        except BaseException as e:
            if attempt >= max_retries or not is_transient_error(e):
                raise
            attempt += 1
            _DATA_RETRIES.inc()
            if sleep(backoff * (2 ** (attempt - 1))):
                raise


def _as_batch_array(a):
    """numpy for host data, untouched for device-resident arrays."""
    if a is None or isinstance(a, jax.Array):
        return a
    return np.asarray(a)


class DataSet:
    """Features + labels (+ masks) batch container (ref: DataSet)."""

    def __init__(self, features=None, labels=None,
                 features_mask=None, labels_mask=None):
        self.features = _as_batch_array(features)
        self.labels = _as_batch_array(labels)
        self.features_mask = _as_batch_array(features_mask)
        self.labels_mask = _as_batch_array(labels_mask)

    def getFeatures(self):
        return self.features

    def getLabels(self):
        return self.labels

    def numExamples(self) -> int:
        return 0 if self.features is None else self.features.shape[0]

    def splitTestAndTrain(self, fraction_or_n) -> "SplitTestAndTrain":
        n = self.numExamples()
        n_train = int(fraction_or_n * n) if isinstance(fraction_or_n, float) \
            else int(fraction_or_n)
        def cut(a, lo, hi):
            return a[lo:hi] if a is not None else None
        train = DataSet(cut(self.features, 0, n_train), cut(self.labels, 0, n_train),
                        cut(self.features_mask, 0, n_train), cut(self.labels_mask, 0, n_train))
        test = DataSet(cut(self.features, n_train, n), cut(self.labels, n_train, n),
                       cut(self.features_mask, n_train, n), cut(self.labels_mask, n_train, n))
        return SplitTestAndTrain(train, test)

    def shuffle(self, seed: int = None):
        rng = np.random.RandomState(seed)
        perm = rng.permutation(self.numExamples())
        for attr in ("features", "labels", "features_mask", "labels_mask"):
            a = getattr(self, attr)
            if a is not None:
                setattr(self, attr, a[perm])

    def batchBy(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.numExamples(), batch_size):
            sl = slice(i, i + batch_size)
            out.append(DataSet(
                self.features[sl],
                self.labels[sl] if self.labels is not None else None,
                self.features_mask[sl] if self.features_mask is not None else None,
                self.labels_mask[sl] if self.labels_mask is not None else None))
        return out

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(attr):
            arrs = [getattr(d, attr) for d in datasets]
            if any(a is None for a in arrs):
                return None
            return np.concatenate(arrs, axis=0)
        return DataSet(cat("features"), cat("labels"),
                       cat("features_mask"), cat("labels_mask"))


class SplitTestAndTrain:
    def __init__(self, train: DataSet, test: DataSet):
        self.train = train
        self.test = test

    def getTrain(self):
        return self.train

    def getTest(self):
        return self.test


class MultiDataSet:
    """Multiple features/labels arrays (ref: MultiDataSet) — the
    ComputationGraph batch container."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks: Sequence = None, labels_masks: Sequence = None):
        as_list = lambda x: [_as_batch_array(a) for a in x] if x is not None else None
        self.features = as_list(features if isinstance(features, (list, tuple)) else [features])
        self.labels = as_list(labels if isinstance(labels, (list, tuple)) else [labels])
        self.features_masks = as_list(features_masks)
        self.labels_masks = as_list(labels_masks)

    def numExamples(self):
        return self.features[0].shape[0]


class DataSetIterator:
    """Iterator contract (ref: DataSetIterator): python-iterable over
    DataSet minibatches, restartable via reset()."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.hasNext():
            raise StopIteration
        return self.next()

    def hasNext(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    # -- checkpoint/resume cursor protocol (train.resilience) --
    def cursor(self):
        """JSON-able position token for checkpoint/resume, or None when
        the source cannot seek. Captured by the resilience layer right
        after each pull so a resumed fit continues from the exact batch
        the restored step count expects."""
        return None

    def seek(self, cursor) -> None:
        """Restore a position previously returned by :meth:`cursor`."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support seek(); checkpoint "
            "resume will restart this iterator from the beginning")

    def setPreProcessor(self, pre):
        self._pre = pre

    def _apply_pre(self, ds: DataSet) -> DataSet:
        pre = getattr(self, "_pre", None)
        if pre is not None:
            pre.transform(ds)
        return ds


class ListDataSetIterator(DataSetIterator):
    """Iterate an in-memory DataSet in minibatches (ref: ListDataSetIterator)."""

    def __init__(self, data: DataSet, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 12345):
        self.data = data
        self.batch_size = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self.reset()

    def reset(self):
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            self._order = rng.permutation(self.data.numExamples())
            self._epoch += 1
        else:
            self._order = np.arange(self.data.numExamples())
        self._pos = 0

    def hasNext(self):
        return self._pos < self.data.numExamples()

    def next(self):
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        d = self.data
        ds = DataSet(
            d.features[idx],
            d.labels[idx] if d.labels is not None else None,
            d.features_mask[idx] if d.features_mask is not None else None,
            d.labels_mask[idx] if d.labels_mask is not None else None)
        return self._apply_pre(ds)

    def batch(self):
        return self.batch_size

    def cursor(self):
        """Position + epoch: enough to rebuild the (seeded) shuffle
        order deterministically on seek."""
        return {"pos": int(self._pos), "epoch": int(self._epoch)}

    def seek(self, cursor) -> None:
        epoch = int(cursor["epoch"])
        if self._shuffle:
            # reset() drew the order from seed + epoch THEN incremented
            # _epoch, so the order for stored epoch e came from seed+e-1
            rng = np.random.RandomState(self._seed + max(epoch - 1, 0))
            self._order = rng.permutation(self.data.numExamples())
        else:
            self._order = np.arange(self.data.numExamples())
        self._epoch = epoch
        self._pos = int(cursor["pos"])

    def totalOutcomes(self):
        return self.data.labels.shape[1] if self.data.labels is not None else 0

    def inputColumns(self):
        return int(np.prod(self.data.features.shape[1:]))


def _offer_until_stopped(q, item, stop) -> bool:
    """Blocking queue put that aborts when ``stop`` is set — the shared
    worker->consumer handoff of AsyncDataSetIterator and
    DevicePrefetcher (items, failure wrappers, and END sentinels all go
    through it, so shutdown semantics cannot drift between the two)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


class AsyncDataSetIterator(DataSetIterator):
    """Background prefetch wrapper (ref: AsyncDataSetIterator — the
    process-internal thread boundary in SURVEY.md §3.1).

    ``max_retries`` adds a bounded retry-with-exponential-backoff around
    the worker's base-iterator pulls for errors marked transient
    (:class:`TransientDataError` / a truthy ``transient`` attribute),
    counted in ``dl4j_data_retries_total``. Any worker error the
    consumer never observed is re-raised by ``close()`` — before that,
    an exception racing a ``close()`` was silently dropped. Double
    ``close()`` is idempotent."""

    _END = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 2,
                 max_retries: int = 0, retry_backoff: float = 0.05):
        self.base = base
        self.prefetch = prefetch
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self._queue = None
        self._thread = None
        self._next_item = None
        self._stop = None
        self._pending = _ErrorLatch()
        self.reset()

    def _pull_with_retry(self, stop):
        # stop.wait as the sleep: a shutdown mid-backoff aborts the retry
        return _retry_pull(self.base.next, self.max_retries,
                           self.retry_backoff, stop.wait)

    def _worker(self, q, stop):
        try:
            while not stop.is_set() and self.base.hasNext():
                if not _offer_until_stopped(q, self._pull_with_retry(stop),
                                            stop):
                    return
        except BaseException as e:
            # surface on the consumer thread: letting the exception kill
            # the worker would enqueue _END and silently truncate the
            # stream (e.g. an evaluation quietly computed on 2 of 100
            # batches). Also latched so close() can propagate an error
            # the consumer never pulled.
            self._pending.record(e)
            _offer_until_stopped(q, _PrefetchFailure(e), stop)
        finally:
            # block-put the END sentinel with the same stop-checked retry as
            # real items — dropping it deadlocks the consumer on the last batch
            _offer_until_stopped(q, self._END, stop)

    def _shutdown_worker(self):
        # stop + drain the previous worker before touching self.base, or two
        # threads race on the underlying iterator and drop batches
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            while self._thread.is_alive():
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.05)
        self._thread = None

    def reset(self):
        self._shutdown_worker()
        self._pending.clear()           # explicit restart: fresh slate
        self.base.reset()
        self._restart_worker()

    def _restart_worker(self):
        self._stop = threading.Event()
        # instrumented queue (PR-8 carried follow-up): producer/consumer
        # contention on the prefetch buffer shows up in dl4j_lock_*
        self._queue = _prof.InstrumentedQueue(maxsize=self.prefetch,
                                              name="async_iterator_queue")
        self._thread = threading.Thread(target=self._worker,
                                        args=(self._queue, self._stop),
                                        daemon=True)
        self._thread.start()
        self._next_item = self._queue.get()

    def close(self):
        """Stop the prefetch thread and drop buffered batches.
        Idempotent; the iterator reads as exhausted afterwards (a later
        reset() restarts it). Re-raises the FIRST worker error the
        consumer never saw — a failure that landed in the buffer just as
        the consumer stopped pulling must not vanish."""
        self._shutdown_worker()
        self._next_item = self._END
        err = self._pending.take()
        if err is not None:
            raise err

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # already unwinding: shut down without masking the original
            try:
                self.close()
            except BaseException:
                pass
            return False
        self.close()
        return False

    def hasNext(self):
        return self._next_item is not self._END

    def next(self):
        item = self._next_item
        if isinstance(item, _PrefetchFailure):
            self._next_item = self._END
            self._pending.delivered(item.error)  # raised here, not close()
            raise item.error
        self._next_item = self._queue.get()
        if _prof.instrumentation_active():
            _ASYNC_QUEUE_DEPTH.set(self._queue.qsize())
        return item

    def batch(self):
        return self.base.batch()

    def cursor(self):
        """Base cursor — NOTE: the worker prefetches ahead, so this can
        overstate consumed position by up to ``prefetch+1`` batches; for
        exact resume cursors feed the resilience layer an un-prefetched
        iterator (it records cursors at the pull seam itself)."""
        return self.base.cursor()

    def seek(self, cursor) -> None:
        self._shutdown_worker()
        self._pending.clear()
        self.base.seek(cursor)
        self._restart_worker()


class RetryingDataSetIterator(DataSetIterator):
    """Bounded retry-with-exponential-backoff around a flaky source
    iterator: ``next()`` re-pulls on errors marked transient
    (:class:`TransientDataError` / ``transient`` attribute) up to
    ``max_retries`` times, counting ``dl4j_data_retries_total``;
    permanent errors propagate immediately. The resilience layer wraps
    fit() iterators with this automatically."""

    def __init__(self, base: DataSetIterator, max_retries: int = 3,
                 backoff: float = 0.05):
        self.base = base
        self.max_retries = max_retries
        self.backoff = backoff

    def hasNext(self):
        return self.base.hasNext()

    def next(self):
        return _retry_pull(self.base.next, self.max_retries, self.backoff,
                           time.sleep)

    def reset(self):
        self.base.reset()

    def batch(self):
        return self.base.batch()

    def cursor(self):
        return self.base.cursor()

    def seek(self, cursor) -> None:
        self.base.seek(cursor)


class IterableDataSetIterator(DataSetIterator):
    """Adapter: any python iterable of DataSets -> DataSetIterator.

    Lets evaluate()/AsyncDataSetIterator accept plain lists or generators.
    reset() re-iterates the source — exact for restartable iterables
    (lists, tuples); a one-shot generator supports a single pass."""

    _DONE = object()

    def __init__(self, iterable: Iterable):
        self._iterable = iterable
        # a one-shot iterator IS its own iter(); re-iterating it on reset()
        # would silently drop the element buffered for hasNext()
        self._one_shot = iter(iterable) is iterable
        self._it = iter(iterable)
        self._nxt = next(self._it, self._DONE)

    def reset(self):
        if self._one_shot:
            return  # single pass: keep position (and the buffered item)
        self._it = iter(self._iterable)
        self._nxt = next(self._it, self._DONE)

    def hasNext(self):
        return self._nxt is not self._DONE

    def next(self):
        if self._nxt is self._DONE:
            raise StopIteration
        item = self._nxt
        self._nxt = next(self._it, self._DONE)
        return self._apply_pre(item)

    def batch(self):
        return -1


class DevicePrefetcher:
    """Thread + ``jax.device_put`` double buffer: stage the NEXT
    (mega)batch onto the device while the current one computes.

    Builds on AsyncDataSetIterator's worker/queue shape, but the worker
    also *places* each batch on the device (``jax.device_put`` returns
    immediately; the transfer overlaps prior dispatched compute), and the
    stream is grouped into ``steps_per_dispatch``-sized
    :class:`~deeplearning4j_tpu.train.stepping.MegaBatch` items first.
    With the default ``prefetch=2`` the queue holds the in-flight staged
    megabatch plus one more — a classic double buffer.

    ``placement`` customizes device placement: a callable
    ``(array, mega: bool) -> staged array`` (``mega`` is True for stacked
    ``[K, B, ...]`` arrays) — ParallelWrapper passes a mesh-sharding
    placement. Default: ``jax.device_put`` onto the default device.

    Iterable; also a context manager (``close()`` stops the worker).
    """

    _END = object()

    def __init__(self, batches: Iterable, steps_per_dispatch: int = 1,
                 prefetch: int = 2, placement: Callable = None,
                 max_retries: int = 0, retry_backoff: float = 0.05):
        from deeplearning4j_tpu.train.stepping import group_into_megabatches
        self._placement = placement
        # instrumented queue (PR-8 carried follow-up): staging-buffer
        # contention is observable via dl4j_lock_*{lock=prefetch_queue}
        self._queue = _prof.InstrumentedQueue(maxsize=max(1, prefetch),
                                              name="prefetch_queue")
        self._stop = threading.Event()
        if max_retries and isinstance(batches, DataSetIterator):
            # transient-error retry happens at the pull seam: a generator
            # source dies on raise and cannot be retried, a DataSetIterator
            # can re-serve the failed pull
            batches = RetryingDataSetIterator(batches, max_retries,
                                              retry_backoff)
        self._src = group_into_megabatches(batches, steps_per_dispatch)
        self._done = False
        self._pending = _ErrorLatch()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- staging
    def _stage(self, item):
        return stage_item(item, self._placement)

    # -------------------------------------------------------------- worker
    def _offer(self, item) -> bool:
        if not _offer_until_stopped(self._queue, item, self._stop):
            return False
        if _prof.instrumentation_active():
            _PREFETCH_QUEUE_DEPTH.set(self._queue.qsize())
        return True

    def _worker(self):
        try:
            for item in self._src:
                if self._stop.is_set():
                    return
                if not self._offer(self._stage(item)):
                    return
        except BaseException as e:  # surface in the consumer, not the log
            # latch first so a close() racing this offer still sees it
            self._pending.record(e)
            self._offer(_PrefetchFailure(e))
        finally:
            self._offer(self._END)

    # ------------------------------------------------------------ consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._queue.get()
        if _prof.instrumentation_active():
            _PREFETCH_QUEUE_DEPTH.set(self._queue.qsize())
        if item is self._END:
            self._done = True
            raise StopIteration
        if isinstance(item, _PrefetchFailure):
            self._done = True
            self._pending.delivered(item.error)
            raise item.error
        return item

    def close(self):
        """Stop the worker and drop staged batches. Idempotent; re-raises
        the FIRST worker error the consumer never pulled (a failure
        buffered just as the consumer stopped iterating must not be
        silently dropped)."""
        self._stop.set()
        while self._thread is not None and self._thread.is_alive():
            try:
                self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        self._thread = None
        self._done = True
        _PREFETCH_QUEUE_DEPTH.set(0)
        err = self._pending.take()
        if err is not None:
            raise err

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            try:
                self.close()
            except BaseException:
                pass                # don't mask the in-flight exception
            return False
        self.close()
        return False


class _PrefetchFailure:
    """Wraps a worker-thread exception for re-raise on the consumer side."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


def _stage_array(a, mega: bool, placement: Callable):
    if a is None:
        return None
    if placement is not None:
        staged = placement(a, mega)
    else:
        staged = jax.device_put(a)
    if not isinstance(a, jax.Array) and _prof.instrumentation_active():
        _PREFETCH_H2D_BYTES.inc(int(getattr(a, "nbytes", 0)))
    return staged


def stage_item(item, placement: Callable = None):
    """Place one DataSet/MultiDataSet/MegaBatch's arrays onto the device
    (``placement(array, mega)`` override, else default ``device_put``) —
    the staging step DevicePrefetcher runs in its worker and the
    synchronous (prefetch<=0) multi-step path runs inline."""
    from deeplearning4j_tpu.train.stepping import MegaBatch
    if isinstance(item, MegaBatch):
        put = lambda a: _stage_array(a, True, placement)
        lput = lambda xs: [put(a) for a in xs] if xs is not None else None
        if item.multi:
            item.features = lput(item.features)
            item.labels = lput(item.labels)
            item.features_mask = lput(item.features_mask)
            item.labels_mask = lput(item.labels_mask)
        else:
            item.features = put(item.features)
            item.labels = put(item.labels)
            item.features_mask = put(item.features_mask)
            item.labels_mask = put(item.labels_mask)
        return item
    put = lambda a: _stage_array(a, False, placement)
    if isinstance(item, MultiDataSet):
        out = MultiDataSet.__new__(MultiDataSet)
        lput = lambda xs: [put(a) for a in xs] if xs is not None else None
        out.features = lput(item.features)
        out.labels = lput(item.labels)
        out.features_masks = lput(item.features_masks)
        out.labels_masks = lput(item.labels_masks)
        return out
    if isinstance(item, DataSet):
        out = DataSet.__new__(DataSet)
        out.features = put(item.features)
        out.labels = put(item.labels)
        out.features_mask = put(item.features_mask)
        out.labels_mask = put(item.labels_mask)
        return out
    return item


# ------------------------------------------------------------------ normalizers
class NormalizerStandardize:
    """Zero-mean unit-variance (ref: NormalizerStandardize): fit, transform,
    revert; serializable state."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, data):
        feats = data.features if isinstance(data, DataSet) else np.asarray(data)
        axes = tuple(i for i in range(feats.ndim) if i != 1) if feats.ndim > 2 else (0,)
        self.mean = feats.mean(axis=axes, keepdims=True)[0] if feats.ndim <= 2 \
            else feats.mean(axis=axes)
        self.std = feats.std(axis=axes, keepdims=True)[0] if feats.ndim <= 2 \
            else feats.std(axis=axes)
        self.std = np.where(self.std < 1e-8, 1.0, self.std)

    def transform(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        mean, std = self.mean, self.std
        if feats.ndim > 2:  # broadcast over channel axis
            shape = [1] * feats.ndim
            shape[1] = -1
            mean = mean.reshape(shape)
            std = std.reshape(shape)
        out = (feats - mean) / std
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out

    def revert(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        out = feats * self.std + self.mean
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out

    def state(self):
        return {"mean": self.mean, "std": self.std}

    def load_state(self, d):
        self.mean, self.std = d["mean"], d["std"]


class NormalizerMinMaxScaler:
    """Scale to [min, max] (ref: NormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range, self.max_range = min_range, max_range
        self.data_min = None
        self.data_max = None

    def fit(self, data):
        feats = data.features if isinstance(data, DataSet) else np.asarray(data)
        flat = feats.reshape(feats.shape[0], -1)
        self.data_min = flat.min()
        self.data_max = flat.max()

    def transform(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        denom = max(self.data_max - self.data_min, 1e-8)
        out = (feats - self.data_min) / denom * (self.max_range - self.min_range) \
            + self.min_range
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out


class ImagePreProcessingScaler:
    """Pixel [0, 255] -> [a, b] (ref: ImagePreProcessingScaler)."""

    def __init__(self, a: float = 0.0, b: float = 1.0):
        self.a, self.b = a, b

    def fit(self, data):
        pass

    def transform(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        out = feats / 255.0 * (self.b - self.a) + self.a
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out
