"""DataSet containers + iterator contract + normalizers.

Reference parity: ``org.nd4j.linalg.dataset.{DataSet, MultiDataSet}``,
``api.iterator.DataSetIterator``, preprocessors ``NormalizerStandardize``,
``NormalizerMinMaxScaler``, ``ImagePreProcessingScaler`` (SURVEY.md §2.2
"DataSet API"), and ``AsyncDataSetIterator`` (background prefetch,
§2.2 "Iterators").

TPU-native: host arrays stay as numpy until the train step moves a batch
to device; arrays that are ALREADY device-resident (jax.Array) are kept
as-is — coercing them to numpy would round-trip every batch through the
host link on each step. AsyncDataSetIterator double-buffers host→device
transfer behind compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import jax
import numpy as np


def _as_batch_array(a):
    """numpy for host data, untouched for device-resident arrays."""
    if a is None or isinstance(a, jax.Array):
        return a
    return np.asarray(a)


class DataSet:
    """Features + labels (+ masks) batch container (ref: DataSet)."""

    def __init__(self, features=None, labels=None,
                 features_mask=None, labels_mask=None):
        self.features = _as_batch_array(features)
        self.labels = _as_batch_array(labels)
        self.features_mask = _as_batch_array(features_mask)
        self.labels_mask = _as_batch_array(labels_mask)

    def getFeatures(self):
        return self.features

    def getLabels(self):
        return self.labels

    def numExamples(self) -> int:
        return 0 if self.features is None else self.features.shape[0]

    def splitTestAndTrain(self, fraction_or_n) -> "SplitTestAndTrain":
        n = self.numExamples()
        n_train = int(fraction_or_n * n) if isinstance(fraction_or_n, float) \
            else int(fraction_or_n)
        def cut(a, lo, hi):
            return a[lo:hi] if a is not None else None
        train = DataSet(cut(self.features, 0, n_train), cut(self.labels, 0, n_train),
                        cut(self.features_mask, 0, n_train), cut(self.labels_mask, 0, n_train))
        test = DataSet(cut(self.features, n_train, n), cut(self.labels, n_train, n),
                       cut(self.features_mask, n_train, n), cut(self.labels_mask, n_train, n))
        return SplitTestAndTrain(train, test)

    def shuffle(self, seed: int = None):
        rng = np.random.RandomState(seed)
        perm = rng.permutation(self.numExamples())
        for attr in ("features", "labels", "features_mask", "labels_mask"):
            a = getattr(self, attr)
            if a is not None:
                setattr(self, attr, a[perm])

    def batchBy(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.numExamples(), batch_size):
            sl = slice(i, i + batch_size)
            out.append(DataSet(
                self.features[sl],
                self.labels[sl] if self.labels is not None else None,
                self.features_mask[sl] if self.features_mask is not None else None,
                self.labels_mask[sl] if self.labels_mask is not None else None))
        return out

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(attr):
            arrs = [getattr(d, attr) for d in datasets]
            if any(a is None for a in arrs):
                return None
            return np.concatenate(arrs, axis=0)
        return DataSet(cat("features"), cat("labels"),
                       cat("features_mask"), cat("labels_mask"))


class SplitTestAndTrain:
    def __init__(self, train: DataSet, test: DataSet):
        self.train = train
        self.test = test

    def getTrain(self):
        return self.train

    def getTest(self):
        return self.test


class MultiDataSet:
    """Multiple features/labels arrays (ref: MultiDataSet) — the
    ComputationGraph batch container."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks: Sequence = None, labels_masks: Sequence = None):
        as_list = lambda x: [_as_batch_array(a) for a in x] if x is not None else None
        self.features = as_list(features if isinstance(features, (list, tuple)) else [features])
        self.labels = as_list(labels if isinstance(labels, (list, tuple)) else [labels])
        self.features_masks = as_list(features_masks)
        self.labels_masks = as_list(labels_masks)

    def numExamples(self):
        return self.features[0].shape[0]


class DataSetIterator:
    """Iterator contract (ref: DataSetIterator): python-iterable over
    DataSet minibatches, restartable via reset()."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.hasNext():
            raise StopIteration
        return self.next()

    def hasNext(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def setPreProcessor(self, pre):
        self._pre = pre

    def _apply_pre(self, ds: DataSet) -> DataSet:
        pre = getattr(self, "_pre", None)
        if pre is not None:
            pre.transform(ds)
        return ds


class ListDataSetIterator(DataSetIterator):
    """Iterate an in-memory DataSet in minibatches (ref: ListDataSetIterator)."""

    def __init__(self, data: DataSet, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 12345):
        self.data = data
        self.batch_size = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self.reset()

    def reset(self):
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            self._order = rng.permutation(self.data.numExamples())
            self._epoch += 1
        else:
            self._order = np.arange(self.data.numExamples())
        self._pos = 0

    def hasNext(self):
        return self._pos < self.data.numExamples()

    def next(self):
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        d = self.data
        ds = DataSet(
            d.features[idx],
            d.labels[idx] if d.labels is not None else None,
            d.features_mask[idx] if d.features_mask is not None else None,
            d.labels_mask[idx] if d.labels_mask is not None else None)
        return self._apply_pre(ds)

    def batch(self):
        return self.batch_size

    def totalOutcomes(self):
        return self.data.labels.shape[1] if self.data.labels is not None else 0

    def inputColumns(self):
        return int(np.prod(self.data.features.shape[1:]))


class AsyncDataSetIterator(DataSetIterator):
    """Background prefetch wrapper (ref: AsyncDataSetIterator — the
    process-internal thread boundary in SURVEY.md §3.1)."""

    _END = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 2):
        self.base = base
        self.prefetch = prefetch
        self._queue = None
        self._thread = None
        self._next_item = None
        self._stop = None
        self.reset()

    def _worker(self, q, stop):
        try:
            while not stop.is_set() and self.base.hasNext():
                item = self.base.next()
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        finally:
            # block-put the END sentinel with the same stop-checked retry as
            # real items — dropping it deadlocks the consumer on the last batch
            while True:
                try:
                    q.put(self._END, timeout=0.1)
                    break
                except queue.Full:
                    if stop.is_set():
                        break

    def reset(self):
        # stop + drain the previous worker before touching self.base, or two
        # threads race on the underlying iterator and drop batches
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            while self._thread.is_alive():
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(timeout=0.05)
        self.base.reset()
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self.prefetch)
        self._thread = threading.Thread(target=self._worker,
                                        args=(self._queue, self._stop),
                                        daemon=True)
        self._thread.start()
        self._next_item = self._queue.get()

    def hasNext(self):
        return self._next_item is not self._END

    def next(self):
        item = self._next_item
        self._next_item = self._queue.get()
        return item

    def batch(self):
        return self.base.batch()


# ------------------------------------------------------------------ normalizers
class NormalizerStandardize:
    """Zero-mean unit-variance (ref: NormalizerStandardize): fit, transform,
    revert; serializable state."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, data):
        feats = data.features if isinstance(data, DataSet) else np.asarray(data)
        axes = tuple(i for i in range(feats.ndim) if i != 1) if feats.ndim > 2 else (0,)
        self.mean = feats.mean(axis=axes, keepdims=True)[0] if feats.ndim <= 2 \
            else feats.mean(axis=axes)
        self.std = feats.std(axis=axes, keepdims=True)[0] if feats.ndim <= 2 \
            else feats.std(axis=axes)
        self.std = np.where(self.std < 1e-8, 1.0, self.std)

    def transform(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        mean, std = self.mean, self.std
        if feats.ndim > 2:  # broadcast over channel axis
            shape = [1] * feats.ndim
            shape[1] = -1
            mean = mean.reshape(shape)
            std = std.reshape(shape)
        out = (feats - mean) / std
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out

    def revert(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        out = feats * self.std + self.mean
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out

    def state(self):
        return {"mean": self.mean, "std": self.std}

    def load_state(self, d):
        self.mean, self.std = d["mean"], d["std"]


class NormalizerMinMaxScaler:
    """Scale to [min, max] (ref: NormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range, self.max_range = min_range, max_range
        self.data_min = None
        self.data_max = None

    def fit(self, data):
        feats = data.features if isinstance(data, DataSet) else np.asarray(data)
        flat = feats.reshape(feats.shape[0], -1)
        self.data_min = flat.min()
        self.data_max = flat.max()

    def transform(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        denom = max(self.data_max - self.data_min, 1e-8)
        out = (feats - self.data_min) / denom * (self.max_range - self.min_range) \
            + self.min_range
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out


class ImagePreProcessingScaler:
    """Pixel [0, 255] -> [a, b] (ref: ImagePreProcessingScaler)."""

    def __init__(self, a: float = 0.0, b: float = 1.0):
        self.a, self.b = a, b

    def fit(self, data):
        pass

    def transform(self, data):
        feats = data.features if isinstance(data, DataSet) else data
        out = feats / 255.0 * (self.b - self.a) + self.a
        if isinstance(data, DataSet):
            data.features = out
            return data
        return out
