"""Multi-worker host data pipeline: decode + augment in worker processes,
hand batches to the training loop through a shared-memory ring.

Reference parity: the reference feeds its training loops through
``ImageRecordReader -> RecordReaderDataSetIterator -> AsyncDataSetIterator``
with JavaCV decoding on host threads (SURVEY.md §3.1 input pipeline;
§7 hard-part #5 "prove the host can feed the chip"). The TPU-native
re-design differs in three ways:

1. **Worker processes, not threads** — Python decode (cv2/PIL) holds the
   GIL for numpy conversion, so real parallelism needs processes. Batches
   cross the process boundary through a ``multiprocessing.shared_memory``
   ring: workers write decoded pixels straight into a preallocated slot,
   the consumer hands the slot to ``jax.device_put`` — no pickling, no
   per-batch allocation, one host memcpy total.
2. **uint8 to the device** — slots hold uint8 NCHW; the cast to the
   compute dtype happens ON DEVICE inside the jitted train step
   (``nn/layers.policy_cast``), so the host ships 1/4 the bytes and never
   pays a float conversion. ``dtype="float32"`` opts back into host-side
   float batches for nets that need pre-normalized input.
3. **Fixed shapes** — every ring batch has the same [B, C, H, W] shape
   (tail files that do not fill a batch are dropped by default, or folded
   into a final host-decoded partial batch with ``drop_last=False``), so
   the train step compiles exactly once.

Throughput model (documented for the bench): sustained img/s =
min(workers x per-core decode rate, device step rate). On a single-core
host the pipeline is decode-bound at ~1/decode_ms img/s no matter how
many workers run; see BASELINE.md "data pipeline" for the measured
numbers and the multi-core projection.
"""

from __future__ import annotations

import atexit
import os
import queue
import uuid
from multiprocessing import get_context
from multiprocessing import shared_memory as _shm
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.data.image import (ImageTransform, NativeImageLoader,
                                           ParentPathLabelGenerator,
                                           _list_images)


def _decode_one(path: str, height: int, width: int, channels: int
                ) -> np.ndarray:
    """Decode + resize one file to CHW uint8. cv2 (libjpeg-turbo) when
    available — ~1.5x PIL on the same core — else PIL."""
    try:
        import cv2
        flag = cv2.IMREAD_GRAYSCALE if channels == 1 else cv2.IMREAD_COLOR
        img = cv2.imread(path, flag)
        if img is None:
            raise ValueError(f"cv2 failed to decode {path}")
        if img.shape[:2] != (height, width):
            img = cv2.resize(img, (width, height),
                             interpolation=cv2.INTER_LINEAR)
        if channels == 1:
            img = img[:, :, None]
        else:
            img = img[:, :, ::-1]                    # BGR -> RGB (PIL parity)
        return np.ascontiguousarray(np.transpose(img, (2, 0, 1)))
    except ImportError:
        from PIL import Image
        img = Image.open(path).convert("L" if channels == 1 else "RGB")
        if img.size != (width, height):
            img = img.resize((width, height), Image.BILINEAR)
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, (2, 0, 1))


def _worker_main(shm_name: str, slot_shape, slot_dtype: str, n_slots: int,
                 files: List[str], hw, task_q, free_q, ready_q,
                 transform_bytes: Optional[bytes], seed: int):
    """Worker loop: pull a batch assignment, decode into a free ring slot,
    announce it ready. Runs until the ``None`` sentinel."""
    try:
        import cv2
        cv2.setNumThreads(1)        # one decode stream per worker process
    except ImportError:
        pass
    height, width, channels = hw
    transform = None
    if transform_bytes is not None:
        import pickle
        transform = pickle.loads(transform_bytes)
    rng = np.random.RandomState(seed)
    # the parent owns the ring; this process must not register (and later
    # unlink) it with the shared resource tracker — Python <3.13 has no
    # track=False, so stub the register call around the attach
    from multiprocessing import resource_tracker
    _orig_register = resource_tracker.register
    resource_tracker.register = lambda *a, **kw: None
    try:
        shm = _shm.SharedMemory(name=shm_name)
    finally:
        resource_tracker.register = _orig_register
    ring = np.ndarray((n_slots,) + tuple(slot_shape),
                      dtype=np.dtype(slot_dtype), buffer=shm.buf)
    try:
        while True:
            task = task_q.get()
            if task is None:
                break
            batch_id, idxs, labels = task
            slot = free_q.get()
            buf = ring[slot]
            for row, i in enumerate(idxs):
                img = _decode_one(files[i], height, width, channels)
                if transform is not None:
                    img = transform.transform(img.astype(np.float32), rng)
                    img = np.clip(img, 0, 255)
                buf[row] = img          # implicit cast to the slot dtype
            ready_q.put((batch_id, slot, labels))
    finally:
        shm.close()


class MultiWorkerImageIterator(DataSetIterator):
    """Directory-of-class-directories image pipeline with N decode worker
    processes (ref: ImageRecordReader + RecordReaderDataSetIterator +
    AsyncDataSetIterator, collapsed into the one seam that matters for
    feeding a TPU — see module docstring for the design deltas).

    ``next()`` returns uint8 NCHW DataSets by default; the network casts
    on device. Worker processes use the ``spawn`` start method: this
    process typically holds a live TPU client, and forking a process with
    an initialized accelerator runtime is undefined behaviour.
    """

    def __init__(self, root: str, height: int, width: int, channels: int = 3,
                 batch_size: int = 32, workers: Optional[int] = None,
                 n_slots: Optional[int] = None, dtype: str = "uint8",
                 transform: Optional[ImageTransform] = None,
                 label_generator=None, shuffle: bool = False,
                 drop_last: bool = True, seed: int = 12345,
                 files: Optional[Sequence[str]] = None,
                 start_method: str = "spawn"):
        self.height, self.width, self.channels = height, width, channels
        self.batch_size = int(batch_size)
        self.workers = max(1, workers if workers is not None
                           else (os.cpu_count() or 1))
        self.n_slots = n_slots if n_slots is not None else 2 * self.workers + 2
        self.np_dtype = np.dtype({"uint8": np.uint8,
                                  "float32": np.float32}[dtype])
        self.transform = transform
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self._label_gen = label_generator or ParentPathLabelGenerator()
        self._files = list(files) if files is not None else _list_images(root)
        if not self._files:
            raise FileNotFoundError(f"no images under {root}")
        self.labels = sorted({self._label_gen.getLabelForPath(f)
                              for f in self._files})
        self._label_idx = np.asarray(
            [self.labels.index(self._label_gen.getLabelForPath(f))
             for f in self._files], np.int32)
        self._ctx = get_context(start_method)
        self._shm = None
        self._procs: List = []
        self._epoch = 0
        self._started = False
        self._loader = NativeImageLoader(height, width, channels)
        atexit.register(self.close)
        self.reset()

    # ------------------------------------------------------------ lifecycle
    def _start(self):
        slot_shape = (self.batch_size, self.channels, self.height, self.width)
        slot_bytes = int(np.prod(slot_shape)) * self.np_dtype.itemsize
        self._shm = _shm.SharedMemory(
            create=True, size=self.n_slots * slot_bytes,
            name=f"dl4jtpu_{uuid.uuid4().hex[:12]}")
        self._ring = np.ndarray((self.n_slots,) + slot_shape,
                                dtype=self.np_dtype, buffer=self._shm.buf)
        self._task_q = self._ctx.Queue()
        self._free_q = self._ctx.Queue()
        self._ready_q = self._ctx.Queue()
        for s in range(self.n_slots):
            self._free_q.put(s)
        tbytes = None
        if self.transform is not None:
            import pickle
            tbytes = pickle.dumps(self.transform)
        # decode workers must NOT initialize an accelerator backend: spawn
        # re-runs sitecustomize in each child, and a TPU bootstrap there
        # would fight the parent for the chip. Pin the children to CPU and
        # strip the TPU bootstrap trigger for the duration of the spawn.
        saved = {k: os.environ.get(k)
                 for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")}
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for w in range(self.workers):
                p = self._ctx.Process(
                    target=_worker_main,
                    args=(self._shm.name, slot_shape, self.np_dtype.str,
                          self.n_slots, self._files,
                          (self.height, self.width, self.channels),
                          self._task_q, self._free_q, self._ready_q,
                          tbytes, self.seed + 7919 * w),
                    daemon=True)
                p.start()
                self._procs.append(p)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        self._started = True

    def close(self):
        """Stop workers and release the shared-memory ring."""
        if not self._started:
            return
        self._started = False
        for _ in self._procs:
            self._task_q.put(None)
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._procs = []
        try:
            self._shm.close()
            self._shm.unlink()
        except FileNotFoundError:
            pass
        self._shm = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------- epoching
    def reset(self):
        if self._started and getattr(self, "_pending", 0):
            # mid-epoch reset: discard unstarted tasks, then absorb whatever
            # the workers already have in flight (count-based, so a task a
            # worker popped but hasn't finished is simply awaited)
            try:
                while True:
                    self._task_q.get_nowait()
                    self._pending -= 1
            except queue.Empty:
                pass
            while self._pending > 0:
                _, slot, _ = self._ready_q.get()
                self._free_q.put(slot)
                self._pending -= 1
        order = np.arange(len(self._files))
        if self.shuffle:
            np.random.RandomState(self.seed + self._epoch).shuffle(order)
            self._epoch += 1
        n_full = len(order) // self.batch_size
        self._tail = [] if self.drop_last \
            else order[n_full * self.batch_size:].tolist()
        if not self._started:
            self._start()
        self._pending = 0
        for b in range(n_full):
            idxs = order[b * self.batch_size:(b + 1) * self.batch_size]
            self._task_q.put((b, idxs.tolist(),
                              self._label_idx[idxs].tolist()))
            self._pending += 1
        self._tail_done = False

    def hasNext(self):
        return self._pending > 0 or (bool(self._tail) and not self._tail_done)

    def next(self) -> DataSet:
        if self._pending > 0:
            batch_id, slot, labels = self._ready_q.get()
            self._pending -= 1
            # one host memcpy out of the ring; the slot is immediately
            # reusable, and jax.device_put on the copy overlaps with the
            # next decode
            feats = np.array(self._ring[slot], copy=True)
            self._free_q.put(slot)
        else:
            self._tail_done = True
            idxs = self._tail
            feats = np.empty((len(idxs), self.channels, self.height,
                              self.width), self.np_dtype)
            rng = np.random.RandomState(self.seed - 1)
            for row, i in enumerate(idxs):
                img = _decode_one(self._files[i], self.height, self.width,
                                  self.channels)
                if self.transform is not None:
                    img = np.clip(self.transform.transform(
                        img.astype(np.float32), rng), 0, 255)
                feats[row] = img
            labels = self._label_idx[idxs].tolist()
        y = np.eye(len(self.labels), dtype=np.float32)[
            np.asarray(labels, np.int64)]
        return self._apply_pre(DataSet(feats, y))

    # ------------------------------------------------------------- metadata
    def batch(self):
        return self.batch_size

    def totalOutcomes(self):
        return len(self.labels)

    def inputColumns(self):
        return self.channels * self.height * self.width
