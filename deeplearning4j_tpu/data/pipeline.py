"""Staged, composable host input pipeline: decode in worker processes,
megabatch staging through a shared-memory ring, one uint8 H2D transfer
per dispatch.

Reference parity: the reference feeds its training loops through
``ImageRecordReader -> RecordReaderDataSetIterator -> AsyncDataSetIterator``
with JavaCV decoding on host threads (SURVEY.md §3.1 input pipeline;
§7 hard-part #5 "prove the host can feed the chip"). The TPU-native
re-design composes the pipeline out of independent stages the way
``tf.data`` does (Abadi et al., 2016: composable, independently-parallel
input stages with prefetch so host work fully overlaps device compute):

    list -> shuffle -> interleave -> decode(workers) -> batch
         -> stage(K) -> prefetch

- **list / shuffle / interleave** are order stages: enumerate files,
  seeded per-epoch permutation, round-robin interleave across shards so
  consecutive batches mix directories even without a full shuffle.
- **decode(workers)** is a multi-process stage: Python decode (cv2/PIL)
  holds the GIL for numpy conversion, so real parallelism needs
  processes. Decoded pixels land straight in a preallocated
  ``multiprocessing.shared_memory`` ring slot — no pickling, no
  per-image allocation.
- **batch + stage(K)** are fused into the ring geometry: each ring slot
  is one *megabatch* ``[K, B, C, H, W]``; workers decode the K
  sub-batches of a slot in parallel (any worker takes any sub-batch)
  and the consumer ships the completed slot as ONE contiguous uint8
  transfer per ``fit(steps_per_dispatch=K)`` dispatch — K ring copies +
  K float device_puts collapse into one copy + one uint8 put (~4xK
  fewer H2D bytes-trips than per-batch float staging).
- **prefetch** bounds how many megabatches the decode pool may run
  ahead (the ring depth); ``DevicePrefetcher`` then double-buffers the
  actual ``device_put`` behind compute.

Batches are uint8 NCHW by default; the cast to the compute dtype — and,
with :class:`~deeplearning4j_tpu.nn.augment.DeviceAugmentation`, the
crop/flip/normalize augmentation — happens ON DEVICE inside the jitted
train step, so the host ships 1/4 the bytes and never pays a float
conversion or an augment pass.

Every ring batch has the same shape, so the train step compiles exactly
once; tail files that do not fill a batch are dropped by default or
folded into a final host-decoded partial batch (``drop_last=False``).

Observability (all under ``instrumentation_active()``):

- ``dl4j_pipeline_stage_seconds{stage=...}`` — work time per stage
  (``shuffle`` order build, ``decode`` per sub-batch, ``stage``
  ring-to-contiguous copy, ``tail`` host decode).
- ``dl4j_pipeline_stall_seconds{stage=...}`` — blocked time: ``consume``
  = the consumer waiting on decode, ``decode_idle`` = workers starved
  for tasks (ring full / consumer slow).
- ``dl4j_pipeline_queue_depth{stage=...}`` — ``ready`` megabatches
  decoded but not yet consumed, ``tasks`` sub-batches queued.
- ``dl4j_pipeline_h2d_bytes_total`` — bytes handed to device staging.

Throughput model (documented for the bench): sustained img/s =
min(workers x per-core decode rate, H2D rate / image bytes, device step
rate). The ``DL4J-W108`` lint (analysis/pipeline.py) checks this
statically from a declared pipeline spec.

Worker liveness: every blocking wait on the decode pool polls worker
processes; a dead worker raises a structured :class:`DataPipelineError`
naming it instead of hanging forever. ``reset()`` after such an error
rebuilds the pool.
"""

from __future__ import annotations

import atexit
import os
import queue
import time
import uuid
from multiprocessing import get_context
from multiprocessing import shared_memory as _shm
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.profiler.locks import InstrumentedRLock
from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.data.image import (ImageTransform, NativeImageLoader,
                                           ParentPathLabelGenerator,
                                           _list_images)

_REG = _prof.get_registry()
_STAGE_SECONDS = _REG.histogram(
    "dl4j_pipeline_stage_seconds",
    "Work time per input-pipeline stage (decode = one sub-batch in a "
    "worker process, stage = ring-to-contiguous megabatch copy)",
    labelnames=("stage",))
_STALL_SECONDS = _REG.counter(
    "dl4j_pipeline_stall_seconds",
    "Seconds a pipeline stage spent blocked: consume = the training "
    "thread waiting on decode output, decode_idle = decode workers "
    "starved for tasks (ring full or consumer slow)",
    labelnames=("stage",))
_QUEUE_DEPTH = _REG.gauge(
    "dl4j_pipeline_queue_depth",
    "Input-pipeline queue depths: ready = decoded megabatches awaiting "
    "the consumer, tasks = sub-batches queued for the decode pool",
    labelnames=("stage",))
_H2D_BYTES = _REG.counter(
    "dl4j_pipeline_h2d_bytes_total",
    "Bytes the staged pipeline handed to device staging (uint8 megabatch "
    "payloads; the H2D bill of the input path)")


class DataPipelineError(IOError):
    """A structural input-pipeline failure: a decode worker process died
    (OOM-killed, segfaulted native decoder) or reported a decode error.
    NOT transient (``is_transient_error`` -> False): the retry loops in
    data/dataset.py must not re-pull — the pool needs a ``reset()`` (which
    rebuilds dead workers) or a fix to the offending file."""

    transient = False


def _decode_one(path: str, height: int, width: int, channels: int
                ) -> np.ndarray:
    """Decode + resize one file to CHW uint8. cv2 (libjpeg-turbo) when
    available — ~1.5x PIL on the same core — else PIL."""
    try:
        import cv2
        flag = cv2.IMREAD_GRAYSCALE if channels == 1 else cv2.IMREAD_COLOR
        img = cv2.imread(path, flag)
        if img is None:
            raise ValueError(f"cv2 failed to decode {path}")
        if img.shape[:2] != (height, width):
            img = cv2.resize(img, (width, height),
                             interpolation=cv2.INTER_LINEAR)
        if channels == 1:
            img = img[:, :, None]
        else:
            img = img[:, :, ::-1]                    # BGR -> RGB (PIL parity)
        return np.ascontiguousarray(np.transpose(img, (2, 0, 1)))
    except ImportError:
        from PIL import Image
        img = Image.open(path).convert("L" if channels == 1 else "RGB")
        if img.size != (width, height):
            img = img.resize((width, height), Image.BILINEAR)
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, (2, 0, 1))


def _worker_main(shm_name: str, ring_shape, slot_dtype: str,
                 files: List[str], hw, task_q, ready_q,
                 transform_bytes: Optional[bytes]):
    """Decode-worker loop: pull a sub-batch task ``(mega_id, k, slot,
    idxs, task_seed)``, decode into ``ring[slot][k]``, report
    ``("ok", mega_id, k, slot, decode_s, idle_s)`` (or ``("error", ...,
    message)`` — a decode failure must surface on the consumer, not kill
    the worker silently). Runs until the ``None`` sentinel. The
    augmentation RNG is seeded per TASK, not per worker, so transform
    content is deterministic regardless of which worker wins the task."""
    try:
        import cv2
        cv2.setNumThreads(1)        # one decode stream per worker process
    except ImportError:
        pass
    height, width, channels = hw
    transform = None
    if transform_bytes is not None:
        import pickle
        transform = pickle.loads(transform_bytes)
    # the parent owns the ring; this process must not register (and later
    # unlink) it with the shared resource tracker — Python <3.13 has no
    # track=False, so stub the register call around the attach
    from multiprocessing import resource_tracker
    _orig_register = resource_tracker.register
    resource_tracker.register = lambda *a, **kw: None
    try:
        shm = _shm.SharedMemory(name=shm_name)
    finally:
        resource_tracker.register = _orig_register
    ring = np.ndarray(tuple(ring_shape), dtype=np.dtype(slot_dtype),
                      buffer=shm.buf)
    try:
        while True:
            t_idle = time.perf_counter()
            task = task_q.get()
            if task is None:
                break
            idle_s = time.perf_counter() - t_idle
            mega_id, k, slot, idxs, task_seed = task
            t0 = time.perf_counter()
            try:
                rng = np.random.RandomState(task_seed) \
                    if transform is not None else None
                buf = ring[slot][k]
                for row, i in enumerate(idxs):
                    img = _decode_one(files[i], height, width, channels)
                    if transform is not None:
                        img = transform.transform(img.astype(np.float32), rng)
                        img = np.clip(img, 0, 255)
                    buf[row] = img      # implicit cast to the slot dtype
            except BaseException as e:
                ready_q.put(("error", mega_id, k, slot,
                             f"{type(e).__name__}: {e}"))
            else:
                ready_q.put(("ok", mega_id, k, slot,
                             time.perf_counter() - t0, idle_s))
    finally:
        shm.close()


# --------------------------------------------------------------------- stages
class Stage:
    """One declarative pipeline stage: a name plus its parameters.
    Stages carry no runtime state — :meth:`ImagePipeline.build` compiles
    the stage list into a :class:`StagedImageIterator` (the way a tf.data
    graph compiles into its iterator)."""

    name = "stage"

    def __init__(self, **params):
        self.params = dict(params)

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in sorted(self.params.items())
                          if v is not None)
        return f"{self.name}({inner})"


class ListStage(Stage):
    name = "list"


class ShuffleStage(Stage):
    name = "shuffle"


class InterleaveStage(Stage):
    name = "interleave"


class DecodeStage(Stage):
    name = "decode"


class BatchStage(Stage):
    name = "batch"


class MegabatchStage(Stage):
    name = "stage"


class PrefetchStage(Stage):
    name = "prefetch"


class ImagePipeline:
    """Composable builder for the staged image input pipeline::

        it = (ImagePipeline.list("/data/train")
              .shuffle(seed=7)
              .interleave(shards=4)
              .decode(height=224, width=224, workers=8)
              .batch(256)
              .stage(steps_per_dispatch=4)
              .prefetch(4)
              .build())
        net.fit(it, epochs=5, steps_per_dispatch=4)

    Stages may be declared in any order after :meth:`list`; ``decode``
    and ``batch`` are required, the rest are optional. ``describe()``
    returns the declared stage graph; ``build()`` compiles it into a
    :class:`StagedImageIterator`. :class:`MultiWorkerImageIterator` is a
    one-call preset over exactly these stages."""

    def __init__(self):
        self._list: Optional[ListStage] = None
        self._shuffle: Optional[ShuffleStage] = None
        self._interleave: Optional[InterleaveStage] = None
        self._decode: Optional[DecodeStage] = None
        self._batch: Optional[BatchStage] = None
        self._stage: Optional[MegabatchStage] = None
        self._prefetch: Optional[PrefetchStage] = None

    # -- construction ---------------------------------------------------
    @staticmethod
    def list(root: Optional[str] = None, files: Optional[Sequence[str]] = None,
             label_generator=None) -> "ImagePipeline":
        """Source stage: a directory of class-directories, or an explicit
        file list. Labels come from ``label_generator`` (default: parent
        directory name)."""
        p = ImagePipeline()
        p._list = ListStage(root=root, files=list(files) if files else None,
                            label_generator=label_generator)
        return p

    def shuffle(self, seed: int = 12345) -> "ImagePipeline":
        """Seeded per-epoch permutation of the file order (epoch e draws
        from ``seed + e`` — rebuildable exactly by ``seek()``)."""
        self._shuffle = ShuffleStage(seed=int(seed))
        return self

    def interleave(self, shards: int) -> "ImagePipeline":
        """Round-robin interleave across ``shards`` contiguous slices of
        the (possibly shuffled) file order, so consecutive batches mix
        directories even without a full shuffle (tf.data interleave)."""
        if int(shards) < 1:
            raise ValueError("interleave shards must be >= 1")
        self._interleave = InterleaveStage(shards=int(shards))
        return self

    def decode(self, height: int, width: int, channels: int = 3,
               workers: Optional[int] = None,
               transform: Optional[ImageTransform] = None,
               dtype: str = "uint8") -> "ImagePipeline":
        """Multi-process decode (+ optional host-side ``transform``) to
        fixed ``[C, height, width]`` pixels. ``dtype="uint8"`` (default)
        ships bytes and casts/augments on device; ``"float32"`` opts back
        into host floats for nets needing pre-normalized input."""
        self._decode = DecodeStage(height=int(height), width=int(width),
                                   channels=int(channels), workers=workers,
                                   transform=transform, dtype=dtype)
        return self

    def batch(self, batch_size: int, drop_last: bool = True) -> "ImagePipeline":
        self._batch = BatchStage(batch_size=int(batch_size),
                                 drop_last=bool(drop_last))
        return self

    def stage(self, steps_per_dispatch: int) -> "ImagePipeline":
        """Megabatch staging: group K batches into one contiguous
        ``[K, B, C, H, W]`` buffer shipped as ONE uint8 H2D transfer per
        ``fit(steps_per_dispatch=K)`` dispatch."""
        if int(steps_per_dispatch) < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        self._stage = MegabatchStage(steps_per_dispatch=int(steps_per_dispatch))
        return self

    def prefetch(self, depth: int) -> "ImagePipeline":
        """Ring depth: how many megabatches the decode pool may run ahead
        of the consumer (default ``2*workers/K + 2``-ish)."""
        if int(depth) < 1:
            raise ValueError("prefetch depth must be >= 1")
        self._prefetch = PrefetchStage(depth=int(depth))
        return self

    def describe(self) -> List[Stage]:
        """The declared stage graph, in execution order."""
        return [s for s in (self._list, self._shuffle, self._interleave,
                            self._decode, self._batch, self._stage,
                            self._prefetch) if s is not None]

    def build(self, seed: int = 12345,
              start_method: str = "spawn") -> "StagedImageIterator":
        if self._list is None or self._decode is None or self._batch is None:
            raise ValueError("an ImagePipeline needs at least "
                             "list().decode(...).batch(...) stages")
        d, b = self._decode.params, self._batch.params
        return StagedImageIterator(
            root=self._list.params["root"], files=self._list.params["files"],
            label_generator=self._list.params["label_generator"],
            height=d["height"], width=d["width"], channels=d["channels"],
            workers=d["workers"], transform=d["transform"], dtype=d["dtype"],
            batch_size=b["batch_size"], drop_last=b["drop_last"],
            steps_per_dispatch=(self._stage.params["steps_per_dispatch"]
                                if self._stage else 1),
            n_slots=(self._prefetch.params["depth"] if self._prefetch
                     else None),
            shuffle=self._shuffle is not None,
            seed=(self._shuffle.params["seed"] if self._shuffle else seed),
            interleave=(self._interleave.params["shards"]
                        if self._interleave else 1),
            start_method=start_method)


# -------------------------------------------------------------------- runtime
class StagedImageIterator(DataSetIterator):
    """Runtime of the staged pipeline (build via :class:`ImagePipeline`
    or the :class:`MultiWorkerImageIterator` preset).

    Ring geometry: the shared-memory ring holds ``n_slots`` megaslots of
    ``[K, B, C, H, W]``; a *task* is one sub-batch ``(mega_id, k)`` and
    any worker may take any task, so the K sub-batches of a megabatch
    decode in parallel. Megabatches are emitted IN ORDER (a small
    reorder buffer absorbs out-of-order completions), which makes epoch
    content deterministic and ``cursor()``/``seek()`` exact.

    ``next()`` yields per-batch uint8 NCHW DataSets;
    ``dispatch_stream()`` yields whole
    :class:`~deeplearning4j_tpu.train.stepping.MegaBatch` items for
    ``fit(steps_per_dispatch=K)`` — the fit loops use it automatically
    when K matches :attr:`megabatch_steps`.

    Worker processes use the ``spawn`` start method: this process
    typically holds a live TPU client, and forking a process with an
    initialized accelerator runtime is undefined behaviour.
    """

    def __init__(self, root: Optional[str] = None,
                 height: int = 224, width: int = 224, channels: int = 3,
                 batch_size: int = 32, workers: Optional[int] = None,
                 n_slots: Optional[int] = None, dtype: str = "uint8",
                 transform: Optional[ImageTransform] = None,
                 label_generator=None, shuffle: bool = False,
                 drop_last: bool = True, seed: int = 12345,
                 files: Optional[Sequence[str]] = None,
                 steps_per_dispatch: int = 1, interleave: int = 1,
                 start_method: str = "spawn",
                 liveness_poll: float = 0.5):
        self.height, self.width, self.channels = height, width, channels
        self.batch_size = int(batch_size)
        self.workers = max(1, workers if workers is not None
                           else (os.cpu_count() or 1))
        self.megabatch_steps = max(1, int(steps_per_dispatch))
        k = self.megabatch_steps
        # enough outstanding sub-batch tasks to keep every worker busy
        # plus a double buffer, in units of megaslots
        self.n_slots = int(n_slots) if n_slots is not None \
            else max(2, -(-(2 * self.workers + 2) // k))
        self.np_dtype = np.dtype({"uint8": np.uint8,
                                  "float32": np.float32}[dtype])
        self.transform = transform
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.interleave_shards = max(1, int(interleave))
        self.liveness_poll = float(liveness_poll)
        self._label_gen = label_generator or ParentPathLabelGenerator()
        self._files = list(files) if files is not None else _list_images(root)
        if not self._files:
            raise FileNotFoundError(f"no images under {root}")
        self.labels = sorted({self._label_gen.getLabelForPath(f)
                              for f in self._files})
        self._label_idx = np.asarray(
            [self.labels.index(self._label_gen.getLabelForPath(f))
             for f in self._files], np.int32)
        self._ctx = get_context(start_method)
        self._shm = None
        self._procs: List = []
        self._epoch = 0
        self._started = False
        # reset()/close() may race (a fit teardown against a lifecycle
        # hook): serialize them, and every _pending/_started update takes
        # the same (re-entrant) lock. next() stays consumer-thread-only.
        # Instrumented (PR-8 adoption sweep): held per megabatch pull, so
        # its hold histogram is the staged pipeline's consumer-side bill.
        self._lifecycle = InstrumentedRLock("staged_pipeline_lifecycle")
        self._loader = NativeImageLoader(height, width, channels)
        self._pending = 0
        self._failed = None     # latched DataPipelineError (decode failure)
        atexit.register(self.close)
        self.reset()

    # ------------------------------------------------------------ lifecycle
    def _start(self):
        with self._lifecycle:   # re-entrant: reset()/seek() hold it
            k = self.megabatch_steps
            slot_shape = (k, self.batch_size, self.channels, self.height,
                          self.width)
            ring_shape = (self.n_slots,) + slot_shape
            slot_bytes = int(np.prod(slot_shape)) * self.np_dtype.itemsize
            self._shm = _shm.SharedMemory(
                create=True, size=self.n_slots * slot_bytes,
                name=f"dl4jtpu_{uuid.uuid4().hex[:12]}")
            self._ring = np.ndarray(ring_shape, dtype=self.np_dtype,
                                    buffer=self._shm.buf)
            self._task_q = self._ctx.Queue()
            self._ready_q = self._ctx.Queue()
            tbytes = None
            if self.transform is not None:
                import pickle
                tbytes = pickle.dumps(self.transform)
            # decode workers must NOT initialize an accelerator backend: spawn
            # re-runs sitecustomize in each child, and a TPU bootstrap there
            # would fight the parent for the chip. Pin the children to CPU and
            # strip the TPU bootstrap trigger for the duration of the spawn.
            saved = {k: os.environ.get(k)
                     for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")}
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                for _ in range(self.workers):
                    p = self._ctx.Process(
                        target=_worker_main,
                        args=(self._shm.name, ring_shape, self.np_dtype.str,
                              self._files,
                              (self.height, self.width, self.channels),
                              self._task_q, self._ready_q, tbytes),
                        daemon=True)
                    p.start()
                    self._procs.append(p)
            finally:
                for key, v in saved.items():
                    if v is None:
                        os.environ.pop(key, None)
                    else:
                        os.environ[key] = v
            self._started = True
            self._pending = 0

    def close(self):
        """Stop workers and release the shared-memory ring. Idempotent;
        safe against a concurrent ``reset()``."""
        with self._lifecycle:
            self._close_locked()

    def _close_locked(self):
        with self._lifecycle:           # re-entrant: close()/reset() hold it
            if not self._started:
                return
            self._started = False
            for _ in self._procs:
                try:
                    self._task_q.put(None)
                except (ValueError, OSError):
                    break               # queue already torn down
            for p in self._procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()
            self._procs = []
            try:
                self._shm.close()
                self._shm.unlink()
            except FileNotFoundError:
                pass
            self._shm = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------- worker liveness
    def _dead_workers(self):
        return [(i, p) for i, p in enumerate(self._procs) if not p.is_alive()]

    def _get_ready_msg(self):
        """Bounded-timeout pull from the decode pool: every
        ``liveness_poll`` seconds of silence the worker processes are
        polled, and a dead one raises a structured
        :class:`DataPipelineError` naming it — ``next()`` must never
        block forever on a pool that can no longer produce."""
        t0 = time.perf_counter()
        try:
            while True:
                try:
                    msg = self._ready_q.get(timeout=self.liveness_poll)
                    break
                except queue.Empty:
                    dead = self._dead_workers()
                    if dead:
                        names = ", ".join(
                            f"worker {i} (pid={p.pid}, "
                            f"exitcode={p.exitcode})" for i, p in dead)
                        raise DataPipelineError(
                            f"decode worker died: {names}; "
                            f"{self._pending} sub-batch task(s) were in "
                            f"flight — reset() rebuilds the pool") from None
        finally:
            if _prof.instrumentation_active():
                _STALL_SECONDS.labels(stage="consume").inc(
                    time.perf_counter() - t0)
        if msg[0] == "error":
            _, mega_id, k, slot, err = msg
            with self._lifecycle:
                self._pending -= 1
                # latch: the errored sub-batch never completes, so a
                # retried next() would otherwise wait forever for its
                # megabatch — every later pull re-raises until reset()
                self._failed = DataPipelineError(
                    f"decode failed for sub-batch {k} of megabatch "
                    f"{mega_id}: {err}")
            raise self._failed
        return msg

    # ------------------------------------------------------------- epoching
    def reset(self):
        with self._lifecycle:
            if self._started and self._dead_workers():
                # a dead pool cannot drain: rebuild it wholesale
                self._close_locked()
            if self._started and self._pending:
                self._drain_locked()
            t0 = time.perf_counter()
            if self.shuffle:
                order = np.random.RandomState(
                    self.seed + self._epoch).permutation(len(self._files))
                self._epoch += 1
            else:
                order = np.arange(len(self._files))
            if _prof.instrumentation_active():
                # order build only — _setup_epoch may spawn the worker
                # pool, which must not bill the shuffle stage
                _STAGE_SECONDS.labels(stage="shuffle").observe(
                    time.perf_counter() - t0)
            self._setup_epoch(order, start_batch=0)

    def _drain_locked(self):
        """Mid-epoch drain: discard unstarted tasks, then absorb whatever
        the workers already have in flight (count-based, so a task a
        worker popped but hasn't finished is simply awaited)."""
        with self._lifecycle:           # re-entrant: reset()/seek() hold it
            try:
                while True:
                    self._task_q.get_nowait()
                    self._pending -= 1
            except queue.Empty:
                pass
            while self._pending > 0:
                if self._dead_workers():
                    # dead worker mid-drain: its in-flight task will never
                    # complete — rebuild the pool instead of hanging
                    self._close_locked()
                    return
                try:
                    self._ready_q.get(timeout=max(self.liveness_poll, 0.05))
                except queue.Empty:
                    continue
                self._pending -= 1

    def _setup_epoch(self, order: np.ndarray, start_batch: int):
        with self._lifecycle:   # re-entrant: reset()/seek() hold it
            self._failed = None     # fresh epoch clears the error latch
            if self.interleave_shards > 1:
                shards = np.array_split(order, self.interleave_shards)
                width = max(len(s) for s in shards)
                inter = []
                for j in range(width):
                    for s in shards:
                        if j < len(s):
                            inter.append(s[j])
                order = np.asarray(inter, dtype=order.dtype)
            self._order = order
            b, k = self.batch_size, self.megabatch_steps
            self._n_full = len(order) // b
            self._tail = [] if self.drop_last \
                else order[self._n_full * b:].tolist()
            self._total_batches = self._n_full + (1 if self._tail else 0)
            self._n_megas = -(-self._n_full // k) if self._n_full else 0
            if not self._started:
                self._start()
            self._free_slots = list(range(self.n_slots))
            self._completed = {}            # mega_id -> slot (reorder buffer)
            self._done_counts = {}          # mega_id -> sub-batches finished
            self._emitted = int(start_batch)
            if start_batch >= self._n_full:     # only the tail (if any) remains
                self._emit_next = self._n_megas
                self._start_j = 0
            else:
                self._emit_next = start_batch // k
                self._start_j = start_batch - self._emit_next * k
            # exact slot-resume: a mid-group seek() decodes ONLY the
            # sub-batches at or after the resume offset — the already-
            # consumed head of the group is never re-decoded (its slot
            # rows stay stale and are never emitted: _cur_j starts at
            # _start_j)
            self._skip_j = ({self._emit_next: self._start_j}
                            if self._start_j else {})
            self._task_counts = {}      # mega_id -> tasks actually queued
            self._next_assign = self._emit_next
            self._cur = None                # current copied megabatch
            self._cur_labels = None
            self._cur_j = 0
            self._cur_r = 0
            self._pump()

    def _mega_batches(self, mega_id: int) -> int:
        """Number of full batches in megabatch ``mega_id`` (the last
        group of an epoch may hold fewer than K)."""
        k = self.megabatch_steps
        return min(self._n_full - mega_id * k, k)

    def _pump(self):
        """Assign megabatches to free ring slots and enqueue their
        sub-batch decode tasks — the consumer-side feeder that bounds
        decode run-ahead to the ring depth."""
        b, k = self.batch_size, self.megabatch_steps
        with self._lifecycle:
            while self._free_slots and self._next_assign < self._n_megas:
                mega_id = self._next_assign
                slot = self._free_slots.pop()
                n_tasks = 0
                for j in range(self._skip_j.get(mega_id, 0),
                               self._mega_batches(mega_id)):
                    batch = mega_id * k + j
                    idxs = self._order[batch * b:(batch + 1) * b]
                    task_seed = (self.seed + 104729 * self._epoch + batch) \
                        % (2 ** 31)
                    self._task_q.put((mega_id, j, slot, idxs.tolist(),
                                      task_seed))
                    self._pending += 1
                    n_tasks += 1
                self._task_counts[mega_id] = n_tasks
                self._next_assign += 1
        if _prof.instrumentation_active():
            try:
                self._set_depth_gauges()
            except NotImplementedError:     # qsize on platforms without it
                pass

    def _set_depth_gauges(self):
        _QUEUE_DEPTH.labels(stage="ready").set(len(self._completed))
        _QUEUE_DEPTH.labels(stage="tasks").set(self._task_q.qsize())

    def _collect_until(self, mega_id: int) -> int:
        """Pull ready messages until ``mega_id`` is fully decoded; returns
        its slot. Out-of-order completions park in the reorder buffer.
        Holds the lifecycle lock (re-entrant; the consumer path owns it
        for the duration of a pull — a racing close()/reset() waits for
        the in-flight pull instead of tearing the ring down under it)."""
        active = _prof.instrumentation_active()
        with self._lifecycle:
            if self._failed is not None:
                raise self._failed      # see _get_ready_msg's error latch
            expected = self._task_counts.get(mega_id,
                                             self._mega_batches(mega_id))
            while mega_id not in self._completed \
                    or self._done_counts.get(mega_id, 0) < expected:
                _, mid, k, slot, decode_s, idle_s = self._get_ready_msg()
                self._pending -= 1
                self._completed[mid] = slot
                self._done_counts[mid] = self._done_counts.get(mid, 0) + 1
                if active:
                    _STAGE_SECONDS.labels(stage="decode").observe(decode_s)
                    if idle_s > 0:
                        _STALL_SECONDS.labels(stage="decode_idle").inc(idle_s)
            self._done_counts.pop(mega_id)
            self._task_counts.pop(mega_id, None)
            return self._completed.pop(mega_id)

    # -------------------------------------------------------------- consume
    def hasNext(self):
        return self._emitted < self._total_batches

    def _onehot(self, idx: np.ndarray) -> np.ndarray:
        return np.eye(len(self.labels), dtype=np.float32)[
            np.asarray(idx, np.int64)]

    def _load_group(self):
        """Copy the next in-order megabatch out of the ring into a
        contiguous host buffer (ONE memcpy; the slot is immediately
        reusable) and refill the decode pool."""
        with self._lifecycle:
            mega_id = self._emit_next
            r = self._mega_batches(mega_id)
            slot = self._collect_until(mega_id)
            t0 = time.perf_counter()
            self._cur = np.array(self._ring[slot][:r], copy=True)
            if _prof.instrumentation_active():
                _STAGE_SECONDS.labels(stage="stage").observe(
                    time.perf_counter() - t0)
            b, k = self.batch_size, self.megabatch_steps
            lab = self._label_idx[
                self._order[mega_id * k * b:(mega_id * k + r) * b]]
            self._cur_labels = self._onehot(lab).reshape(
                r, b, len(self.labels))
            self._cur_j = self._start_j
            self._start_j = 0
            self._cur_r = r
            self._emit_next += 1
            self._free_slots.append(slot)
            self._pump()

    def _next_tail(self) -> DataSet:
        """Host-decoded partial final batch (``drop_last=False``)."""
        with self._lifecycle:
            t0 = time.perf_counter()
            idxs = self._tail
            feats = np.empty((len(idxs), self.channels, self.height,
                              self.width), self.np_dtype)
            rng = np.random.RandomState(self.seed - 1)
            for row, i in enumerate(idxs):
                img = _decode_one(self._files[i], self.height, self.width,
                                  self.channels)
                if self.transform is not None:
                    img = np.clip(self.transform.transform(
                        img.astype(np.float32), rng), 0, 255)
                feats[row] = img
            self._emitted += 1
            if _prof.instrumentation_active():
                _STAGE_SECONDS.labels(stage="tail").observe(
                    time.perf_counter() - t0)
                _H2D_BYTES.inc(feats.nbytes)
            y = self._onehot(self._label_idx[np.asarray(idxs, np.int64)]) \
                if idxs else np.zeros((0, len(self.labels)), np.float32)
            return self._apply_pre(DataSet(feats, y))

    def next(self) -> DataSet:
        with self._lifecycle:
            if self._emitted >= self._n_full:
                if not self._tail or self._emitted >= self._total_batches:
                    raise StopIteration
                return self._next_tail()
            if self._cur is None or self._cur_j >= self._cur_r:
                self._load_group()
            j = self._cur_j
            self._cur_j += 1
            self._emitted += 1
            feats, y = self._cur[j], self._cur_labels[j]
            if _prof.instrumentation_active():
                _H2D_BYTES.inc(feats.nbytes)
            if self._cur_j >= self._cur_r:
                self._cur = None        # buffer handed out; drop our ref
            return self._apply_pre(DataSet(feats, y))

    def _next_mega(self):
        """One full-K MegaBatch if the position allows it, else None
        (the caller falls back to a per-batch ``next()``)."""
        from deeplearning4j_tpu.train.stepping import MegaBatch
        k = self.megabatch_steps
        with self._lifecycle:
            if not (k > 1 and self._cur is None
                    and self._emitted < self._n_full
                    and self._emit_next < self._n_megas
                    and self._mega_batches(self._emit_next) == k
                    and self._start_j == 0
                    # a seek-resumed group decoded only its tail: rows
                    # below the skip offset are stale — per-batch path
                    and self._skip_j.get(self._emit_next, 0) == 0):
                return None
            self._load_group()
            # the preconditions above guarantee a full, unoffset group
            assert self._cur_r == k and self._cur_j == 0
            mb = MegaBatch()
            mb.multi = False
            mb.steps = k
            mb.features = self._cur
            mb.labels = self._cur_labels
            mb.features_mask = None
            mb.labels_mask = None
            self._cur = None
            self._cur_labels = None
            self._emitted += k
            if _prof.instrumentation_active():
                _H2D_BYTES.inc(mb.features.nbytes)
            return mb

    def dispatch_stream(self):
        """Yield the epoch as dispatch-ready items: one
        :class:`~deeplearning4j_tpu.train.stepping.MegaBatch` per full
        K-group (features = the contiguous ``[K, B, C, H, W]`` staging
        buffer — no re-stack) and plain DataSets for the partial final
        group / host-decoded tail. The fit loops consume this stream
        when ``steps_per_dispatch`` matches :attr:`megabatch_steps`
        (preprocessors force the per-batch path — set them on the
        device-augment or host-transform seams instead). The lifecycle
        lock is never held across a yield."""
        while self.hasNext():
            mb = self._next_mega()
            yield mb if mb is not None else self.next()

    # ------------------------------------------------- cursor/seek protocol
    def cursor(self):
        """Exact position: batches emitted this epoch + the epoch counter
        (enough to rebuild the seeded shuffle order, exactly like
        ``ListDataSetIterator``) — megabatch emission is in-order, so the
        count is exact even under multi-process decode."""
        return {"batch": int(self._emitted), "epoch": int(self._epoch)}

    def seek(self, cursor) -> None:
        """Restore a :meth:`cursor` position: drain in-flight decode,
        rebuild the epoch order for the stored epoch (``reset()`` drew it
        from ``seed + epoch`` THEN incremented, so epoch e's order came
        from ``seed + e - 1``), and resume task assignment mid-epoch.
        A mid-group position is an EXACT slot resume: only the group's
        remaining sub-batches (j >= the resume offset) are decoded —
        the already-consumed head is never re-decoded."""
        epoch = int(cursor["epoch"])
        with self._lifecycle:
            if self._started and self._dead_workers():
                self._close_locked()
            if self._started and self._pending:
                self._drain_locked()
            if self.shuffle:
                order = np.random.RandomState(
                    self.seed + max(epoch - 1, 0)).permutation(
                    len(self._files))
            else:
                order = np.arange(len(self._files))
            self._epoch = epoch
            self._setup_epoch(order, start_batch=int(cursor["batch"]))

    # ------------------------------------------------------------- metadata
    def batch(self):
        return self.batch_size

    def totalOutcomes(self):
        return len(self.labels)

    def inputColumns(self):
        return self.channels * self.height * self.width


class MultiWorkerImageIterator(StagedImageIterator):
    """Directory-of-class-directories preset over the staged pipeline
    (ref: ImageRecordReader + RecordReaderDataSetIterator +
    AsyncDataSetIterator, collapsed into the one seam that matters for
    feeding a TPU): ``list -> [shuffle] -> decode(workers) -> batch ->
    stage(steps_per_dispatch) -> prefetch(n_slots)`` with the historical
    constructor signature. Equivalent to building the same stages by
    hand with :class:`ImagePipeline`."""

    def __init__(self, root: str, height: int, width: int, channels: int = 3,
                 batch_size: int = 32, workers: Optional[int] = None,
                 n_slots: Optional[int] = None, dtype: str = "uint8",
                 transform: Optional[ImageTransform] = None,
                 label_generator=None, shuffle: bool = False,
                 drop_last: bool = True, seed: int = 12345,
                 files: Optional[Sequence[str]] = None,
                 start_method: str = "spawn", steps_per_dispatch: int = 1,
                 interleave: int = 1, liveness_poll: float = 0.5):
        super().__init__(
            root=root, height=height, width=width, channels=channels,
            batch_size=batch_size, workers=workers, n_slots=n_slots,
            dtype=dtype, transform=transform,
            label_generator=label_generator, shuffle=shuffle,
            drop_last=drop_last, seed=seed, files=files,
            steps_per_dispatch=steps_per_dispatch, interleave=interleave,
            start_method=start_method, liveness_poll=liveness_poll)
