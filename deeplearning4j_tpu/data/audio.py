"""DataVec audio pipeline: WAV loading + spectrogram/mel/MFCC features.

Reference parity: ``datavec-data-audio`` (``WavFileRecordReader``,
``AudioRecordReader`` with windowed FFT features — SURVEY.md §2.2
"DataVec image/audio"). Decode AND feature extraction are HOST-side
numpy, like the image pipeline: ETL feeding a tunneled/remote device must
not issue per-file eager device ops (a 40-filter eager loop per file per
epoch costs thousands of dispatch round-trips).
"""

from __future__ import annotations

import os
import wave
from typing import List, Optional, Tuple

import functools

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.data.records import RecordReader


def read_wav(path: str) -> Tuple[np.ndarray, int]:
    """WAV file -> (float32 samples in [-1, 1] shaped [T] or [T, C], rate).
    Supports 8/16/32-bit PCM (ref: WavFileLoader)."""
    with wave.open(path, "rb") as w:
        rate = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(n)
    if width == 2:
        x = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
    elif width == 1:
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if ch > 1:
        x = x.reshape(-1, ch)
    return x, rate


def write_wav(path: str, samples: np.ndarray, rate: int):
    """float [-1, 1] -> 16-bit PCM WAV (test fixture / export helper)."""
    s = np.clip(np.asarray(samples), -1.0, 1.0)
    pcm = (s * 32767.0).astype(np.int16)
    ch = 1 if pcm.ndim == 1 else pcm.shape[1]
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with wave.open(path, "wb") as w:
        w.setnchannels(ch)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())


# ----------------------------------------------------------------- features

def frame_signal(x, frame_length: int, hop: int):
    """[T] -> [n_frames, frame_length] (drops the tail remainder)."""
    x = np.asarray(x)
    n = 1 + (x.shape[0] - frame_length) // hop if x.shape[0] >= frame_length \
        else 0
    idx = (np.arange(n)[:, None] * hop + np.arange(frame_length)[None, :])
    return x[idx]


def spectrogram(x, frame_length: int = 256, hop: int = 128,
                window: str = "hann"):
    """Magnitude STFT [n_frames, frame_length//2 + 1]; multi-channel
    input is downmixed to mono first."""
    x = np.asarray(x)
    if x.ndim > 1:
        x = x.mean(axis=1)
    frames = frame_signal(x, frame_length, hop)
    if window == "hann":
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(frame_length)
                               / frame_length)
        frames = frames * w
    return np.abs(np.fft.rfft(frames, axis=-1))


def _hz_to_mel(f):
    return 2595.0 * np.log10(1.0 + f / 700.0)


def _mel_to_hz(m):
    return 700.0 * (10.0 ** (m / 2595.0) - 1.0)


@functools.lru_cache(maxsize=16)
def mel_filterbank(n_mels: int, n_fft: int, rate: int,
                   fmin: float = 0.0, fmax: Optional[float] = None):
    """[n_mels, n_fft//2 + 1] triangular filters (HTK-style mel scale).

    Triangles are evaluated on CONTINUOUS bin-center frequencies (not
    floored bin indices), so no filter degenerates to all-zero even when
    adjacent mel points fall inside one FFT bin (e.g. n_mels=40,
    n_fft=256 at 16 kHz). Cached per configuration; returned read-only.
    """
    fmax = fmax if fmax is not None else rate / 2.0
    n_bins = n_fft // 2 + 1
    hz_pts = _mel_to_hz(np.linspace(_hz_to_mel(fmin), _hz_to_mel(fmax),
                                    n_mels + 2))
    bin_freqs = np.arange(n_bins)[None, :] * (rate / n_fft)
    lo = hz_pts[:-2, None]
    c = hz_pts[1:-1, None]
    hi = hz_pts[2:, None]
    up = (bin_freqs - lo) / np.maximum(c - lo, 1e-6)
    down = (hi - bin_freqs) / np.maximum(hi - c, 1e-6)
    fb = np.clip(np.minimum(up, down), 0.0, 1.0)
    # guarantee support: the peak bin of a narrow filter gets weight 1
    peak = np.clip(np.round(c[:, 0] * n_fft / rate).astype(np.int64),
                   0, n_bins - 1)
    fb[np.arange(n_mels), peak] = np.maximum(fb[np.arange(n_mels), peak],
                                             1.0)
    fb.setflags(write=False)
    return fb


def mel_spectrogram(x, rate: int, n_mels: int = 40, frame_length: int = 256,
                    hop: int = 128):
    s = spectrogram(x, frame_length, hop)
    fb = mel_filterbank(n_mels, frame_length, rate)
    return (s ** 2) @ fb.T


@functools.lru_cache(maxsize=16)
def _dct_ii(n_out: int, n_in: int):
    k = np.arange(n_out)[:, None]
    i = np.arange(n_in)[None, :]
    m = np.cos(np.pi * k * (2 * i + 1) / (2 * n_in)) * np.sqrt(2.0 / n_in)
    m.setflags(write=False)   # cached: callers must not mutate
    return m


def mfcc(x, rate: int, n_mfcc: int = 13, n_mels: int = 40,
         frame_length: int = 256, hop: int = 128):
    """[n_frames, n_mfcc] mel-frequency cepstral coefficients."""
    m = mel_spectrogram(x, rate, n_mels, frame_length, hop)
    logm = np.log(np.maximum(m, 1e-10))
    return logm @ _dct_ii(n_mfcc, n_mels).T


# ------------------------------------------------------------------ readers

class WavFileRecordReader(RecordReader):
    """Directory-of-class-directories WAV reader (ref: datavec-data-audio
    WavFileRecordReader + ParentPathLabelGenerator labels); records are
    [feature ndarray, IntWritable(label)]."""

    def __init__(self, feature: str = "mfcc", n_frames: int = 32,
                 frame_length: int = 256, hop: int = 128, n_mfcc: int = 13,
                 n_mels: int = 40):
        self.feature = feature
        self.n_frames = n_frames
        self.frame_length = frame_length
        self.hop = hop
        self.n_mfcc = n_mfcc
        self.n_mels = n_mels
        self._files: List[str] = []
        self.labels: List[str] = []
        self._pos = 0

    def initialize(self, path: str):
        from deeplearning4j_tpu.data.image import (ParentPathLabelGenerator,
                                                   _list_files)
        out = _list_files(path, (".wav",))
        if not out:
            raise FileNotFoundError(f"no .wav files under {path}")
        self._files = out
        self._label_gen = ParentPathLabelGenerator()
        self.labels = sorted({self._label_gen.getLabelForPath(f)
                              for f in self._files})
        self._pos = 0
        return self

    def numLabels(self) -> int:
        return len(self.labels)

    def hasNext(self):
        return self._pos < len(self._files)

    def reset(self):
        self._pos = 0

    def _features(self, x: np.ndarray, rate: int) -> np.ndarray:
        if x.ndim > 1:
            x = x.mean(axis=1)                # downmix to mono
        if self.feature == "mfcc":
            f = np.asarray(mfcc(x, rate, self.n_mfcc, self.n_mels,
                                self.frame_length, self.hop))
        elif self.feature == "mel":
            f = np.asarray(mel_spectrogram(x, rate, self.n_mels,
                                           self.frame_length, self.hop))
        elif self.feature == "spectrogram":
            f = np.asarray(spectrogram(x, self.frame_length, self.hop))
        elif self.feature == "raw":
            need = self.n_frames * self.hop
            buf = np.zeros(need, np.float32)   # zero-pad/truncate like the
            n = min(len(x), need)              # other feature branches
            buf[:n] = x[:n]
            f = buf.reshape(self.n_frames, self.hop)
        else:
            raise ValueError(self.feature)
        # fix the time dimension (pad with zeros / truncate)
        if f.shape[0] < self.n_frames:
            f = np.pad(f, ((0, self.n_frames - f.shape[0]), (0, 0)))
        return f[:self.n_frames].astype(np.float32)

    def next(self):
        from deeplearning4j_tpu.data.image import NDArrayWritable
        from deeplearning4j_tpu.data.records import IntWritable
        path = self._files[self._pos]
        self._pos += 1
        x, rate = read_wav(path)
        label = self.labels.index(self._label_gen.getLabelForPath(path))
        return [NDArrayWritable(self._features(x, rate)), IntWritable(label)]


class AudioDataSetIterator(DataSetIterator):
    """WavFileRecordReader -> DataSet batches: features [N, C(=coeffs), T]
    (NCW, ready for Conv1D/RNN layers)."""

    def __init__(self, reader: WavFileRecordReader, batch_size: int):
        self.reader = reader
        self.batch_size = batch_size

    def reset(self):
        self.reader.reset()

    def hasNext(self):
        return self.reader.hasNext()

    def next(self) -> DataSet:
        feats, labels = [], []
        while self.reader.hasNext() and len(feats) < self.batch_size:
            f, l = self.reader.next()
            feats.append(f.value.T)           # [T, C] -> [C, T]
            labels.append(l.value)
        x = np.stack(feats).astype(np.float32)
        y = np.eye(self.reader.numLabels(), dtype=np.float32)[
            np.asarray(labels, np.int64)]
        return self._apply_pre(DataSet(x, y))

    def batch(self):
        return self.batch_size
