"""Built-in dataset iterators.

Reference parity: ``org.deeplearning4j.datasets.iterator.impl.{
MnistDataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
Cifar10DataSetIterator}`` (SURVEY.md §2.2 "Iterators").

This environment has zero network egress, so downloads are impossible:
- ``MnistDataSetIterator`` reads standard IDX files from
  ``DL4J_TPU_DATA_DIR`` (or ~/.deeplearning4j_tpu/mnist) when present —
  the same ubyte format the reference's fetcher caches — and otherwise
  falls back to a deterministic synthetic digit set (template digits +
  noise/shift augmentation) that is structurally MNIST-shaped
  ([N, 784] rows, 10 classes) and learnable, so training/eval pipelines
  are exercised end-to-end.
- ``IrisDataSetIterator`` embeds the canonical 150-row Fisher data
  (public domain) like the reference bundles it.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator


def _data_dir() -> str:
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.expanduser("~/.deeplearning4j_tpu"))


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_mnist(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    base = os.path.join(_data_dir(), "mnist")
    img_names = ["train-images-idx3-ubyte", "train-images.idx3-ubyte"] if train \
        else ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"]
    lab_names = ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"] if train \
        else ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"]
    for img, lab in zip(img_names, lab_names):
        for suffix in ("", ".gz"):
            ip = os.path.join(base, img + suffix)
            lp = os.path.join(base, lab + suffix)
            if os.path.exists(ip) and os.path.exists(lp):
                return _read_idx(ip), _read_idx(lp)
    return None


def _synthetic_digits(n: int, seed: int, image_hw: int = 28):
    """Deterministic learnable digit-like dataset: one blocky template per
    class, augmented with shift + noise. NOT MNIST — a stand-in where the
    real IDX files are unavailable (no egress)."""
    rng = np.random.RandomState(seed)
    tmpl_rng = np.random.RandomState(1234)  # templates fixed across splits
    templates = []
    for c in range(10):
        t = np.zeros((image_hw, image_hw), np.float32)
        cells = tmpl_rng.choice(16, size=6 + c % 4, replace=False)
        for cell in cells:
            r, cc = divmod(cell, 4)
            sz = image_hw // 4
            t[r * sz:(r + 1) * sz, cc * sz:(cc + 1) * sz] = 1.0
        templates.append(t)
    labels = rng.randint(0, 10, n)
    imgs = np.zeros((n, image_hw, image_hw), np.float32)
    for i, c in enumerate(labels):
        img = templates[c].copy()
        dx, dy = rng.randint(-2, 3, 2)
        img = np.roll(np.roll(img, dx, axis=0), dy, axis=1)
        img += 0.25 * rng.randn(image_hw, image_hw).astype(np.float32)
        imgs[i] = np.clip(img, 0, 1)
    return (imgs.reshape(n, -1) * 255).astype(np.float32), labels


class MnistDataSetIterator(ListDataSetIterator):
    """ref: MnistDataSetIterator(batch, train) — features [N, 784] float
    scaled to [0,1], labels one-hot [N, 10]."""

    def __init__(self, batch_size: int, train: bool, seed: int = 12345,
                 num_examples: int = None):
        found = _find_mnist(train)
        if found is not None:
            imgs, labels = found
            feats = imgs.reshape(imgs.shape[0], -1).astype(np.float32)
            self.synthetic = False
        else:
            n = num_examples or (6000 if train else 1000)
            feats, labels = _synthetic_digits(n, seed + (0 if train else 777))
            self.synthetic = True
        if num_examples:
            feats, labels = feats[:num_examples], labels[:num_examples]
        feats = feats / 255.0
        onehot = np.eye(10, dtype=np.float32)[labels.astype(np.int64)]
        super().__init__(DataSet(feats, onehot), batch_size,
                         shuffle=train, seed=seed)


class EmnistDataSetIterator(ListDataSetIterator):
    """ref: EmnistDataSetIterator(dataSet, batch, train) — EMNIST splits
    (LETTERS 26 classes, BALANCED 47, DIGITS 10, ...). This image has no
    egress and ships no EMNIST IDX files, so batches come from the
    deterministic synthetic class generator with the split's class count
    (``self.synthetic`` is always True here)."""

    SPLITS = {"LETTERS": 26, "BALANCED": 47, "DIGITS": 10, "MNIST": 10,
              "COMPLETE": 62, "BYCLASS": 62, "BYMERGE": 47}

    def __init__(self, data_set: str, batch_size: int, train: bool,
                 seed: int = 12345, num_examples: int = None):
        split = str(data_set).upper()
        if split not in self.SPLITS:
            raise ValueError(f"unknown EMNIST split '{data_set}' "
                             f"(one of {sorted(self.SPLITS)})")
        self.num_classes = self.SPLITS[split]
        n = num_examples or (4096 if train else 512)
        feats, labels = _synthetic_classes(
            n, self.num_classes, seed + (0 if train else 777))
        self.synthetic = True
        feats = feats / 255.0
        onehot = np.eye(self.num_classes, dtype=np.float32)[
            labels.astype(np.int64)]
        super().__init__(DataSet(feats, onehot), batch_size,
                         shuffle=train, seed=seed)


class TinyImageNetDataSetIterator(ListDataSetIterator):
    """ref: TinyImageNetDataSetIterator — 200-class 64x64 RGB. Real data
    when present under $DL4J_TPU_TINYIMAGENET_DIR (class-per-directory,
    via ImageRecordReader), else deterministic synthetic textures."""

    NUM_CLASSES = 200
    HW = 64

    def __init__(self, batch_size: int, train: bool = True,
                 seed: int = 12345, num_examples: int = None):
        import os as _os
        root = _os.environ.get("DL4J_TPU_TINYIMAGENET_DIR")
        if root and _os.path.isdir(root):
            from deeplearning4j_tpu.data.image import (
                ImageRecordReader, _list_images)
            files = _list_images(root)
            # deterministic 90/10 train/test split over a fixed
            # permutation — a sorted class-per-directory walk would give
            # train==test and class-skewed truncation otherwise
            perm = np.random.RandomState(20481).permutation(len(files))
            cut = int(len(files) * 0.9)
            chosen = perm[:cut] if train else perm[cut:]
            if num_examples is not None:
                chosen = chosen[:num_examples]
            rr = ImageRecordReader(self.HW, self.HW, 3)
            rr._files = files                  # label map over ALL classes
            rr.labels = sorted({rr.label_generator.getLabelForPath(f)
                                for f in files})
            feats, labels = [], []
            from deeplearning4j_tpu.data.records import IntWritable  # noqa
            for i in chosen:
                img = rr.loader.asMatrix(files[i])
                feats.append(img / 255.0)
                labels.append(rr.labels.index(
                    rr.label_generator.getLabelForPath(files[i])))
            feats = np.stack(feats).astype(np.float32)
            labels = np.asarray(labels)
            n_cls = len(rr.labels)
            self.synthetic = False
        else:
            n = num_examples or (2048 if train else 256)
            flat, labels = _synthetic_classes(
                n, self.NUM_CLASSES, seed + (0 if train else 777),
                image_hw=self.HW, channels=3)
            feats = flat.reshape(n, 3, self.HW, self.HW) / 255.0
            n_cls = self.NUM_CLASSES
            self.synthetic = True
        onehot = np.eye(n_cls, dtype=np.float32)[labels.astype(np.int64)]
        super().__init__(DataSet(feats, onehot), batch_size,
                         shuffle=train, seed=seed)


def _synthetic_classes(n: int, num_classes: int, seed: int,
                       image_hw: int = 28, channels: int = 1):
    """Deterministic learnable stand-in with an arbitrary class count:
    per-class blocky template (+ per-channel tint) + shift + noise.

    Deliberately NOT merged with ``_synthetic_digits``: that generator's
    exact bytes back the pinned LeNet >=99% regression bar
    (tests/test_nn.py) and must never change; this one is free to
    evolve."""
    rng = np.random.RandomState(seed)
    tmpl_rng = np.random.RandomState(4321)
    templates = []
    for c in range(num_classes):
        t = np.zeros((image_hw, image_hw), np.float32)
        cells = tmpl_rng.choice(16, size=4 + c % 8, replace=False)
        sz = image_hw // 4
        for cell in cells:
            r, cc = divmod(cell, 4)
            t[r * sz:(r + 1) * sz, cc * sz:(cc + 1) * sz] = 1.0
        templates.append(t)
    tints = tmpl_rng.rand(num_classes, channels).astype(np.float32) * 0.5 \
        + 0.5
    labels = rng.randint(0, num_classes, n)
    out = np.zeros((n, channels, image_hw, image_hw), np.float32)
    for i, c in enumerate(labels):
        img = templates[c].copy()
        dx, dy = rng.randint(-2, 3, 2)
        img = np.roll(np.roll(img, dx, axis=0), dy, axis=1)
        for ch in range(channels):
            plane = img * tints[c, ch] \
                + 0.2 * rng.randn(image_hw, image_hw).astype(np.float32)
            out[i, ch] = np.clip(plane, 0, 1)
    if channels == 1:
        return (out[:, 0].reshape(n, -1) * 255).astype(np.float32), labels
    return (out.reshape(n, -1) * 255).astype(np.float32), labels


class IrisDataSetIterator(ListDataSetIterator):
    """ref: IrisDataSetIterator — the canonical 150-row Fisher iris data."""

    def __init__(self, batch_size: int = 150, total: int = 150):
        feats, labels = _iris_data()
        onehot = np.eye(3, dtype=np.float32)[labels]
        super().__init__(DataSet(feats[:total], onehot[:total]), batch_size)


def _iris_data():
    raw = np.array([
        [5.1,3.5,1.4,0.2,0],[4.9,3.0,1.4,0.2,0],[4.7,3.2,1.3,0.2,0],[4.6,3.1,1.5,0.2,0],
        [5.0,3.6,1.4,0.2,0],[5.4,3.9,1.7,0.4,0],[4.6,3.4,1.4,0.3,0],[5.0,3.4,1.5,0.2,0],
        [4.4,2.9,1.4,0.2,0],[4.9,3.1,1.5,0.1,0],[5.4,3.7,1.5,0.2,0],[4.8,3.4,1.6,0.2,0],
        [4.8,3.0,1.4,0.1,0],[4.3,3.0,1.1,0.1,0],[5.8,4.0,1.2,0.2,0],[5.7,4.4,1.5,0.4,0],
        [5.4,3.9,1.3,0.4,0],[5.1,3.5,1.4,0.3,0],[5.7,3.8,1.7,0.3,0],[5.1,3.8,1.5,0.3,0],
        [5.4,3.4,1.7,0.2,0],[5.1,3.7,1.5,0.4,0],[4.6,3.6,1.0,0.2,0],[5.1,3.3,1.7,0.5,0],
        [4.8,3.4,1.9,0.2,0],[5.0,3.0,1.6,0.2,0],[5.0,3.4,1.6,0.4,0],[5.2,3.5,1.5,0.2,0],
        [5.2,3.4,1.4,0.2,0],[4.7,3.2,1.6,0.2,0],[4.8,3.1,1.6,0.2,0],[5.4,3.4,1.5,0.4,0],
        [5.2,4.1,1.5,0.1,0],[5.5,4.2,1.4,0.2,0],[4.9,3.1,1.5,0.2,0],[5.0,3.2,1.2,0.2,0],
        [5.5,3.5,1.3,0.2,0],[4.9,3.6,1.4,0.1,0],[4.4,3.0,1.3,0.2,0],[5.1,3.4,1.5,0.2,0],
        [5.0,3.5,1.3,0.3,0],[4.5,2.3,1.3,0.3,0],[4.4,3.2,1.3,0.2,0],[5.0,3.5,1.6,0.6,0],
        [5.1,3.8,1.9,0.4,0],[4.8,3.0,1.4,0.3,0],[5.1,3.8,1.6,0.2,0],[4.6,3.2,1.4,0.2,0],
        [5.3,3.7,1.5,0.2,0],[5.0,3.3,1.4,0.2,0],[7.0,3.2,4.7,1.4,1],[6.4,3.2,4.5,1.5,1],
        [6.9,3.1,4.9,1.5,1],[5.5,2.3,4.0,1.3,1],[6.5,2.8,4.6,1.5,1],[5.7,2.8,4.5,1.3,1],
        [6.3,3.3,4.7,1.6,1],[4.9,2.4,3.3,1.0,1],[6.6,2.9,4.6,1.3,1],[5.2,2.7,3.9,1.4,1],
        [5.0,2.0,3.5,1.0,1],[5.9,3.0,4.2,1.5,1],[6.0,2.2,4.0,1.0,1],[6.1,2.9,4.7,1.4,1],
        [5.6,2.9,3.6,1.3,1],[6.7,3.1,4.4,1.4,1],[5.6,3.0,4.5,1.5,1],[5.8,2.7,4.1,1.0,1],
        [6.2,2.2,4.5,1.5,1],[5.6,2.5,3.9,1.1,1],[5.9,3.2,4.8,1.8,1],[6.1,2.8,4.0,1.3,1],
        [6.3,2.5,4.9,1.5,1],[6.1,2.8,4.7,1.2,1],[6.4,2.9,4.3,1.3,1],[6.6,3.0,4.4,1.4,1],
        [6.8,2.8,4.8,1.4,1],[6.7,3.0,5.0,1.7,1],[6.0,2.9,4.5,1.5,1],[5.7,2.6,3.5,1.0,1],
        [5.5,2.4,3.8,1.1,1],[5.5,2.4,3.7,1.0,1],[5.8,2.7,3.9,1.2,1],[6.0,2.7,5.1,1.6,1],
        [5.4,3.0,4.5,1.5,1],[6.0,3.4,4.5,1.6,1],[6.7,3.1,4.7,1.5,1],[6.3,2.3,4.4,1.3,1],
        [5.6,3.0,4.1,1.3,1],[5.5,2.5,4.0,1.3,1],[5.5,2.6,4.4,1.2,1],[6.1,3.0,4.6,1.4,1],
        [5.8,2.6,4.0,1.2,1],[5.0,2.3,3.3,1.0,1],[5.6,2.7,4.2,1.3,1],[5.7,3.0,4.2,1.2,1],
        [5.7,2.9,4.2,1.3,1],[6.2,2.9,4.3,1.3,1],[5.1,2.5,3.0,1.1,1],[5.7,2.8,4.1,1.3,1],
        [6.3,3.3,6.0,2.5,2],[5.8,2.7,5.1,1.9,2],[7.1,3.0,5.9,2.1,2],[6.3,2.9,5.6,1.8,2],
        [6.5,3.0,5.8,2.2,2],[7.6,3.0,6.6,2.1,2],[4.9,2.5,4.5,1.7,2],[7.3,2.9,6.3,1.8,2],
        [6.7,2.5,5.8,1.8,2],[7.2,3.6,6.1,2.5,2],[6.5,3.2,5.1,2.0,2],[6.4,2.7,5.3,1.9,2],
        [6.8,3.0,5.5,2.1,2],[5.7,2.5,5.0,2.0,2],[5.8,2.8,5.1,2.4,2],[6.4,3.2,5.3,2.3,2],
        [6.5,3.0,5.5,1.8,2],[7.7,3.8,6.7,2.2,2],[7.7,2.6,6.9,2.3,2],[6.0,2.2,5.0,1.5,2],
        [6.9,3.2,5.7,2.3,2],[5.6,2.8,4.9,2.0,2],[7.7,2.8,6.7,2.0,2],[6.3,2.7,4.9,1.8,2],
        [6.7,3.3,5.7,2.1,2],[7.2,3.2,6.0,1.8,2],[6.2,2.8,4.8,1.8,2],[6.1,3.0,4.9,1.8,2],
        [6.4,2.8,5.6,2.1,2],[7.2,3.0,5.8,1.6,2],[7.4,2.8,6.1,1.9,2],[7.9,3.8,6.4,2.0,2],
        [6.4,2.8,5.6,2.2,2],[6.3,2.8,5.1,1.5,2],[6.1,2.6,5.6,1.4,2],[7.7,3.0,6.1,2.3,2],
        [6.3,3.4,5.6,2.4,2],[6.4,3.1,5.5,1.8,2],[6.0,3.0,4.8,1.8,2],[6.9,3.1,5.4,2.1,2],
        [6.7,3.1,5.6,2.4,2],[6.9,3.1,5.1,2.3,2],[5.8,2.7,5.1,1.9,2],[6.8,3.2,5.9,2.3,2],
        [6.7,3.3,5.7,2.5,2],[6.7,3.0,5.2,2.3,2],[6.3,2.5,5.0,1.9,2],[6.5,3.0,5.2,2.0,2],
        [6.2,3.4,5.4,2.3,2],[5.9,3.0,5.1,1.8,2]], dtype=np.float32)
    return raw[:, :4], raw[:, 4].astype(np.int64)


def _find_cifar10(train: bool):
    """CIFAR-10 python-pickle batches under DL4J_TPU_DATA_DIR/cifar10
    (data_batch_1..5 / test_batch, optionally inside
    cifar-10-batches-py/)."""
    import pickle
    for sub in ("cifar10", os.path.join("cifar10", "cifar-10-batches-py"),
                "cifar-10-batches-py"):
        base = os.path.join(_data_dir(), sub)
        names = [f"data_batch_{i}" for i in range(1, 6)] if train \
            else ["test_batch"]
        if not all(os.path.exists(os.path.join(base, n)) for n in names):
            continue
        xs, ys = [], []
        for n in names:
            with open(os.path.join(base, n), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.append(np.asarray(d[b"labels"], np.int64))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32)
        return x, np.concatenate(ys)
    return None


def _synthetic_cifar(n: int, seed: int):
    """Class-dependent colored blobs standing in for CIFAR-10 when the real
    batches are absent (same honest-fallback policy as MNIST)."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 3, 32, 32).astype(np.float32) * 0.25
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32)
    for i in range(n):
        c = y[i]
        cx, cy = 8 + 2 * (c % 4), 8 + 2 * (c // 4)
        blob = np.exp(-(((xx - cx * 1.5) ** 2 + (yy - cy * 1.5) ** 2)
                        / (2.0 * (3 + c % 3) ** 2)))
        x[i, c % 3] += blob
        x[i, (c + 1) % 3] += 0.5 * blob.T
    return (np.clip(x, 0, 1) * 255).astype(np.uint8), y


class Cifar10DataSetIterator(ListDataSetIterator):
    """ref: org.deeplearning4j.datasets.iterator.impl.Cifar10DataSetIterator.

    Loads the real CIFAR-10 python batches when present under
    DL4J_TPU_DATA_DIR (zero-egress environment: no download); otherwise
    synthesizes class-dependent colored blobs so pipelines/tests run.
    Features are NCHW float32 in [0, 1]."""

    NUM_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: int = None, seed: int = 123,
                 shuffle: bool = True):
        found = _find_cifar10(train)
        self.real_data = found is not None
        if found is not None:
            x, y = found
        else:
            # split-dependent seed: a synthetic 'test' set must not be the
            # training set (same policy as MnistDataSetIterator)
            x, y = _synthetic_cifar(num_examples or 2048,
                                    seed + (0 if train else 777))
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        feats = x.astype(np.float32) / 255.0
        labels = np.eye(self.NUM_CLASSES, dtype=np.float32)[y]
        super().__init__(DataSet(feats, labels), batch_size=batch_size,
                         shuffle=shuffle, seed=seed)
