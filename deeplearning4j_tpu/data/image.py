"""DataVec image pipeline: loaders, transforms, record readers.

Reference parity: ``datavec-data-image`` (SURVEY.md §2.2 "DataVec
image/audio") — ``NativeImageLoader``, the ``ImageTransform`` hierarchy
(crop/flip/rotate/scale/pipeline), ``ImageRecordReader`` with
``ParentPathLabelGenerator``, and ``ObjectDetectionRecordReader`` emitting
the YOLO2 label layout.

TPU-native split: image DECODE + AUGMENT are host-side work (PIL/numpy —
the reference uses JavaCV/OpenCV on the host for the same reason); the
produced batches are dense float tensors that stream to the device, where
the compiled train step consumes them. Layout is NCHW float32 to match
``nn/layers.ConvolutionLayer`` (the reference's default layout).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.data.records import RecordReader, Writable

_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm")


class NDArrayWritable(Writable):
    """ref: org.datavec.api.writable.NDArrayWritable."""

    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)


# ------------------------------------------------------------------ loaders

class NativeImageLoader:
    """Decode + resize an image file/array to CHW float32
    (ref: org.datavec.image.loader.NativeImageLoader)."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height = int(height)
        self.width = int(width)
        self.channels = int(channels)

    def asMatrix(self, src) -> np.ndarray:
        """Image path / PIL image / HWC array -> [C, H, W] float32."""
        from PIL import Image
        if isinstance(src, (str, os.PathLike)):
            img = Image.open(src)
        elif isinstance(src, np.ndarray):
            arr = src
            if arr.ndim == 2:
                arr = arr[:, :, None]
            img = Image.fromarray(
                arr.astype(np.uint8).squeeze() if arr.shape[2] == 1
                else arr.astype(np.uint8))
        else:
            img = src
        img = img.convert("L" if self.channels == 1 else "RGB")
        if img.size != (self.width, self.height):
            img = img.resize((self.width, self.height), Image.BILINEAR)
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, (2, 0, 1))   # HWC -> CHW


# --------------------------------------------------------------- transforms

class ImageTransform:
    """Host-side augmentation op on a CHW float array (ref:
    org.datavec.image.transform.ImageTransform). Chainable; each transform
    also maps box coordinates so object-detection labels stay aligned."""

    def transform(self, img: np.ndarray, rng: np.random.RandomState
                  ) -> np.ndarray:
        raise NotImplementedError

    def transform_boxes(self, boxes, img_shape, rng):
        """Default: geometry-preserving transform — boxes unchanged."""
        return boxes

    def __call__(self, img, rng=None):
        return self.transform(img, rng or np.random.RandomState())


class ResizeImageTransform(ImageTransform):
    def __init__(self, height: int, width: int):
        self.height, self.width = height, width

    def transform(self, img, rng):
        from PIL import Image
        c = img.shape[0]
        out = np.empty((c, self.height, self.width), np.float32)
        for i in range(c):
            out[i] = np.asarray(Image.fromarray(img[i]).resize(
                (self.width, self.height), Image.BILINEAR), np.float32)
        return out


class CropImageTransform(ImageTransform):
    """Random crop by up to crop pixels from each border (ref:
    CropImageTransform)."""

    def __init__(self, crop: int):
        self.crop = int(crop)

    def transform(self, img, rng):
        c, h, w = img.shape
        t = rng.randint(0, self.crop + 1)
        l = rng.randint(0, self.crop + 1)
        b = rng.randint(0, self.crop + 1)
        r = rng.randint(0, self.crop + 1)
        return img[:, t:h - b, l:w - r]


class FlipImageTransform(ImageTransform):
    """mode: 0 = vertical, 1 = horizontal, -1 = both, None = random
    (ref: FlipImageTransform's OpenCV flip codes)."""

    def __init__(self, mode: Optional[int] = 1):
        self.mode = mode

    def transform(self, img, rng):
        mode = rng.choice([0, 1, -1]) if self.mode is None else self.mode
        if mode in (1, -1):
            img = img[:, :, ::-1]
        if mode in (0, -1):
            img = img[:, ::-1, :]
        return np.ascontiguousarray(img)

    def transform_boxes(self, boxes, img_shape, rng):
        if self.mode is None:
            raise ValueError(
                "random FlipImageTransform cannot be used with object-"
                "detection labels (the image flip and the box flip would "
                "draw different random modes); use a fixed mode")
        _, h, w = img_shape
        out = []
        for (x1, y1, x2, y2, cls) in boxes:
            if self.mode in (1, -1):
                x1, x2 = w - x2, w - x1
            if self.mode in (0, -1):
                y1, y2 = h - y2, h - y1
            out.append((x1, y1, x2, y2, cls))
        return out


class RotateImageTransform(ImageTransform):
    """Rotate by a fixed or random angle in degrees (ref:
    RotateImageTransform)."""

    def __init__(self, angle: float, random: bool = False):
        self.angle = float(angle)
        self.random = random

    def transform(self, img, rng):
        from PIL import Image
        a = rng.uniform(-self.angle, self.angle) if self.random else self.angle
        c = img.shape[0]
        out = np.empty_like(img)
        for i in range(c):
            out[i] = np.asarray(Image.fromarray(img[i]).rotate(
                a, Image.BILINEAR), np.float32)
        return out


class ScaleImageTransform(ImageTransform):
    """Multiply pixel values (ref: ScaleImageTransform)."""

    def __init__(self, scale: float):
        self.scale = float(scale)

    def transform(self, img, rng):
        return img * self.scale


class BrightnessTransform(ImageTransform):
    def __init__(self, delta: float, random: bool = False):
        self.delta = float(delta)
        self.random = random

    def transform(self, img, rng):
        d = rng.uniform(-self.delta, self.delta) if self.random else self.delta
        return np.clip(img + d, 0.0, 255.0)


class ColorConversionTransform(ImageTransform):
    """RGB -> grayscale, kept 3-channel (ref: ColorConversionTransform)."""

    def transform(self, img, rng):
        if img.shape[0] != 3:
            return img
        g = 0.299 * img[0] + 0.587 * img[1] + 0.114 * img[2]
        return np.stack([g, g, g])


class PipelineImageTransform(ImageTransform):
    """Chain transforms, each applied with a probability
    (ref: PipelineImageTransform)."""

    def __init__(self, steps: Sequence, shuffle: bool = False):
        # steps: [(transform, prob)] or [transform, ...]
        self.steps = [(s, 1.0) if isinstance(s, ImageTransform) else s
                      for s in steps]
        self.shuffle = shuffle

    def transform(self, img, rng):
        steps = list(self.steps)
        if self.shuffle:
            rng.shuffle(steps)
        for t, p in steps:
            if rng.rand() < p:
                img = t.transform(img, rng)
        return img

    def transform_boxes(self, boxes, img_shape, rng):
        # box mapping is only well-defined for an unconditional, unshuffled
        # chain (probabilistic steps would transform image and boxes with
        # different coin flips)
        if self.shuffle or any(p < 1.0 for _, p in self.steps):
            raise ValueError(
                "PipelineImageTransform with shuffle/probabilistic steps "
                "cannot map object-detection boxes; use p=1.0 steps")
        for t, _ in self.steps:
            boxes = t.transform_boxes(boxes, img_shape, rng)
        return boxes


# ------------------------------------------------------------ label sources

class ParentPathLabelGenerator:
    """Label = name of the file's parent directory (ref:
    org.datavec.api.io.labels.ParentPathLabelGenerator)."""

    def getLabelForPath(self, path: str) -> str:
        return os.path.basename(os.path.dirname(os.path.abspath(path)))


class PathLabelGenerator(ParentPathLabelGenerator):
    pass


def _list_files(root: str, exts) -> List[str]:
    out = []
    for dirpath, _, files in os.walk(root):
        for f in sorted(files):
            if f.lower().endswith(tuple(exts)):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def _list_images(root: str) -> List[str]:
    return _list_files(root, _IMG_EXTS)


# ----------------------------------------------------------- record readers

class ImageRecordReader(RecordReader):
    """Directory-of-class-directories image reader
    (ref: org.datavec.image.recordreader.ImageRecordReader).

    Records are ``[NDArrayWritable(CHW float32), IntWritable(label)]``;
    label classes are the sorted parent-directory names."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator=None, transform: ImageTransform = None,
                 seed: int = 12345):
        self.loader = NativeImageLoader(height, width, channels)
        self.label_generator = label_generator or ParentPathLabelGenerator()
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._files: List[str] = []
        self.labels: List[str] = []
        self._pos = 0

    def initialize(self, path: str):
        """path: root directory (FileSplit equivalent)."""
        self._files = _list_images(path)
        if not self._files:
            raise FileNotFoundError(f"no images under {path}")
        self.labels = sorted({self.label_generator.getLabelForPath(f)
                              for f in self._files})
        self._pos = 0
        return self

    def numLabels(self) -> int:
        return len(self.labels)

    def hasNext(self):
        return self._pos < len(self._files)

    def next(self):
        from deeplearning4j_tpu.data.records import IntWritable
        f = self._files[self._pos]
        self._pos += 1
        img = self.loader.asMatrix(f)
        if self.transform is not None:
            img = self.transform.transform(img, self._rng)
        label = self.labels.index(self.label_generator.getLabelForPath(f))
        return [NDArrayWritable(img), IntWritable(label)]

    def reset(self):
        self._pos = 0


class ImageRecordReaderDataSetIterator(DataSetIterator):
    """ImageRecordReader -> NCHW DataSet batches (the image case of
    RecordReaderDataSetIterator — ref: same class, NDArrayWritable
    branch)."""

    def __init__(self, reader: ImageRecordReader, batch_size: int,
                 num_classes: int = None):
        self.reader = reader
        self.batch_size = batch_size
        self.num_classes = num_classes or reader.numLabels()

    def reset(self):
        self.reader.reset()

    def hasNext(self):
        return self.reader.hasNext()

    def next(self) -> DataSet:
        feats, labels = [], []
        while self.reader.hasNext() and len(feats) < self.batch_size:
            img_w, lab_w = self.reader.next()
            feats.append(img_w.value)
            labels.append(lab_w.value)
        x = np.stack(feats).astype(np.float32)
        y = np.eye(self.num_classes, dtype=np.float32)[
            np.asarray(labels, np.int64)]
        return self._apply_pre(DataSet(x, y))

    def batch(self):
        return self.batch_size

    def totalOutcomes(self):
        return self.num_classes


class ObjectDetectionRecordReader(RecordReader):
    """Images + bounding boxes -> YOLO2 training records
    (ref: org.datavec.image.recordreader.objdetect.ObjectDetectionRecordReader).

    ``label_provider(path) -> [(x1, y1, x2, y2, class_name)]`` in PIXEL
    coordinates of the ORIGINAL image (ref: ImageObjectLabelProvider).
    Records are ``[NDArrayWritable(CHW image), NDArrayWritable(label)]``
    where the label tensor is ``[4 + C, gridH, gridW]`` — channels 0..3 =
    (x1, y1, x2, y2) in GRID units stored at the box-center cell, then a
    one-hot class plane — exactly ``nn/objdetect.Yolo2OutputLayer``'s
    ``compute_loss`` label format."""

    def __init__(self, height: int, width: int, channels: int,
                 grid_h: int, grid_w: int, label_provider: Callable,
                 classes: Sequence[str], transform: ImageTransform = None,
                 seed: int = 12345):
        self.loader = NativeImageLoader(height, width, channels)
        self.grid_h, self.grid_w = int(grid_h), int(grid_w)
        self.label_provider = label_provider
        self.classes = list(classes)
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._files: List[str] = []
        self._pos = 0

    def initialize(self, path: str):
        self._files = _list_images(path)
        if not self._files:
            raise FileNotFoundError(f"no images under {path}")
        self._pos = 0
        return self

    def hasNext(self):
        return self._pos < len(self._files)

    def reset(self):
        self._pos = 0

    def _label_tensor(self, boxes, orig_hw) -> np.ndarray:
        C = len(self.classes)
        lab = np.zeros((4 + C, self.grid_h, self.grid_w), np.float32)
        oh, ow = orig_hw
        sx = self.grid_w / float(ow)
        sy = self.grid_h / float(oh)
        for (x1, y1, x2, y2, cls) in boxes:
            gx1, gy1, gx2, gy2 = x1 * sx, y1 * sy, x2 * sx, y2 * sy
            cx = min(int((gx1 + gx2) / 2.0), self.grid_w - 1)
            cy = min(int((gy1 + gy2) / 2.0), self.grid_h - 1)
            lab[0, cy, cx] = gx1
            lab[1, cy, cx] = gy1
            lab[2, cy, cx] = gx2
            lab[3, cy, cx] = gy2
            lab[4 + self.classes.index(cls), cy, cx] = 1.0
        return lab

    def next(self):
        from PIL import Image
        f = self._files[self._pos]
        self._pos += 1
        with Image.open(f) as im:
            orig_hw = (im.size[1], im.size[0])
            img = self.loader.asMatrix(im)  # single open+decode per record
        boxes = [(x1, y1, x2, y2, c)
                 for (x1, y1, x2, y2, c) in self.label_provider(f)]
        if self.transform is not None:
            boxes = self.transform.transform_boxes(
                boxes, (img.shape[0],) + orig_hw, self._rng)
            img = self.transform.transform(img, self._rng)
        return [NDArrayWritable(img),
                NDArrayWritable(self._label_tensor(boxes, orig_hw))]


class ObjectDetectionDataSetIterator(DataSetIterator):
    """ObjectDetectionRecordReader -> (images, YOLO label grid) batches."""

    def __init__(self, reader: ObjectDetectionRecordReader, batch_size: int):
        self.reader = reader
        self.batch_size = batch_size

    def reset(self):
        self.reader.reset()

    def hasNext(self):
        return self.reader.hasNext()

    def next(self) -> DataSet:
        feats, labs = [], []
        while self.reader.hasNext() and len(feats) < self.batch_size:
            f, l = self.reader.next()
            feats.append(f.value)
            labs.append(l.value)
        return self._apply_pre(DataSet(np.stack(feats).astype(np.float32),
                                       np.stack(labs).astype(np.float32)))

    def batch(self):
        return self.batch_size
