"""DataVec equivalent: record readers, schema, transform process.

Reference parity: ``datavec/datavec-api`` —
``org.datavec.api.records.reader.RecordReader`` impls (CSV, line,
collection, sequence), the ``Writable`` type system,
``org.datavec.api.transform.{TransformProcess, schema.Schema}`` with its
transform ops (remove/rename columns, categorical→integer/one-hot,
normalize, filter, conditional replace, ...) — SURVEY.md §2.2 "DataVec
core" (~100 transform ops; the most-used surface is implemented here and
the DSL is extensible via ``custom``).

TPU-native: transforms run columnar on the host (numpy object arrays /
python lists) and terminate in ``RecordReaderDataSetIterator`` which emits
device-ready numpy batches.
"""

from __future__ import annotations

import csv
import io
import os
from typing import Any, Callable, Dict, Iterable, List, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator


# ------------------------------------------------------------------ writables
class Writable:
    """Base value wrapper (ref: org.datavec.api.writable.Writable)."""

    def __init__(self, value):
        self.value = value

    def toDouble(self) -> float:
        return float(self.value)

    def toInt(self) -> int:
        return int(float(self.value))

    def toString(self) -> str:
        return str(self.value)

    def __repr__(self):
        return f"{type(self).__name__}({self.value!r})"

    def __eq__(self, other):
        return isinstance(other, Writable) and self.value == other.value


class DoubleWritable(Writable):
    pass


class IntWritable(Writable):
    pass


class Text(Writable):
    pass


class FloatWritable(Writable):
    pass


# -------------------------------------------------------------------- schema
class ColumnType:
    DOUBLE = "Double"
    INTEGER = "Integer"
    CATEGORICAL = "Categorical"
    STRING = "String"
    TIME = "Time"


class Schema:
    """Column schema (ref: org.datavec.api.transform.schema.Schema)."""

    def __init__(self, columns: List[Dict] = None):
        self.columns = columns or []

    class Builder:
        def __init__(self):
            self._cols = []

        def addColumnDouble(self, name):
            self._cols.append({"name": name, "type": ColumnType.DOUBLE})
            return self

        def addColumnsDouble(self, *names):
            for n in names:
                self.addColumnDouble(n)
            return self

        def addColumnInteger(self, name):
            self._cols.append({"name": name, "type": ColumnType.INTEGER})
            return self

        def addColumnsInteger(self, *names):
            for n in names:
                self.addColumnInteger(n)
            return self

        def addColumnCategorical(self, name, *state_names):
            self._cols.append({"name": name, "type": ColumnType.CATEGORICAL,
                               "states": list(state_names)})
            return self

        def addColumnString(self, name):
            self._cols.append({"name": name, "type": ColumnType.STRING})
            return self

        def build(self):
            return Schema(self._cols)

    def numColumns(self) -> int:
        return len(self.columns)

    def getColumnNames(self) -> List[str]:
        return [c["name"] for c in self.columns]

    def getIndexOfColumn(self, name: str) -> int:
        return self.getColumnNames().index(name)

    def getColumnTypes(self):
        return [c["type"] for c in self.columns]

    def __repr__(self):
        return "Schema(" + ", ".join(f"{c['name']}:{c['type']}"
                                     for c in self.columns) + ")"


# ----------------------------------------------------------- record readers
class RecordReader:
    """ref: org.datavec.api.records.reader.RecordReader — iterator over
    records (lists of Writables)."""

    def hasNext(self) -> bool:
        raise NotImplementedError

    def next(self) -> List[Writable]:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class CSVRecordReader(RecordReader):
    """ref: org.datavec.api.records.reader.impl.csv.CSVRecordReader."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._rows = []
        self._pos = 0

    def initialize(self, source: Union[str, io.TextIOBase, List[str]]):
        if isinstance(source, str):
            with open(source) as f:
                lines = f.read().splitlines()
        elif isinstance(source, list):
            lines = source
        else:
            lines = source.read().splitlines()
        reader = csv.reader(lines[self.skip_lines:], delimiter=self.delimiter)
        self._rows = [[_auto_writable(v) for v in row] for row in reader if row]
        self._pos = 0
        return self

    def hasNext(self):
        return self._pos < len(self._rows)

    def next(self):
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def reset(self):
        self._pos = 0


class LineRecordReader(RecordReader):
    """ref: impl.LineRecordReader — one Text writable per line."""

    def __init__(self):
        self._lines = []
        self._pos = 0

    def initialize(self, source: Union[str, List[str]]):
        if isinstance(source, str) and os.path.exists(source):
            with open(source) as f:
                self._lines = f.read().splitlines()
        elif isinstance(source, list):
            self._lines = source
        else:
            self._lines = str(source).splitlines()
        self._pos = 0
        return self

    def hasNext(self):
        return self._pos < len(self._lines)

    def next(self):
        line = self._lines[self._pos]
        self._pos += 1
        return [Text(line)]

    def reset(self):
        self._pos = 0


class CollectionRecordReader(RecordReader):
    """ref: impl.collection.CollectionRecordReader."""

    def __init__(self, records: List[List]):
        self._records = [[v if isinstance(v, Writable) else _auto_writable(v)
                          for v in r] for r in records]
        self._pos = 0

    def hasNext(self):
        return self._pos < len(self._records)

    def next(self):
        r = self._records[self._pos]
        self._pos += 1
        return r

    def reset(self):
        self._pos = 0


class CSVSequenceRecordReader(RecordReader):
    """ref: impl.csv.CSVSequenceRecordReader — one CSV file per sequence."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._sequences = []
        self._pos = 0

    def initialize(self, sources: Sequence[Union[str, List[str]]]):
        self._sequences = []
        for src in sources:
            rr = CSVRecordReader(self.skip_lines, self.delimiter).initialize(src)
            self._sequences.append(list(rr))
        self._pos = 0
        return self

    def hasNext(self):
        return self._pos < len(self._sequences)

    def next(self):
        s = self._sequences[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._pos = 0


def _auto_writable(v) -> Writable:
    try:
        f = float(v)
        if f.is_integer() and "." not in str(v):
            return IntWritable(int(f))
        return DoubleWritable(f)
    except (TypeError, ValueError):
        return Text(v)


# ------------------------------------------------------------ transform DSL
class TransformProcess:
    """Columnar transform pipeline (ref:
    org.datavec.api.transform.TransformProcess). Build with the Builder,
    execute with ``execute(records)`` (the LocalTransformExecutor path)."""

    def __init__(self, initial_schema: Schema, steps: List):
        self.initial_schema = initial_schema
        self.steps = steps

    class Builder:
        def __init__(self, schema: Schema):
            self.schema = schema
            self.steps = []

        def removeColumns(self, *names):
            self.steps.append(("remove", names))
            return self

        def removeAllColumnsExceptFor(self, *names):
            self.steps.append(("keep", names))
            return self

        def renameColumn(self, old, new):
            self.steps.append(("rename", (old, new)))
            return self

        def categoricalToInteger(self, *names):
            self.steps.append(("cat2int", names))
            return self

        def categoricalToOneHot(self, *names):
            self.steps.append(("cat2onehot", names))
            return self

        def integerToCategorical(self, name, states):
            self.steps.append(("int2cat", (name, states)))
            return self

        def stringToCategorical(self, name, states):
            self.steps.append(("str2cat", (name, states)))
            return self

        def doubleMathOp(self, name, op, value):
            self.steps.append(("math", (name, op, value)))
            return self

        def normalize(self, name, kind: str = "MinMax"):
            self.steps.append(("normalize", (name, kind)))
            return self

        def filter(self, predicate: Callable[[Dict], bool]):
            """Remove rows where predicate(row_dict) is True (ref:
            ConditionFilter)."""
            self.steps.append(("filter", predicate))
            return self

        def conditionalReplaceValueTransform(self, name, new_value,
                                             predicate: Callable[[Any], bool]):
            self.steps.append(("cond_replace", (name, new_value, predicate)))
            return self

        def custom(self, fn: Callable):
            """Escape hatch: fn(rows, schema) -> (rows, schema)."""
            self.steps.append(("custom", fn))
            return self

        # -- column management (ref: transform.column.*) --
        def addConstantColumn(self, name, col_type, value):
            self.steps.append(("add_const", (name, col_type, value)))
            return self

        def duplicateColumns(self, names, new_names):
            self.steps.append(("duplicate", (tuple(names), tuple(new_names))))
            return self

        def reorderColumns(self, *names):
            self.steps.append(("reorder", names))
            return self

        def convertToString(self, name):
            self.steps.append(("convert", (name, str, ColumnType.STRING)))
            return self

        def convertToDouble(self, name):
            self.steps.append(("convert", (name, float, ColumnType.DOUBLE)))
            return self

        def convertToInteger(self, name):
            self.steps.append(("convert", (name, lambda v: int(float(v)),
                                           ColumnType.INTEGER)))
            return self

        # -- numeric (ref: transform.doubletransform.*) --
        def doubleMathFunction(self, name, fn_name):
            self.steps.append(("mathfn", (name, fn_name)))
            return self

        def doubleColumnsMathOp(self, new_name, op, *columns):
            self.steps.append(("colmath", (new_name, op, columns)))
            return self

        def integerMathOp(self, name, op, value):
            self.steps.append(("math", (name, op, value)))
            return self

        longMathOp = integerMathOp

        def clipValues(self, name, lo, hi):
            self.steps.append(("clip", (name, lo, hi)))
            return self

        def replaceInvalidWithInteger(self, name, value):
            self.steps.append(("replace_invalid", (name, value)))
            return self

        # -- strings (ref: transform.string.*) --
        def appendStringColumnTransform(self, name, suffix):
            self.steps.append(("append_str", (name, suffix)))
            return self

        def changeCase(self, name, case: str = "LOWER"):
            self.steps.append(("change_case", (name, case)))
            return self

        def stringMapTransform(self, name, mapping: Dict[str, str]):
            self.steps.append(("str_map", (name, dict(mapping))))
            return self

        def stringRemoveWhitespaceTransform(self, name):
            self.steps.append(("rm_ws", (name,)))
            return self

        def replaceStringTransform(self, name, regex_map: Dict[str, str]):
            self.steps.append(("str_regex", (name, dict(regex_map))))
            return self

        def concatenateStringColumns(self, new_name, delimiter, *columns):
            self.steps.append(("concat_str", (new_name, delimiter, columns)))
            return self

        # -- time (ref: transform.time.*) --
        def stringToTimeTransform(self, name, fmt: str):
            self.steps.append(("str2time", (name, fmt)))
            return self

        def timeMathOp(self, name, op, amount_ms: int):
            self.steps.append(("math", (name, op, amount_ms)))
            return self

        def deriveColumnsFromTime(self, name, *fields):
            """fields from: hourOfDay, dayOfWeek, dayOfMonth, monthOfYear,
            year, minuteOfHour, secondOfMinute."""
            self.steps.append(("derive_time", (name, fields)))
            return self

        def firstDigitTransform(self, name, new_name):
            self.steps.append(("first_digit", (name, new_name)))
            return self

        # -- r4 numeric additions (ref: transform.doubletransform.*) --
        def absValueColumn(self, name):
            self.steps.append(("mathfn", (name, "Abs")))
            return self

        def roundDoubleColumn(self, name, decimals: int = 0):
            self.steps.append(("round_double", (name, decimals)))
            return self

        def subtractMean(self, name):
            self.steps.append(("subtract_mean", (name,)))
            return self

        def replaceEmptyWithValue(self, name, value):
            self.steps.append(("replace_empty", (name, value)))
            return self

        # -- r4 string additions (ref: transform.string.*) --
        def stringLengthColumn(self, name, new_name):
            self.steps.append(("str_len", (name, new_name)))
            return self

        def trimStringTransform(self, name):
            self.steps.append(("str_trim", (name,)))
            return self

        def padStringTransform(self, name, length: int, pad_char: str = " ",
                               side: str = "LEFT"):
            self.steps.append(("str_pad", (name, length, pad_char, side)))
            return self

        def substringTransform(self, name, frm: int, to: int = None):
            self.steps.append(("str_sub", (name, frm, to)))
            return self

        def mapAllStringsExceptList(self, name, new_value, keep):
            self.steps.append(("str_map_except", (name, new_value,
                                                  tuple(keep))))
            return self

        # -- r4 categorical additions --
        def oneHotToCategorical(self, new_name, *onehot_columns):
            self.steps.append(("onehot2cat", (new_name,
                                              tuple(onehot_columns))))
            return self

        # -- r4 filters / conditional copies --
        def filterInvalidValues(self, *names):
            """Drop rows whose named columns fail float conversion or are
            NaN (ref: FilterInvalidValues)."""
            self.steps.append(("filter_invalid", names))
            return self

        def conditionalCopyValueTransform(self, col_to_change, col_to_copy,
                                          predicate):
            self.steps.append(("cond_copy", (col_to_change, col_to_copy,
                                             predicate)))
            return self

        # -- r4 aggregation (ref: transform.reduce.Reducer) --
        def reduce(self, reducer: "Reducer"):
            self.steps.append(("reduce", reducer))
            return self

        # -- sequence ops (ref: transform.sequence.*; VERDICT r3 #6) --
        def convertToSequence(self, key_columns, sort_column=None):
            """Group rows by key column(s) into sequences, sorted within
            each sequence by ``sort_column`` (ref: convertToSequence +
            comparator)."""
            keys = ([key_columns] if isinstance(key_columns, str)
                    else list(key_columns))
            self.steps.append(("to_sequence", (keys, sort_column)))
            return self

        def convertFromSequence(self):
            self.steps.append(("from_sequence", ()))
            return self

        def window(self, size: int, step: int = None):
            """Sliding windows over each sequence; each window becomes its
            own sequence (ref: sequence window functions)."""
            self.steps.append(("seq_window", (size, step or size)))
            return self

        def padSequenceToLength(self, length: int, pad_value=0):
            self.steps.append(("seq_pad", (length, pad_value)))
            return self

        def trimSequence(self, num_steps: int, from_start: bool = True):
            """Remove ``num_steps`` steps from the start (or end) of each
            sequence (ref: SequenceTrimTransform)."""
            self.steps.append(("seq_trim", (num_steps, from_start)))
            return self

        def trimSequenceToLength(self, length: int):
            self.steps.append(("seq_trim_len", (length,)))
            return self

        def offsetSequence(self, columns, offset: int, pad_value=0):
            """Shift the named columns by ``offset`` steps WITHIN each
            sequence (ref: SequenceOffsetTransform; e.g. next-step labels
            with offset=-1)."""
            cols = [columns] if isinstance(columns, str) else list(columns)
            self.steps.append(("seq_offset", (cols, offset, pad_value)))
            return self

        def reverseSequence(self):
            self.steps.append(("seq_reverse", ()))
            return self

        def sequenceDifference(self, name):
            """Replace the column with step-to-step differences (first
            step becomes 0; ref: SequenceDifferenceTransform)."""
            self.steps.append(("seq_diff", (name,)))
            return self

        def sequenceMovingWindowReduce(self, name, window: int,
                                      op: str = "Mean"):
            """New column = reduction over the trailing window of the named
            column (ref: SequenceMovingWindowReduceTransform)."""
            self.steps.append(("seq_moving", (name, window, op)))
            return self

        def splitSequenceMaxLength(self, max_length: int):
            self.steps.append(("seq_split_max", (max_length,)))
            return self

        def build(self):
            return TransformProcess(self.schema, self.steps)

    # -- execution (ref: LocalTransformExecutor.execute) --
    _SEQ_OPS = {"seq_window", "seq_pad", "seq_trim", "seq_trim_len",
                "seq_offset", "seq_reverse", "seq_diff", "seq_moving",
                "seq_split_max"}

    def execute(self, records: Iterable[List]) -> List[List]:
        rows = [[w.value if isinstance(w, Writable) else w for w in r]
                for r in records]
        rows, schema = self._run(rows, False)
        return rows

    def executeSequence(self, sequences: Iterable[List[List]]) -> List:
        """Sequence-mode execution (ref: LocalTransformExecutor
        .executeSequence): input is a list of sequences of rows."""
        seqs = [[[w.value if isinstance(w, Writable) else w for w in r]
                 for r in seq] for seq in sequences]
        seqs, schema = self._run(seqs, True)
        return seqs

    def _run(self, rows, seq_mode: bool):
        schema = Schema([dict(c) for c in self.initial_schema.columns])
        for kind, arg in self.steps:
            if kind == "to_sequence":
                if seq_mode:
                    raise ValueError("convertToSequence: already sequential")
                rows, schema = self._to_sequence(arg, rows, schema)
                seq_mode = True
            elif kind == "from_sequence":
                rows = [r for seq in rows for r in seq]
                seq_mode = False
            elif kind in self._SEQ_OPS:
                if not seq_mode:
                    raise ValueError(f"{kind}: sequence op before "
                                     f"convertToSequence / executeSequence")
                rows, schema = self._apply_seq(kind, arg, rows, schema)
            elif seq_mode:
                # columnar ops map over each sequence's rows (row filters
                # apply within each sequence). Each application gets a
                # FRESH schema copy — _apply mutates schema in place, and
                # running it once per sequence must not append the same
                # new column repeatedly. The first sequence's resulting
                # schema becomes the pipeline schema.
                new_seqs = []
                schema_out = schema
                for i, seq in enumerate(rows):
                    fresh = Schema([dict(c) for c in schema.columns])
                    out, s2 = self._apply(kind, arg, seq, fresh)
                    if i == 0:
                        schema_out = s2
                    new_seqs.append(out)
                if not rows:   # empty input still advances the schema
                    _, schema_out = self._apply(
                        kind, arg, [], Schema([dict(c)
                                               for c in schema.columns]))
                rows, schema = new_seqs, schema_out
            else:
                rows, schema = self._apply(kind, arg, rows, schema)
        self.final_schema = schema
        return rows, schema

    def _to_sequence(self, arg, rows, schema):
        keys, sort_col = arg
        names = schema.getColumnNames()
        kidx = [names.index(k) for k in keys]
        sidx = names.index(sort_col) if sort_col is not None else None
        groups = {}
        for r in rows:
            groups.setdefault(tuple(r[i] for i in kidx), []).append(r)
        seqs = []
        for k in sorted(groups, key=lambda t: tuple(str(v) for v in t)):
            seq = groups[k]
            if sidx is not None:
                seq = sorted(seq, key=lambda r: r[sidx])
            seqs.append(seq)
        return seqs, schema

    def _apply_seq(self, kind, arg, seqs, schema):
        names = schema.getColumnNames()
        if kind == "seq_window":
            size, step = arg
            out = []
            for seq in seqs:
                for start in range(0, max(len(seq) - size, 0) + 1, step):
                    out.append([list(r) for r in seq[start:start + size]])
            return out, schema
        if kind == "seq_pad":
            length, pad = arg
            out = []
            for seq in seqs:
                seq = [list(r) for r in seq[:length]]
                while len(seq) < length:
                    seq.append([pad] * len(names))
                out.append(seq)
            return out, schema
        if kind == "seq_trim":
            n, from_start = arg
            if n == 0:
                return seqs, schema
            return ([seq[n:] if from_start else seq[:-n] for seq in seqs],
                    schema)
        if kind == "seq_trim_len":
            (length,) = arg
            return [seq[:length] for seq in seqs], schema
        if kind == "seq_offset":
            cols, offset, pad = arg
            idxs = [names.index(c) for c in cols]
            out = []
            for seq in seqs:
                seq = [list(r) for r in seq]
                vals = [[r[i] for i in idxs] for r in seq]
                T = len(seq)
                for t, r in enumerate(seq):
                    src = t - offset
                    for j, i in enumerate(idxs):
                        r[i] = vals[src][j] if 0 <= src < T else pad
                out.append(seq)
            return out, schema
        if kind == "seq_reverse":
            return [list(reversed(seq)) for seq in seqs], schema
        if kind == "seq_diff":
            (name,) = arg
            i = names.index(name)
            out = []
            for seq in seqs:
                seq = [list(r) for r in seq]
                prev = None
                for r in seq:
                    cur = float(r[i])
                    r[i] = cur - prev if prev is not None else 0.0
                    prev = cur
                out.append(seq)
            return out, schema
        if kind == "seq_moving":
            name, window, op = arg
            i = names.index(name)
            red = {"Mean": lambda vs: sum(vs) / len(vs), "Sum": sum,
                   "Min": min, "Max": max}[op]
            out = []
            for seq in seqs:
                seq = [list(r) for r in seq]
                vals = [float(r[i]) for r in seq]
                for t, r in enumerate(seq):
                    r.append(red(vals[max(0, t - window + 1):t + 1]))
                out.append(seq)
            return out, Schema(schema.columns + [
                {"name": f"{op.lower()}({window})({name})",
                 "type": ColumnType.DOUBLE}])
        if kind == "seq_split_max":
            (n,) = arg
            out = []
            for seq in seqs:
                for start in range(0, len(seq), n):
                    out.append(seq[start:start + n])
            return out, schema
        raise ValueError(kind)

    def getFinalSchema(self) -> Schema:
        if not hasattr(self, "final_schema"):
            # dry-run on empty data to compute the schema
            self.execute([])
        return self.final_schema

    def _apply(self, kind, arg, rows, schema: Schema):
        names = schema.getColumnNames()
        if kind == "remove":
            idxs = [names.index(n) for n in arg]
            keep = [i for i in range(len(names)) if i not in idxs]
            return ([[r[i] for i in keep] for r in rows],
                    Schema([schema.columns[i] for i in keep]))
        if kind == "keep":
            idxs = [names.index(n) for n in arg]
            return ([[r[i] for i in idxs] for r in rows],
                    Schema([schema.columns[i] for i in idxs]))
        if kind == "rename":
            old, new = arg
            cols = [dict(c) for c in schema.columns]
            cols[names.index(old)]["name"] = new
            return rows, Schema(cols)
        if kind == "cat2int":
            for n in arg:
                i = names.index(n)
                states = schema.columns[i].get("states")
                if states is None:
                    states = sorted({r[i] for r in rows})
                lut = {s: j for j, s in enumerate(states)}
                for r in rows:
                    r[i] = lut[r[i]]
                schema.columns[i] = {"name": n, "type": ColumnType.INTEGER}
            return rows, schema
        if kind == "cat2onehot":
            for n in arg:
                i = schema.getColumnNames().index(n)
                states = schema.columns[i].get("states")
                if states is None:
                    states = sorted({r[i] for r in rows})
                new_cols = [{"name": f"{n}[{s}]", "type": ColumnType.INTEGER}
                            for s in states]
                for r in rows:
                    onehot = [1 if r[i] == s else 0 for s in states]
                    r[i:i + 1] = onehot
                schema.columns[i:i + 1] = new_cols
            return rows, schema
        if kind == "int2cat" or kind == "str2cat":
            name, states = arg
            i = names.index(name)
            if kind == "int2cat":
                for r in rows:
                    r[i] = states[int(r[i])]
            schema.columns[i] = {"name": name, "type": ColumnType.CATEGORICAL,
                                 "states": list(states)}
            return rows, schema
        if kind == "math":
            name, op, value = arg
            i = names.index(name)
            fn = {"Add": lambda x: x + value, "Subtract": lambda x: x - value,
                  "Multiply": lambda x: x * value, "Divide": lambda x: x / value,
                  "Power": lambda x: x ** value}[op]
            for r in rows:
                r[i] = fn(float(r[i]))
            return rows, schema
        if kind == "normalize":
            name, how = arg
            i = names.index(name)
            vals = np.asarray([float(r[i]) for r in rows]) if rows else np.zeros(0)
            if how == "MinMax":
                lo, hi = (vals.min(), vals.max()) if len(vals) else (0, 1)
                rng = max(hi - lo, 1e-12)
                for r in rows:
                    r[i] = (float(r[i]) - lo) / rng
            elif how == "Standardize":
                m, s = (vals.mean(), max(vals.std(), 1e-12)) if len(vals) else (0, 1)
                for r in rows:
                    r[i] = (float(r[i]) - m) / s
            return rows, schema
        if kind == "filter":
            pred = arg
            names_now = schema.getColumnNames()
            rows = [r for r in rows
                    if not pred(dict(zip(names_now, r)))]
            return rows, schema
        if kind == "cond_replace":
            name, new_value, pred = arg
            i = names.index(name)
            for r in rows:
                if pred(r[i]):
                    r[i] = new_value
            return rows, schema
        if kind == "custom":
            return arg(rows, schema)
        if kind == "add_const":
            name, col_type, value = arg
            for r in rows:
                r.append(value)
            schema.columns.append({"name": name, "type": col_type})
            return rows, schema
        if kind == "duplicate":
            src, dst = arg
            idxs = [names.index(n) for n in src]
            for r in rows:
                r.extend(r[i] for i in idxs)
            for n, i in zip(dst, idxs):
                schema.columns.append({**schema.columns[i], "name": n})
            return rows, schema
        if kind == "reorder":
            idxs = [names.index(n) for n in arg]
            idxs += [i for i in range(len(names)) if i not in idxs]
            return ([[r[i] for i in idxs] for r in rows],
                    Schema([schema.columns[i] for i in idxs]))
        if kind == "convert":
            name, caster, col_type = arg
            i = names.index(name)
            for r in rows:
                r[i] = caster(r[i])
            schema.columns[i] = {"name": name, "type": col_type}
            return rows, schema
        if kind == "mathfn":
            import math
            name, fn_name = arg
            i = names.index(name)
            fn = {"Log": math.log, "Log2": lambda v: math.log2(v),
                  "Log10": math.log10, "Sqrt": math.sqrt, "Abs": abs,
                  "Exp": math.exp, "Sin": math.sin, "Cos": math.cos,
                  "Tan": math.tan, "Floor": math.floor, "Ceil": math.ceil,
                  "Sign": lambda v: (v > 0) - (v < 0)}[fn_name]
            for r in rows:
                r[i] = float(fn(float(r[i])))
            return rows, schema
        if kind == "colmath":
            new_name, op, cols = arg
            idxs = [names.index(n) for n in cols]
            red = {"Add": lambda vs: sum(vs),
                   "Subtract": lambda vs: vs[0] - sum(vs[1:]),
                   "Multiply": lambda vs: float(np.prod(vs)),
                   "Divide": lambda vs: vs[0] / vs[1],
                   "Max": max, "Min": min,
                   "Average": lambda vs: sum(vs) / len(vs)}[op]
            for r in rows:
                r.append(float(red([float(r[i]) for i in idxs])))
            schema.columns.append({"name": new_name, "type": ColumnType.DOUBLE})
            return rows, schema
        if kind == "clip":
            name, lo, hi = arg
            i = names.index(name)
            for r in rows:
                v = float(r[i])
                r[i] = min(max(v, lo), hi)
            return rows, schema
        if kind == "replace_invalid":
            name, value = arg
            i = names.index(name)
            for r in rows:
                try:
                    float(r[i])
                except (TypeError, ValueError):
                    r[i] = value
            return rows, schema
        if kind == "append_str":
            name, suffix = arg
            i = names.index(name)
            for r in rows:
                r[i] = str(r[i]) + suffix
            return rows, schema
        if kind == "change_case":
            name, case = arg
            i = names.index(name)
            for r in rows:
                r[i] = str(r[i]).upper() if case.upper() == "UPPER" \
                    else str(r[i]).lower()
            return rows, schema
        if kind == "str_map":
            name, mapping = arg
            i = names.index(name)
            for r in rows:
                r[i] = mapping.get(str(r[i]), r[i])
            return rows, schema
        if kind == "rm_ws":
            (name,) = arg
            i = names.index(name)
            for r in rows:
                r[i] = "".join(str(r[i]).split())
            return rows, schema
        if kind == "str_regex":
            import re as _re
            name, regex_map = arg
            i = names.index(name)
            for r in rows:
                v = str(r[i])
                for pat, rep in regex_map.items():
                    v = _re.sub(pat, rep, v)
                r[i] = v
            return rows, schema
        if kind == "concat_str":
            new_name, delim, cols = arg
            idxs = [names.index(n) for n in cols]
            for r in rows:
                r.append(delim.join(str(r[i]) for i in idxs))
            schema.columns.append({"name": new_name, "type": ColumnType.STRING})
            return rows, schema
        if kind == "str2time":
            from datetime import datetime, timezone
            name, fmt = arg
            i = names.index(name)
            for r in rows:
                dt = datetime.strptime(str(r[i]), fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=timezone.utc)
                r[i] = int(dt.timestamp() * 1000)
            schema.columns[i] = {"name": name, "type": ColumnType.TIME}
            return rows, schema
        if kind == "derive_time":
            from datetime import datetime, timezone
            name, fields = arg
            i = names.index(name)
            getters = {"hourOfDay": lambda d: d.hour,
                       "minuteOfHour": lambda d: d.minute,
                       "secondOfMinute": lambda d: d.second,
                       "dayOfWeek": lambda d: d.isoweekday(),
                       "dayOfMonth": lambda d: d.day,
                       "monthOfYear": lambda d: d.month,
                       "year": lambda d: d.year}
            for r in rows:
                d = datetime.fromtimestamp(int(r[i]) / 1000.0, tz=timezone.utc)
                r.extend(getters[f](d) for f in fields)
            for f in fields:
                schema.columns.append({"name": f"{name}[{f}]",
                                       "type": ColumnType.INTEGER})
            return rows, schema
        if kind == "first_digit":
            name, new_name = arg
            i = names.index(name)
            for r in rows:
                s = str(abs(float(r[i]))).lstrip("0.")
                r.append(int(s[0]) if s and s[0].isdigit() else 0)
            schema.columns.append({"name": new_name, "type": ColumnType.INTEGER})
            return rows, schema
        if kind == "round_double":
            name, decimals = arg
            i = names.index(name)
            for r in rows:
                r[i] = round(float(r[i]), decimals)
            return rows, schema
        if kind == "subtract_mean":
            (name,) = arg
            i = names.index(name)
            m = (sum(float(r[i]) for r in rows) / len(rows)) if rows else 0.0
            for r in rows:
                r[i] = float(r[i]) - m
            return rows, schema
        if kind == "replace_empty":
            name, value = arg
            i = names.index(name)
            for r in rows:
                if r[i] is None or str(r[i]).strip() == "":
                    r[i] = value
            return rows, schema
        if kind == "str_len":
            name, new_name = arg
            i = names.index(name)
            for r in rows:
                r.append(len(str(r[i])))
            schema.columns.append({"name": new_name,
                                   "type": ColumnType.INTEGER})
            return rows, schema
        if kind == "str_trim":
            (name,) = arg
            i = names.index(name)
            for r in rows:
                r[i] = str(r[i]).strip()
            return rows, schema
        if kind == "str_pad":
            name, length, ch, side = arg
            i = names.index(name)
            for r in rows:
                v = str(r[i])
                r[i] = (v.rjust(length, ch) if side.upper() == "LEFT"
                        else v.ljust(length, ch))
            return rows, schema
        if kind == "str_sub":
            name, frm, to = arg
            i = names.index(name)
            for r in rows:
                r[i] = str(r[i])[frm:to]
            return rows, schema
        if kind == "str_map_except":
            name, new_value, keep = arg
            i = names.index(name)
            keep = set(keep)
            for r in rows:
                if str(r[i]) not in keep:
                    r[i] = new_value
            return rows, schema
        if kind == "onehot2cat":
            new_name, cols = arg
            idxs = [names.index(c) for c in cols]
            # state name = the text inside "col[state]" when present
            states = [c[c.index("[") + 1:-1] if "[" in c else c for c in cols]
            first = min(idxs)
            for r in rows:
                hot = [j for j, i in enumerate(idxs) if float(r[i]) > 0.5]
                val = states[hot[0]] if hot else states[0]
                for i in sorted(idxs, reverse=True):
                    del r[i]
                r.insert(first, val)
            keep_cols = [c for j, c in enumerate(schema.columns)
                         if j not in idxs]
            keep_cols.insert(first, {"name": new_name,
                                     "type": ColumnType.CATEGORICAL,
                                     "states": states})
            return rows, Schema(keep_cols)
        if kind == "filter_invalid":
            idxs = [names.index(n) for n in arg]

            def bad(r):
                for i in idxs:
                    try:
                        v = float(r[i])
                    except (TypeError, ValueError):
                        return True
                    if v != v:  # NaN
                        return True
                return False
            return [r for r in rows if not bad(r)], schema
        if kind == "cond_copy":
            dst, src, pred = arg
            di, si = names.index(dst), names.index(src)
            for r in rows:
                if pred(r[di]):
                    r[di] = r[si]
            return rows, schema
        if kind == "reduce":
            return arg.reduce(rows, schema)
        raise ValueError(kind)


class RecordReaderDataSetIterator(DataSetIterator):
    """Bridge RecordReader → DataSet batches
    (ref: org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: int = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.reset()

    def reset(self):
        self.reader.reset()

    def hasNext(self):
        return self.reader.hasNext()

    def next(self) -> DataSet:
        feats, labels = [], []
        n = 0
        while self.reader.hasNext() and n < self.batch_size:
            rec = [w.value if isinstance(w, Writable) else w
                   for w in self.reader.next()]
            if self.label_index is None:
                feats.append([float(v) for v in rec])
            else:
                li = self.label_index if self.label_index >= 0 \
                    else len(rec) + self.label_index
                lab = rec[li]
                row = [float(v) for j, v in enumerate(rec) if j != li]
                feats.append(row)
                labels.append(lab)
            n += 1
        features = np.asarray(feats, np.float32)
        if self.label_index is None:
            return self._apply_pre(DataSet(features, None))
        if self.regression:
            y = np.asarray(labels, np.float32).reshape(-1, 1)
        else:
            y = np.eye(self.num_classes, dtype=np.float32)[
                np.asarray(labels, np.int64)]
        return self._apply_pre(DataSet(features, y))

    def batch(self):
        return self.batch_size


# --------------------------------------------------------------- aggregation
class Reducer:
    """Group-by aggregation (ref: org.datavec.api.transform.reduce.Reducer):
    key columns plus per-column reduction ops; one output row per key,
    reduced columns named ``op(column)`` like the reference."""

    _OPS = {
        "Sum": lambda vs: float(sum(vs)),
        "Mean": lambda vs: float(sum(vs) / len(vs)),
        "Min": lambda vs: float(min(vs)),
        "Max": lambda vs: float(max(vs)),
        "Stdev": lambda vs: float(np.std(np.asarray(vs), ddof=1))
        if len(vs) > 1 else 0.0,
        "Count": len,
        "CountUnique": lambda vs: len(set(vs)),
        "First": lambda vs: vs[0],
        "Last": lambda vs: vs[-1],
    }

    def __init__(self, key_columns, column_ops):
        self.key_columns = list(key_columns)
        self.column_ops = column_ops          # [(column, op), ...]

    class Builder:
        def __init__(self, *key_columns):
            self._keys = list(key_columns)
            self._ops = []

        def _add(self, op, names):
            self._ops.extend((n, op) for n in names)
            return self

        def sumColumns(self, *names): return self._add("Sum", names)
        def meanColumns(self, *names): return self._add("Mean", names)
        def minColumns(self, *names): return self._add("Min", names)
        def maxColumns(self, *names): return self._add("Max", names)
        def stdevColumns(self, *names): return self._add("Stdev", names)
        def countColumns(self, *names): return self._add("Count", names)
        def countUniqueColumns(self, *names):
            return self._add("CountUnique", names)
        def firstColumns(self, *names): return self._add("First", names)
        def lastColumns(self, *names): return self._add("Last", names)

        def build(self):
            return Reducer(self._keys, self._ops)

    def reduce(self, rows, schema: Schema):
        names = schema.getColumnNames()
        kidx = [names.index(k) for k in self.key_columns]
        groups = {}
        order = []
        for r in rows:
            k = tuple(r[i] for i in kidx)
            if k not in groups:
                order.append(k)
            groups.setdefault(k, []).append(r)
        out = []
        for k in order:
            grp = groups[k]
            row = list(k)
            for col, op in self.column_ops:
                i = names.index(col)
                vals = [g[i] for g in grp]
                if op not in ("First", "Last", "Count", "CountUnique"):
                    vals = [float(v) for v in vals]
                row.append(self._OPS[op](vals))
            out.append(row)
        cols = [dict(schema.columns[i]) for i in kidx]
        for col, op in self.column_ops:
            ct = (ColumnType.INTEGER if op in ("Count", "CountUnique")
                  else ColumnType.DOUBLE if op not in ("First", "Last")
                  else schema.columns[names.index(col)]["type"])
            cols.append({"name": f"{op.lower()}({col})", "type": ct})
        return out, Schema(cols)


# --------------------------------------------------------------------- joins
class Join:
    """ref: org.datavec.api.transform.join.Join — Inner/LeftOuter/
    RightOuter/FullOuter on key columns. Execute with ``executeJoin``."""

    def __init__(self, join_type, join_columns, left_schema, right_schema):
        self.join_type = join_type
        self.join_columns = list(join_columns)
        self.left_schema = left_schema
        self.right_schema = right_schema

    class Builder:
        def __init__(self, join_type: str = "Inner"):
            if join_type not in ("Inner", "LeftOuter", "RightOuter",
                                 "FullOuter"):
                raise ValueError(f"unknown join type '{join_type}'")
            self._type = join_type
            self._cols = []
            self._left = self._right = None

        def setJoinColumns(self, *names):
            self._cols = list(names)
            return self

        def setSchemas(self, left: Schema, right: Schema):
            self._left, self._right = left, right
            return self

        def build(self):
            return Join(self._type, self._cols, self._left, self._right)

    def outputSchema(self) -> Schema:
        keep_right = [c for c in self.right_schema.columns
                      if c["name"] not in self.join_columns]
        return Schema([dict(c) for c in self.left_schema.columns]
                      + [dict(c) for c in keep_right])


def executeJoin(join: Join, left_rows, right_rows):
    """ref: LocalTransformExecutor.executeJoin — hash join on the key
    columns; missing sides null-fill (None) for the outer types."""
    lnames = join.left_schema.getColumnNames()
    rnames = join.right_schema.getColumnNames()
    lk = [lnames.index(c) for c in join.join_columns]
    rk = [rnames.index(c) for c in join.join_columns]
    r_rest = [i for i in range(len(rnames)) if i not in rk]
    l_width = len(lnames)

    def _vals(rows):
        return [[w.value if isinstance(w, Writable) else w for w in r]
                for r in rows]
    left_rows, right_rows = _vals(left_rows), _vals(right_rows)

    rindex = {}
    for r in right_rows:
        rindex.setdefault(tuple(r[i] for i in rk), []).append(r)
    out = []
    matched_right = set()
    for l in left_rows:
        k = tuple(l[i] for i in lk)
        matches = rindex.get(k, [])
        if matches:
            matched_right.add(k)
            for r in matches:
                out.append(list(l) + [r[i] for i in r_rest])
        elif join.join_type in ("LeftOuter", "FullOuter"):
            out.append(list(l) + [None] * len(r_rest))
    if join.join_type in ("RightOuter", "FullOuter"):
        for k, rs in rindex.items():
            if k in matched_right:
                continue
            for r in rs:
                row = [None] * l_width
                for li, ri in zip(lk, rk):
                    row[li] = r[ri]
                out.append(row + [r[i] for i in r_rest])
    return out


class CollectionSequenceRecordReader(RecordReader):
    """ref: impl.collection.CollectionSequenceRecordReader — iterate
    in-memory sequences (lists of rows)."""

    def __init__(self, sequences):
        self._sequences = [[list(r) for r in seq] for seq in sequences]
        self._pos = 0

    def hasNext(self):
        return self._pos < len(self._sequences)

    def next(self):
        s = self._sequences[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._pos = 0


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence reader → [N, C, T] DataSet batches (ref:
    org.deeplearning4j.datasets.datavec
    .SequenceRecordReaderDataSetIterator, single-reader mode: the label
    column is part of each timestep row)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: int = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def reset(self):
        self.reader.reset()

    def hasNext(self):
        return self.reader.hasNext()

    def next(self) -> DataSet:
        seqs = []
        while self.reader.hasNext() and len(seqs) < self.batch_size:
            seq = [[w.value if isinstance(w, Writable) else w for w in r]
                   for r in self.reader.next()]
            seqs.append(seq)
        T = max(len(s) for s in seqs)
        n_cols = len(seqs[0][0])
        li = self.label_index if self.label_index >= 0 \
            else n_cols + self.label_index
        f_idx = [i for i in range(n_cols) if i != li]
        N = len(seqs)
        feats = np.zeros((N, len(f_idx), T), np.float32)
        mask = np.zeros((N, T), np.float32)
        if self.regression:
            labels = np.zeros((N, 1, T), np.float32)
        else:
            labels = np.zeros((N, self.num_classes, T), np.float32)
        for n, seq in enumerate(seqs):
            for t, row in enumerate(seq):
                for j, i in enumerate(f_idx):
                    feats[n, j, t] = float(row[i])
                if self.regression:
                    labels[n, 0, t] = float(row[li])
                else:
                    labels[n, int(float(row[li])), t] = 1.0
                mask[n, t] = 1.0
        full = bool(mask.all())
        return DataSet(feats, labels,
                       None if full else mask, None if full else mask)
