"""Datasets/ETL (ref: DataVec + deeplearning4j-data — SURVEY.md §2.2)."""

from deeplearning4j_tpu.data.dataset import (  # noqa: F401
    AsyncDataSetIterator,
    DataSet,
    DataSetIterator,
    DevicePrefetcher,
    ImagePreProcessingScaler,
    IterableDataSetIterator,
    ListDataSetIterator,
    MultiDataSet,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    RetryingDataSetIterator,
    TransientDataError,
    is_transient_error,
)
from deeplearning4j_tpu.data.iterators import (  # noqa: F401
    IrisDataSetIterator,
    MnistDataSetIterator,
)
from deeplearning4j_tpu.data.image import (  # noqa: F401
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
    NativeImageLoader,
    ObjectDetectionDataSetIterator,
    ObjectDetectionRecordReader,
    ParentPathLabelGenerator,
    PipelineImageTransform,
)
from deeplearning4j_tpu.data.iterators import Cifar10DataSetIterator  # noqa: F401
from deeplearning4j_tpu.data.pipeline import (  # noqa: F401
    DataPipelineError,
    ImagePipeline,
    MultiWorkerImageIterator,
    StagedImageIterator,
)
from deeplearning4j_tpu.data.audio import (  # noqa: F401
    AudioDataSetIterator,
    WavFileRecordReader,
    mel_spectrogram,
    mfcc,
    read_wav,
    spectrogram,
    write_wav,
)
