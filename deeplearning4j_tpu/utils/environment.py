"""Central runtime environment / flag registry.

Reference parity: ND4J centralises every ``-D``/env knob in
``org.nd4j.common.config.{ND4JSystemProperties,ND4JEnvironmentVars}`` and
bridges JVM state to libnd4j's ``include/system/Environment.h`` via
``Nd4j.getEnvironment()`` (SURVEY.md §5 "Config / flag system").

Here the registry is a single process-wide :class:`Environment` singleton.
Every knob has (a) a typed attribute, (b) an environment-variable override
(``DL4J_TPU_*``), and (c) a docstring row in :data:`KNOBS` so the full
registry is introspectable (``Environment.describe()``).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field, fields
from typing import Any


def _env(name: str, default: Any, typ: type) -> Any:
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return typ(raw)


@dataclass
class Environment:
    """Process-wide runtime flags (singleton via :meth:`get`)."""

    # -- debug / verbosity (ref: libnd4j Environment::setDebug/setVerbose) --
    debug: bool = field(default_factory=lambda: _env("DL4J_TPU_DEBUG", False, bool))
    verbose: bool = field(default_factory=lambda: _env("DL4J_TPU_VERBOSE", False, bool))

    # -- numerics (ref: OpExecutioner ProfilingMode NAN_PANIC/INF_PANIC) --
    nan_panic: bool = field(default_factory=lambda: _env("DL4J_TPU_NAN_PANIC", False, bool))
    inf_panic: bool = field(default_factory=lambda: _env("DL4J_TPU_INF_PANIC", False, bool))

    # -- precision policy: compute dtype for matmul/conv on the MXU --
    # bf16 matmuls with f32 accumulation are the TPU-native default; set to
    # "float32" ("highest") to force full-precision MXU passes.
    matmul_precision: str = field(
        default_factory=lambda: _env("DL4J_TPU_MATMUL_PRECISION", "bfloat16", str)
    )

    # -- profiling (ref: OpProfiler / ProfilingListener) --
    profiling: bool = field(default_factory=lambda: _env("DL4J_TPU_PROFILING", False, bool))
    profile_dir: str = field(default_factory=lambda: _env("DL4J_TPU_PROFILE_DIR", "/tmp/dl4j_tpu_profile", str))

    # -- compile cache --
    compile_cache_dir: str = field(
        default_factory=lambda: _env("DL4J_TPU_COMPILE_CACHE", "", str)
    )

    # -- data pipeline --
    prefetch_buffer: int = field(default_factory=lambda: _env("DL4J_TPU_PREFETCH", 2, int))
    loader_threads: int = field(default_factory=lambda: _env("DL4J_TPU_LOADER_THREADS", 4, int))

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> "Environment":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    def describe(self) -> str:
        """Human-readable registry of every knob and its current value."""
        rows = []
        for f in fields(self):
            if f.name.startswith("_"):
                continue
            env_var = "DL4J_TPU_" + f.name.upper()
            rows.append(f"{f.name:<22} {env_var:<28} = {getattr(self, f.name)!r}")
        return "\n".join(rows)


KNOBS = {
    "debug": "Verbose per-op debug logging (ref: libnd4j Environment::setDebug)",
    "verbose": "Extra execution logging (ref: Environment::setVerbose)",
    "nan_panic": "Raise if any op output contains NaN (ref: ProfilingMode.NAN_PANIC)",
    "inf_panic": "Raise if any op output contains Inf (ref: ProfilingMode.INF_PANIC)",
    "matmul_precision": "MXU compute precision: bfloat16|tensorfloat32|float32",
    "profiling": "Enable per-op profiling (ref: OpProfiler)",
    "profile_dir": "Directory for Chrome-trace profiles (ref: ProfilingListener)",
    "compile_cache_dir": "Persistent XLA compile cache directory",
    "prefetch_buffer": "Async iterator prefetch depth (ref: AsyncDataSetIterator)",
    "loader_threads": "Host data-loading threads (ref: libnd4j Threads, data only)",
}


class NumericsPanicError(ArithmeticError):
    """Raised by NAN_PANIC/INF_PANIC debug modes (ref: OpExecutioner
    ProfilingMode.NAN_PANIC / INF_PANIC)."""


def panic_check(value, context: str = "loss"):
    """Debug-mode numerics gate: under ``ProfilingMode.NAN_PANIC`` /
    ``INF_PANIC`` (set via ``profiler.set_profiling_mode`` or the
    ``DL4J_TPU_{NAN,INF}_PANIC`` env knobs — one unified mode, ref:
    OpExecutioner.ProfilingMode), synchronously pull ``value`` and raise
    on NaN/Inf with the training context. Costs a host sync per call — a
    DEBUG mode, off by default."""
    from deeplearning4j_tpu.profiler.modes import (ProfilingMode,
                                                   get_profiling_mode)
    # the unified mode is the single gate: an explicit
    # set_profiling_mode(...) override wins over the env knobs
    mode = get_profiling_mode()
    check_nan = mode is ProfilingMode.NAN_PANIC
    check_inf = mode is ProfilingMode.INF_PANIC
    if not (check_nan or check_inf):
        return
    import numpy as _np
    v = _np.asarray(value)
    if check_nan and _np.isnan(v).any():
        raise NumericsPanicError(f"NAN_PANIC: NaN detected in {context}")
    if check_inf and _np.isinf(v).any():
        raise NumericsPanicError(f"INF_PANIC: Inf detected in {context}")
