"""Small shared concurrency primitives (jax-free).

Home of cross-thread plumbing used by more than one subsystem; keeping
one implementation means its exactly-once semantics are race-tested in
one place (``pytest -m races``) instead of drifting between copies.
"""

from __future__ import annotations

import threading


class ErrorLatch:
    """First-error latch shared by a worker thread and its consumer.
    The worker records the first failure, the consumer marks it
    delivered when it surfaces through the normal result channel, and
    ``close()``-style paths take whatever was never delivered — every
    transition under one lock, so a worker error racing a shutdown can
    neither be lost nor double-raised (DL4J-E201/E202: such fields used
    to be bare cross-thread writes). Used by AsyncDataSetIterator,
    DevicePrefetcher, and the async checkpoint writer."""

    __slots__ = ("_lock", "_error")

    def __init__(self):
        self._lock = threading.Lock()
        self._error: "BaseException | None" = None

    def record(self, e: BaseException) -> None:
        """Worker side: the FIRST error wins."""
        with self._lock:
            if self._error is None:
                self._error = e

    def delivered(self, e: BaseException) -> None:
        """Consumer side: this error surfaced via the queue — close()
        must not re-raise it."""
        with self._lock:
            if self._error is e:
                self._error = None

    def clear(self) -> None:
        with self._lock:
            self._error = None

    def take(self) -> "BaseException | None":
        with self._lock:
            e, self._error = self._error, None
            return e
