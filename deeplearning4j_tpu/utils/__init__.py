from deeplearning4j_tpu.utils.environment import Environment  # noqa: F401
