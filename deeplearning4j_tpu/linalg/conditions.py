"""Conditions + BooleanIndexing (ref: ``org.nd4j.linalg.indexing.
conditions.Conditions`` and ``BooleanIndexing`` — SURVEY.md §2.2 L1).

A Condition is a predicate producing a boolean mask over an array;
BooleanIndexing applies them (replaceWhere / countOccurrences / and/or)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class Condition:
    def __init__(self, fn):
        self._fn = fn

    def mask(self, value) -> jnp.ndarray:
        return self._fn(value)

    def __and__(self, other: "Condition") -> "Condition":
        return Condition(lambda v: jnp.logical_and(self.mask(v),
                                                   other.mask(v)))

    def __or__(self, other: "Condition") -> "Condition":
        return Condition(lambda v: jnp.logical_or(self.mask(v),
                                                  other.mask(v)))

    def __invert__(self) -> "Condition":
        return Condition(lambda v: jnp.logical_not(self.mask(v)))


class Conditions:
    """ref: Conditions.{greaterThan, lessThan, ...} static factories."""

    @staticmethod
    def greaterThan(x): return Condition(lambda v: v > x)

    @staticmethod
    def greaterThanOrEqual(x): return Condition(lambda v: v >= x)

    @staticmethod
    def lessThan(x): return Condition(lambda v: v < x)

    @staticmethod
    def lessThanOrEqual(x): return Condition(lambda v: v <= x)

    @staticmethod
    def equals(x): return Condition(lambda v: v == x)

    @staticmethod
    def notEquals(x): return Condition(lambda v: v != x)

    @staticmethod
    def epsEquals(x, eps: float = 1e-5):
        return Condition(lambda v: jnp.abs(v - x) <= eps)

    @staticmethod
    def epsNotEquals(x, eps: float = 1e-5):
        return Condition(lambda v: jnp.abs(v - x) > eps)

    @staticmethod
    def isNan(): return Condition(jnp.isnan)

    @staticmethod
    def isInfinite(): return Condition(jnp.isinf)

    @staticmethod
    def isFinite(): return Condition(jnp.isfinite)

    @staticmethod
    def notFinite(): return Condition(lambda v: ~jnp.isfinite(v))

    @staticmethod
    def absGreaterThan(x): return Condition(lambda v: jnp.abs(v) > x)

    @staticmethod
    def absLessThan(x): return Condition(lambda v: jnp.abs(v) < x)


class BooleanIndexing:
    """ref: org.nd4j.linalg.indexing.BooleanIndexing statics."""

    @staticmethod
    def replaceWhere(arr, replacement, condition: Condition):
        return arr.replaceWhere(replacement, condition)

    @staticmethod
    def countOccurrences(arr, condition: Condition) -> int:
        return int(jnp.sum(condition.mask(arr.jax())))

    @staticmethod
    def and_(arr, condition: Condition) -> bool:
        return bool(jnp.all(condition.mask(arr.jax())))

    @staticmethod
    def or_(arr, condition: Condition) -> bool:
        return bool(jnp.any(condition.mask(arr.jax())))

    @staticmethod
    def firstIndex(arr, condition: Condition) -> int:
        m = np.asarray(condition.mask(arr.jax())).reshape(-1)
        idx = np.nonzero(m)[0]
        return int(idx[0]) if idx.size else -1

    @staticmethod
    def lastIndex(arr, condition: Condition) -> int:
        m = np.asarray(condition.mask(arr.jax())).reshape(-1)
        idx = np.nonzero(m)[0]
        return int(idx[-1]) if idx.size else -1
