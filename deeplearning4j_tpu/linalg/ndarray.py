"""Eager NDArray — the INDArray equivalent.

Reference parity: ``org.nd4j.linalg.api.ndarray.INDArray`` /
``BaseNDArray`` (~300 methods; views, in-place ``*i`` variants,
broadcasting, ``mmul``). SURVEY.md §2.2 "INDArray API".

TPU-native design (NOT a port of BaseNDArray):

- The array is an immutable ``jax.Array``; "in-place" ``*i`` methods swap
  the wrapper's buffer (functional under the hood — XLA-friendly, no
  aliasing machinery). This preserves the reference's *API contract*
  (``x.addi(y)`` mutates ``x`` as observed by every holder of the same
  NDArray object) without libnd4j's strided-buffer machinery.
- Views (``get``, ``getRow``, ``slice_``, ``__getitem__``) return
  write-back views: mutating a view updates the base via a functional
  ``at[...].set`` — the observable semantics of ND4J views for the
  patterns the framework itself uses (param vector regions, row assigns).
- There is no TAD/stride engine: XLA owns layout (SURVEY.md §2.1 "Shape
  machinery → mostly vanishes").
- Ops dispatch straight to jnp/lax; XLA fuses. Eager dispatch is cheap
  because jax caches per-shape compiled single-op programs.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.linalg.dtypes import DataType

Index = Union[int, slice, tuple, "NDArray", jnp.ndarray]


def _unwrap(x):
    if isinstance(x, NDArray):
        return x.jax()
    return x


class NDArray:
    """Device ndarray with INDArray-style API over a ``jax.Array``."""

    __slots__ = ("_buf", "_base", "_index")
    __array_priority__ = 100  # beat numpy in mixed binary ops

    def __init__(self, value, base: Optional["NDArray"] = None, index: Optional[Index] = None):
        if base is None:
            if isinstance(value, NDArray):
                value = value.jax()
            if not isinstance(value, jax.Array):
                value = jnp.asarray(value)
            self._buf = value
        else:
            self._buf = None  # views read through to the base, never snapshot
        self._base = base
        self._index = index

    # ------------------------------------------------------------------ core
    @property
    def _value(self) -> jax.Array:
        if self._base is not None:
            return self._base._value[self._index]
        return self._buf

    def jax(self) -> jax.Array:
        return self._value

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._value.shape)

    @property
    def dtype(self) -> DataType:
        return DataType.from_dtype(self._value.dtype)

    def dataType(self) -> DataType:
        return self.dtype

    def rank(self) -> int:
        return self._value.ndim

    def length(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def size(self, dim: int) -> int:
        return self.shape[dim]

    def isView(self) -> bool:
        return self._base is not None

    def isScalar(self) -> bool:
        return self._value.ndim == 0 or self.length() == 1

    def isVector(self) -> bool:
        return self.rank() == 1 or (self.rank() == 2 and 1 in self.shape)

    def isMatrix(self) -> bool:
        return self.rank() == 2

    def rows(self) -> int:
        return self.shape[0]

    def columns(self) -> int:
        return self.shape[1]

    # --------------------------------------------------------- mutation core
    def _set_value(self, new: jax.Array) -> "NDArray":
        """Install a new buffer; propagate to base if this is a view."""
        cur = self._value
        if new.dtype != cur.dtype:
            new = new.astype(cur.dtype)
        if new.shape != cur.shape:
            raise ValueError(
                f"in-place op cannot change shape: {cur.shape} -> {new.shape}"
            )
        if self._base is not None:
            self._base._set_value(self._base._value.at[self._index].set(new))
        else:
            self._buf = new
        return self

    def assign(self, other) -> "NDArray":
        """In-place overwrite (ref: INDArray.assign)."""
        other = _unwrap(other)
        return self._set_value(jnp.broadcast_to(jnp.asarray(other, self._value.dtype), self.shape))

    # -------------------------------------------------------------- elementwise
    def _binary(self, other, fn, inplace: bool = False) -> "NDArray":
        res = fn(self._value, _unwrap(other))
        if inplace:
            return self._set_value(res)
        return NDArray(res)

    def add(self, o):  return self._binary(o, jnp.add)
    def sub(self, o):  return self._binary(o, jnp.subtract)
    def mul(self, o):  return self._binary(o, jnp.multiply)
    def div(self, o):  return self._binary(o, jnp.divide)
    def rsub(self, o): return self._binary(o, lambda a, b: b - a)
    def rdiv(self, o): return self._binary(o, lambda a, b: b / a)
    def addi(self, o): return self._binary(o, jnp.add, inplace=True)
    def subi(self, o): return self._binary(o, jnp.subtract, inplace=True)
    def muli(self, o): return self._binary(o, jnp.multiply, inplace=True)
    def divi(self, o): return self._binary(o, jnp.divide, inplace=True)

    __add__ = add
    __sub__ = sub
    __mul__ = mul
    __truediv__ = div
    __radd__ = add
    __rmul__ = mul
    def __rsub__(self, o): return self.rsub(o)
    def __rtruediv__(self, o): return self.rdiv(o)
    def __neg__(self): return NDArray(-self._value)
    def __pow__(self, p): return NDArray(self._value ** _unwrap(p))
    def __matmul__(self, o): return self.mmul(o)

    def neg(self): return NDArray(-self._value)
    def negi(self): return self._set_value(-self._value)

    # comparison → BOOL arrays (ref: INDArray.gt/lt/eq...)
    def gt(self, o): return self._binary(o, jnp.greater)
    def gte(self, o): return self._binary(o, jnp.greater_equal)
    def lt(self, o): return self._binary(o, jnp.less)
    def lte(self, o): return self._binary(o, jnp.less_equal)
    def eq(self, o): return self._binary(o, jnp.equal)
    def neq(self, o): return self._binary(o, jnp.not_equal)

    # ------------------------------------------------------------- linalg
    def mmul(self, other, transpose_a: bool = False, transpose_b: bool = False) -> "NDArray":
        """Matrix multiply on the MXU (ref: INDArray.mmul → BLAS GEMM;
        here: one XLA dot_general, bf16-accumulate policy via
        ``Environment.matmul_precision``)."""
        a, b = self._value, _unwrap(other)
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        from deeplearning4j_tpu.utils.environment import Environment
        prec = Environment.get().matmul_precision
        precision = {"bfloat16": jax.lax.Precision.DEFAULT,
                     "tensorfloat32": jax.lax.Precision.HIGH,
                     "float32": jax.lax.Precision.HIGHEST}.get(prec, jax.lax.Precision.DEFAULT)
        return NDArray(jnp.matmul(a, b, precision=precision))

    def dot(self, other) -> float:
        return float(jnp.vdot(self._value, _unwrap(other)))

    def transpose(self, *axes) -> "NDArray":
        """No-args form reverses ALL dimensions (ref: INDArray.transpose)."""
        if not axes:
            return NDArray(jnp.transpose(self._value))
        return NDArray(jnp.transpose(self._value, axes))

    def permute(self, *axes) -> "NDArray":
        return NDArray(jnp.transpose(self._value, axes))

    # ------------------------------------------------------------- reductions
    def _reduce(self, fn, dims, keepdims=False):
        axis = None
        if dims:
            axis = tuple(d if d >= 0 else d + self.rank() for d in dims)
        res = fn(self._value, axis=axis, keepdims=keepdims)
        return NDArray(res)

    def sum(self, *dims, keepdims=False):  return self._reduce(jnp.sum, dims, keepdims)
    def mean(self, *dims, keepdims=False): return self._reduce(jnp.mean, dims, keepdims)
    def max(self, *dims, keepdims=False):  return self._reduce(jnp.max, dims, keepdims)
    def min(self, *dims, keepdims=False):  return self._reduce(jnp.min, dims, keepdims)
    def prod(self, *dims, keepdims=False): return self._reduce(jnp.prod, dims, keepdims)
    def std(self, *dims, keepdims=False):
        return self._reduce(lambda v, axis, keepdims: jnp.std(v, axis=axis, ddof=1, keepdims=keepdims), dims, keepdims)
    def var(self, *dims, keepdims=False):
        return self._reduce(lambda v, axis, keepdims: jnp.var(v, axis=axis, ddof=1, keepdims=keepdims), dims, keepdims)
    def _arg_reduce(self, fn, dims):
        """argMax/argMin over one or MORE dims (ref: INDArray.argMax(int...)):
        the given dims are flattened into one plane and the flat index within
        that plane is returned."""
        if not dims:
            return NDArray(fn(self._value))
        dims = tuple(sorted(d if d >= 0 else d + self.rank() for d in dims))
        if len(dims) == 1:
            return NDArray(fn(self._value, axis=dims[0]))
        other = tuple(d for d in range(self.rank()) if d not in dims)
        moved = jnp.transpose(self._value, other + dims)
        flat_shape = tuple(self.shape[d] for d in other) + (-1,)
        return NDArray(fn(jnp.reshape(moved, flat_shape), axis=-1))

    def argMax(self, *dims):
        return self._arg_reduce(jnp.argmax, dims)
    def argMin(self, *dims):
        return self._arg_reduce(jnp.argmin, dims)
    def norm1(self, *dims): return self._reduce(lambda v, axis, keepdims: jnp.sum(jnp.abs(v), axis=axis, keepdims=keepdims), dims, False)
    def norm2(self, *dims): return self._reduce(lambda v, axis, keepdims: jnp.sqrt(jnp.sum(v * v, axis=axis, keepdims=keepdims)), dims, False)
    def normMax(self, *dims): return self._reduce(lambda v, axis, keepdims: jnp.max(jnp.abs(v), axis=axis, keepdims=keepdims), dims, False)

    def sumNumber(self) -> float:  return float(jnp.sum(self._value))
    def meanNumber(self) -> float: return float(jnp.mean(self._value))
    def maxNumber(self) -> float:  return float(jnp.max(self._value))
    def minNumber(self) -> float:  return float(jnp.min(self._value))
    def norm2Number(self) -> float: return float(jnp.sqrt(jnp.sum(self._value * self._value)))
    def norm1Number(self) -> float: return float(jnp.sum(jnp.abs(self._value)))

    def cumsum(self, dim: int = 0): return NDArray(jnp.cumsum(self._value, axis=dim))

    # ------------------------------------------------------------- shape ops
    def reshape(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.reshape(self._value, shape))

    def ravel(self) -> "NDArray":
        return NDArray(jnp.ravel(self._value))

    def flatten(self) -> "NDArray":
        return self.ravel()

    def broadcast(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.broadcast_to(self._value, shape))

    def repeat(self, dim: int, n: int) -> "NDArray":
        return NDArray(jnp.repeat(self._value, n, axis=dim))

    def tile(self, *reps) -> "NDArray":
        return NDArray(jnp.tile(self._value, reps))

    def squeeze(self, axis=None) -> "NDArray":
        return NDArray(jnp.squeeze(self._value, axis=axis))

    def expandDims(self, axis: int) -> "NDArray":
        return NDArray(jnp.expand_dims(self._value, axis))

    def dup(self) -> "NDArray":
        """Detached copy (ref: INDArray.dup)."""
        return NDArray(self._value)

    def castTo(self, dtype: DataType) -> "NDArray":
        return NDArray(self._value.astype(dtype.jnp))

    # ------------------------------------------------------------- indexing
    def __getitem__(self, idx) -> "NDArray":
        idx = tuple(_unwrap(i) for i in idx) if isinstance(idx, tuple) else _unwrap(idx)
        return NDArray(self._value[idx], base=self, index=idx)

    def __setitem__(self, idx, value) -> None:
        idx = tuple(_unwrap(i) for i in idx) if isinstance(idx, tuple) else _unwrap(idx)
        self._set_value(self._value.at[idx].set(jnp.asarray(_unwrap(value), self._value.dtype)))

    def getRow(self, i: int) -> "NDArray":
        return self[i]

    def getColumn(self, i: int) -> "NDArray":
        return self[:, i]

    def putRow(self, i: int, row) -> "NDArray":
        self[i] = row
        return self

    def putColumn(self, i: int, col) -> "NDArray":
        self[:, i] = col
        return self

    def getScalar(self, *indices) -> float:
        return float(self._value[tuple(indices)])

    def getDouble(self, *indices) -> float:
        return float(self._value[tuple(indices)])

    def getInt(self, *indices) -> int:
        return int(self._value[tuple(indices)])

    def putScalar(self, *args) -> "NDArray":
        *indices, value = args
        if len(indices) == 1 and isinstance(indices[0], (tuple, list)):
            indices = list(indices[0])
        self._set_value(self._value.at[tuple(indices)].set(jnp.asarray(value, self._value.dtype)))
        return self

    def slice_(self, i: int, dim: int = 0) -> "NDArray":
        idx = (slice(None),) * dim + (i,)
        return self[idx]

    def tensorAlongDimension(self, index: int, *dims) -> "NDArray":
        """TAD equivalent — kept only for API familiarity; implemented as a
        transpose+reshape+index (ref: libnd4j TAD, SURVEY.md §2.1)."""
        dims = tuple(d if d >= 0 else d + self.rank() for d in dims)
        other = tuple(d for d in range(self.rank()) if d not in dims)
        perm = other + dims
        moved = jnp.transpose(self._value, perm)
        lead = int(np.prod([self.shape[d] for d in other])) if other else 1
        moved = jnp.reshape(moved, (lead,) + tuple(self.shape[d] for d in dims))
        return NDArray(moved[index])

    # ------------------------------------------------------------- misc math
    def _unary(self, fn, inplace=False):
        res = fn(self._value)
        return self._set_value(res) if inplace else NDArray(res)

    def abs(self):   return self._unary(jnp.abs)
    def exp(self):   return self._unary(jnp.exp)
    def log(self):   return self._unary(jnp.log)
    def sqrt(self):  return self._unary(jnp.sqrt)
    def tanh(self):  return self._unary(jnp.tanh)
    def sigmoid(self): return self._unary(jax.nn.sigmoid)
    def relu(self):  return self._unary(jax.nn.relu)
    def sin(self):   return self._unary(jnp.sin)
    def cos(self):   return self._unary(jnp.cos)
    def floor(self): return self._unary(jnp.floor)
    def ceil(self):  return self._unary(jnp.ceil)
    def round(self): return self._unary(jnp.round)
    def sign(self):  return self._unary(jnp.sign)
    def clip(self, lo, hi): return self._unary(lambda v: jnp.clip(v, lo, hi))

    # ------------------------------------------------------------- protocol
    def __len__(self) -> int:
        return self.shape[0] if self.shape else 1

    def __float__(self) -> float:
        return float(self._value)

    def __int__(self) -> int:
        return int(self._value)

    def __bool__(self) -> bool:
        if self.length() != 1:
            raise ValueError("Truth value of multi-element NDArray is ambiguous")
        return bool(self._value)

    def __repr__(self) -> str:
        return f"NDArray(shape={self.shape}, dtype={self.dtype.name})\n{np.asarray(self._value)!r}"

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    def equalsWithEps(self, other, eps: float = 1e-5) -> bool:
        o = _unwrap(other)
        if tuple(jnp.shape(o)) != self.shape:
            return False
        return bool(jnp.all(jnp.abs(self._value.astype(jnp.float32) - jnp.asarray(o, jnp.float32)) <= eps))

    def equals(self, other) -> bool:
        return self.equalsWithEps(other, 1e-5)


jax.tree_util.register_pytree_node(
    NDArray,
    lambda nd: ((nd.jax(),), None),
    lambda aux, children: NDArray(children[0]),
)


# ---------------------------------------------------------------------------
# round-3 surface widening (VERDICT r2 weak #7): the most-used remaining
# INDArray methods — row/column-vector broadcast ops with i-variants,
# absolute reductions, distances, entropy family, cumulative/product ops,
# axis utilities, conversions. All pure-functional underneath; i-variants
# install the new buffer via _set_value (write-through for views).
# ---------------------------------------------------------------------------

def _rowvec(o):
    v = _unwrap(o)
    return jnp.reshape(jnp.asarray(v), (1, -1))


def _colvec(o):
    v = _unwrap(o)
    return jnp.reshape(jnp.asarray(v), (-1, 1))


def _like_self(v, res):
    """Broadcast results keep self's shape when sizes match (a 1-D row
    operand against a 1-D self must not grow a leading axis)."""
    return jnp.reshape(res, v.shape) if res.size == v.size else res


def _add_methods():
    def rowop(fn):
        def m(self, o):
            return NDArray(_like_self(self._value,
                                      fn(self._value, _rowvec(o))))
        return m

    def rowopi(fn):
        def m(self, o):
            return self._set_value(_like_self(self._value,
                                              fn(self._value, _rowvec(o))))
        return m

    def _need2d(self):
        if self._value.ndim < 2:
            raise ValueError(
                "column-vector ops need a matrix self (a 1-D array against "
                "a column vector would outer-broadcast)")

    def colop(fn):
        def m(self, o):
            _need2d(self)
            return NDArray(_like_self(self._value,
                                      fn(self._value, _colvec(o))))
        return m

    def colopi(fn):
        def m(self, o):
            _need2d(self)
            return self._set_value(_like_self(self._value,
                                              fn(self._value, _colvec(o))))
        return m

    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    for name, fn in ops.items():
        setattr(NDArray, f"{name}RowVector", rowop(fn))
        setattr(NDArray, f"{name}iRowVector", rowopi(fn))
        setattr(NDArray, f"{name}ColumnVector", colop(fn))
        setattr(NDArray, f"{name}iColumnVector", colopi(fn))


_add_methods()


def _extend(cls):
    def deco(fn):
        setattr(cls, fn.__name__, fn)
        return fn
    return deco


@_extend(NDArray)
def mmuli(self, other):
    # route through mmul so Environment.matmul_precision applies
    return self._set_value(self.mmul(other).jax())


@_extend(NDArray)
def rsubi(self, o):
    return self._set_value(_unwrap(o) - self._value)


@_extend(NDArray)
def rdivi(self, o):
    return self._set_value(jnp.asarray(_unwrap(o)) / self._value)


@_extend(NDArray)
def fmod(self, o):
    return NDArray(jnp.fmod(self._value, _unwrap(o)))


@_extend(NDArray)
def fmodi(self, o):
    return self._set_value(jnp.fmod(self._value, _unwrap(o)))


@_extend(NDArray)
def remainder(self, o):
    return NDArray(jnp.mod(self._value, _unwrap(o)))


# absolute-value reductions (ref: amax/amin/amean + *Number variants)
@_extend(NDArray)
def amax(self, *dims):
    return self._reduce(lambda v, axis, keepdims:
                        jnp.max(jnp.abs(v), axis=axis, keepdims=keepdims),
                        dims, False)


@_extend(NDArray)
def amin(self, *dims):
    return self._reduce(lambda v, axis, keepdims:
                        jnp.min(jnp.abs(v), axis=axis, keepdims=keepdims),
                        dims, False)


@_extend(NDArray)
def amean(self, *dims):
    return self._reduce(lambda v, axis, keepdims:
                        jnp.mean(jnp.abs(v), axis=axis, keepdims=keepdims),
                        dims, False)


@_extend(NDArray)
def amaxNumber(self):
    return float(jnp.max(jnp.abs(self._value)))


@_extend(NDArray)
def aminNumber(self):
    return float(jnp.min(jnp.abs(self._value)))


@_extend(NDArray)
def ameanNumber(self):
    return float(jnp.mean(jnp.abs(self._value)))


@_extend(NDArray)
def prodNumber(self):
    return float(jnp.prod(self._value))


@_extend(NDArray)
def stdNumber(self):
    return float(jnp.std(self._value, ddof=1))


@_extend(NDArray)
def varNumber(self):
    return float(jnp.var(self._value, ddof=1))


@_extend(NDArray)
def medianNumber(self):
    return float(jnp.median(self._value))


@_extend(NDArray)
def median(self, *dims):
    return self._reduce(lambda v, axis, keepdims:
                        jnp.median(v, axis=axis), dims, False)


@_extend(NDArray)
def percentile(self, q, *dims):
    if not dims:
        return float(jnp.percentile(self._value, q))
    return self._reduce(lambda v, axis, keepdims:
                        jnp.percentile(v, q, axis=axis), dims, False)


# distances (ref: INDArray.distance1/distance2/squaredDistance)
@_extend(NDArray)
def distance1(self, other) -> float:
    return float(jnp.sum(jnp.abs(self._value - _unwrap(other))))


@_extend(NDArray)
def distance2(self, other) -> float:
    d = self._value - _unwrap(other)
    return float(jnp.sqrt(jnp.sum(d * d)))


@_extend(NDArray)
def squaredDistance(self, other) -> float:
    d = self._value - _unwrap(other)
    return float(jnp.sum(d * d))


# entropy family (ref: INDArray.entropy/shannonEntropy/logEntropy)
@_extend(NDArray)
def entropy(self) -> float:
    p = self._value
    return float(-jnp.sum(p * jnp.log(jnp.maximum(p, 1e-12))))


@_extend(NDArray)
def shannonEntropy(self) -> float:
    p = self._value
    return float(-jnp.sum(p * jnp.log2(jnp.maximum(p, 1e-12))))


@_extend(NDArray)
def logEntropy(self) -> float:
    return float(jnp.log(jnp.maximum(self.entropy(), 1e-12)))


@_extend(NDArray)
def cumprod(self, dim: int = 0):
    return NDArray(jnp.cumprod(self._value, axis=dim))


@_extend(NDArray)
def cumsumi(self, dim: int = 0):
    return self._set_value(jnp.cumsum(self._value, axis=dim))


@_extend(NDArray)
def swapAxes(self, a: int, b: int):
    return NDArray(jnp.swapaxes(self._value, a, b))


@_extend(NDArray)
def reverse(self, *dims):
    ax = dims if dims else None
    return NDArray(jnp.flip(self._value, axis=ax))


@_extend(NDArray)
def sort(self, dim: int = -1, ascending: bool = True):
    out = jnp.sort(self._value, axis=dim)
    return NDArray(out if ascending else jnp.flip(out, axis=dim))


@_extend(NDArray)
def put(self, idx, value):
    """General indexed write (ref: INDArray.put)."""
    if isinstance(idx, tuple):
        idx = tuple(_unwrap(i) for i in idx)
    else:
        idx = _unwrap(idx)
    return self._set_value(
        self._value.at[idx].set(jnp.asarray(_unwrap(value),
                                            self._value.dtype)))


@_extend(NDArray)
def putWhere(self, mask, value):
    m = jnp.asarray(_unwrap(mask), bool)
    v = jnp.asarray(_unwrap(value), self._value.dtype)
    return self._set_value(jnp.where(m, v, self._value))


@_extend(NDArray)
def replaceWhere(self, replacement, condition):
    """ref: BooleanIndexing.replaceWhere(this, replacement, condition)."""
    from deeplearning4j_tpu.linalg.conditions import Condition
    m = condition.mask(self._value) if isinstance(condition, Condition) \
        else jnp.asarray(_unwrap(condition), bool)
    r = jnp.broadcast_to(jnp.asarray(_unwrap(replacement),
                                     self._value.dtype), self.shape)
    return self._set_value(jnp.where(m, r, self._value))


@_extend(NDArray)
def isNaN(self):
    return NDArray(jnp.isnan(self._value))


@_extend(NDArray)
def isInfinite(self):
    return NDArray(jnp.isinf(self._value))


@_extend(NDArray)
def any(self) -> bool:
    return bool(jnp.any(self._value))


@_extend(NDArray)
def all(self) -> bool:
    return bool(jnp.all(self._value))


@_extend(NDArray)
def none(self) -> bool:
    return not self.any()


# boolean combinators over condition masks / bool arrays
@_extend(NDArray)
def and_(self, o):
    return NDArray(jnp.logical_and(self._value, _unwrap(o)))


@_extend(NDArray)
def or_(self, o):
    return NDArray(jnp.logical_or(self._value, _unwrap(o)))


@_extend(NDArray)
def xor_(self, o):
    return NDArray(jnp.logical_xor(self._value, _unwrap(o)))


@_extend(NDArray)
def not_(self):
    return NDArray(jnp.logical_not(self._value))


# host conversions (ref: toDoubleMatrix/toFloatVector/... )
@_extend(NDArray)
def toDoubleMatrix(self):
    return np.asarray(self._value, np.float64)


@_extend(NDArray)
def toFloatMatrix(self):
    return np.asarray(self._value, np.float32)


@_extend(NDArray)
def toDoubleVector(self):
    return np.asarray(self._value, np.float64).reshape(-1)


@_extend(NDArray)
def toFloatVector(self):
    return np.asarray(self._value, np.float32).reshape(-1)


@_extend(NDArray)
def toIntVector(self):
    return np.asarray(self._value, np.int32).reshape(-1)


@_extend(NDArray)
def toIntMatrix(self):
    return np.asarray(self._value, np.int32)


# layout compatibility shims: XLA owns physical layout; logical C-order
@_extend(NDArray)
def stride(self, dim=None):
    """Logical C-order element strides (XLA owns the physical layout;
    pure shape arithmetic, no host transfer)."""
    st = []
    acc = 1
    for d in reversed(self.shape):
        st.append(acc)
        acc *= d
    st = tuple(reversed(st))
    return st if dim is None else st[dim]


@_extend(NDArray)
def ordering(self) -> str:
    return "c"


@_extend(NDArray)
def maxIndex(self) -> int:
    return int(jnp.argmax(self._value))


@_extend(NDArray)
def minIndex(self) -> int:
    return int(jnp.argmin(self._value))


# ---------------------------------------------------------------------------
# r4 surface push toward the ~300-method INDArray interface (VERDICT r3 #9).
# Families are generated like _add_methods above; the inventory test
# (tests/test_linalg.py) asserts the method list against a checked-in set.
# ---------------------------------------------------------------------------

def _add_r4_methods():
    # -- elementwise transform family (ref: Transforms.* instance forms) --
    unaries = {
        "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos,
        "atan": jnp.arctan, "sinh": jnp.sinh, "cosh": jnp.cosh,
        "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
        "log10": jnp.log10, "log2": jnp.log2, "log1p": jnp.log1p,
        "expm1": jnp.expm1, "cbrt": jnp.cbrt, "rsqrt": jax.lax.rsqrt,
        "reciprocal": jnp.reciprocal, "erf": jax.scipy.special.erf,
        "erfc": jax.scipy.special.erfc, "rint": jnp.round,
        "trunc": jnp.trunc, "square": jnp.square,
        "cube": lambda v: v * v * v, "oneMinus": lambda v: 1.0 - v,
        "frac": lambda v: v - jnp.trunc(v),
        "softplus": jax.nn.softplus, "softsign": jax.nn.soft_sign,
        "elu": jax.nn.elu, "selu": jax.nn.selu, "gelu": jax.nn.gelu,
        "swish": jax.nn.swish, "mish": lambda v: v * jnp.tanh(
            jax.nn.softplus(v)),
        "hardSigmoid": jax.nn.hard_sigmoid,
        "hardTanh": lambda v: jnp.clip(v, -1.0, 1.0),
        "leakyRelu": lambda v: jnp.where(v >= 0, v, 0.01 * v),
    }
    for name, fn in unaries.items():
        setattr(NDArray, name,
                (lambda _f: lambda self: self._unary(_f))(fn))
        setattr(NDArray, name + "i",
                (lambda _f: lambda self: self._unary(_f, inplace=True))(fn))

    # -- remaining broadcast-vector ops (rsub/rdiv row/column + i) --
    rops = {"rsub": lambda a, b: b - a, "rdiv": lambda a, b: b / a}
    for name, fn in rops.items():
        setattr(NDArray, f"{name}RowVector", (lambda _f: lambda self, o:
                NDArray(_like_self(self._value,
                                   _f(self._value, _rowvec(o)))))(fn))
        setattr(NDArray, f"{name}iRowVector", (lambda _f: lambda self, o:
                self._set_value(_like_self(self._value,
                                           _f(self._value, _rowvec(o)))))(fn))
        setattr(NDArray, f"{name}ColumnVector", (lambda _f: lambda self, o:
                NDArray(_like_self(self._value,
                                   _f(self._value, _colvec(o)))))(fn))
        setattr(NDArray, f"{name}iColumnVector", (lambda _f: lambda self, o:
                self._set_value(_like_self(self._value,
                                           _f(self._value, _colvec(o)))))(fn))

    # -- in-place comparison family (ref: eqi/neqi/gti/lti/gtei/ltei write
    # 0/1 into self, keeping self's dtype) --
    comps = {"eqi": jnp.equal, "neqi": jnp.not_equal, "gti": jnp.greater,
             "gtei": jnp.greater_equal, "lti": jnp.less,
             "ltei": jnp.less_equal}
    for name, fn in comps.items():
        setattr(NDArray, name, (lambda _f: lambda self, o: self._set_value(
            _f(self._value, _unwrap(o)).astype(self._value.dtype)))(fn))


_add_r4_methods()


@_extend(NDArray)
def pow(self, p) -> "NDArray":
    return NDArray(self._value ** _unwrap(p))


@_extend(NDArray)
def powi(self, p) -> "NDArray":
    return self._set_value(self._value ** _unwrap(p))


@_extend(NDArray)
def remainderi(self, o) -> "NDArray":
    return self._set_value(jnp.remainder(self._value, _unwrap(o)))


@_extend(NDArray)
def cumprodi(self, dim: int = 0) -> "NDArray":
    return self._set_value(jnp.cumprod(self._value, axis=dim))


@_extend(NDArray)
def argsort(self, dim: int = -1, descending: bool = False) -> "NDArray":
    idx = jnp.argsort(self._value, axis=dim)
    return NDArray(jnp.flip(idx, axis=dim) if descending else idx)


@_extend(NDArray)
def isMax(self) -> "NDArray":
    """1.0 where the (global) max lives (ref: isMax op)."""
    return NDArray((self._value == jnp.max(self._value))
                   .astype(self._value.dtype))


@_extend(NDArray)
def logSumExp(self, *dims) -> "NDArray":
    axis = tuple(dims) if dims else None
    return NDArray(jax.scipy.special.logsumexp(self._value, axis=axis))


# -- matrix helpers --
@_extend(NDArray)
def diag(self) -> "NDArray":
    """Vector -> diagonal matrix; matrix -> diagonal vector (ref: Nd4j.diag)."""
    return NDArray(jnp.diag(self._value))


@_extend(NDArray)
def trace(self) -> float:
    return float(jnp.trace(self._value))


@_extend(NDArray)
def outer(self, other) -> "NDArray":
    return NDArray(jnp.outer(self._value, _unwrap(other)))


# -- stats --
@_extend(NDArray)
def skewness(self, *dims) -> "NDArray":
    v = self._value
    axis = tuple(dims) if dims else None
    m = jnp.mean(v, axis=axis, keepdims=True)
    s = jnp.std(v, axis=axis, keepdims=True)
    return NDArray(jnp.squeeze(jnp.mean(((v - m) / s) ** 3, axis=axis,
                                        keepdims=True),
                               axis=axis if axis else None))


@_extend(NDArray)
def kurtosis(self, *dims) -> "NDArray":
    v = self._value
    axis = tuple(dims) if dims else None
    m = jnp.mean(v, axis=axis, keepdims=True)
    s = jnp.std(v, axis=axis, keepdims=True)
    return NDArray(jnp.squeeze(jnp.mean(((v - m) / s) ** 4, axis=axis,
                                        keepdims=True) - 3.0,
                               axis=axis if axis else None))


@_extend(NDArray)
def normMaxNumber(self) -> float:
    return float(jnp.max(jnp.abs(self._value)))


# -- shape / layout --
def _reinstall(self, new) -> "NDArray":
    """Swap the buffer allowing a SHAPE change — only for non-views
    (reshapei/transposei/permutei; a view's footprint in its base is
    fixed)."""
    if self._base is not None:
        raise ValueError("cannot reshape/transpose a view in place")
    self._buf = new
    return self


@_extend(NDArray)
def reshapei(self, *shape) -> "NDArray":
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return _reinstall(self, jnp.reshape(self._value, shape))


@_extend(NDArray)
def transposei(self) -> "NDArray":
    return _reinstall(self, jnp.transpose(self._value))


@_extend(NDArray)
def permutei(self, *axes) -> "NDArray":
    return _reinstall(self, jnp.transpose(self._value, axes))


@_extend(NDArray)
def moveAxis(self, src: int, dst: int) -> "NDArray":
    return NDArray(jnp.moveaxis(self._value, src, dst))


@_extend(NDArray)
def repmat(self, *reps) -> "NDArray":
    """ref: INDArray.repmat — tile like MATLAB repmat."""
    return NDArray(jnp.tile(self._value, reps))


@_extend(NDArray)
def broadcastTo(self, *shape) -> "NDArray":
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return NDArray(jnp.broadcast_to(self._value, shape))


# -- is-checks --
@_extend(NDArray)
def isRowVector(self) -> bool:
    return self.rank() == 1 or (self.rank() == 2 and self.shape[0] == 1)


@_extend(NDArray)
def isColumnVector(self) -> bool:
    return self.rank() == 2 and self.shape[1] == 1


@_extend(NDArray)
def isSquare(self) -> bool:
    return self.rank() == 2 and self.shape[0] == self.shape[1]


@_extend(NDArray)
def isEmpty(self) -> bool:
    return self.length() == 0


# -- scalar getters / conversions --
@_extend(NDArray)
def getFloat(self, *idx) -> float:
    return float(self._value[idx if len(idx) > 1 else idx[0]])


@_extend(NDArray)
def getLong(self, *idx) -> int:
    return int(self._value[idx if len(idx) > 1 else idx[0]])


@_extend(NDArray)
def toLongVector(self):
    return np.asarray(self._value).astype(np.int64).reshape(-1)


@_extend(NDArray)
def toLongMatrix(self):
    return np.asarray(self._value).astype(np.int64)


@_extend(NDArray)
def toByteVector(self):
    return np.asarray(self._value).astype(np.int8).reshape(-1)


@_extend(NDArray)
def data(self):
    """Flat host view of the buffer (ref: INDArray.data())."""
    return np.asarray(self._value).reshape(-1)


# -- rows/columns/put --
@_extend(NDArray)
def getRows(self, *rows) -> "NDArray":
    return NDArray(self._value[jnp.asarray(rows, jnp.int32)])


@_extend(NDArray)
def getColumns(self, *cols) -> "NDArray":
    return NDArray(self._value[:, jnp.asarray(cols, jnp.int32)])


@_extend(NDArray)
def getWhere(self, comp, condition):
    """Elements matching ``condition`` as a flat host array (ref:
    getWhere; ``comp`` is unused here because linalg.conditions
    predicates already carry their comparison value). Data-dependent
    output size — an eager host op like unique/listdiff."""
    fn = condition.mask if hasattr(condition, "mask") else condition
    v = np.asarray(self._value)
    return NDArray(v[np.asarray(fn(jnp.asarray(v)))].reshape(-1))


@_extend(NDArray)
def putWhereWithMask(self, mask, put) -> "NDArray":
    m = _unwrap(mask)
    return NDArray(jnp.where(m > 0, _unwrap(put), self._value))


@_extend(NDArray)
def putSlice(self, dim_0_index: int, value) -> "NDArray":
    """Write a slice along dim 0 in place (ref: putSlice)."""
    return self._set_value(self._value.at[dim_0_index].set(_unwrap(value)))


# -- allocation-alikes --
@_extend(NDArray)
def like(self) -> "NDArray":
    """Zeros with self's shape+dtype (ref: INDArray.like)."""
    return NDArray(jnp.zeros_like(self._value))


@_extend(NDArray)
def ulike(self) -> "NDArray":
    """Uninitialized-alike: same contract as like() here — XLA has no
    uninitialized allocation (ref: INDArray.ulike)."""
    return NDArray(jnp.zeros_like(self._value))


# -- workspace API (ref: INDArray.detach/leverage/migrate). There are no
# workspaces in this runtime: XLA owns allocation and buffers are
# immutable, so these are documented identities kept for API parity. --
@_extend(NDArray)
def detach(self) -> "NDArray":
    return self


@_extend(NDArray)
def leverage(self) -> "NDArray":
    return self


@_extend(NDArray)
def migrate(self) -> "NDArray":
    return self


# -- round-5 surface completion (ref: the remaining INDArray names) --
@_extend(NDArray)
def negative(self) -> "NDArray":
    return NDArray(-self._value)


@_extend(NDArray)
def negativei(self) -> "NDArray":
    return self._set_value(-self._value)


@_extend(NDArray)
def asum(self, *dims):
    """ref: INDArray.asum — sum of absolute values."""
    return self.norm1(*dims)


@_extend(NDArray)
def normmax(self, *dims):
    return self.normMax(*dims)


@_extend(NDArray)
def normmaxNumber(self) -> float:
    return float(jnp.max(jnp.abs(self._value)))


@_extend(NDArray)
def percentileNumber(self, q: float) -> float:
    """ref: INDArray.percentileNumber(Number) — linear interpolation."""
    return float(jnp.percentile(self._value.astype(jnp.float32), q))


@_extend(NDArray)
def cosineSim(self, other) -> float:
    """ref: Transforms.cosineSim companion on the array surface."""
    a = self._value.ravel().astype(jnp.float32)
    b = _unwrap(other).ravel().astype(jnp.float32)
    return float(jnp.dot(a, b)
                 / jnp.maximum(jnp.linalg.norm(a) * jnp.linalg.norm(b),
                               1e-12))


@_extend(NDArray)
def eps(self, other, eps_val: float = 1e-5) -> "NDArray":
    """ref: INDArray.eps — elementwise |a-b| < eps mask."""
    return NDArray(jnp.abs(self._value - _unwrap(other)) < eps_val)


@_extend(NDArray)
def epsi(self, other, eps_val: float = 1e-5) -> "NDArray":
    return self._set_value(
        (jnp.abs(self._value - _unwrap(other)) < eps_val)
        .astype(self._value.dtype))


@_extend(NDArray)
def slice(self, i: int, dim: int = 0) -> "NDArray":
    """ref: INDArray.slice(i[, dim]) — one hyperplane along ``dim``
    (a VIEW in the reference; a value here — write-back views come from
    getRow/getColumn/subArray)."""
    return NDArray(jnp.take(self._value, i, axis=dim))


@_extend(NDArray)
def subArray(self, offsets, shape) -> "NDArray":
    """ref: INDArray.subArray(offsets, shape, strides=1)."""
    import builtins
    idx = tuple(builtins.slice(int(o), int(o) + int(s))
                for o, s in zip(offsets, shape))
    return NDArray(self._value[idx])


@_extend(NDArray)
def tensorsAlongDimension(self, *dims) -> int:
    """ref: INDArray.tensorsAlongDimension — how many sub-tensors the
    dimension set yields."""
    keep = int(np.prod([self._value.shape[d] for d in dims]))
    return int(self._value.size // max(keep, 1))


@_extend(NDArray)
def vectorsAlongDimension(self, dim: int) -> int:
    return int(self._value.size // max(self._value.shape[dim], 1))


@_extend(NDArray)
def sumAlongDimension(self, *dims) -> "NDArray":
    return self.sum(*dims)


@_extend(NDArray)
def meanAlongDimension(self, *dims) -> "NDArray":
    return self.mean(*dims)


@_extend(NDArray)
def cond(self, condition) -> "NDArray":
    """ref: INDArray.cond(Condition) — 1/0 mask of elements matching."""
    return NDArray(condition.mask(self._value).astype(jnp.float32))


@_extend(NDArray)
def close(self):
    """ref: INDArray.close — buffer release is XLA's job; parity no-op."""
    return None
