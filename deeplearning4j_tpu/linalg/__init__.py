from deeplearning4j_tpu.linalg.dtypes import DataType  # noqa: F401
from deeplearning4j_tpu.linalg.ndarray import NDArray  # noqa: F401
from deeplearning4j_tpu.linalg import factory as nd  # noqa: F401
from deeplearning4j_tpu.linalg.conditions import (  # noqa: F401
    BooleanIndexing,
    Condition,
    Conditions,
)
from deeplearning4j_tpu.linalg import transforms as Transforms  # noqa: F401
