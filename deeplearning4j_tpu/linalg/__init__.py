from deeplearning4j_tpu.linalg.dtypes import DataType  # noqa: F401
from deeplearning4j_tpu.linalg.ndarray import NDArray  # noqa: F401
from deeplearning4j_tpu.linalg import factory as nd  # noqa: F401
