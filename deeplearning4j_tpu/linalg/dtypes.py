"""Data types.

Reference parity: ``org.nd4j.linalg.api.buffer.DataType`` (the enum every
INDArray carries). The TPU-native twist: BFLOAT16 is the preferred compute
type (MXU-native), FLOAT is the default storage type, DOUBLE exists for
gradient checks (ref: gradient-check tests run fp64, SURVEY.md §4).
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    DOUBLE = "float64"
    FLOAT = "float32"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT16 = "int16"
    INT8 = "int8"
    UINT64 = "uint64"
    UINT32 = "uint32"
    UINT16 = "uint16"
    UINT8 = "uint8"
    BOOL = "bool"

    @property
    def jnp(self):
        return jnp.dtype(self.value)

    @property
    def np(self):
        return np.dtype(self.value)

    def is_fp(self) -> bool:
        return self in (DataType.DOUBLE, DataType.FLOAT, DataType.HALF, DataType.BFLOAT16)

    def is_int(self) -> bool:
        return self in (
            DataType.INT64, DataType.INT32, DataType.INT16, DataType.INT8,
            DataType.UINT64, DataType.UINT32, DataType.UINT16, DataType.UINT8,
        )

    @staticmethod
    def from_dtype(dt) -> "DataType":
        name = jnp.dtype(dt).name
        for member in DataType:
            if member.value == name:
                return member
        raise ValueError(f"Unsupported dtype: {dt}")


# Type-promotion order used by pairwise ops (ref: ND4J's
# Nd4j.defaultFloatingPointType + DataTypeUtil promotion rules; we follow
# jnp's promotion which matches in practice for the supported set).
DEFAULT_FLOAT = DataType.FLOAT
