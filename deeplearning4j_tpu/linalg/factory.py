"""Array factory — the ``Nd4j`` static-factory equivalent.

Reference parity: ``org.nd4j.linalg.factory.Nd4j`` (create/zeros/ones/
rand/randn/arange/linspace/eye/concat/...) plus the default RNG seam
(``Nd4j.getRandom()``; ref: org.nd4j.linalg.api.rng, counter-based RNG with
saveable state — SURVEY.md §2.1 "RNG").

TPU-native: randomness is JAX Threefry — the :class:`Random` wrapper keeps
a (seed, counter) pair so streams are deterministic, forkable, and
checkpointable (seed→stream contract preserved, not bit-compat with
libnd4j, per SURVEY.md).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.linalg.dtypes import DataType
from deeplearning4j_tpu.linalg.ndarray import NDArray, _unwrap
from deeplearning4j_tpu.profiler.locks import InstrumentedLock


class Random:
    """Stateful, saveable counter-based RNG over JAX Threefry."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._counter = 0
        self._lock = InstrumentedLock("linalg:random")

    def setSeed(self, seed: int) -> None:
        with self._lock:
            self._seed = int(seed)
            self._counter = 0

    def getSeed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        with self._lock:
            key = jax.random.fold_in(jax.random.PRNGKey(self._seed), self._counter)
            self._counter += 1
            return key

    # state save/restore (ref: saveable RNG state)
    def getState(self):
        return {"seed": self._seed, "counter": self._counter}

    def setState(self, state) -> None:
        with self._lock:
            self._seed = int(state["seed"])
            self._counter = int(state["counter"])


_default_random = Random(seed=np.random.SeedSequence().entropy % (2**31))


def getRandom() -> Random:
    return _default_random


def setSeed(seed: int) -> None:
    _default_random.setSeed(seed)


def _shape(args) -> tuple:
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(int(s) for s in args[0])
    return tuple(int(s) for s in args)


# ----------------------------------------------------------------- creation
def create(data, shape=None, dtype: DataType = DataType.FLOAT) -> NDArray:
    arr = jnp.asarray(np.asarray(data), dtype.jnp)
    if shape is not None:
        arr = jnp.reshape(arr, tuple(shape))
    return NDArray(arr)


def zeros(*shape, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.zeros(_shape(shape), dtype.jnp))


def ones(*shape, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.ones(_shape(shape), dtype.jnp))


def valueArrayOf(shape, value, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.full(tuple(shape), value, dtype.jnp))


def full(shape, value, dtype: DataType = DataType.FLOAT) -> NDArray:
    return valueArrayOf(shape, value, dtype)


def zerosLike(arr) -> NDArray:
    return NDArray(jnp.zeros_like(_unwrap(arr)))


def onesLike(arr) -> NDArray:
    return NDArray(jnp.ones_like(_unwrap(arr)))


def eye(n: int, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.eye(n, dtype=dtype.jnp))


def arange(*args, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.arange(*args, dtype=dtype.jnp))


def linspace(start, stop, num, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.linspace(start, stop, int(num), dtype=dtype.jnp))


def scalar(value, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.asarray(value, dtype.jnp))


def empty(dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jnp.zeros((0,), dtype.jnp))


# ------------------------------------------------------------------- random
def rand(*shape, rng: Optional[Random] = None, dtype: DataType = DataType.FLOAT) -> NDArray:
    rng = rng or _default_random
    return NDArray(jax.random.uniform(rng.next_key(), _shape(shape), dtype.jnp))


def randn(*shape, rng: Optional[Random] = None, dtype: DataType = DataType.FLOAT) -> NDArray:
    rng = rng or _default_random
    return NDArray(jax.random.normal(rng.next_key(), _shape(shape), dtype.jnp))


def randint(low: int, high: int, shape, rng: Optional[Random] = None,
            dtype: DataType = DataType.INT32) -> NDArray:
    rng = rng or _default_random
    return NDArray(jax.random.randint(rng.next_key(), tuple(shape), low, high, dtype.jnp))


def bernoulli(p: float, shape, rng: Optional[Random] = None) -> NDArray:
    rng = rng or _default_random
    return NDArray(jax.random.bernoulli(rng.next_key(), p, tuple(shape)).astype(jnp.float32))


def shuffle(arr: NDArray, rng: Optional[Random] = None) -> NDArray:
    """IN-PLACE row shuffle (ref: Nd4j.shuffle mutates its argument)."""
    rng = rng or _default_random
    arr._set_value(jax.random.permutation(rng.next_key(), _unwrap(arr), axis=0))
    return arr


# ----------------------------------------------------------------- combining
def concat(dim: int, *arrs) -> NDArray:
    return NDArray(jnp.concatenate([_unwrap(a) for a in arrs], axis=dim))


def stack(dim: int, *arrs) -> NDArray:
    return NDArray(jnp.stack([_unwrap(a) for a in arrs], axis=dim))


def vstack(*arrs) -> NDArray:
    return NDArray(jnp.vstack([_unwrap(a) for a in arrs]))


def hstack(*arrs) -> NDArray:
    return NDArray(jnp.hstack([_unwrap(a) for a in arrs]))


def pile(*arrs) -> NDArray:
    return stack(0, *arrs)


def where(cond, x, y) -> NDArray:
    return NDArray(jnp.where(_unwrap(cond), _unwrap(x), _unwrap(y)))


def gather(arr, indices, axis: int = 0) -> NDArray:
    return NDArray(jnp.take(_unwrap(arr), jnp.asarray(_unwrap(indices)), axis=axis))


def sortWithIndices(arr, dim: int = -1, ascending: bool = True):
    v = _unwrap(arr)
    idx = jnp.argsort(v, axis=dim)
    if not ascending:
        idx = jnp.flip(idx, axis=dim)
    return NDArray(jnp.take_along_axis(v, idx, axis=dim)), NDArray(idx)


def oneHot(indices, depth: int, dtype: DataType = DataType.FLOAT) -> NDArray:
    return NDArray(jax.nn.one_hot(jnp.asarray(_unwrap(indices), jnp.int32), depth, dtype=dtype.jnp))
