"""Transforms — the static math-helper surface
(ref: ``org.nd4j.linalg.ops.transforms.Transforms`` — SURVEY.md §2.2 L1:
the utility entry point user code calls for out-of-place math over
INDArrays). Thin delegating layer over the op registry / jnp; every
function accepts NDArray or anything array-like and returns NDArray."""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.linalg.ndarray import NDArray
from deeplearning4j_tpu.linalg.ndarray import _unwrap as _unwrap_nd


def _unwrap(x):
    return jnp.asarray(_unwrap_nd(x))


def _wrap1(fn):
    def f(x, dup: bool = True):
        res = fn(_unwrap(x))
        if not dup:
            # reference semantics: dup=False mutates the input in place
            if not isinstance(x, NDArray):
                raise TypeError("dup=False needs an NDArray input to mutate")
            return x._set_value(res)
        return NDArray(res)
    return f


def _wrap_op(name):
    """Delegate through the registry so platform (Pallas) overrides apply
    and the activation surface keeps ONE source of truth."""
    from deeplearning4j_tpu.ops import registry as _registry
    return _wrap1(lambda v: _registry.get(name)(v))


sigmoid = _wrap_op("sigmoid")
tanh = _wrap_op("tanh")
relu = _wrap_op("relu")
relu6 = _wrap_op("relu6")
elu = _wrap_op("elu")
selu = _wrap_op("selu")
gelu = _wrap_op("gelu")
softPlus = _wrap_op("softplus")
softsign = _wrap_op("softsign")
sign = _wrap1(jnp.sign)
abs = _wrap1(jnp.abs)          # noqa: A001 (reference name)
exp = _wrap1(jnp.exp)
expm1 = _wrap1(jnp.expm1)
log = _wrap1(jnp.log)
log1p = _wrap1(jnp.log1p)
sqrt = _wrap1(jnp.sqrt)
sin = _wrap1(jnp.sin)
cos = _wrap1(jnp.cos)
atan = _wrap1(jnp.arctan)
asin = _wrap1(jnp.arcsin)
acos = _wrap1(jnp.arccos)
floor = _wrap1(jnp.floor)
ceil = _wrap1(jnp.ceil)
round = _wrap1(jnp.round)      # noqa: A001
neg = _wrap1(jnp.negative)
hardTanh = _wrap_op("hardtanh")
hardSigmoid = _wrap_op("hardsigmoid")
identity = _wrap1(lambda x: x)
stabilize = _wrap1(lambda x: jnp.clip(x, -1e6, 1e6))


def leakyRelu(x, alpha: float = 0.01):
    v = _unwrap(x)
    return NDArray(jnp.where(v >= 0, v, alpha * v))


def softmax(x, axis: int = -1):
    from deeplearning4j_tpu.ops import registry as _registry
    return NDArray(_registry.get("softmax")(_unwrap(x), axis=axis))


def logSoftmax(x, axis: int = -1):
    from deeplearning4j_tpu.ops import registry as _registry
    return NDArray(_registry.get("log_softmax")(_unwrap(x), axis=axis))


def pow(x, p):                  # noqa: A001
    return NDArray(jnp.power(_unwrap(x), _unwrap(p)))


def max(x, y):                  # noqa: A001
    return NDArray(jnp.maximum(_unwrap(x), _unwrap(y)))


def min(x, y):                  # noqa: A001
    return NDArray(jnp.minimum(_unwrap(x), _unwrap(y)))


def unitVec(x):
    v = _unwrap(x)
    return NDArray(v / jnp.maximum(jnp.linalg.norm(v), 1e-12))


def normalizeZeroMeanAndUnitVariance(x):
    v = _unwrap(x)
    return NDArray((v - jnp.mean(v)) / jnp.maximum(jnp.std(v), 1e-12))


def cosineSim(a, b) -> float:
    va, vb = jnp.ravel(_unwrap(a)), jnp.ravel(_unwrap(b))
    return float(jnp.dot(va, vb)
                 / jnp.maximum(jnp.linalg.norm(va) * jnp.linalg.norm(vb),
                               1e-12))


def cosineDistance(a, b) -> float:
    return 1.0 - cosineSim(a, b)


def euclideanDistance(a, b) -> float:
    return float(jnp.linalg.norm(jnp.ravel(_unwrap(a))
                                 - jnp.ravel(_unwrap(b))))


def manhattanDistance(a, b) -> float:
    return float(jnp.sum(jnp.abs(jnp.ravel(_unwrap(a))
                                 - jnp.ravel(_unwrap(b)))))


def hammingDistance(a, b) -> float:
    return float(jnp.sum(jnp.ravel(_unwrap(a)) != jnp.ravel(_unwrap(b))))


def jaccardDistance(a, b) -> float:
    va, vb = jnp.ravel(_unwrap(a)), jnp.ravel(_unwrap(b))
    mx = jnp.sum(jnp.maximum(va, vb))
    mn = jnp.sum(jnp.minimum(va, vb))
    return float(jnp.where(mx == 0, 0.0, 1.0 - mn / jnp.maximum(mx, 1e-12)))


def allEuclideanDistances(x, y, dim: int = 1):
    """Pairwise distances between rows/cols of x and y (ref:
    Transforms.allEuclideanDistances)."""
    vx, vy = _unwrap(x), _unwrap(y)
    if dim == 0:
        vx, vy = vx.T, vy.T
    d = vx[:, None, :] - vy[None, :, :]
    return NDArray(jnp.sqrt(jnp.sum(d * d, axis=-1)))


def allCosineSimilarities(x, y, dim: int = 1):
    vx, vy = _unwrap(x), _unwrap(y)
    if dim == 0:
        vx, vy = vx.T, vy.T
    nx = vx / jnp.maximum(jnp.linalg.norm(vx, axis=1, keepdims=True), 1e-12)
    ny = vy / jnp.maximum(jnp.linalg.norm(vy, axis=1, keepdims=True), 1e-12)
    return NDArray(nx @ ny.T)


def dot(a, b) -> float:
    return float(jnp.dot(jnp.ravel(_unwrap(a)), jnp.ravel(_unwrap(b))))


class Transforms:
    """Class-style access (``Transforms.sigmoid(x)``) for reference-shaped
    call sites; the module-level functions are the same objects."""


for _name, _obj in list(globals().items()):
    if callable(_obj) and not _name.startswith("_") and \
            _name not in ("NDArray", "Transforms"):
        setattr(Transforms, _name, staticmethod(_obj))
