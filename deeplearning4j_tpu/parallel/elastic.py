"""Elastic multi-device training: device-loss detection, dispatch
watchdogs, and coordinated mesh-shrink resume.

Production training systems treat worker failure/restart as part of the
training loop, not an operator incident (TensorFlow system paper,
PAPERS.md), and at collective scale the pathologies are stragglers and
hung allreduces as much as hard crashes ("Scalable Distributed DNN
Training using TensorFlow and CUDA-Aware MPI", PAPERS.md). PR 5 made a
single-process ``fit()`` survive preemption and NaNs; this module makes
a multi-chip :class:`~deeplearning4j_tpu.parallel.wrapper.
ParallelWrapper` run survive the failures that live BELOW the process:

- :class:`DeviceMonitor` — between dispatches, a tiny sentinel dispatch
  per mesh device classifies each as healthy / degraded (probe slower
  than ``degraded_after``) / dead (probe raises). Under a
  :class:`~deeplearning4j_tpu.faults.FaultPlan` the planned device
  losses are injected at this seam, so every shrink path is a seeded
  deterministic chaos test.
- :class:`DispatchWatchdog` — runs the blocking device dispatch on a
  watchdog-supervised thread with a SOFT deadline (exceeding it records
  a ``dl4j_dispatch_watchdog_timeouts_total`` timeout; if the dispatch
  then completes it is a straggler, observed in
  ``dl4j_dispatch_straggler_seconds``) and a HARD grace deadline
  (exceeding that abandons the dispatch and raises
  :class:`DispatchTimeoutError` — the elastic loop probes the devices
  and converts a confirmed loss into the shrink path).
- :class:`CoordinationService` — the multi-host seam for the resume
  barrier: every participant reports its last completed step and all
  agree on the minimum before anyone restarts.
  :class:`InProcessCoordinator` is the in-process implementation;
  file- or socket-based rendezvous plugs in behind the same two-method
  contract later.
- :func:`fit_elastic` — the driver ``ParallelWrapper.fit(elastic=...)``
  delegates to: on device loss it drains in-flight work (the
  DevicePrefetcher's staged megabatches for the OLD mesh layout are
  discarded, never dispatched onto dead devices), runs the resume
  barrier, writes a coordinated checkpoint of the agreed step through
  the PR-5 CheckpointManager, rebuilds a smaller
  :class:`~deeplearning4j_tpu.parallel.mesh.DeviceMesh` from the
  survivors (re-validated through the E101/E102 distribution lints),
  rescales the learning rate per :class:`ElasticConfig.lr_policy`, and
  resumes bit-exactly from the checkpoint on the shrunk mesh.

Metrics: ``dl4j_device_lost_total``, ``dl4j_mesh_shrinks_total``,
``dl4j_dispatch_watchdog_timeouts_total``,
``dl4j_dispatch_straggler_seconds``, ``dl4j_device_probe_seconds``,
``dl4j_elastic_recovery_seconds``.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
import warnings
from contextlib import ExitStack
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

import jax
import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.parallel.mesh import DeviceMesh

logger = logging.getLogger("deeplearning4j_tpu")

_REG = _prof.get_registry()
DEVICE_LOST = _REG.counter(
    "dl4j_device_lost_total",
    "Mesh devices classified dead by the elastic layer's health probes")
MESH_SHRINKS = _REG.counter(
    "dl4j_mesh_shrinks_total",
    "Elastic mesh shrinks performed (coordinated checkpoint + rebuild "
    "on the surviving devices + resume)")
WATCHDOG_TIMEOUTS = _REG.counter(
    "dl4j_dispatch_watchdog_timeouts_total",
    "Dispatches that exceeded the watchdog's soft deadline")
STRAGGLER_SECONDS = _REG.histogram(
    "dl4j_dispatch_straggler_seconds",
    "Wall time of dispatches that exceeded the watchdog deadline but "
    "eventually completed (stragglers)")
PROBE_SECONDS = _REG.histogram(
    "dl4j_device_probe_seconds",
    "Per-device sentinel-dispatch health probe round-trip time")
RECOVERY_SECONDS = _REG.histogram(
    "dl4j_elastic_recovery_seconds",
    "Wall time from device-loss detection to the resumed state on the "
    "shrunk mesh (barrier + checkpoint + rebuild + restore)")


class DeviceLossError(RuntimeError):
    """One or more mesh devices are dead. Carries ``dead`` (device ids)
    and ``surviving`` (live jax devices) so the shrink path can rebuild."""

    def __init__(self, dead: Set[int], surviving: List, step: int):
        self.dead = set(dead)
        self.surviving = list(surviving)
        self.step = int(step)
        super().__init__(
            f"device(s) {sorted(self.dead)} dead at step {step} "
            f"({len(self.surviving)} surviving)")


class DispatchTimeoutError(RuntimeError):
    """A dispatch exceeded the watchdog's hard grace deadline and was
    abandoned. The update for its step(s) never landed; model state is
    the last completed step's."""


class ElasticShrinkError(RuntimeError):
    """The mesh cannot shrink any further (too few survivors, a
    non-data-parallel mesh, shrink budget exhausted, or the shrunk
    configuration fails static validation)."""


@dataclass
class DeviceHealth:
    """One probe sweep's classification."""

    dead: Set[int] = field(default_factory=set)
    degraded: Set[int] = field(default_factory=set)
    probe_seconds: Dict[int, float] = field(default_factory=dict)

    def healthy(self) -> bool:
        return not self.dead


class DeviceMonitor:
    """Sentinel-dispatch device health prober.

    ``probe()`` pushes a tiny array to each device and pulls it back —
    one full host<->device round trip per device, the cheapest dispatch
    that still proves the device answers. A probe that raises marks the
    device DEAD; one slower than ``degraded_after`` seconds marks it
    DEGRADED (recorded, not acted on — degradation is the straggler
    signal, loss is the shrink signal). A
    :class:`~deeplearning4j_tpu.faults.FaultPlan` injects planned
    losses at this seam deterministically.
    """

    def __init__(self, degraded_after: float = 0.25, plan=None):
        self.degraded_after = float(degraded_after)
        self.plan = plan
        self._sentinel = np.ones((8,), np.float32)

    def probe(self, devices, step: Optional[int] = None) -> DeviceHealth:
        health = DeviceHealth()
        planned = set()
        if self.plan is not None:
            planned = self.plan.dead_devices(step)
        for d in devices:
            if d.id in planned:
                health.dead.add(d.id)
                continue
            t0 = time.perf_counter()
            try:
                back = np.asarray(jax.device_put(self._sentinel, d))
                if not np.array_equal(back, self._sentinel):
                    raise RuntimeError(f"sentinel round-trip corrupt on {d}")
            except Exception:
                health.dead.add(d.id)
                continue
            dt = time.perf_counter() - t0
            health.probe_seconds[d.id] = dt
            PROBE_SECONDS.observe(dt)
            if dt > self.degraded_after:
                health.degraded.add(d.id)
        return health


def shrink_mesh_on_dead(mesh: DeviceMesh, plan=None,
                        context: str = "serving") -> Optional[DeviceMesh]:
    """Probe ``mesh``'s devices and return a data-parallel survivor
    mesh when some are dead — or ``None`` when the mesh must stay as it
    is: no deaths, a tensor/sequence-parallel mesh (each device holds
    an unreplicated shard, so dropping one would break the model's
    sharding — mirrors the training shrink guard), or no survivors at
    all. Shared by :class:`~deeplearning4j_tpu.parallel.wrapper.
    ParallelInference` and ``serving.ModelServer`` so the two serving
    paths cannot drift; emits the operator-facing warnings either way
    (``context`` prefixes them)."""
    devices = mesh.devices
    health = DeviceMonitor(plan=plan).probe(devices)
    if not health.dead:
        return None
    if mesh.size("model") * mesh.size("seq") > 1:
        warnings.warn(
            f"{context}: device(s) {sorted(health.dead)} are dead but the "
            "mesh has model/seq axes — cannot shrink a tensor-parallel "
            "mesh; retrying on the full mesh", stacklevel=3)
        return None
    surviving = [d for d in devices if d.id not in health.dead]
    if not surviving:
        warnings.warn(
            f"{context}: every device is dead — keeping the mesh, the "
            "next retry will fail structurally", stacklevel=3)
        return None
    DEVICE_LOST.inc(len(health.dead))
    warnings.warn(
        f"{context}: dropping dead device(s) {sorted(health.dead)}; "
        f"continuing on {len(surviving)} replica(s)", stacklevel=3)
    return DeviceMesh.create(data=len(surviving), model=1, seq=1,
                             devices=surviving)


class DispatchFence:
    """Commit fence between the elastic recovery path and abandoned
    dispatch threads. ``fit_elastic`` attaches one to the model as
    ``_dispatch_fence``; the fit functions read ``generation`` at entry
    and COMMIT their outputs (state assignment + bookkeeping) only if,
    under the lock, the generation is unchanged. The shrink path bumps
    the generation and performs its checkpoint-restore under the same
    lock — so a hung dispatch that un-hangs after the mesh shrank
    discards its result instead of overwriting the restored state (or
    checkpointing a stale step)."""

    def __init__(self):
        self.lock = _prof.InstrumentedLock("elastic:fence")
        self.generation = 0


class DispatchWatchdog:
    """Deadline supervision around a blocking device dispatch.

    ``run(fn, step)`` executes ``fn`` on a dispatch thread and waits:

    - within ``deadline`` s: normal completion.
    - past ``deadline`` but within ``grace`` (default ``4*deadline``):
      a TIMEOUT is recorded; if the dispatch then completes it counts
      as a straggler and its result is used — transient stalls do not
      kill training.
    - past ``grace``: the dispatch is abandoned (the thread is a
      daemon; a truly hung XLA collective cannot be interrupted from
      Python) and :class:`DispatchTimeoutError` is raised. The caller
      must treat the step as never applied.

    ``deadline=None`` disables supervision: the dispatch runs inline on
    the calling thread (fault-injection delays still honored).

    The first ``warmup`` dispatches after :meth:`begin_attempt` are
    UNSUPERVISED (no deadline): they include XLA compilation, whose
    wall time has nothing to do with device health — counting it
    against the deadline would flag every cold start as hung. The
    elastic loop calls ``begin_attempt()`` on entry and again after
    every mesh shrink (a new mesh recompiles). Steady-state dispatches
    that recompile (a new batch signature mid-run) should be covered by
    setting ``deadline`` above worst-case compile time or raising
    ``grace``.
    """

    def __init__(self, deadline: Optional[float] = None,
                 grace: Optional[float] = None, plan=None, warmup: int = 2):
        self.deadline = deadline
        self.grace = grace if grace is not None else (
            None if deadline is None else deadline * 4)
        self.plan = plan
        self.warmup = int(warmup)
        self._lenient = self.warmup
        self.timeouts = 0
        self.stragglers = 0

    def begin_attempt(self, count: Optional[int] = None):
        """The next ``warmup`` dispatches will compile (fresh program /
        fresh mesh): run them unsupervised. ``count`` overrides the
        leniency for callers whose steady-state ``warmup`` is 0 (the
        model server AOT-compiles everything, but a mesh rebuild still
        legitimately compiles once)."""
        self._lenient = max(self._lenient,
                            self.warmup if count is None else int(count))

    def _hold(self, step: int) -> bool:
        """Fault seam: returns False when the planned hang says the
        dispatch never completes."""
        if self.plan is None:
            return True
        return self.plan.dispatch_hold(step)

    def run(self, fn, step: int):
        lenient = self._lenient > 0
        if lenient:
            self._lenient -= 1
        if self.deadline is None or lenient:
            if self._hold(step):
                return fn()
            raise DispatchTimeoutError(
                f"dispatch for step {step} never completed (injected hang "
                "outside watchdog supervision)")
        done = threading.Event()
        result: list = []
        error: list = []

        def work():
            try:
                if self._hold(step):
                    result.append(fn())
            except BaseException as e:      # re-raised on the caller
                error.append(e)
            finally:
                done.set()

        t = threading.Thread(target=work, daemon=True,
                             name=f"dl4j-dispatch-{step}")
        t0 = time.perf_counter()
        t.start()
        timed_out = False
        if not done.wait(self.deadline):
            timed_out = True
            self.timeouts += 1
            WATCHDOG_TIMEOUTS.inc()
            logger.warning("dispatch watchdog: step %d exceeded the %.3gs "
                           "deadline", step, self.deadline)
            remaining = None if self.grace is None \
                else max(self.grace - self.deadline, 0.0)
            if not done.wait(remaining):
                if self.plan is not None:
                    # let an injected hard hang exit WITHOUT dispatching
                    self.plan.release_hangs()
                raise DispatchTimeoutError(
                    f"dispatch for step {step} still running after the "
                    f"{self.grace:.3g}s grace deadline — abandoning it "
                    "(state is the last completed step's)")
        if error:
            raise error[0]
        dt = time.perf_counter() - t0
        if not result:
            # the injected hang was released without dispatching: the
            # step never completed even though the thread exited
            raise DispatchTimeoutError(
                f"dispatch for step {step} never completed")
        if timed_out:
            self.stragglers += 1
            STRAGGLER_SECONDS.observe(dt)
            logger.warning("dispatch watchdog: step %d completed late "
                           "(%.3fs) — straggler recorded", step, dt)
        return result[0]


# ----------------------------------------------------------- coordination
class CoordinationService:
    """Pluggable multi-host rendezvous for the elastic resume barrier.

    ``resume_barrier(participant, step)`` blocks until every participant
    has reported its last locally completed step and returns the agreed
    step — the MINIMUM across participants, i.e. the last GLOBALLY
    completed step every survivor can restore.
    :class:`InProcessCoordinator` serves single-process jobs; REAL
    multi-host jobs pass ``ElasticConfig(coordinator=
    distributed.coordinator.SocketCoordinator(...))`` (TCP rendezvous
    with heartbeats + dead-peer detection) or ``FileCoordinator``
    (shared-filesystem rendezvous) — both implement this same
    two-method contract across OS processes (ISSUE 15 tier 3,
    ``pytest -m multihost``).
    """

    def resume_barrier(self, participant: str, step: int,
                       timeout: float = 60.0) -> int:
        raise NotImplementedError


class InProcessCoordinator(CoordinationService):
    """Threading-based coordinator for single-process (possibly
    multi-threaded-test) jobs. Reusable across successive barriers."""

    def __init__(self, participants: int = 1):
        self.participants = int(participants)
        self._cond = _prof.InstrumentedCondition("elastic:coordinator")
        self._round: Dict[str, int] = {}
        self._results: Dict[int, int] = {}
        self._generation = 0

    def resume_barrier(self, participant: str, step: int,
                       timeout: float = 60.0) -> int:
        with self._cond:
            gen = self._generation
            self._round[str(participant)] = int(step)
            if len(self._round) >= self.participants:
                self._results[gen] = min(self._round.values())
                self._round = {}
                self._generation += 1
                self._cond.notify_all()
                return self._results[gen]
            deadline = time.monotonic() + timeout
            while gen not in self._results:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    arrived = len(self._round)
                    self._round.pop(str(participant), None)
                    raise TimeoutError(
                        f"resume barrier: only {arrived}/"
                        f"{self.participants} participants arrived within "
                        f"{timeout}s")
                self._cond.wait(remaining)
            return self._results[gen]


# ----------------------------------------------------------------- config
@dataclass
class ElasticConfig:
    """Tuning for :func:`fit_elastic` / ``ParallelWrapper.fit(elastic=)``.

    ``lr_policy`` governs the learning-rate rescale on shrink. The
    GLOBAL batch is unchanged by a shrink (each survivor's per-replica
    batch grows), so the linear-scaling rule says the LR should not
    change — ``"none"`` is the default and keeps the shrunk run
    bit-exact with a fresh small-mesh fit. ``"linear"``/``"sqrt"``
    scale by the survivor fraction (or its square root) for recipes
    that tie LR to replica count.
    """

    watchdog_deadline: Optional[float] = None   # soft, seconds; None = off
    watchdog_grace: Optional[float] = None      # hard; default 4x deadline
    watchdog_warmup: int = 2      # unsupervised compile dispatches/attempt
    probe_every: int = 1          # dispatches between health probes; 0 = off
    degraded_after: float = 0.25  # probe slower than this -> degraded
    max_shrinks: int = 4
    min_devices: int = 1
    lr_policy: str = "none"       # none | linear | sqrt
    coordinator: Optional[CoordinationService] = None
    participant: str = "proc0"
    barrier_timeout: float = 60.0


# ------------------------------------------------------------------ driver
def fit_elastic(wrapper, iterator, epochs: int = 1,
                steps_per_dispatch: int = 1, checkpoint=None,
                nan_policy=None, faults=None,
                config: Optional[ElasticConfig] = None):
    """Elastic data-parallel fit over ``wrapper.mesh`` (see module doc).

    Requires ``checkpoint=CheckpointConfig(...)`` — the shrink path
    resumes from the coordinated checkpoint, and a run that cannot
    checkpoint cannot shrink. All PR-5 resilience features
    (``nan_policy``, fault injection, preemption, periodic saves)
    compose with the elastic layer unchanged.
    """
    from deeplearning4j_tpu.train import resilience as _res

    cfg = config or ElasticConfig()
    if checkpoint is None:
        raise ValueError(
            "elastic training requires checkpoint=CheckpointConfig(...): "
            "the mesh-shrink path resumes from the coordinated checkpoint")
    if cfg.lr_policy not in ("none", "linear", "sqrt"):
        # reject before begin_session installs signal handlers — and long
        # before a device loss would surface the typo mid-recovery
        raise ValueError(f"unknown lr_policy {cfg.lr_policy!r} (expected "
                         "none|linear|sqrt)")
    model = wrapper.model
    if not model._initialized:
        model.init()
    model._ensure_opt_state()
    session, stream_iter = _res.begin_session(model, iterator, checkpoint,
                                              nan_policy, faults)
    coordinator = cfg.coordinator or InProcessCoordinator(1)
    monitor = DeviceMonitor(degraded_after=cfg.degraded_after, plan=faults)
    watchdog = DispatchWatchdog(cfg.watchdog_deadline, cfg.watchdog_grace,
                                plan=faults, warmup=cfg.watchdog_warmup)
    model._dispatch_fence = DispatchFence()
    k = max(int(steps_per_dispatch), 1)
    # fit_scope's epoch accounting, shared: every post-shrink re-entry
    # continues toward the same absolute target
    target_epochs = _res.epoch_target(session, model, epochs)
    shrinks = 0
    try:
        while True:
            try:
                _run_epochs(wrapper, model, session, stream_iter,
                            target_epochs, k, monitor, watchdog, cfg)
                return model
            except _res.PreemptionRequested:
                session.on_preempt()
                return model
            except DeviceLossError as e:
                shrinks += 1
                if shrinks > cfg.max_shrinks:
                    raise ElasticShrinkError(
                        f"{shrinks} mesh shrinks exceed max_shrinks="
                        f"{cfg.max_shrinks} — giving up") from e
                _shrink_and_resume(wrapper, model, session, stream_iter, e,
                                   cfg, coordinator, steps_per_dispatch=k)
    finally:
        model._dispatch_fence = None
        session.close(raise_errors=sys.exc_info()[1] is None)


def _run_epochs(wrapper, model, session, iterator, epochs, k, monitor,
                watchdog, cfg):
    """The supervised epoch loop over the CURRENT mesh: one unified
    DevicePrefetcher-fed dispatch loop for K=1 and K>1 (staged items are
    sharded for this mesh; a shrink discards them with the prefetcher)."""
    from deeplearning4j_tpu.data.dataset import DevicePrefetcher, stage_item
    from deeplearning4j_tpu.train.resilience import PreemptionRequested
    from deeplearning4j_tpu.train.stepping import (MegaBatch,
                                                   group_into_megabatches)

    mesh = wrapper.mesh
    watchdog.begin_attempt()    # first dispatches on this mesh compile
    with _prof.trace_span("collective:replicate_params",
                          devices=mesh.size("data")):
        model._params = mesh.replicate(model._params)
        model._states = mesh.replicate(model._states)
        model._opt_state = mesh.replicate(model._opt_state)
    model._t_dev = None     # rebuild the device clock on the new mesh
    n_epochs = max(epochs - model._epoch, 0)
    for _ in range(n_epochs):
        if not session.consume_skip_reset():
            iterator.reset()

        def padded():
            while iterator.hasNext():
                yield wrapper._pad(iterator.next())

        stream = session.wrap_batches(padded())
        dispatches = 0
        with ExitStack() as stack:
            if wrapper.prefetch and wrapper.prefetch > 0:
                items = stack.enter_context(DevicePrefetcher(
                    stream, steps_per_dispatch=k,
                    prefetch=wrapper.prefetch,
                    placement=wrapper._mesh_placement))
            else:   # thread-affine sources: inline staging
                items = (stage_item(it, wrapper._mesh_placement)
                         for it in group_into_megabatches(stream, k))
            it = iter(items)
            while True:
                try:
                    item = next(it)
                except StopIteration:
                    break
                except (PreemptionRequested, DeviceLossError):
                    raise
                except Exception as e:
                    # a staging failure (device_put onto a dying chip)
                    # is a loss signal too: probe before giving up
                    _check_health(monitor, mesh, model._iteration,
                                  cause=e)
                    raise
                step0 = model._iteration + 1

                def fn(i=item):
                    # the jax mesh context is THREAD-LOCAL, and the
                    # trace-cache key contains the entered-mesh stack:
                    # enter it HERE (dispatch thread or inline) and
                    # nowhere else, so warmup and supervised dispatches
                    # trace under the identical context
                    with mesh:
                        if isinstance(i, MegaBatch):
                            model._fit_mega(i)
                        else:
                            model._fit_one(i)
                try:
                    watchdog.run(fn, step0)
                except DispatchTimeoutError as e:
                    # hung dispatch: a dead device is the usual cause —
                    # confirmed loss shrinks, a healthy mesh surfaces
                    # the timeout (the abandoned step MAY have landed;
                    # blind retry could double-apply it)
                    _check_health(monitor, mesh, step0, cause=e)
                    raise
                dispatches += 1
                if cfg.probe_every and dispatches % cfg.probe_every == 0:
                    _check_health(monitor, mesh, model._iteration)
        model._epoch += 1
        session.on_epoch_end()


def _check_health(monitor, mesh: DeviceMesh, step: int, cause=None):
    """Probe every device of ``mesh``; raise DeviceLossError when any
    are dead (chained to ``cause`` when the probe was triggered by a
    dispatch/staging failure)."""
    devices = mesh.devices
    health = monitor.probe(devices, step)
    if health.dead:
        surviving = [d for d in devices if d.id not in health.dead]
        raise DeviceLossError(health.dead, surviving, step) from cause


def _shrink_and_resume(wrapper, model, session, iterator,
                       loss: DeviceLossError, cfg: ElasticConfig,
                       coordinator: CoordinationService,
                       steps_per_dispatch: int = 1):
    """The coordinated shrink: barrier -> checkpoint -> smaller mesh ->
    revalidate -> LR rescale -> restore + data-pipeline rebind."""
    t0 = time.perf_counter()
    DEVICE_LOST.inc(len(loss.dead))
    logger.warning("device loss at step %d: %s dead, %d surviving — "
                   "starting coordinated mesh shrink", loss.step,
                   sorted(loss.dead), len(loss.surviving))
    mesh = wrapper.mesh
    if mesh.size("model") * mesh.size("seq") > 1:
        raise ElasticShrinkError(
            "elastic shrink supports data-parallel meshes only (model/seq "
            f"axes are {mesh.size('model')}x{mesh.size('seq')}): a lost "
            "device holds an unreplicated parameter shard") from loss
    if len(loss.surviving) < max(cfg.min_devices, 1):
        raise ElasticShrinkError(
            f"only {len(loss.surviving)} devices survive (< min_devices="
            f"{cfg.min_devices})") from loss

    # 1. resume barrier: all participants agree on the last GLOBALLY
    #    completed step before anyone restarts
    agreed = coordinator.resume_barrier(cfg.participant,
                                        int(model._iteration),
                                        timeout=cfg.barrier_timeout)
    # 2. coordinated checkpoint OF THE AGREED STEP: written by the
    #    participant(s) standing at it; anyone ahead rolls back to it in
    #    the restore below (writing a local ahead-of-agreement checkpoint
    #    would desync the participants the barrier just synchronized)
    if agreed == int(model._iteration):
        session.checkpoint(status="elastic-shrink")
    else:
        logger.warning("resume barrier agreed on step %d (local %d): "
                       "rolling back to the agreed checkpoint", agreed,
                       model._iteration)
    if session.manager is not None:
        session.manager.flush()     # async writer: restore needs it on disk

    # 3. smaller mesh from the survivors, re-validated statically
    old_data = mesh.size("data")
    new_mesh = DeviceMesh.create(data=len(loss.surviving), model=1, seq=1,
                                 devices=loss.surviving)
    _revalidate_shrink(model, session, new_mesh)

    # 4. per-replica batch grew (global batch unchanged); rescale LR per
    #    policy
    _rescale_lr(model, session, cfg, old_data, len(loss.surviving))

    # 5. restore THE AGREED checkpoint (not the newest — a stale straggler
    #    write or a local ahead-of-agreement save must not hijack the
    #    coordinated resume) and rebind the data pipeline (the old
    #    prefetcher died with the unwind; its staged megabatches for the
    #    old mesh layout were discarded, not dispatched). The fence bump
    #    + restore run under one lock: an abandoned hung dispatch that
    #    un-hangs later sees the new generation and discards its result
    #    instead of overwriting the restored state (see DispatchFence).
    def _restore():
        return session.manager.restore(model, normalizer=session.normalizer,
                                       count_resume=False, step=agreed)
    fence = getattr(model, "_dispatch_fence", None)
    if fence is not None:
        with fence.lock:
            fence.generation += 1
            info = _restore()
    else:
        info = _restore()
    if info is None:
        raise ElasticShrinkError(
            f"mesh shrink: no valid checkpoint for the agreed step "
            f"{agreed} (the coordinated checkpoint is missing or failed "
            "validation)") from loss
    session._cursors.clear()        # pulled-ahead cursors are stale
    cursor = info.get("cursor")
    if cursor is not None and iterator is not None:
        try:
            iterator.seek(cursor)
            session._skip_reset = True
        except NotImplementedError:
            warnings.warn(
                "elastic resume: iterator does not support seek(); "
                "replaying the interrupted epoch from its start",
                stacklevel=2)
    wrapper.mesh = new_mesh
    # 6. survivor-mesh warmup through the unified compile-cache seam:
    #    with the persistent cache configured, a survivor layout any
    #    earlier run (or process) already compiled deserializes from
    #    disk, so the post-shrink first dispatch is a read, not an XLA
    #    compile. Best-effort — a warm miss just compiles as before.
    _warm_survivor_mesh(wrapper, model, session, new_mesh,
                        steps_per_dispatch)
    MESH_SHRINKS.inc()
    dt = time.perf_counter() - t0
    RECOVERY_SECONDS.observe(dt)
    logger.info("mesh shrink complete in %.3fs: data axis %d -> %d, "
                "resuming from step %d", dt, old_data,
                len(loss.surviving), model._iteration)


def _warm_survivor_mesh(wrapper, model, session, new_mesh: DeviceMesh,
                        k: int) -> None:
    """AOT-warm the train step for the shrunk layout (module step 6):
    rebuild a zero batch from the checkpoint-recorded batch signature,
    pad + stage it exactly like the dispatch loop will (wrapper._pad +
    _mesh_placement), and compile WITHOUT executing. Gated on the
    persistent cache being configured — without it the first post-shrink
    dispatch compiles under the watchdog's warmup leniency exactly as
    before. Never raises: recovery must not die warming."""
    from deeplearning4j_tpu.nn import compilecache as _cc
    if _cc.cache_dir() is None:
        return
    sig = getattr(session, "_last_batch_sig", None)
    if not sig:
        return
    try:
        from deeplearning4j_tpu.data.dataset import DataSet, stage_item
        from deeplearning4j_tpu.train.stepping import stack_megabatch
        f, lab = sig["features"], sig["labels"]
        ds = DataSet(np.zeros(tuple(f[0]), np.dtype(f[1])),
                     np.zeros(tuple(lab[0]), np.dtype(lab[1])))
        ds = wrapper._pad(ds)
        item = stage_item(stack_megabatch([ds] * k) if k > 1 else ds,
                          wrapper._mesh_placement)
        with new_mesh:
            model._warm_dispatch(item.features, item.labels,
                                 fmask=getattr(item, "features_mask", None),
                                 lmask=getattr(item, "labels_mask", None),
                                 steps=k)
        logger.info("elastic shrink: survivor-mesh train step warmed "
                    "through the compile cache (k=%d)", k)
    except Exception as e:
        warnings.warn(f"elastic shrink: survivor-mesh warmup skipped "
                      f"({type(e).__name__}: {e})", stacklevel=2)


def _revalidate_shrink(model, session, new_mesh: DeviceMesh):
    """Static E1xx/W10x pass over the shrunk mesh. Non-E101 errors
    (structural: bad axes, HBM budget) abort the shrink; E101 (batch
    not divisible by the new data axis) only warns — the wrapper pads
    tail shards with zero-weight examples, so training stays correct."""
    batch = None
    it = session.iterator
    if it is not None:
        try:
            b = it.batch()
            if isinstance(b, int) and b > 0:
                batch = b
        except Exception:
            batch = None
    try:
        # .spec() declares the physical device count, so E102 also checks
        # axes-product-vs-survivors consistency
        report = model.validate(batch_size=batch, mesh=new_mesh.spec())
    except Exception as e:          # analysis must never block recovery
        logger.warning("elastic shrink: static revalidation failed (%s) — "
                       "continuing without it", e)
        return
    errors = report.errors()
    hard = [d for d in errors if d.code != "DL4J-E101"]
    if hard:
        raise ElasticShrinkError(
            "shrunk mesh fails static validation: "
            + "; ".join(f"{d.code}: {d.message}" for d in hard))
    for d in errors:                # E101: padding handles raggedness
        warnings.warn(f"elastic shrink: {d.code}: {d.message} "
                      "(tail shards will be zero-weight padded)",
                      stacklevel=2)


def _rescale_lr(model, session, cfg: ElasticConfig, old_n: int, new_n: int):
    if cfg.lr_policy == "none" or old_n == new_n:
        return
    frac = new_n / float(old_n)
    if cfg.lr_policy == "linear":
        factor = frac
    elif cfg.lr_policy == "sqrt":
        factor = frac ** 0.5
    else:
        raise ValueError(f"unknown lr_policy {cfg.lr_policy!r} "
                         "(expected none|linear|sqrt)")
    upd = model.conf.base.updater
    upd._lr_scale = getattr(upd, "_lr_scale", 1.0) * factor
    session._bust_step_caches()     # the scale is baked in at trace time
    logger.info("elastic shrink: lr scale x%.3g (policy=%s, %d -> %d "
                "replicas)", factor, cfg.lr_policy, old_n, new_n)
