"""Multi-host-safe sharded checkpointing.

Reference parity: the reference checkpoints from the Spark driver after
parameter averaging (one writer, full array — SURVEY.md §2.3); on a TPU
pod the parameters may be SHARDED across processes (FSDP/TP), so the
TPU-native layout is: every process writes exactly the shards it can
address (``arr.addressable_shards``), plus a process-0 manifest recording
tree structure, global shapes, and which file holds which shard index.
Loading is the mirror: each process reads only the shards its target
sharding makes addressable and assembles them with
``jax.make_array_from_single_device_arrays`` — no gather, no full-array
host materialization on any single host. The target sharding need NOT
match the saved one: a device slice with no exact saved shard is
stitched from the shards that cover it (elastic mesh shrink/grow
resumes a checkpoint written under the old topology).

Layout on disk::

    <dir>/manifest.json                  (process 0)
    <dir>/shards_p<K>.npz                (process K: its addressable data)
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple
from zipfile import BadZipFile as zipfile_BadZipFile

import jax
import numpy as np
from jax.sharding import NamedSharding

from deeplearning4j_tpu.train.resilience import CorruptCheckpointError

# One deadline governs BOTH rank 0's sub-manifest merge and every reader's
# wait for the merged manifest — a shorter reader wait can race a
# legitimately slow merge (ADVICE r3).
MANIFEST_TIMEOUT_S = 60.0


def _flatten(tree):
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _ in leaves:
        names.append("/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                              for p in path))
    return names, [l for _, l in leaves], treedef


def _index_key(index: Tuple[slice, ...], shape: Tuple[int, ...]) -> str:
    """Canonical key for a shard's global index: explicit starts/stops."""
    return ";".join(
        f"{s.start or 0}:{s.stop if s.stop is not None else dim}"
        for s, dim in zip(index, shape))


def save_sharded(directory: str, tree, step: int = 0):
    """Each process writes its addressable shards; process 0 writes the
    manifest. Barrier-free (the filesystem is the rendezvous; callers on
    multi-host should barrier before reading, as trainers naturally do
    between steps)."""
    os.makedirs(directory, exist_ok=True)
    names, leaves, _ = _flatten(tree)
    pidx = jax.process_index()
    local: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {"step": step, "leaves": {}}
    for name, leaf in zip(names, leaves):
        is_array = isinstance(leaf, jax.Array)
        arr = leaf if is_array else jax.numpy.asarray(leaf)
        entry: Dict[str, Any] = {"shape": list(arr.shape),
                                 "dtype": str(arr.dtype), "shards": {}}
        if not is_array and np.ndim(leaf) == 0:
            # plain Python scalar leaf: restore with the original type
            entry["pytype"] = type(leaf).__name__
        for sh in arr.addressable_shards:
            if sh.replica_id != 0:
                # replicated shards are written exactly once GLOBALLY —
                # a 64-host pure-DP job must not write 64 copies
                continue
            key = _index_key(sh.index, arr.shape)
            if f"{name}::{key}" in local:
                continue
            data = np.asarray(sh.data)
            local[f"{name}::{key}"] = data
            # per-shard SHA-256 in the manifest: a truncated/bit-flipped
            # .npz otherwise loads garbage (or throws an opaque numpy
            # error) — load_sharded verifies before assembling
            entry["shards"][key] = {
                "file": f"shards_p{pidx}.npz",
                "sha256": _shard_digest(data)}
        manifest["leaves"][name] = entry
    np.savez(os.path.join(directory, f"shards_p{pidx}.npz"), **local)

    if jax.process_count() > 1:
        # merge shard->file maps across processes: each rank atomically
        # writes a step-stamped sub-manifest; rank 0 merges the set for
        # THIS step (stale files from earlier saves can't satisfy it)
        _atomic_json(os.path.join(directory, f"manifest_p{pidx}.json"),
                     manifest)
        _merge_manifests(directory, step)
    else:
        # single-writer save into a possibly-reused directory: stale rank
        # sub-manifests from an earlier multi-process save would trip the
        # load-time step-agreement check — they describe nothing current
        import glob as _glob
        for stale in _glob.glob(os.path.join(directory, "manifest_p*.json")):
            try:
                os.remove(stale)
            except OSError:
                pass
        _atomic_json(os.path.join(directory, "manifest.json"), manifest)


def _shard_digest(data: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(data).tobytes()).hexdigest()


def _parse_key(key: str) -> Tuple[Tuple[int, int], ...]:
    """Inverse of :func:`_index_key`: ``"0:4;0:8"`` -> ((0, 4), (0, 8))."""
    return tuple(tuple(int(x) for x in part.split(":"))
                 for part in key.split(";"))


def _assemble_slice(name: str, entry: Dict[str, Any],
                    index: Tuple[slice, ...], shape: Tuple[int, ...],
                    shard_data) -> np.ndarray:
    """Stitch the requested global slice from whatever shards the
    checkpoint holds — the RESHARD path: a checkpoint saved under one
    mesh layout loads under another (elastic shrink: 8-way batch shards
    reassemble into 4 wider ones; grow: wide shards slice down). Raises
    FileNotFoundError when the saved shards don't cover the request."""
    want = tuple((s.start or 0, s.stop if s.stop is not None else dim)
                 for s, dim in zip(index, shape))
    out = np.empty(tuple(hi - lo for lo, hi in want),
                   dtype=np.dtype(entry["dtype"]))
    covered = 0
    for key in entry["shards"]:
        have = _parse_key(key)
        inter = tuple((max(wl, hl), min(wh, hh))
                      for (wl, wh), (hl, hh) in zip(want, have))
        if any(lo >= hi for lo, hi in inter):
            continue
        src = shard_data(name, key)
        src_idx = tuple(slice(lo - hl, hi - hl)
                        for (lo, hi), (hl, _hh) in zip(inter, have))
        dst_idx = tuple(slice(lo - wl, hi - wl)
                        for (lo, hi), (wl, _wh) in zip(inter, want))
        out[dst_idx] = src[src_idx]
        vol = 1
        for lo, hi in inter:
            vol *= hi - lo
        covered += vol
    total = 1
    for lo, hi in want:
        total *= hi - lo
    if covered != total:
        # shards are disjoint boxes, so covered volume == requested volume
        # iff the request is fully tiled
        raise FileNotFoundError(
            f"checkpoint shards for {name} cover only {covered}/{total} "
            f"elements of requested slice {want} (saved under an "
            f"incompatible sharding/topology)")
    return out


def _shard_entry(entry_shards: Dict[str, Any], key: str):
    """(file, sha256-or-None) for a manifest shard entry — tolerates the
    pre-checksum manifest format where the value was a bare filename."""
    v = entry_shards[key]
    if isinstance(v, str):
        return v, None
    return v["file"], v.get("sha256")


def _atomic_json(path: str, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _merge_manifests(directory: str, step: int,
                     timeout_s: float = MANIFEST_TIMEOUT_S):
    import glob as _glob
    import time
    if jax.process_index() != 0:
        return
    expect = jax.process_count()
    deadline = time.monotonic() + timeout_s
    merged: Optional[Dict] = None
    while True:
        subs = sorted(_glob.glob(os.path.join(directory, "manifest_p*.json")))
        current = []
        for p in subs:
            try:
                with open(p) as f:
                    m = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue       # mid-rename from a non-atomic filesystem
            if m.get("step") == step:
                current.append(m)
        if len(current) >= expect:
            merged = current[0]
            for m in current[1:]:
                for name, entry in m["leaves"].items():
                    merged["leaves"][name]["shards"].update(entry["shards"])
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"checkpoint merge: only {len(current)}/{expect} rank "
                f"manifests for step {step} appeared in {directory} within "
                f"{timeout_s}s")
        time.sleep(0.05)
    _atomic_json(os.path.join(directory, "manifest.json"), merged)


def load_sharded(directory: str, target_tree, mesh=None, specs=None):
    """Load into the sharding of ``target_tree`` (a pytree of jax.Arrays
    whose shardings define what this process needs), or — when ``mesh``
    and ``specs`` (same-structure pytree of PartitionSpecs) are given —
    into fresh arrays with those shardings.

    Returns (tree, step)."""
    import time
    if not os.path.isdir(directory):
        raise FileNotFoundError(
            f"load_sharded: checkpoint directory {directory!r} does not exist")
    man_path = os.path.join(directory, "manifest.json")
    deadline = time.monotonic() + MANIFEST_TIMEOUT_S
    while not os.path.exists(man_path):
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"load_sharded: {man_path} did not appear within "
                f"{MANIFEST_TIMEOUT_S}s — rank 0's manifest merge may have "
                f"failed or the directory is not a completed checkpoint")
        time.sleep(0.05)
    with open(man_path) as f:
        manifest = json.load(f)

    # a sub-manifest for a NEWER step than the merged manifest means a
    # later save started (and overwrote shard files) but never finished
    # merging — the merged manifest's checksums no longer describe what is
    # on disk, so refuse up front with a structured error. OLDER stale
    # sub-manifests (e.g. a directory reused by a save with a smaller
    # process count) are harmless leftovers and are ignored — the
    # per-shard checksums still guard the data actually referenced.
    import glob as _glob
    for sub_path in sorted(_glob.glob(os.path.join(directory,
                                                   "manifest_p*.json"))):
        try:
            with open(sub_path) as f:
                sub = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if (isinstance(sub.get("step"), int)
                and sub["step"] > manifest.get("step", 0)):
            raise CorruptCheckpointError(
                f"{directory}: rank sub-manifest {os.path.basename(sub_path)} "
                f"is for step {sub.get('step')} but the merged manifest is "
                f"for step {manifest.get('step')} — a newer partial "
                "overlapping save corrupted this checkpoint")

    names, leaves, treedef = _flatten(target_tree)
    if specs is not None:
        spec_leaves = treedef.flatten_up_to(specs)
    # open every shard file lazily
    files: Dict[str, Any] = {}

    def shard_data(name: str, key: str) -> np.ndarray:
        fname, digest = _shard_entry(manifest["leaves"][name]["shards"], key)
        if fname not in files:
            try:
                files[fname] = np.load(os.path.join(directory, fname))
            except (ValueError, OSError, EOFError) as e:
                raise CorruptCheckpointError(
                    f"{directory}/{fname}: unloadable shard archive "
                    f"({e})") from e
        try:
            data = files[fname][f"{name}::{key}"]
        except (KeyError, ValueError, zipfile_BadZipFile) as e:
            raise CorruptCheckpointError(
                f"{directory}/{fname}: missing/unreadable shard "
                f"{name}::{key} ({e})") from e
        if digest is not None and _shard_digest(data) != digest:
            raise CorruptCheckpointError(
                f"{directory}/{fname}: checksum mismatch for shard "
                f"{name}::{key} (truncated or bit-flipped write)")
        return data

    out_leaves: List[Any] = []
    for i, (name, leaf) in enumerate(zip(names, leaves)):
        entry = manifest["leaves"][name]
        if specs is not None and mesh is not None:
            sharding = NamedSharding(mesh, spec_leaves[i])
        elif isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            sharding = leaf.sharding
        else:
            # host-tree leaf (numpy/scalar): assemble the FULL global array
            # from every shard — a checkpoint saved under a sharded layout
            # must not silently restore as one shard's slice
            if len(entry["shards"]) == 1:
                data = shard_data(name, next(iter(entry["shards"])))
            else:
                full = np.empty(tuple(entry["shape"]),
                                dtype=np.dtype(entry["dtype"]))
                for key in entry["shards"]:
                    idx = tuple(slice(int(a), int(b))
                                for a, b in
                                (part.split(":") for part in key.split(";")))
                    full[idx] = shard_data(name, key)
                data = full
            pytype = entry.get("pytype")
            if pytype in ("int", "float", "bool"):
                out_leaves.append(
                    {"int": int, "float": float, "bool": bool}[pytype](
                        np.asarray(data).item()))
            else:
                out_leaves.append(jax.numpy.asarray(data))
            continue
        shape = tuple(entry["shape"])
        # assemble from per-device addressable shards
        dev_arrays = []
        devices = []
        index_map = sharding.addressable_devices_indices_map(shape)
        for device, index in index_map.items():
            key = _index_key(index, shape)
            if key in entry["shards"]:
                data = shard_data(name, key)
            else:
                # mesh layout changed since the save (elastic shrink/
                # grow): stitch this device's slice from the saved shards
                data = _assemble_slice(name, entry, index, shape,
                                       shard_data)
            dev_arrays.append(jax.device_put(data, device))
            devices.append(device)
        arr = jax.make_array_from_single_device_arrays(shape, sharding,
                                                       dev_arrays)
        out_leaves.append(arr)
    return (jax.tree_util.tree_unflatten(treedef, out_leaves),
            manifest.get("step", 0))
