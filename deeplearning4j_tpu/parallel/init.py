"""Multi-host distributed initialization.

Reference parity: the reference's multi-node entry points — Spark
``SharedTrainingMaster`` + Aeron ``MeshOrganizer`` bootstrap (SURVEY.md
§2.3, §3.4) and the ``NativeOpsHolder`` MPI/NCCL init underneath — whose
TPU-native replacement is one call: ``jax.distributed.initialize`` wires
every process into one global device mesh; afterwards the SAME
``Mesh``/``pjit`` code that runs single-host runs multi-host, with XLA
placing collectives on ICI within a slice and DCN across slices
(SURVEY.md §5 "Distributed communication backend", §7 hard-part #7).

Environment-variable driven (all optional on TPU pods, where jax
auto-discovers the topology):

- ``DL4J_TPU_COORDINATOR``   — "host:port" of process 0
- ``DL4J_TPU_NUM_PROCESSES`` — world size
- ``DL4J_TPU_PROCESS_ID``    — this process's rank
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence


@dataclass
class DistributedInfo:
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int
    coordinator: Optional[str]


_initialized: Optional[DistributedInfo] = None


def initializeDistributed(coordinator_address: str = None,
                          num_processes: int = None,
                          process_id: int = None,
                          local_device_ids: Sequence[int] = None,
                          ) -> DistributedInfo:
    """ref: the SharedTrainingMaster bootstrap, collapsed to one call.

    On a TPU pod slice all arguments are auto-discovered (call with no
    args in every process). For CPU/GPU clusters or tests, pass (or set
    via DL4J_TPU_* env vars) the coordinator address, world size, and
    rank. Idempotent per process."""
    global _initialized
    import jax

    if _initialized is not None:
        return _initialized

    coordinator_address = coordinator_address or os.environ.get(
        "DL4J_TPU_COORDINATOR")
    if num_processes is None and "DL4J_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DL4J_TPU_NUM_PROCESSES"])
    if process_id is None and "DL4J_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DL4J_TPU_PROCESS_ID"])

    if coordinator_address is not None or num_processes is not None:
        # CPU backends need a cross-process collectives implementation.
        # NOTE: must not touch jax.devices()/default_backend() here — the
        # backend must not initialize before distributed.initialize().
        platforms = (jax.config.jax_platforms or
                     os.environ.get("JAX_PLATFORMS", ""))
        if str(platforms).startswith("cpu"):
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids)
    else:
        # TPU pod: everything auto-discovered (no-op on a single host
        # with no coordinator configured)
        try:
            jax.distributed.initialize()
        except Exception:
            # Swallow ONLY when nothing in the environment says this is a
            # real multi-host job — silently degrading a pod to isolated
            # single-process training (wrong grads, corrupt checkpoints)
            # is far worse than failing loud.
            cluster_markers = ("COORDINATOR_ADDRESS",
                               "JAX_COORDINATOR_ADDRESS",
                               "MEGASCALE_COORDINATOR_ADDRESS",
                               "TPU_CLUSTER_COORDINATOR")
            if any(m in os.environ for m in cluster_markers):
                raise
            hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
            if "," in hosts:
                raise

    _initialized = DistributedInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
        coordinator=coordinator_address)
    return _initialized


def shutdownDistributed():
    global _initialized
    import jax
    if _initialized is not None:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        _initialized = None


def distributed_info() -> Optional[DistributedInfo]:
    return _initialized
