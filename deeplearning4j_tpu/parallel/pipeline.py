"""Pipeline parallelism: GPipe-style microbatched stage execution over a
``pipe`` mesh axis.

Reference parity: the reference has NO pipeline parallelism (SURVEY.md
§2.3 marks it "optional later") — this is capability the TPU-native
framework adds. Design follows the scaling-book recipe rather than
GPipe's original per-device threading: stage weights are sharded over the
``pipe`` axis of the same ``jax.sharding.Mesh`` every other strategy
uses, the schedule is ONE ``lax.fori_loop`` inside ``shard_map``, and
stage-to-stage transfer is ``lax.ppermute`` riding ICI. Reverse-mode
autodiff through the loop + ppermute yields the GPipe backward schedule
automatically — no hand-written backward pipeline.

Schedule (P stages, M microbatches, T = M + P - 1 ticks):

    tick t: stage 0 injects microbatch t (while t < M); every stage s
    runs its block on the activation it holds; results ppermute s -> s+1;
    stage P-1's result for microbatch t-(P-1) lands in the output buffer.

The bubble fraction is (P-1)/T, exactly GPipe's; raise M to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
try:                                    # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
except ImportError:                     # newer jax: promoted to top level
    from jax import shard_map as _shard_map

import inspect as _inspect

_SM_PARAMS = frozenset(_inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """shard_map with the replication-check kwarg spelled for whichever
    jax is installed (``check_rep`` pre-0.6, ``check_vma`` after)."""
    if "check_vma" in kwargs and "check_vma" not in _SM_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SM_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DeviceMesh


def microbatch(x, n_micro: int):
    """[B, ...] -> [n_micro, B/n_micro, ...]."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


def unmicrobatch(x):
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def stack_stage_params(layer_params_list):
    """List of per-layer pytrees (identical structure) -> one pytree whose
    leaves gain a leading layer dim [L, ...] — the shape ``pipe`` shards."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *layer_params_list)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x, mesh: DeviceMesh,
                   axis: str = "pipe", data_axis: Optional[str] = "data"):
    """Run ``x`` through all pipeline stages.

    ``stage_fn(local_params, act) -> act``: applied once per stage; it
    receives this stage's slice of ``stage_params`` (leading layer dim
    L/P — scan over it for multi-layer stages) and must preserve ``act``'s
    shape. ``stage_params`` leaves are [L, ...] sharded over ``axis`` on
    dim 0. ``x`` is [n_micro, mb, ...] (microbatch the batch first);
    returns the same shape. Differentiable end-to-end.
    """
    m = mesh.mesh
    n_pipe = mesh.size(axis)
    n_micro = x.shape[0]
    if n_micro < n_pipe:
        raise ValueError(f"n_micro={n_micro} < pipeline depth {n_pipe}: "
                         f"every stage needs at least one microbatch")
    other = tuple(a for a in m.axis_names if a != axis)
    p_params = P(axis)
    # microbatch dim replicated; per-microbatch batch dim data-sharded
    p_x = P(None, data_axis) if data_axis in other else P()

    @partial(shard_map, mesh=m, in_specs=(p_params, p_x),
             out_specs=p_x, check_vma=False)
    def run(local_params, xs):
        stage = jax.lax.axis_index(axis)
        ticks = n_micro + n_pipe - 1
        state = jnp.zeros_like(xs[0])            # activation held by stage
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outs = carry
            inject = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
            act = jnp.where(stage == 0, inject, state)
            y = stage_fn(local_params, act)
            # last stage banks microbatch t-(P-1) once the fill completes
            slot = jnp.clip(t - (n_pipe - 1), 0, n_micro - 1)
            write = (stage == n_pipe - 1) & (t >= n_pipe - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, slot, axis=0,
                                               keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, y, cur), slot, axis=0)
            # hand activations downstream (stage P-1's output retires)
            state = jax.lax.ppermute(
                y, axis, [(i, i + 1) for i in range(n_pipe - 1)])
            return (state, outs), None

        # scan (not fori_loop): the schedule must be reverse-differentiable
        # — backprop through it IS the GPipe backward pipeline
        (_, outs), _ = jax.lax.scan(tick, (state, outs),
                                    jnp.arange(ticks))
        # only the last stage holds real outputs; broadcast over the pipe
        # axis so downstream (head/loss) code sees them everywhere
        outs = jax.lax.psum(
            jnp.where(stage == n_pipe - 1, outs, jnp.zeros_like(outs)),
            axis)
        return outs

    return run(stage_params, x)


# --------------------------------------------------------- flagship wiring

def pipeline_param_shardings(cfg, mesh: DeviceMesh, axis: str = "pipe"):
    """Shardings for ``pipeline_params``: blocks [L, ...] split over the
    pipe axis, embeddings/head replicated (they run data-parallel outside
    the pipeline region)."""
    m = mesh.mesh
    s = lambda *spec: NamedSharding(m, P(*spec))
    blocks = {
        "ln1": {"g": s(axis), "b": s(axis)},
        "wqkv": s(axis), "bqkv": s(axis),
        "wo": s(axis), "bo": s(axis),
        "ln2": {"g": s(axis), "b": s(axis)},
        "w1": s(axis), "b1": s(axis),
        "w2": s(axis), "b2": s(axis),
    }
    out = {"embed": {"tok": s(), "pos": s()},
           "final_norm": {"g": s(), "b": s()},
           "blocks": blocks}
    return out


def to_pipeline_params(params):
    """models.transformer.init_params layout -> pipeline layout: the
    per-layer list becomes stacked [L, ...] leaves."""
    out = {k: v for k, v in params.items() if k != "layers"}
    out["blocks"] = stack_stage_params(params["layers"])
    return out


def _block(lp, x, cfg):
    """One pre-LN transformer block on a microbatch (the body
    models.transformer.forward runs per layer, minus mesh constraints —
    sharding inside shard_map is explicit)."""
    from deeplearning4j_tpu.ops import attention as attn_ops
    from deeplearning4j_tpu.ops import normalization as norm_ops
    B, T, E = x.shape
    H = cfg.n_heads
    ln = lambda v, p: norm_ops.layer_norm(
        v.astype(jnp.float32), p["g"].astype(jnp.float32),
        p["b"].astype(jnp.float32)).astype(cfg.dtype)
    h = ln(x, lp["ln1"])
    qkv = h @ lp["wqkv"] + lp["bqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    ctx = attn_ops.dot_product_attention(
        q.reshape(B, T, H, E // H), k.reshape(B, T, H, E // H),
        v.reshape(B, T, H, E // H), is_causal=cfg.causal)
    x = x + (ctx.reshape(B, T, E) @ lp["wo"] + lp["bo"])
    h = ln(x, lp["ln2"])
    h = jax.nn.gelu(h @ lp["w1"] + lp["b1"])
    return x + (h @ lp["w2"] + lp["b2"])


def pipeline_loss_fn(params, tokens, targets, cfg, mesh: DeviceMesh,
                     n_micro: int, axis: str = "pipe"):
    """Transformer LM loss with the L blocks executed as a pipeline.
    Embedding + head run data-parallel outside the pipeline region."""
    from deeplearning4j_tpu.ops import normalization as norm_ops
    B, T = tokens.shape
    x = jnp.take(params["embed"]["tok"], tokens, axis=0) \
        + params["embed"]["pos"][:T][None]
    x = x.astype(cfg.dtype)

    def stage_fn(local_blocks, act):
        def body(a, lp):
            return _block(lp, a, cfg), None
        out, _ = jax.lax.scan(body, act, local_blocks)
        return out

    xm = microbatch(x, n_micro)
    ym = pipeline_apply(stage_fn, params["blocks"], xm, mesh, axis=axis)
    x = unmicrobatch(ym)
    x = norm_ops.layer_norm(x.astype(jnp.float32),
                            params["final_norm"]["g"].astype(jnp.float32),
                            params["final_norm"]["b"].astype(jnp.float32))
    head = params["embed"]["tok"].T
    logits = (x.astype(cfg.dtype) @ head.astype(cfg.dtype)).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    return jnp.mean(nll)


def make_pipeline_train_step(cfg, updater, mesh: DeviceMesh, n_micro: int,
                             axis: str = "pipe"):
    """Compiled fwd+bwd+update with pipelined blocks (GPipe backward via
    reverse-mode through the schedule)."""

    def step(params, opt_state, t, tokens, targets):
        loss, grads = jax.value_and_grad(pipeline_loss_fn)(
            params, tokens, targets, cfg, mesh, n_micro, axis)
        tf = t.astype(jnp.float32)
        lr = updater.lr_at(tf)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        s_leaves = treedef.flatten_up_to(opt_state)
        new_p, new_s = [], []
        for pv, gv, sv in zip(leaves, g_leaves, s_leaves):
            u, s2 = updater.apply(gv.astype(jnp.float32), sv, lr, tf)
            new_p.append((pv.astype(jnp.float32) - u).astype(pv.dtype))
            new_s.append(s2)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s), t + 1, loss)

    return jax.jit(step, donate_argnums=(0, 1, 2))
