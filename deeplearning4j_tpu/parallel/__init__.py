"""Parallelism: mesh/sharding, DP/TP/SP, parallel inference
(ref: deeplearning4j-scaleout — SURVEY.md §2.3; redesigned as synchronous
SPMD over a device mesh with XLA collectives)."""

from deeplearning4j_tpu.parallel.checkpoint import (  # noqa: F401
    load_sharded,
    save_sharded,
)
from deeplearning4j_tpu.parallel.data import (  # noqa: F401
    ShardedDataSetIterator,
    make_global_view,
)
from deeplearning4j_tpu.parallel.init import (  # noqa: F401
    distributed_info,
    initializeDistributed,
    shutdownDistributed,
)
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    CoordinationService,
    DeviceLossError,
    DeviceMonitor,
    DispatchTimeoutError,
    DispatchWatchdog,
    ElasticConfig,
    ElasticShrinkError,
    InProcessCoordinator,
)
from deeplearning4j_tpu.parallel.mesh import DeviceMesh, ShardingRule  # noqa: F401
from deeplearning4j_tpu.parallel.sequence import ring_attention  # noqa: F401
from deeplearning4j_tpu.parallel.wrapper import (  # noqa: F401
    InferenceFailedError,
    InferenceObservable,
    InferenceShutdownError,
    ParallelInference,
    ParallelWrapper,
)
