"""Sequence/context parallelism: ring attention over the mesh ``seq`` axis.

Reference parity: ABSENT in the reference (SURVEY.md §5 "Long-context /
sequence parallelism: Absent... green-field") — this is the
capability-parity-plus long-context subsystem the rebuild adds: shard the
sequence dimension across devices; keys/values rotate around the ring via
``ppermute`` over ICI while each device accumulates its queries' attention
with an online-softmax (flash-style) update. Memory per device is
O(T/ring) and the KV transfer overlaps with compute.

Layout inside shard_map: q, k, v are [B, T_local, H, D] per-device shards
of a [B, T_global, H, D] tensor sharded on axis 1.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:                                    # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map
except ImportError:                     # newer jax: promoted to top level
    from jax import shard_map


def _block_attend(q, kb, vb, q_off, k_off, is_causal, m, l, acc, scale):
    """One flash-style accumulation step against a single KV block.
    q [B,Tq,H,D]; kb,vb [B,Tk,H,D]; returns updated (m, l, acc)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kb).astype(jnp.float32) * scale
    if is_causal:
        q_pos = q_off + jnp.arange(q.shape[1])
        k_pos = k_off + jnp.arange(kb.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, jnp.float32(-1e30))
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
    return m_new, l_new, acc_new


def _ring_attention_local(q, k, v, *, axis_name: str, is_causal: bool,
                          varying_axes=()):
    """Runs INSIDE shard_map: each device owns one sequence block."""
    B, Tl, H, D = q.shape
    size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    q_off = my_idx * Tl

    perm = [(j, (j + 1) % size) for j in range(size)]

    def body(i, carry):
        m, l, acc, kb, vb = carry
        # block currently held originated at rank (my_idx - i) mod size
        src = (my_idx - i) % size
        k_off = src * Tl
        m, l, acc = _block_attend(q, kb, vb, q_off, k_off, is_causal,
                                  m, l, acc, scale)
        # rotate KV around the ring (ICI neighbour exchange)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, acc, kb, vb

    m0 = jnp.full((B, H, Tl), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
    # mark the accumulators as device-varying so the loop carry type matches
    # (jax's shard_map varying-manual-axes tracking)
    if varying_axes and hasattr(lax, "pcast"):
        m0, l0, acc0 = jax.tree_util.tree_map(
            lambda x: lax.pcast(x, tuple(varying_axes), to="varying"),
            (m0, l0, acc0))
    m, l, acc, _, _ = lax.fori_loop(0, size, body, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Tl,H,D]


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "seq",
                   is_causal: bool = False, batch_axis: str = "data",
                   head_axis: str = None):
    """Ring attention over a [B, T, H, D] tensor sharded on T.

    q, k, v: global arrays (or shardings compatible with) [B, T, H, D];
    T is split over ``axis_name``; B over ``batch_axis``. Pass
    ``head_axis='model'`` under tensor parallelism so heads stay sharded
    (otherwise GSPMD would allgather QKV over the model axis).
    """
    spec = P(batch_axis, axis_name, head_axis, None)
    varying = tuple(a for a in (batch_axis, axis_name, head_axis) if a)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis_name, is_causal=is_causal,
                varying_axes=varying),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_attention_reference(q, k, v, is_causal: bool = False):
    """Single-device reference for tests: exact attention."""
    from deeplearning4j_tpu.ops.attention import dot_product_attention
    return dot_product_attention(q, k, v, is_causal=is_causal)
