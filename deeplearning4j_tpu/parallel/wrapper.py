"""Data-parallel training + parallel inference over the mesh.

Reference parity:
- ``ParallelWrapper`` (SURVEY.md §2.2/§2.3): N replicas fed round-robin,
  periodic averaging / encoded gradient sharing → here: synchronous SPMD —
  batch sharded over the ``data`` axis, params replicated, XLA emits the
  gradient allreduce over ICI. Strictly stronger consistency than the
  reference's async modes at higher throughput (SURVEY.md §2.3 "sync
  allreduce strictly dominates").
- ``ParallelInference`` (SURVEY.md §3.5): request queue + dynamic batching
  across device workers → here: a batcher in front of a data-sharded
  compiled forward.
"""

from __future__ import annotations

import queue
import threading
import warnings

import jax
import numpy as np

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.data.dataset import (AsyncDataSetIterator, DataSet,
                                             DataSetIterator)
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


class ParallelWrapper:
    """Sync data-parallel trainer over the mesh (ref: ParallelWrapper).

    Wraps a MultiLayerNetwork; ``fit`` shards each batch over the mesh's
    ``data`` axis and keeps params replicated — the train step is the
    network's own compiled step, so gradients are allreduced by XLA inside
    ONE program (no EncodedGradientsAccumulator, no averaging interval).
    """

    def __init__(self, model, mesh: DeviceMesh = None,
                 prefetch_buffer: int = 2, workers: int = None):
        self.model = model
        self.mesh = mesh or DeviceMesh.data_parallel()
        self.prefetch = prefetch_buffer

    def validate(self, batch_size: int = None, **kw):
        """Static lint of the wrapped model against THIS wrapper's mesh:
        the full configuration analysis plus the E1xx/W10x distribution
        lints (batch divisibility, replicated giants, HBM budget, ...).
        Pass ``batch_size`` for the per-step checks; extra keywords
        forward to ``analysis.analyze`` (``sharding=``, ``hbm_gb=``,
        ``suppress=``, ...)."""
        return self.model.validate(batch_size=batch_size, mesh=self.mesh,
                                   **kw)

    def warmup(self, shapes, *, steps_per_dispatch: int = 1, dtype=None,
               label_dtype=None, policy=None):
        """AOT-warm the wrapped model's programs under THIS wrapper's
        mesh — replicated params, batch-sharded inputs — through the
        PR-13 compile-cache seam (the replication-path warmup the
        elastic shrink path already had). ``shapes`` follows
        ``nn.compilecache.warmup``: ``(features, labels)`` pairs warm
        the train step/megastep, bare feature shapes warm the forward.
        Batch dims are padded up to a multiple of the data-axis width
        exactly like ``fit`` pads real batches, so the warmed program IS
        the dispatched one. With a persistent cache dir configured, a
        fresh process warms from disk (zero cold compiles)."""
        from deeplearning4j_tpu.nn import compilecache as _cc
        model = self.model
        if not model._initialized:
            model.init()
        n = self.mesh.size("data")

        def pad_shape(shape):
            shape = tuple(int(d) for d in shape)
            b = shape[0]
            if b % n:
                b += n - b % n
            return (b,) + shape[1:]

        padded = []
        for spec in shapes:
            if (isinstance(spec, (tuple, list)) and len(spec) == 2
                    and isinstance(spec[0], (tuple, list))):
                padded.append((pad_shape(spec[0]), pad_shape(spec[1])))
            else:
                padded.append(pad_shape(spec))
        k = max(int(steps_per_dispatch), 1)
        if k > 1 and any(not (isinstance(s, (tuple, list)) and len(s) == 2
                              and isinstance(s[0], (tuple, list)))
                         for s in padded):
            raise ValueError(
                "steps_per_dispatch>1 warms the megastep from "
                "(features, labels) pairs; bare forward shapes cannot "
                "be megabatched — warm them in a separate call")
        with self.mesh:
            model._ensure_opt_state()
            model._params = self.mesh.replicate(model._params)
            model._states = self.mesh.replicate(model._states)
            model._opt_state = self.mesh.replicate(model._opt_state)
            model._t_dev = None
            _cc.warmup(model, padded, policy=policy,
                       steps_per_dispatch=k, dtype=dtype,
                       label_dtype=label_dtype,
                       placement=lambda a: self._mesh_placement(a, k > 1))
        return model

    def fit(self, iterator: DataSetIterator, epochs: int = 1,
            steps_per_dispatch: int = 1, checkpoint=None, nan_policy=None,
            faults=None, elastic=None):
        """``steps_per_dispatch=K`` composes the data-parallel path with
        the K-step lax.scan megastep: each megabatch is staged as
        ``[K, B, ...]`` arrays batch-sharded over the mesh's ``data`` axis
        (axis 1) by a DevicePrefetcher, so ONE dispatch per K sharded
        update steps.

        ``checkpoint=``/``nan_policy=``/``faults=`` enable the fault-
        tolerance layer (train.resilience) exactly as on the wrapped
        model's own ``fit``; resume restores the full training state
        BEFORE replication so the restored params are distributed over
        the mesh like freshly initialized ones. With resilience active
        the K=1 AsyncDataSetIterator auto-wrap is skipped so checkpoint
        cursors stay exact (the async worker prefetches ahead of the
        applied step).

        ``elastic=ElasticConfig(...)`` (or ``elastic=True`` for the
        defaults) turns on elastic multi-device training
        (parallel.elastic): device health probes between dispatches, a
        dispatch watchdog, and on device loss a coordinated checkpoint +
        mesh shrink onto the survivors + bit-exact resume. Requires
        ``checkpoint=``; ``self.mesh`` reflects the shrunk mesh after a
        recovery."""
        if elastic is not None and elastic is not False:
            from deeplearning4j_tpu.parallel import elastic as _elastic
            cfg = elastic if isinstance(elastic, _elastic.ElasticConfig) \
                else _elastic.ElasticConfig()
            return _elastic.fit_elastic(
                self, iterator, epochs=epochs,
                steps_per_dispatch=steps_per_dispatch,
                checkpoint=checkpoint, nan_policy=nan_policy, faults=faults,
                config=cfg)
        model = self.model
        if not model._initialized:
            model.init()
        k = int(steps_per_dispatch)
        session = None
        if checkpoint is not None or nan_policy is not None \
                or faults is not None:
            from deeplearning4j_tpu.train import resilience as _resilience
            model._ensure_opt_state()
            session, iterator = _resilience.begin_session(
                model, iterator, checkpoint, nan_policy, faults)
        fresh = False
        if session is None and k <= 1 and self.prefetch \
                and not isinstance(iterator, AsyncDataSetIterator):
            # the wrapper's constructor resets the base and starts
            # prefetching (the K-step path prefetches via DevicePrefetcher
            # instead — its worker already pulls the base iterator)
            iterator = AsyncDataSetIterator(iterator, prefetch=self.prefetch)
            fresh = True
        # replicate params/opt state once; batches are sharded per step
        with self.mesh:
            model._ensure_opt_state()
            with _prof.trace_span("collective:replicate_params",
                                  devices=self.mesh.size("data")):
                model._params = self.mesh.replicate(model._params)
                model._states = self.mesh.replicate(model._states)
                model._opt_state = self.mesh.replicate(model._opt_state)
            # reset the device-resident clock: a _t_dev committed to a single
            # device by a previous non-mesh fit() would make the jitted step
            # see incompatible devices; _ensure_clock rebuilds it (fresh,
            # uncommitted) from _iteration on the first sharded step
            model._t_dev = None
            from deeplearning4j_tpu.nn import compilecache as _cc
            # auto-warm the first sharded batch signature when the
            # persistent cache is engaged (PR-13 carried remainder: the
            # plain replication path now flows through the same seam the
            # elastic shrink re-warm uses)
            warm_first = _cc.cache_dir() is not None
            from deeplearning4j_tpu.train.resilience import fit_scope
            with fit_scope(session, model, epochs) as n_epochs:
                for e in range(n_epochs):
                    if (e or not fresh) and not (
                            session is not None
                            and session.consume_skip_reset()):
                        iterator.reset()
                    if k > 1:
                        self._fit_epoch_multistep(model, iterator, k, session)
                    else:
                        def pulls():
                            while iterator.hasNext():
                                yield iterator.next()
                        stream = session.wrap_batches(pulls()) \
                            if session is not None else pulls()
                        for ds in stream:
                            sds = self._shard(ds)
                            if warm_first:
                                # replication-path warmup through the
                                # compile-cache seam: the first sharded
                                # signature AOT-compiles (or loads from
                                # the persistent disk tier) before the
                                # dispatch, which then hits the warmed
                                # executable — zero extra compiles
                                warm_first = False
                                model._warm_dispatch(
                                    sds.features, sds.labels,
                                    fmask=sds.features_mask,
                                    lmask=sds.labels_mask)
                            model._fit_one(sds)
                    model._epoch += 1
                    if session is not None:
                        session.on_epoch_end()
        return model

    def _fit_epoch_multistep(self, model, iterator, k: int, session=None):
        from deeplearning4j_tpu.train import stepping as _stepping

        def padded():
            while iterator.hasNext():
                yield self._pad(iterator.next())

        stream = session.wrap_batches(padded()) if session is not None \
            else padded()
        # honor prefetch_buffer exactly: 0 keeps the base iterator on the
        # calling thread (thread-affine data sources) with inline staging,
        # N bounds staged megabatches in device memory to N — each is K
        # minibatches, so the user's bound is a real memory bound
        _stepping.fit_epoch_multistep(
            model, stream, k, prefetch=self.prefetch or 0,
            placement=self._mesh_placement)

    def _mesh_placement(self, a, mega: bool):
        """DevicePrefetcher placement hook: megabatch arrays [K, B, ...]
        shard axis 1 over ``data``; leftover single batches shard axis 0
        (same as _shard_impl)."""
        ndim = np.ndim(a)
        if not mega:
            return jax.device_put(a, self.mesh.batch_sharding(ndim))
        return jax.device_put(
            a, self.mesh.sharding(None, "data", *([None] * (ndim - 2))))

    def _shard(self, ds: DataSet) -> DataSet:
        if _prof.instrumentation_active():
            from deeplearning4j_tpu.parallel.data import SHARD_BYTES
            nbytes = sum(int(np.asarray(a).nbytes)
                         for a in (ds.features, ds.labels) if a is not None)
            SHARD_BYTES.labels(site="wrapper").inc(nbytes)
            with _prof.trace_span("parallel:shard_batch", bytes=nbytes,
                                  devices=self.mesh.size("data")):
                return self._shard_impl(ds)
        return self._shard_impl(ds)

    def _shard_impl(self, ds: DataSet) -> DataSet:
        ds = self._pad(ds)
        out = DataSet.__new__(DataSet)
        put = lambda a: jax.device_put(
            a, self.mesh.batch_sharding(np.ndim(a))) if a is not None else None
        out.features = put(ds.features)
        out.labels = put(ds.labels)
        out.features_mask = put(ds.features_mask)
        out.labels_mask = put(ds.labels_mask)
        return out

    def _pad(self, ds: DataSet) -> DataSet:
        # zero-weight tail padding shared with the GSPMD trainer
        # (parallel.data.pad_to_data_axis): gradients exactly match the
        # unpadded batch
        from deeplearning4j_tpu.parallel.data import pad_to_data_axis
        return pad_to_data_axis(ds, self.mesh.size("data"))

    def averagingFrequency(self, n):
        # API-parity shim: sync SPMD allreduces inside ONE XLA program every
        # step; there is no averaging interval to configure. Warn so callers
        # porting reference configs know the knob has no effect here.
        warnings.warn(
            "ParallelWrapper.averagingFrequency has no effect: gradients are "
            "allreduced synchronously by XLA every step (no interval)",
            stacklevel=2)
        return self

    def workers(self, n):
        warnings.warn(
            "ParallelWrapper.workers has no effect: the worker count is the "
            "mesh's data-axis size (%d); pass a different DeviceMesh instead"
            % self.mesh.size("data"), stacklevel=2)
        return self


_INFERENCE_REPLICA_FAILURES = _prof.get_registry().counter(
    "dl4j_inference_replica_failures_total",
    "Inference forwards that raised or exceeded replica_timeout (each "
    "marks the serving replica set unhealthy and is retried on the "
    "survivors up to max_retries)")


class InferenceFailedError(RuntimeError):
    """An inference batch failed every attempt. ``attempts`` counts the
    forwards tried; ``last_error`` is the final failure."""

    def __init__(self, attempts: int, last_error: BaseException):
        self.attempts = attempts
        self.last_error = last_error
        super().__init__(
            f"inference failed after {attempts} attempt(s); last error: "
            f"{type(last_error).__name__}: {last_error}")


class InferenceShutdownError(RuntimeError):
    """The ParallelInference instance was closed while this request was
    still pending (queued, never dispatched). Retriable against another
    replica — the request was not executed."""

    retriable = True

    def __init__(self):
        super().__init__("ParallelInference closed: request was pending "
                         "and has not been executed — retry elsewhere")


class InferenceObservable:
    """Future-like handle for one inference request (ref: ObservablesProvider)."""

    def __init__(self):
        self._event = threading.Event()
        self._result = None

    def _complete(self, result):
        self._result = result
        self._event.set()

    def _fail(self, exc: Exception):
        self._error = exc
        self._event.set()

    def get(self, timeout: float = None):
        if not self._event.wait(timeout):
            raise TimeoutError("inference result not ready")
        if getattr(self, "_error", None) is not None:
            raise self._error
        return self._result


class ParallelInference:
    """Batched inference server object (ref: ParallelInference,
    InferenceMode.BATCHED): queue requests, coalesce up to batchLimit,
    run ONE sharded forward over the mesh, fan results back out.

    Robustness (ISSUE 6): a forward that raises — or exceeds
    ``replica_timeout`` seconds — marks the replica set unhealthy: the
    mesh devices are health-probed, dead ones dropped (the mesh
    rebuilds on the survivors), and the SAME coalesced batch is retried
    on the surviving replicas up to ``max_retries`` times
    (``dl4j_inference_replica_failures_total`` counts the failures).
    After exhaustion every request in the batch fails with a structured
    :class:`InferenceFailedError` instead of a raw backend exception.

    Superseded by :class:`deeplearning4j_tpu.serving.ModelServer`
    (ISSUE 7) — bounded admission with structured overload errors,
    per-request deadlines, AOT bucket warmup, a circuit breaker, and
    graceful drain. This class is kept for reference API parity; it
    shares the bounded-queue + close() semantics:

    - the request queue is bounded (``max_queue``); a full queue raises
      :class:`~deeplearning4j_tpu.serving.ServerOverloadedError`
      instead of blocking the producer unboundedly.
    - ``close()`` (also the context-manager exit; ``shutdown()`` is the
      reference-named alias) stops the worker and fails every pending
      request with :class:`InferenceShutdownError` — callers blocked in
      ``get(timeout)`` unblock immediately instead of timing out.
    """

    def __init__(self, model, mesh: DeviceMesh = None, batch_limit: int = 32,
                 queue_timeout_ms: float = 5.0, max_retries: int = 2,
                 replica_timeout: float = None, faults=None,
                 max_queue: int = 256):
        self.model = model
        self.mesh = mesh or DeviceMesh.data_parallel()
        self.batch_limit = batch_limit
        self.timeout = queue_timeout_ms / 1000.0
        self.max_retries = int(max_retries)
        self.replica_timeout = replica_timeout
        self.max_queue = int(max_queue)
        self._faults = faults
        self._watchdog = None
        if replica_timeout:
            from deeplearning4j_tpu.parallel.elastic import DispatchWatchdog
            # warmup: the first forwards compile; their wall time says
            # nothing about replica health
            self._watchdog = DispatchWatchdog(deadline=replica_timeout,
                                              grace=replica_timeout)
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.max_queue)
        # instrumented (PR-8 adoption sweep): taken per submit AND by the
        # recovery path's mesh swap — wait-time spikes here are the
        # client-visible symptom of a dead-replica rebuild
        from deeplearning4j_tpu.profiler.locks import InstrumentedLock
        self._submit_lock = InstrumentedLock("parallel_inference_submit")
        self._shutdown = False
        self._worker = threading.Thread(target=self._serve, daemon=True)
        self._worker.start()

    def output(self, x, timeout: float = 30.0):
        """Synchronous single-request API (ref: ParallelInference.output)."""
        return self.submit(x).get(timeout)

    def submit(self, x) -> InferenceObservable:
        obs = InferenceObservable()
        # the lock serializes against close(): no request can slip into
        # the queue after close() drained it (it would hang forever)
        with self._submit_lock:
            if self._shutdown:
                raise InferenceShutdownError()
            try:
                self._queue.put_nowait((np.asarray(x), obs))
            except queue.Full:
                from deeplearning4j_tpu.serving.errors import \
                    ServerOverloadedError
                raise ServerOverloadedError(self._queue.qsize(),
                                            self.max_queue) from None
        return obs

    def _serve(self):
        while not self._shutdown:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            sizes = [first[0].shape[0]]
            while sum(sizes) < self.batch_limit:
                try:
                    item = self._queue.get(timeout=self.timeout)
                    batch.append(item)
                    sizes.append(item[0].shape[0])
                except queue.Empty:
                    break
            try:
                feats = np.concatenate([b[0] for b in batch], axis=0)
                total = feats.shape[0]
                # pad to the next power-of-two bucket (capped at
                # batch_limit): ONE compiled program per bucket size
                # instead of one per coalesced request count
                bucket = 1
                while bucket < total:
                    bucket *= 2
                bucket = min(max(bucket, 1), max(self.batch_limit, total))
                if bucket > total:
                    pad = np.zeros((bucket - total,) + feats.shape[1:],
                                   feats.dtype)
                    feats = np.concatenate([feats, pad], axis=0)
                out = self._forward(feats)[:total]
                pos = 0
                for (x, obs), n in zip(batch, sizes):
                    obs._complete(out[pos:pos + n])
                    pos += n
            except Exception as e:  # fail the requests, keep the server alive
                for _, obs in batch:
                    obs._fail(e)

    # ------------------------------------------------------- fault handling
    def _forward_once(self, feats) -> np.ndarray:
        with self.mesh:
            return np.asarray(self.model.output(feats))

    def _forward(self, feats) -> np.ndarray:
        """One coalesced batch through the sharded forward, with bounded
        retry on a surviving replica set after a failure or timeout."""
        last = None
        attempts = 0
        for _ in range(self.max_retries + 1):
            attempts += 1
            try:
                if self._watchdog is not None:
                    return self._watchdog.run(
                        lambda: self._forward_once(feats), attempts)
                return self._forward_once(feats)
            except Exception as e:
                last = e
                _INFERENCE_REPLICA_FAILURES.inc()
                warnings.warn(
                    f"inference replica failure (attempt {attempts}): "
                    f"{type(e).__name__}: {e} — probing devices and "
                    "retrying on the survivors", stacklevel=2)
                self._drop_dead_replicas()
        raise InferenceFailedError(attempts, last)

    def _drop_dead_replicas(self):
        """Health-probe the serving mesh; rebuild it on the survivors
        when devices are dead (the retried forward then runs only on
        replicas that still answer)."""
        from deeplearning4j_tpu.parallel.elastic import shrink_mesh_on_dead
        new_mesh = shrink_mesh_on_dead(self.mesh, plan=self._faults,
                                       context="inference")
        if new_mesh is None:
            return
        with self._submit_lock:     # submitters/close() read the mesh
            self.mesh = new_mesh
        if self._watchdog is not None:
            self._watchdog.begin_attempt()  # the shrunk forward recompiles

    def close(self, timeout: float = 5.0):
        """Stop the worker and fail every still-pending request with
        :class:`InferenceShutdownError` (previously they silently sat
        in an unbounded queue until their own ``get(timeout)`` gave
        up). Idempotent; also the context-manager exit."""
        with self._submit_lock:
            if self._shutdown:
                return
            self._shutdown = True
        self._worker.join(timeout=timeout)
        while True:
            try:
                _x, obs = self._queue.get_nowait()
            except queue.Empty:
                break
            obs._fail(InferenceShutdownError())

    def shutdown(self):
        """Reference-named alias for :meth:`close`."""
        self.close()

    def __enter__(self) -> "ParallelInference":
        return self

    def __exit__(self, *exc):
        self.close()
