"""Device mesh + sharding configuration.

Reference parity: this module replaces the reference's distributed plumbing
(SURVEY.md §2.3): ``ParallelWrapper`` (single-node DP),
``ParameterAveragingTrainingMaster``/``SharedTrainingMaster`` (Spark BSP /
async gradient sharing over Aeron) — all subsumed by synchronous SPMD over
a ``jax.sharding.Mesh`` with XLA collectives riding ICI (SURVEY.md §5
"Distributed communication backend": the north-star replacement).

Axes convention (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):
- ``data``  — batch dim (DP; grads allreduced by XLA)
- ``model`` — tensor parallelism (TP; activations allgathered/reduced)
- ``seq``   — sequence/context parallelism (SP; ring collectives)

Multi-host: the same mesh spans hosts via ``jax.distributed.initialize``
(DCN between slices) — no code change, which is exactly the design win
over the reference's Aeron mesh + Spark topology (MeshOrganizer etc.).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class DeviceMesh:
    """Named-axis device mesh wrapper."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    @staticmethod
    def create(data: int = -1, model: int = 1, seq: int = 1,
               devices: Sequence = None) -> "DeviceMesh":
        """Build a (data, model, seq) mesh. ``data=-1`` = all remaining."""
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        if data == -1:
            assert n % (model * seq) == 0, f"{n} devices not divisible by model*seq"
            data = n // (model * seq)
        assert data * model * seq == n, \
            f"mesh {data}x{model}x{seq} != {n} devices"
        arr = np.asarray(devices).reshape(data, model, seq)
        return DeviceMesh(Mesh(arr, ("data", "model", "seq")))

    @staticmethod
    def data_parallel(devices: Sequence = None) -> "DeviceMesh":
        return DeviceMesh.create(data=-1, model=1, seq=1, devices=devices)

    @property
    def axis_names(self):
        return self.mesh.axis_names

    @property
    def devices(self) -> list:
        """Flat list of the mesh's jax devices (axis-major order) — the
        set the elastic layer health-probes and shrinks from."""
        return list(np.asarray(self.mesh.devices).flat)

    def spec(self, **kw) -> "Any":
        """Jax-free declaration of this mesh for the static distribution
        analyzer (:class:`analysis.distribution.MeshSpec`) — pass it (or
        this DeviceMesh directly) to ``model.validate(mesh=...)``.
        Keywords forward to MeshSpec (``sharding=``, ``pipeline=``,
        ``hbm_gb=``). The physical device count is declared so the
        axes-vs-devices consistency lint (E102) can fire."""
        kw.setdefault("devices", self.size())
        from deeplearning4j_tpu.analysis.distribution import MeshSpec
        return MeshSpec(dict(self.mesh.shape), **kw)

    def size(self, axis: str = None) -> int:
        if axis is None:
            return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))
        return self.mesh.shape[axis]

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding from a PartitionSpec-style tuple."""
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int) -> NamedSharding:
        """Shard dim 0 over data axis, replicate the rest."""
        return NamedSharding(self.mesh, P("data", *([None] * (ndim - 1))))

    def shard_batch(self, tree):
        """Place a host batch onto the mesh, dim-0-sharded over data."""
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self.batch_sharding(np.ndim(x))), tree)

    def replicate(self, tree):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self.replicated()), tree)

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


class ShardingRule:
    """Regex-based parameter sharding rules (the ``pjit`` param-sharding
    config the reference lacked — SURVEY.md §2.3 'TP for free via GSPMD')."""

    def __init__(self, rules: Dict[str, Tuple]):
        """rules: {param-name-regex: partition-spec-tuple}"""
        import re
        self.rules = [(re.compile(k), v) for k, v in rules.items()]

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return P(*spec)
        return P()  # replicate by default

    def shard_params(self, mesh: DeviceMesh, named_params: Dict):
        """Apply rules to a flat {name: array} dict."""
        out = {}
        for name, arr in named_params.items():
            spec = self.spec_for(name, np.ndim(arr))
            out[name] = jax.device_put(arr, NamedSharding(mesh.mesh, spec))
        return out
