"""Per-process data sharding for multi-host training.

Reference parity: the reference's Spark ``RDD<DataSet>`` repartitioning +
per-executor iterators (SURVEY.md §2.3 "Spark data pipelines"): each
worker sees only its slice of the global batch. TPU-native shape: each
process loads 1/``process_count`` of every global batch and
``make_global_view`` assembles the process-local slices into ONE global
``jax.Array`` laid out on the mesh's ``data`` axis — XLA then treats it
exactly like a single-host batch (scaling-book recipe).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator

# single family shared by every host->mesh staging site (wrapper batch
# sharding, multi-host global views) — labelled by site
SHARD_BYTES = _prof.get_registry().counter(
    "dl4j_shard_transfer_bytes_total",
    "Bytes staged host->mesh by batch sharding",
    labelnames=("site",))


def _zero_weight_mask(labels, b: int, pad: int, existing=None):
    """A labels mask whose ``pad`` tail rows weigh zero — shape per the
    output layer's loss contract: per-example [b] for ff labels,
    per-timestep [b, T] for time-series labels [N, C, T]."""
    lmask = existing
    if lmask is None:
        if labels is not None and labels.ndim == 3:
            lmask = np.ones((b, labels.shape[2]), np.float32)
        else:
            lmask = np.ones((b,), np.float32)
    return np.concatenate([lmask, np.zeros((pad,) + lmask.shape[1:],
                                           lmask.dtype)])


def pad_to_data_axis(ds, n: int):
    """Pad a batch up to a multiple of the data-shard count ``n`` with
    ZERO-WEIGHT examples (labels mask 0), so the padded batch's
    gradients exactly match the unpadded one — shared by
    ``ParallelWrapper`` and the GSPMD trainer's padding iterator.
    Accepts a DataSet or a MultiDataSet (multi-input/-output graphs:
    every features/labels array pads, every output gets a zero-weight
    tail mask)."""
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    multi = isinstance(ds, MultiDataSet)
    b = int((ds.features[0] if multi else ds.features).shape[0])
    if n <= 1 or b % n == 0:
        return ds
    pad = n - b % n
    rep = lambda a: np.concatenate([a, np.repeat(a[-1:], pad, 0)]) \
        if a is not None else None
    if multi:
        lmasks = list(ds.labels_masks) if ds.labels_masks \
            else [None] * len(ds.labels)
        lmasks = [_zero_weight_mask(lab, b, pad, existing=m)
                  for lab, m in zip(ds.labels, lmasks)]
        return MultiDataSet(
            [rep(a) for a in ds.features],
            [rep(a) for a in ds.labels],
            [rep(a) for a in ds.features_masks]
            if ds.features_masks else None,
            lmasks)
    return DataSet(rep(ds.features), rep(ds.labels),
                   rep(ds.features_mask),
                   _zero_weight_mask(ds.labels, b, pad,
                                     existing=ds.labels_mask))


class ShardedDataSetIterator(DataSetIterator):
    """Wrap any DataSetIterator: each process keeps its contiguous
    per-process slice of every global batch (ref: Spark repartition +
    worker-local iterators)."""

    def __init__(self, base: DataSetIterator, process_count: int = None,
                 process_index: int = None):
        self.base = base
        self.process_count = (process_count if process_count is not None
                              else jax.process_count())
        self.process_index = (process_index if process_index is not None
                              else jax.process_index())
        self._pending: Optional[DataSet] = None

    def _slice(self, a, lo, hi):
        return None if a is None else a[lo:hi]

    def _advance(self):
        # tail batches smaller than the process count are dropped (every
        # rank drops them symmetrically) rather than crashing mid-epoch
        while self._pending is None and self.base.hasNext():
            ds = self.base.next()
            if int(np.asarray(ds.features).shape[0]) >= self.process_count:
                self._pending = ds

    def next(self) -> DataSet:
        self._advance()
        if self._pending is None:
            raise StopIteration
        ds, self._pending = self._pending, None
        n = int(np.asarray(ds.features).shape[0])
        per = n // self.process_count
        lo = self.process_index * per
        hi = lo + per   # tail remainder dropped symmetrically on every rank
        with _prof.trace_span("parallel:process_shard", rank=self.process_index,
                              rows=per):
            return self._apply_pre(DataSet(
                self._slice(ds.features, lo, hi),
                self._slice(ds.labels, lo, hi),
                self._slice(ds.features_mask, lo, hi),
                self._slice(ds.labels_mask, lo, hi)))

    def hasNext(self) -> bool:
        self._advance()
        return self._pending is not None

    def reset(self):
        self._pending = None
        self.base.reset()

    def batch(self):
        b = self.base.batch()
        return None if b is None else b // self.process_count

    # -- checkpoint/resume cursor protocol (train.resilience) --
    def cursor(self):
        """Base cursor — but None while a batch sits buffered by
        ``hasNext()``'s look-ahead (the base has advanced past a batch
        this rank hasn't served; a cursor taken then would skip it on
        resume). The resilience layer records cursors right after
        ``next()``, where nothing is buffered."""
        if self._pending is not None:
            return None
        return self.base.cursor()

    def seek(self, cursor) -> None:
        self._pending = None
        self.base.seek(cursor)


def make_global_view(local_array, mesh: Mesh, spec: P = None):
    """Assemble each process's local batch slice into one global jax.Array
    sharded over the mesh (batch dim on the 'data' axis by default).

    ref: the conceptual inverse of Spark collect — data STAYS distributed;
    only the view is global."""
    if spec is None:
        spec = P("data")
    local = np.asarray(local_array)
    sharding = NamedSharding(mesh, spec)
    if _prof.instrumentation_active():
        SHARD_BYTES.labels(site="global_view").inc(local.nbytes)
        with _prof.trace_span("parallel:make_global_view",
                              bytes=int(local.nbytes)):
            return jax.make_array_from_process_local_data(sharding, local)
    return jax.make_array_from_process_local_data(sharding, local)
