"""Training UI / stats dashboard (ref: deeplearning4j-ui-parent, SURVEY.md
§1 L8): StatsListener (train.listeners) -> StatsStorage (ui.stats) ->
UIServer (ui.server)."""

from deeplearning4j_tpu.ui.stats import (FileStatsStorage, InMemoryStatsStorage,
                                         StatsStorage, StatsStorageRouter)
from deeplearning4j_tpu.ui.server import UIServer

__all__ = ["StatsStorage", "InMemoryStatsStorage", "FileStatsStorage",
           "StatsStorageRouter", "UIServer"]
