"""Training dashboard server.

Reference parity: ``org.deeplearning4j.ui.api.UIServer`` (the play-based
DL4J training UI, ``deeplearning4j-ui-parent`` — SURVEY.md §1 L8): attach
a StatsStorage, browse score/throughput and per-layer parameter/update
charts while (or after) training runs.

TPU-native/minimal: a stdlib ``http.server`` on a background thread
serving a self-contained HTML page (inline SVG charts, zero external
assets — the build environment is egress-free and so are most TPU pods).
JSON endpoints mirror the dashboard's needs:

- ``GET /api/sessions``                     -> list of session ids
- ``GET /api/static?session=S``             -> static info record
- ``GET /api/overview?session=S``           -> score + timing series
- ``GET /api/model?session=S``              -> per-layer stats series
- ``GET /``                                 -> dashboard page

Profiler subsystem exposure (the two machine-readable seams every later
perf PR cites — see ``deeplearning4j_tpu.profiler``):

- ``GET /metrics``  -> Prometheus text exposition (v0.0.4) of the global
  metrics registry: op-dispatch counters, compile-cache hits/misses,
  H2D/D2H bytes, train step / data-wait histograms, throughput gauges,
  serving counters. Clients that send ``Accept:
  application/openmetrics-text`` get the OpenMetrics dialect instead
  (trace-id exemplars on histogram buckets, ``# EOF`` terminator).
  Served regardless of whether a StatsStorage is attached — ``detach()``
  removes the dashboard's storage but keeps the scrape endpoint (and
  the server) alive.
- ``GET /trace``    -> Chrome Trace Event Format JSON of the global span
  tracer (open in ui.perfetto.dev or chrome://tracing).

Serving health surface (``UIServer.attach_serving(model_server)``):

- ``GET /healthz``  -> 200 while the attached model server's circuit
  breaker is closed/half-open (or no server is attached — process
  liveness), 503 when the breaker is open or the serve loop died.
- ``GET /readyz``   -> 200 only when the attached server is warmed
  (every bucket AOT-compiled) and admitting; 503 while warming,
  draining, closed, or with no server attached — wire this as the load
  balancer's readiness check so a replica drains out of rotation
  before SIGTERM lands.

Storage/serving references live as *instance attributes on the HTTP
server object* (one atomic attribute read per request), not on the
handler class: re-``attach()`` used to reassign a shared class
attribute while serving threads read it — a data race two UIServer
instances could also trample.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu import profiler as _prof
from deeplearning4j_tpu.ui.stats import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j-tpu training UI</title>
<style>
 body{font-family:system-ui,sans-serif;margin:20px;background:#fafafa}
 h1{font-size:18px} h2{font-size:14px;margin:18px 0 4px}
 .card{background:#fff;border:1px solid #ddd;border-radius:6px;
       padding:10px;margin-bottom:14px}
 svg{width:100%;height:180px} .meta{color:#666;font-size:12px}
 select{margin-bottom:10px}
 table{border-collapse:collapse;font-size:12px}
 td,th{border:1px solid #ddd;padding:3px 8px;text-align:right}
 th:first-child,td:first-child{text-align:left}
</style></head><body>
<h1>deeplearning4j-tpu training UI</h1>
<select id="sess"></select>
<div class="card"><h2>Score vs iteration</h2><svg id="score"></svg></div>
<div class="card"><h2>Update:parameter ratio (log10) vs iteration</h2>
  <svg id="ratio"></svg></div>
<div class="card"><h2>Parameter histograms (latest sampled iteration)</h2>
  <div id="hists" class="meta">enable StatsListener(with_histograms=True)
  to populate</div></div>
<div class="card"><h2>Latest layer stats</h2><div id="layers"></div></div>
<div class="card"><h2>Session</h2><div id="static" class="meta"></div></div>
<script>
function esc(s){return String(s).replace(/[&<>"']/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));}
function line(svg, series, names){
  const W=900,H=170,P=30; svg.innerHTML=""; svg.setAttribute("viewBox",
    "0 0 "+W+" "+H);
  let all=series.flatMap(s=>s.y).filter(v=>isFinite(v));
  if(!all.length)return;
  let xs=series[0].x, xmin=Math.min(...xs), xmax=Math.max(...xs)||1;
  let ymin=Math.min(...all), ymax=Math.max(...all);
  if(ymin===ymax){ymin-=1;ymax+=1}
  const sx=x=>P+(x-xmin)/(xmax-xmin||1)*(W-2*P);
  const sy=y=>H-P-(y-ymin)/(ymax-ymin)*(H-2*P);
  const colors=["#1f77b4","#ff7f0e","#2ca02c","#d62728","#9467bd",
                "#8c564b","#e377c2","#7f7f7f"];
  series.forEach((s,i)=>{
    let d=s.x.map((x,j)=>(j?"L":"M")+sx(x)+" "+sy(s.y[j])).join(" ");
    let p=document.createElementNS("http://www.w3.org/2000/svg","path");
    p.setAttribute("d",d); p.setAttribute("fill","none");
    p.setAttribute("stroke",colors[i%colors.length]); svg.appendChild(p);
  });
  [[ymin,H-P],[ymax,P]].forEach(([v,y])=>{
    let t=document.createElementNS("http://www.w3.org/2000/svg","text");
    t.textContent=v.toPrecision(3); t.setAttribute("x",0);
    t.setAttribute("y",y); t.setAttribute("font-size","10");
    svg.appendChild(t);});
}
async function refresh(){
  const sess=document.getElementById("sess").value; if(!sess)return;
  const ov=await (await fetch("/api/overview?session="+encodeURIComponent(sess))).json();
  line(document.getElementById("score"),
       [{x:ov.iterations,y:ov.scores}]);
  const mo=await (await fetch("/api/model?session="+encodeURIComponent(sess))).json();
  const rsvg=document.getElementById("ratio");
  const rser=Object.entries(mo.ratio_series).slice(0,8).map(([k,v])=>(
      {x:mo.iterations,y:v.map(r=>Math.log10(r+1e-12))}));
  line(rsvg,rser);
  const hj=await (await fetch("/api/histograms?session="+encodeURIComponent(sess))).json();
  const hd=document.getElementById("hists");
  const hkeys=Object.keys(hj.hists).slice(0,6);
  if(!hkeys.length){
    hd.innerHTML="enable StatsListener(with_histograms=True) to populate";
  } else {
    hd.innerHTML=hkeys.map(k=>{
      const h=hj.hists[k], W=280, H=80, n=h.counts.length;
      const m=Math.max(...h.counts)||1;
      const bars=h.counts.map((c,i)=>
        `<rect x="${i*W/n}" y="${H-c/m*H}" width="${W/n-1}" `+
        `height="${c/m*H}" fill="#1f77b4"/>`).join("");
      return `<div style="display:inline-block;margin:4px">`+
        `<div class="meta">${esc(k)} [${h.range[0].toPrecision(2)}, `+
        `${h.range[1].toPrecision(2)}]</div>`+
        `<svg viewBox="0 0 ${W} ${H}" style="width:${W}px;height:${H}px">`+
        bars+`</svg></div>`;}).join("");
  }
  let rows="<table><tr><th>layer/param</th><th>mean</th><th>std</th>"+
      "<th>norm</th><th>upd norm</th><th>upd ratio</th></tr>";
  for(const [k,v] of Object.entries(mo.latest))
    rows+=`<tr><td>${esc(k)}</td><td>${v.param_mean.toExponential(2)}</td>`+
      `<td>${v.param_std.toExponential(2)}</td>`+
      `<td>${v.param_norm.toExponential(2)}</td>`+
      `<td>${v.update_norm.toExponential(2)}</td>`+
      `<td>${v.update_ratio.toExponential(2)}</td></tr>`;
  document.getElementById("layers").innerHTML=rows+"</table>";
  const st=await (await fetch("/api/static?session="+encodeURIComponent(sess))).json();
  document.getElementById("static").textContent=JSON.stringify(st);
}
async function syncSessions(){
  const ss=await (await fetch("/api/sessions")).json();
  const sel=document.getElementById("sess");
  const cur=sel.value;
  if(ss.length !== sel.options.length){
    sel.innerHTML=ss.map(s=>`<option>${esc(s)}</option>`).join("");
    if(ss.includes(cur)) sel.value=cur;
  }
}
async function init(){
  await syncSessions();
  const sel=document.getElementById("sess");
  sel.onchange=refresh; refresh();
  setInterval(async()=>{await syncSessions(); refresh();}, 3000);
}
init();
</script></body></html>"""


def _sanitize(x):
    if isinstance(x, dict):
        return {k: _sanitize(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_sanitize(v) for v in x]
    if isinstance(x, float) and (x != x or x in (float("inf"), float("-inf"))):
        return None
    return x


class _Handler(BaseHTTPRequestHandler):
    @property
    def storage(self) -> Optional[StatsStorage]:
        # instance attribute on the serving HTTPServer: one atomic read,
        # swapped by attach()/detach() without touching shared class state
        return getattr(self.server, "dl4j_storage", None)

    @property
    def serving(self):
        return getattr(self.server, "dl4j_serving", None)

    def log_message(self, *a):   # silence request logging
        pass

    def _json(self, payload, code=200):
        # bare NaN/Infinity tokens are invalid JSON for browsers; map
        # non-finite floats (e.g. a NaN score) to null so the dashboard
        # keeps rendering exactly when diagnostics matter most
        self._body(json.dumps(_sanitize(payload)).encode(),
                   "application/json", code)

    def _body(self, body: bytes, ctype: str, code: int = 200):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        url = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        st = self.storage
        if url.path == "/metrics":
            # make sure always-present metric families are registered even
            # if their subsystem hasn't been touched yet this process
            try:
                import deeplearning4j_tpu.native.runtime  # noqa: F401
            except Exception:
                pass
            accept = self.headers.get("Accept", "")
            om = "application/openmetrics-text" in accept
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8" if om else
                     "text/plain; version=0.0.4; charset=utf-8")
            return self._body(
                _prof.get_registry().exposition(openmetrics=om).encode(),
                ctype)
        if url.path == "/trace":
            return self._body(
                _prof.get_tracer().export_chrome_trace().encode(),
                "application/json")
        if url.path == "/healthz":
            sv = self.serving
            if sv is None:
                return self._json({"status": "ok", "serving": "none"})
            if sv.healthy:
                return self._json({"status": "ok", "state": sv.state,
                                   "breaker": sv.breaker.state})
            return self._json({"status": "unhealthy", "state": sv.state,
                               "breaker": sv.breaker.state}, 503)
        if url.path == "/readyz":
            sv = self.serving
            if sv is None:
                return self._json({"ready": False,
                                   "reason": "no model server attached"},
                                  503)
            if sv.ready:
                return self._json({"ready": True, "state": sv.state,
                                   "queue_depth": sv.queue_depth()})
            return self._json({"ready": False, "state": sv.state}, 503)
        if url.path == "/":
            return self._body(_PAGE.encode(), "text/html")
        if st is None:
            # dashboard endpoints need a StatsStorage; /metrics, /trace
            # and the health endpoints above stay live without one
            return self._json({"error": "no stats storage attached"}, 503)
        if url.path == "/api/sessions":
            return self._json(st.listSessionIDs())
        sid = q.get("session", "")
        if url.path == "/api/static":
            return self._json(st.getStaticInfo(sid) or {})
        if url.path == "/api/overview":
            ups = st.getAllUpdates(sid)
            return self._json({
                "iterations": [u.get("iteration") for u in ups],
                "scores": [u.get("score") for u in ups],
                "times": [u.get("iteration_time_sec") for u in ups],
            })
        if url.path == "/api/model":
            ups = st.getAllUpdates(sid)
            ratio_series = {}
            for u in ups:
                for lname, rec in (u.get("layers") or {}).items():
                    ratio_series.setdefault(lname, []).append(
                        rec.get("update_ratio", 0.0))
            latest = (ups[-1].get("layers") or {}) if ups else {}
            return self._json({
                "iterations": [u.get("iteration") for u in ups],
                "ratio_series": ratio_series,
                "latest": latest,
            })
        if url.path == "/api/histograms":
            # newest update carrying per-layer histograms (StatsListener
            # with_histograms=True), ref: the reference UI's parameter /
            # update histogram tab
            ups = st.getAllUpdates(sid)
            for u in reversed(ups):
                layers = u.get("layers") or {}
                hists = {k: {"counts": v["hist_counts"],
                             "range": v["hist_range"]}
                         for k, v in layers.items() if "hist_counts" in v}
                if hists:
                    return self._json({"iteration": u.get("iteration"),
                                       "hists": hists})
            return self._json({"iteration": None, "hists": {}})
        self._json({"error": "not found"}, 404)


class UIServer:
    """ref: UIServer.getInstance().attach(statsStorage)."""

    _instance: Optional["UIServer"] = None
    # class-level twin of the instance _lifecycle lock: two threads
    # racing getInstance() must not both construct (and later bind) a
    # server for the same port
    _instance_lock = _prof.InstrumentedLock("ui:instance")

    def __init__(self, port: int = 9000):
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # serializes start/stop: attach()/attach_serving() from two
        # threads must not both observe _httpd None and double-bind the
        # port (DL4J-W213), and stop() must not race a concurrent start
        self._lifecycle = _prof.InstrumentedLock("ui:lifecycle")

    @classmethod
    def getInstance(cls, port: int = 9000) -> "UIServer":
        with UIServer._instance_lock:
            if cls._instance is None:
                cls._instance = cls(port)
            return cls._instance

    def _ensure_httpd(self) -> ThreadingHTTPServer:
        with self._lifecycle:
            if self._httpd is None:
                self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                                  _Handler)
                self.port = self._httpd.server_address[1]  # resolve port 0
                self._thread = threading.Thread(
                    target=self._httpd.serve_forever, daemon=True)
                self._thread.start()
            return self._httpd

    def attach(self, storage: StatsStorage):
        """Attach (or swap) the dashboard's StatsStorage; starts the
        HTTP server on first use. The reference lives on the server
        object, so re-attach is one atomic attribute write — no shared
        handler-class state for serving threads to race on."""
        self._ensure_httpd().dl4j_storage = storage
        return self

    def attach_serving(self, model_server):
        """Expose a :class:`~deeplearning4j_tpu.serving.ModelServer`'s
        health at ``/healthz`` + ``/readyz`` (starts the HTTP server if
        needed — serving works without any StatsStorage attached)."""
        self._ensure_httpd().dl4j_serving = model_server
        return self

    def detach(self):
        """Detach the stats storage ONLY: the dashboard endpoints go
        503 but the server — and ``/metrics``, ``/trace``, the health
        endpoints — keeps running. Call :meth:`stop` to shut down."""
        if self._httpd is not None:
            self._httpd.dl4j_storage = None
        return self

    def detach_serving(self):
        if self._httpd is not None:
            self._httpd.dl4j_serving = None
        return self

    def stop(self):
        with self._lifecycle:
            if self._httpd is not None:
                self._httpd.shutdown()
                # join before closing the socket: serve_forever has
                # observed the shutdown once join returns, so no request
                # thread touches the server object past this point
                if self._thread is not None:
                    self._thread.join(timeout=10.0)
                self._httpd.server_close()
                self._httpd = None
                self._thread = None
        with UIServer._instance_lock:
            if UIServer._instance is self:
                UIServer._instance = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"
