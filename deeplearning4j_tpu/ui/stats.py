"""Stats storage — the persistence layer of the training dashboard.

Reference parity: ``org.deeplearning4j.api.storage.StatsStorage`` with
``InMemoryStatsStorage`` / ``FileStatsStorage`` implementations and
``StatsStorageRouter`` (SURVEY.md §1 L8, §5 "Metrics/logging": the
StatsListener -> StatsStorage -> UIServer chain).

Records are plain JSON-able dicts with reserved keys:
``session_id``, ``type_id`` ("static" | "update"), ``worker_id``,
``timestamp``, ``iteration``. Everything else is payload. The storage is
append-only; readers query by session and iteration watermark — exactly
the access pattern the dashboard polls with.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.profiler.locks import InstrumentedLock


class StatsStorageEvent:
    """ref: StatsStorageEvent — notification unit for attached listeners."""

    def __init__(self, kind: str, session_id: str, record: Dict):
        self.kind = kind            # "new_session" | "static" | "update"
        self.session_id = session_id
        self.record = record


class StatsStorage:
    """Abstract storage (ref: org.deeplearning4j.api.storage.StatsStorage)."""

    def __init__(self):
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []
        self._lock = InstrumentedLock("ui:stats")

    # ---------------------------------------------------------------- write
    def putStaticInfo(self, record: Dict):
        record = dict(record)
        record.setdefault("type_id", "static")
        record.setdefault("timestamp", time.time())
        is_new = self._store(record, static=True)
        if is_new:
            self._notify(StatsStorageEvent("new_session",
                                           record["session_id"], record))
        self._notify(StatsStorageEvent("static", record["session_id"], record))

    def putUpdate(self, record: Dict):
        record = dict(record)
        record.setdefault("type_id", "update")
        record.setdefault("timestamp", time.time())
        self._store(record, static=False)
        self._notify(StatsStorageEvent("update", record["session_id"], record))

    # ----------------------------------------------------------------- read
    def listSessionIDs(self) -> List[str]:
        raise NotImplementedError

    def getStaticInfo(self, session_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def getAllUpdates(self, session_id: str) -> List[Dict]:
        raise NotImplementedError

    def getLatestUpdate(self, session_id: str) -> Optional[Dict]:
        ups = self.getAllUpdates(session_id)
        return ups[-1] if ups else None

    def getAllUpdatesAfter(self, session_id: str, iteration: int) -> List[Dict]:
        return [u for u in self.getAllUpdates(session_id)
                if u.get("iteration", -1) > iteration]

    # ------------------------------------------------------------ listeners
    def registerStatsStorageListener(self, cb: Callable[[StatsStorageEvent], None]):
        # registration can race a training thread mid-_notify: mutate
        # and snapshot the listener list under the storage lock
        with self._lock:
            self._listeners.append(cb)

    def _notify(self, event: StatsStorageEvent):
        with self._lock:
            listeners = list(self._listeners)
        for cb in listeners:
            cb(event)

    def _store(self, record: Dict, static: bool) -> bool:
        """Persist; returns True if this opened a new session."""
        raise NotImplementedError

    def close(self):
        pass


class InMemoryStatsStorage(StatsStorage):
    """ref: InMemoryStatsStorage — dict-backed, process-local."""

    def __init__(self):
        super().__init__()
        self._static: Dict[str, Dict] = {}
        self._updates: Dict[str, List[Dict]] = {}

    def _store(self, record, static):
        sid = record["session_id"]
        with self._lock:
            is_new = sid not in self._static and sid not in self._updates
            if static:
                self._static[sid] = record
            else:
                self._updates.setdefault(sid, []).append(record)
        return is_new

    def listSessionIDs(self):
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def getStaticInfo(self, session_id):
        # UI request threads read while the training thread stores
        with self._lock:
            return self._static.get(session_id)

    def getAllUpdates(self, session_id):
        with self._lock:
            return list(self._updates.get(session_id, []))


class FileStatsStorage(InMemoryStatsStorage):
    """ref: FileStatsStorage — the in-memory index plus an append-only
    JSONL file, reloaded on open (the UI can be pointed at the file of a
    finished or remote run)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    sid = rec.get("session_id", "?")
                    if rec.get("type_id") == "static":
                        self._static[sid] = rec
                    else:
                        self._updates.setdefault(sid, []).append(rec)
        else:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a")

    def _store(self, record, static):
        is_new = super()._store(record, static)
        with self._lock:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        return is_new

    def close(self):
        with self._lock:        # a racing _store must not hit a closed fh
            self._fh.close()


class StatsStorageRouter:
    """ref: StatsStorageRouter — fan records out to several storages
    (e.g. in-memory for the live UI + file for archival)."""

    def __init__(self, *storages: StatsStorage):
        self.storages = list(storages)

    def putStaticInfo(self, record):
        for s in self.storages:
            s.putStaticInfo(record)

    def putUpdate(self, record):
        for s in self.storages:
            s.putUpdate(record)
