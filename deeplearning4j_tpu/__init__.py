"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the capabilities of Eclipse Deeplearning4j
(reference: 007v/deeplearning4j) designed for TPUs: the eager ndarray API
(ND4J equivalent) and the graph/autodiff engine (SameDiff equivalent) both
lower to XLA via JAX, whole-program-compiled rather than interpreted
op-by-op; distributed training uses `jax.sharding` meshes with XLA
collectives over ICI/DCN instead of Spark/Aeron gradient sharing.

Top-level layout (mirrors the reference's layer map, SURVEY.md §1):

- ``linalg``      — eager NDArray + Nd4j factory (ref: nd4j-api INDArray/Nd4j)
- ``ops``         — op registry + Pallas kernels (ref: libnd4j declarable ops)
- ``autodiff``    — SameDiff graph engine   (ref: org.nd4j.autodiff.samediff)
- ``nn``          — layer/config/network API (ref: deeplearning4j-nn)
- ``train``       — updaters, losses, listeners, checkpoints (ref: org.nd4j.linalg.learning, org.deeplearning4j.optimize)
- ``evaluation``  — metrics (ref: org.nd4j.evaluation)
- ``data``        — datasets/ETL (ref: DataVec + deeplearning4j-data)
- ``parallel``    — mesh/sharding, DP/TP/SP, parallel inference (ref: deeplearning4j-scaleout)
- ``models``      — model zoo (ref: deeplearning4j-zoo)
- ``modelimport`` — Keras h5 import (ref: deeplearning4j-modelimport)
- ``ui``          — stats listeners/storage (ref: deeplearning4j-ui-parent)
- ``profiler``    — span tracer (Chrome trace) + metrics registry
                    (Prometheus) + ProfilingMode (ref: OpProfiler /
                    OpExecutioner.ProfilingMode; served by ui at
                    ``GET /trace`` and ``GET /metrics``)
- ``utils``       — env/flag registry, common helpers (ref: nd4j-common)
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.utils.environment import Environment  # noqa: F401
