"""Static model linter + runtime recompile-churn detector.

Catch misconfiguration BEFORE it burns an XLA compile (TVM-style whole-
graph analysis ahead of codegen; TensorFlow's pre-session graph
validation is the same shape of tool):

- :mod:`analyzer` — walks MultiLayerConfiguration /
  ComputationGraphConfiguration without touching jax, propagating
  InputType shapes layer-by-layer and vertex-by-vertex into structured
  ``Diagnostic(code, severity, location, message, fix_hint)`` findings
  (``DL4J-E001`` nIn mismatch, ``E002`` cycle, ``E003`` dangling vertex,
  ``E004`` duplicate name, ``E005`` missing CNN->Dense flatten, ``E006``
  merge-shape conflict, ``E007`` shape-inference failure, ``E008``
  missing loss head, ``W001`` loss/activation pairing, ``W002`` TBPTT
  without recurrence, ``W003`` frozen layers + stateful updater).
- :mod:`layout` — TPU layout lints: ``W101`` MXU tile-padding waste,
  ``W102`` non-native dtypes, ``W103`` batch vs. data-mesh divisibility.
- :mod:`distribution` — mesh/sharding/pipeline lints against a declared
  :class:`MeshSpec`: ``E101`` batch vs. data axis, ``E102`` absent mesh
  axis, ``E103`` pipeline-split weight tie, ``E104`` per-device HBM
  budget, ``W104`` replicated giant, ``W105`` pipeline FLOP imbalance,
  ``W106`` sub-MXU shard, ``W107`` per-layer collective volume.
- :mod:`pipeline` — input-pipeline feasibility against a declared
  :class:`InputPipelineSpec` (``analyze(..., input_pipeline=...)``, CLI
  ``--pipeline workers=8,batch=256,decode_ms=1.3``): ``W108`` host-bound
  decode/H2D img/s below the model's estimated device img/s — "this
  host cannot feed this chip", caught before any worker spawns.
- :mod:`numerics` — numerics & precision lints under a declared
  :class:`~deeplearning4j_tpu.nn.precision.PrecisionPolicy` and an
  optional :class:`DataRangeSpec` (``analyze(..., policy="bf16",
  data_range="0..255")``, CLI ``--policy bf16 --data-range 0..255``):
  ``E301`` policy conflict, ``E302`` precision-unsafe accumulation,
  ``E303`` dynamic-range overflow (the raw-pixel Adam-overflow class,
  statically), ``W301`` redundant cast churn, ``W302`` loss-scaling
  misconfiguration, ``W303`` unnormalized input.
- :mod:`serving` — serving-config lints (``ModelServer.validate()`` /
  :func:`lint_serving`): ``E110`` bucket vs. data-axis divisibility,
  ``E111`` serving HBM budget (params + largest-bucket activations),
  ``W110`` pathological bucket ladder.
- :mod:`samediff` — recorded-op-graph lints (``sd.validate()``): shape
  propagation over ``_Node`` graphs plus ``E151`` undefined input,
  ``E152`` shape conflict, ``E153`` bad loss variable, ``W151`` dangling
  placeholder, ``W152`` unused variable, ``W153`` no training op.
- :mod:`graphir` — jax-free analysis IR (typed tensor facts: shape,
  dtype, param-vs-activation, per-op FLOPs, producer/consumer edges)
  with two lowerings: :func:`~graphir.from_samediff` (recorded ``_Node``
  graphs, including imported ones) and :func:`~graphir.from_multilayer`
  (native configs — the parity proof). The layout / distribution /
  numerics families run over the IR, so ``sd.validate(mesh=...,
  policy=..., data_range=...)`` emits the same codes native configs get.
- :mod:`imports` — import-time lints shared by the Keras/ONNX/TF
  importers (each attaches a ``ValidationReport`` as ``import_report``
  on the returned model; ``analyze()`` folds it in): ``E161`` unmapped
  op, ``E162`` unhonored attribute semantics, ``E163`` lossy dtype
  narrowing, ``W161`` dynamic-dim placeholder recompile churn, ``W162``
  frozen-graph variable trained as constant, ``W163`` import-time
  const-folding overflow. ``tools/lint.py`` re-imports the TF fixture
  corpus against these codes (``[tool.dl4j.imports]`` suppressions).
- :mod:`concurrency` — AST-level thread-safety lints over source files
  or modules (:func:`analyze_concurrency`, ``--concurrency`` on the
  CLI, and the ``tools/lint.py`` self-lint gate): ``E201`` unguarded
  cross-thread mutation, ``E202`` read-modify-write outside a lock,
  ``E203`` lock-order cycle, ``W210`` wall clock in deadline math,
  ``W211`` un-looped ``Condition.wait``, ``W212`` unjoined worker
  thread, ``W213`` double-checked initialization race.
- :mod:`cost` / :mod:`chipspec` — whole-program static cost model
  against a declared :class:`~chipspec.ChipSpec` (``analyze(...,
  cost=CostSpec(chip="tpu-v4"))``, CLI ``--cost --chip tpu-v4``): an
  activation-lifetime liveness pass over the :mod:`graphir` edges
  computes the true training-step HBM high-water mark (params, grads,
  fp32 masters, ZeRO-aware updater state, live activations held for
  backward, megastep staging, prefetch), a roofline estimator predicts
  step time / per-stage time / MFU, and a capacity planner sizes a
  serving fleet: ``E120`` step-peak HBM overflow, ``E121`` serving-
  bucket peak overflow, ``E122`` capacity shortfall, ``W120`` remat
  opportunity, ``W121`` comms-bound step, ``W122`` predicted MFU below
  target. When ``cost=`` is declared the exact plan supersedes the
  params-only ``E104``/``W109`` heuristics.
- :mod:`churn` — runtime detector behind the fit/compile dispatch seams:
  ``dl4j_recompiles_total{site=...}`` in the profiler registry plus a
  ``W201`` diagnostic when one site crosses the signature threshold.

Entry points: ``config.validate()`` / ``model.validate()`` /
``sd.validate()`` (all accept ``mesh=...``, ``suppress=[...]``,
``severity_overrides={...}``), ``init(strict=True)`` (raises
:class:`ModelValidationError` on E-codes), and ``python -m
deeplearning4j_tpu.analysis [--zoo | <model-or-module>] [--mesh data=8]``.

The package imports no jax at module scope (pinned by a test) — analysis
is pure-static and runs anywhere the configs import.
"""

from deeplearning4j_tpu.analysis.analyzer import analyze
from deeplearning4j_tpu.analysis.chipspec import CHIP_REGISTRY, ChipSpec
from deeplearning4j_tpu.analysis.concurrency import analyze_concurrency
from deeplearning4j_tpu.analysis.cost import (CostSpec, capacity, lint_cost,
                                              memory_plan, plan, step_time)
from deeplearning4j_tpu.analysis.churn import (RecompileChurnDetector,
                                               array_fingerprint,
                                               get_churn_detector)
from deeplearning4j_tpu.analysis.diagnostics import (DIAGNOSTIC_CODES,
                                                     Diagnostic,
                                                     ModelValidationError,
                                                     Severity,
                                                     ValidationReport,
                                                     normalize_code)
from deeplearning4j_tpu.analysis.distribution import (MeshSpec, PipelineSpec,
                                                      StageProfile)
from deeplearning4j_tpu.analysis.graphir import (GraphIR, from_multilayer,
                                                 from_samediff,
                                                 lint_ir_distribution,
                                                 lint_ir_layout,
                                                 lint_ir_numerics)
from deeplearning4j_tpu.analysis.imports import (lint_narrowed_array,
                                                 lint_onnx_model,
                                                 lint_placeholder_shape,
                                                 samediff_import_report)
from deeplearning4j_tpu.analysis.numerics import DataRangeSpec, lint_numerics
from deeplearning4j_tpu.analysis.pipeline import (InputPipelineSpec,
                                                  lint_input_pipeline)
from deeplearning4j_tpu.analysis.samediff import analyze_samediff
from deeplearning4j_tpu.analysis.serving import (lint_compile_cache,
                                                 lint_registry_roll,
                                                 lint_serving)

__all__ = [
    "analyze", "analyze_concurrency", "analyze_samediff", "Diagnostic",
    "Severity",
    "ValidationReport", "ModelValidationError", "DIAGNOSTIC_CODES",
    "MeshSpec", "PipelineSpec", "StageProfile", "InputPipelineSpec",
    "lint_input_pipeline",
    "ChipSpec", "CHIP_REGISTRY", "CostSpec", "memory_plan", "step_time",
    "capacity", "lint_cost", "plan",
    "DataRangeSpec", "lint_numerics",
    "normalize_code", "RecompileChurnDetector",
    "get_churn_detector", "array_fingerprint", "lint_serving",
    "lint_registry_roll", "lint_compile_cache",
    "GraphIR", "from_samediff", "from_multilayer", "lint_ir_layout",
    "lint_ir_distribution", "lint_ir_numerics",
    "lint_onnx_model", "lint_narrowed_array", "lint_placeholder_shape",
    "samediff_import_report",
]
