"""TPU layout lints (W1xx) — static checks against MXU/mesh geometry.

The MXU processes 8x128 tiles: a matmul whose lane (minor-most) dim sits
just past a multiple of 128 pads the whole tile and burns the remainder
as dead FLOPs — e.g. nOut=300 executes as 384 lanes, 22% of every MAC
wasted. Same story for dtypes (f64 is emulated, f16 upcasts through f32
on the MXU — bf16/f32 are the native pair) and for the data-parallel
mesh (a global batch that does not divide the ``parallel/`` data axis
leaves ragged per-device shards).

These lints read only declared config shapes — no jax import, no trace.
Thresholds are deliberately conservative (dim >= 256 and > 20% padding
waste) so realistic published architectures (NASNet's 44-filter cells,
Xception's 728) stay clean while genuinely wasteful layouts get flagged.
"""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.analysis.diagnostics import Diagnostic, Severity

MXU_LANES = 128        # minor-most tile dim
MXU_SUBLANES = 8       # second-minor tile dim
#: Only lint lane dims at least this large — below it the whole operand
#: fits one tile and alignment is noise next to dispatch overhead.
MIN_LINT_DIM = 256
#: Padding-waste fraction above which W101 fires.
WASTE_THRESHOLD = 0.20

#: dtypes that are not MXU-native: f64 is software-emulated, f16 round
#: trips through f32. (bf16 + f32 are the native pair.)
NON_NATIVE_DTYPES = {"float64", "double", "f64", "float16", "half", "f16"}


def padding_waste(dim: int, tile: int = MXU_LANES) -> float:
    """Fraction of a padded tile row that is dead: (ceil-pad - dim)/pad."""
    padded = ((int(dim) + tile - 1) // tile) * tile
    return (padded - dim) / padded


def _is_conv(layer) -> bool:
    """4-D spatial conv-family check WITHOUT importing nn.layers (this
    module stays jax-free): the conv classes all carry kernel+stride
    geometry.  Convolution1D/3D are excluded — the NHWC compute-layout
    seam only stamps the 2-D family, so the layout-aware W101 text
    would prescribe (or claim) a fix that never applies to them."""
    name = type(layer).__name__
    return (("Convolution" in name or "Deconvolution" in name)
            and not name.endswith(("1D", "3D"))
            and hasattr(layer, "kernel") and hasattr(layer, "stride"))


def lint_lane_dim(dim: int, location: str, *, conv: bool = False,
                  compute_layout: str = "NCHW") -> Optional[Diagnostic]:
    """W101 when a single matmul lane dim pads wastefully on the MXU.

    For conv layers the finding is layout-aware (the ISSUE-14 extension):
    under the default NCHW compute layout the fix hint points at the
    NHWC seam (``setComputeLayout("NHWC")`` / ``computeLayout("NHWC")``)
    as well as the channel rounding; when the NHWC layout fix is ACTIVE
    the message says so — the remaining waste is pure tile padding, and
    only the channel count can recover it."""
    if not dim or dim < MIN_LINT_DIM or dim % MXU_LANES == 0:
        return None
    waste = padding_waste(dim)
    if waste <= WASTE_THRESHOLD:
        return None
    padded = ((dim + MXU_LANES - 1) // MXU_LANES) * MXU_LANES
    msg = (f"lane dim {dim} pads to {padded} on the "
           f"{MXU_SUBLANES}x{MXU_LANES} MXU tile grid — {waste:.0%} of "
           f"every MAC in this matmul is dead padding")
    hint = (f"round the feature/channel count to a multiple of "
            f"{MXU_LANES} (e.g. {padded} or "
            f"{max(MXU_LANES, padded - MXU_LANES)})")
    if conv:
        if compute_layout == "NHWC":
            msg += (" (NHWC compute layout is active — the remaining "
                    "waste is tile padding, not layout)")
        else:
            hint += ("; for conv stacks also enable the NHWC compute "
                     "layout (setComputeLayout('NHWC') / builder "
                     ".computeLayout('NHWC')) so channels sit on the "
                     "lane axis natively")
    return Diagnostic("DL4J-W101", Severity.WARNING, location, msg,
                      fix_hint=hint)


def lint_layers(located_layers,
                compute_layout: str = "NCHW") -> List[Diagnostic]:
    """W101 over ``(location, layer)`` pairs using each layer's
    ``mxu_lane_dims()`` declared-shape hook. ``compute_layout`` is the
    model's active conv compute layout — it shapes the conv findings'
    text (see ``lint_lane_dim``) without changing when they fire."""
    diags = []
    for location, layer in located_layers:
        dims = getattr(layer, "mxu_lane_dims", None)
        if dims is None:
            continue
        conv = _is_conv(layer)
        # a per-layer ``data_format`` stamp (the networks' NHWC seam —
        # an INSTANCE attribute; the class default is not a stamp) wins
        # over the config-level declaration
        fmt = getattr(layer, "__dict__", {}).get("data_format") \
            or compute_layout
        for d in dims():
            diag = lint_lane_dim(d, location, conv=conv,
                                 compute_layout=fmt)
            if diag is not None:
                diags.append(diag)
    return diags


#: Backends whose matmul unit wants channels on the minor-most (lane)
#: axis — where an NCHW conv stack predictably pays relayout overhead.
#: CPU is excluded: oneDNN re-layouts internally either way, so the
#: NCHW default is not a predictable loss there.
TPU_LIKE_BACKENDS = frozenset({"tpu"})

#: Minimum run of NCHW convs before the stack lint fires — a single
#: conv's relayout cost is dispatch noise; a stack compounds it.
MIN_CONV_STACK = 2


def _default_backend() -> Optional[str]:
    """The active jax backend WITHOUT importing jax (this module stays
    jax-free): only an ALREADY-imported jax is consulted, so analyzing a
    config in a jax-less tool process never drags the runtime in."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return str(jax.default_backend())
    except Exception:
        return None


def lint_conv_stack(located_layers, compute_layout: str = "NCHW",
                    backend: Optional[str] = None) -> List[Diagnostic]:
    """Proactive W101 (ISSUE 17): an NCHW conv STACK headed for a
    TPU-like backend is flagged BEFORE any training step runs — the
    per-layer lane-dim lint only fires on padding waste, but a stack of
    NCHW convs on the MXU loses to relayout overhead even with perfectly
    aligned channels.  ``backend`` defaults to the live jax backend (via
    ``_default_backend``; None/cpu disables the lint).  Layers carrying
    an NHWC ``data_format`` instance stamp (the ``setComputeLayout``
    seam — exactly what an applied tuning plan sets) don't count, so the
    autotuner's winning plan gets a clean bill through ``validate()``."""
    backend = backend if backend is not None else _default_backend()
    if backend is None or str(backend).lower() not in TPU_LIKE_BACKENDS:
        return []
    convs = []
    for location, layer in located_layers:
        if not _is_conv(layer):
            continue
        fmt = getattr(layer, "__dict__", {}).get("data_format") \
            or compute_layout
        if fmt != "NHWC":
            convs.append(location)
    if len(convs) < MIN_CONV_STACK:
        return []
    first, last = convs[0], convs[-1]
    return [Diagnostic(
        "DL4J-W101", Severity.WARNING, first,
        f"{len(convs)} conv layers ({first} .. {last}) run in the NCHW "
        f"compute layout on the '{backend}' backend — every conv pays "
        f"transpose/relayout overhead instead of keeping channels on the "
        f"MXU lane axis",
        fix_hint='enable the NHWC compute seam before training: '
                 'setComputeLayout("NHWC") (or builder '
                 '.computeLayout("NHWC")); `python -m '
                 'deeplearning4j_tpu.tune <model>` finds and persists '
                 'this plan automatically')]


def lint_dtype(dtype, location: str = "config") -> List[Diagnostic]:
    """W102 for dtypes the MXU cannot execute natively."""
    if dtype is None:
        return []
    name = str(dtype).lower()
    if name not in NON_NATIVE_DTYPES:
        return []
    kind = "software-emulated" if "64" in name or name == "double" \
        else "upcast to float32 on the MXU"
    return [Diagnostic(
        "DL4J-W102", Severity.WARNING, location,
        f"dtype {dtype!r} is not TPU-native and is silently {kind}",
        fix_hint="use float32 (or dataType('bfloat16') for the "
                 "mixed-precision policy) — bf16/f32 are the MXU-native "
                 "pair")]


def lint_batch_mesh(batch_size: Optional[int], data_devices: Optional[int],
                    location: str = "config") -> List[Diagnostic]:
    """W103 when the global batch does not divide the data-mesh axis."""
    if not batch_size or not data_devices or data_devices <= 1:
        return []
    if batch_size % data_devices == 0:
        return []
    return [Diagnostic(
        "DL4J-W103", Severity.WARNING, location,
        f"batch size {batch_size} does not divide the data-parallel mesh "
        f"axis ({data_devices} devices) — per-device shards would be "
        f"ragged and the sharded dispatch will pad or fail",
        fix_hint=f"use a global batch that is a multiple of {data_devices} "
                 f"(e.g. {((batch_size // data_devices) + 1) * data_devices})")]
