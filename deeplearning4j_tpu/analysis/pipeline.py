"""Input-pipeline feasibility lint (DL4J-W108): can this host feed this
chip?

BENCH_r05 measured the failure mode this catches: a ResNet-50 input
pipeline running at 5% of device throughput because single-core decode
(~744 img/s) and a pathological 6.2 MB/s H2D link bounded the feed far
below the ~2184 img/s the chip could train. Both bounds are *statically
decidable* from the declared pipeline configuration — worker count,
per-core decode cost, batch geometry, transfer dtype — before any
worker spawns or XLA compile burns:

    host_bound = min(workers / decode_s_per_img,  H2D_Bps / img_bytes)

compared against the model's estimated device rate (FLOP model at an
assumed MFU, or a measured ``device_img_per_sec``). ``host_bound <
device rate`` means the chip starves no matter how well the stages
overlap — W108 names the binding stage and the fix (more workers /
uint8 megabatch staging).

Jax-free like the rest of ``analysis``; wired into ``analyze(...,
input_pipeline=...)``, ``conf.validate(input_pipeline=...)``, and the
CLI ``--pipeline workers=8,batch=256,decode_ms=1.3,h2d_mbps=6.2``.
"""

from __future__ import annotations

from typing import List, Optional

from deeplearning4j_tpu.analysis.diagnostics import Diagnostic, Severity
from deeplearning4j_tpu.analysis.distribution import (_approx_flops,
                                                      _propagate_types,
                                                      dtype_bytes)

#: public v5e per-chip peak (BASELINE.md), the default for the estimate
PEAK_TFLOPS = 197.0


class InputPipelineSpec:
    """Static declaration of an input pipeline for the W108 lint.

    ``decode_ms_per_img`` is the measured single-core decode+resize cost
    (the data-pipeline bench prints it); ``h2d_mbps`` the measured
    host->device bandwidth. ``dtype`` is what crosses the link
    (``"uint8"`` = on-device cast/augment, 1/4 the bytes of float32).
    ``device_img_per_sec`` overrides the FLOP-model estimate with a
    measured rate (required for graph configs, whose jax-free FLOP
    propagation is sequential-only); ``assumed_mfu`` scales the
    estimate when no measurement exists."""

    def __init__(self, workers: int, batch_size: int,
                 decode_ms_per_img: Optional[float] = None,
                 h2d_mbps: Optional[float] = None,
                 height: Optional[int] = None, width: Optional[int] = None,
                 channels: int = 3, dtype: str = "uint8",
                 steps_per_dispatch: int = 1,
                 device_img_per_sec: Optional[float] = None,
                 assumed_mfu: float = 0.3,
                 peak_tflops: float = PEAK_TFLOPS):
        self.workers = int(workers)
        self.batch_size = int(batch_size)
        self.decode_ms_per_img = \
            None if decode_ms_per_img is None else float(decode_ms_per_img)
        self.h2d_mbps = None if h2d_mbps is None else float(h2d_mbps)
        self.height = None if height is None else int(height)
        self.width = None if width is None else int(width)
        self.channels = int(channels)
        self.dtype = str(dtype)
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.device_img_per_sec = \
            None if device_img_per_sec is None else float(device_img_per_sec)
        self.assumed_mfu = float(assumed_mfu)
        self.peak_tflops = float(peak_tflops)
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")

    _PARSE_KEYS = {
        "workers": ("workers", int),
        "batch": ("batch_size", int),
        "batch_size": ("batch_size", int),
        "decode_ms": ("decode_ms_per_img", float),
        "h2d_mbps": ("h2d_mbps", float),
        "hw": (None, int),                       # height = width = hw
        "height": ("height", int),
        "width": ("width", int),
        "channels": ("channels", int),
        "dtype": ("dtype", str),
        "steps": ("steps_per_dispatch", int),
        "mfu": ("assumed_mfu", float),
        "device_img_s": ("device_img_per_sec", float),
        "peak_tflops": ("peak_tflops", float),
    }

    @staticmethod
    def parse(text: str) -> "InputPipelineSpec":
        """``"workers=8,batch=256,decode_ms=1.3,h2d_mbps=6.2,hw=224"`` ->
        spec (the CLI ``--pipeline`` format)."""
        kw = {}
        for part in str(text).split(","):
            part = part.strip()
            if not part:
                continue
            key, eq, val = part.partition("=")
            key = key.strip().lower()
            if not eq or key not in InputPipelineSpec._PARSE_KEYS:
                known = ", ".join(sorted(InputPipelineSpec._PARSE_KEYS))
                raise ValueError(f"bad pipeline spec entry {part!r} "
                                 f"(known keys: {known})")
            field, conv = InputPipelineSpec._PARSE_KEYS[key]
            if field is None:           # hw shorthand
                kw["height"] = kw["width"] = int(val)
            else:
                kw[field] = conv(val.strip())
        if "workers" not in kw or "batch_size" not in kw:
            raise ValueError("pipeline spec needs at least workers= and "
                             "batch= entries")
        return InputPipelineSpec(**kw)

    @staticmethod
    def coerce(obj) -> Optional["InputPipelineSpec"]:
        if obj is None or isinstance(obj, InputPipelineSpec):
            return obj
        if isinstance(obj, str):
            return InputPipelineSpec.parse(obj)
        if isinstance(obj, dict):
            return InputPipelineSpec(**obj)
        raise TypeError(f"cannot coerce {type(obj).__name__} to "
                        "InputPipelineSpec (pass a spec, a dict, or a "
                        "'workers=8,batch=256,...' string)")

    def __repr__(self):
        return (f"InputPipelineSpec(workers={self.workers}, "
                f"batch={self.batch_size}, dtype={self.dtype!r})")


def _image_dims(conf, spec: InputPipelineSpec):
    """(C, H, W) crossing the link: the spec's declaration, else the
    config's convolutional InputType."""
    if spec.height is not None and spec.width is not None:
        return spec.channels, spec.height, spec.width
    it = getattr(conf, "input_type", None)
    if it is not None and getattr(it, "kind", None) == "cnn":
        d = it.dims
        return (int(d.get("channels", spec.channels)),
                int(d.get("height", 0)), int(d.get("width", 0)))
    return None


def _estimate_device_rate(conf, spec: InputPipelineSpec) -> Optional[float]:
    """img/s the device could train at: measured override, else
    FLOP-model estimate (fwd FLOPs x3 for training) at ``assumed_mfu`` —
    sequential configs only (graph FLOP propagation is not jax-free)."""
    if spec.device_img_per_sec is not None:
        return spec.device_img_per_sec
    layers = getattr(conf, "layers", None)
    if layers is None or not hasattr(conf, "base"):
        return None
    types = _propagate_types(conf)
    fwd = sum(_approx_flops(layer, it, out)
              for layer, (it, out) in zip(layers, types))
    if fwd <= 0:
        return None
    return spec.assumed_mfu * spec.peak_tflops * 1e12 / (3.0 * fwd)


def lint_input_pipeline(conf, spec) -> List[Diagnostic]:
    """The W108 check: host-bound input img/s (decode and H2D bounds
    from the declared pipeline) vs the model's estimated device img/s —
    a pipeline that cannot feed the chip is a configuration bug no
    amount of stage overlap fixes."""
    spec = InputPipelineSpec.coerce(spec)
    if spec is None:
        return []
    diags: List[Diagnostic] = []
    dims = _image_dims(conf, spec)
    bounds = {}
    if spec.decode_ms_per_img:
        bounds["decode"] = spec.workers * 1000.0 / spec.decode_ms_per_img
    if spec.h2d_mbps and dims is not None and all(dims):
        img_bytes = dims[0] * dims[1] * dims[2] * dtype_bytes(spec.dtype)
        bounds["h2d"] = spec.h2d_mbps * 1e6 / img_bytes
    if not bounds:
        return diags                     # nothing declared to bound on
    host_bound = min(bounds.values())
    binder = min(bounds, key=bounds.get)
    device = _estimate_device_rate(conf, spec)
    if device is None or host_bound >= device:
        return diags
    hints = []
    if "decode" in bounds and bounds["decode"] < device \
            and spec.decode_ms_per_img:
        need = int(-(-device * spec.decode_ms_per_img // 1000.0))
        hints.append(f"raise decode workers to >= {need}")
    if "h2d" in bounds and bounds["h2d"] < device:
        if dtype_bytes(spec.dtype) > 1:
            hints.append("ship uint8 and cast/augment on device "
                         "(4x fewer H2D bytes than float32)")
        if spec.steps_per_dispatch <= 1:
            hints.append("stage megabatches (steps_per_dispatch=K ships "
                         "ONE [K,B,...] transfer per dispatch)")
    detail = " / ".join(f"{k} ~{v:,.0f} img/s" for k, v in sorted(bounds.items()))
    diags.append(Diagnostic(
        "DL4J-W108", Severity.WARNING, "input pipeline",
        f"this host cannot feed this chip: host-bound input rate "
        f"~{host_bound:,.0f} img/s ({binder}-bound; {detail}) is below the "
        f"device's estimated ~{device:,.0f} img/s "
        f"({host_bound / device:.0%} of device rate) — the accelerator "
        f"idles no matter how well the pipeline stages overlap",
        fix_hint="; ".join(hints) or
                 "raise the binding stage's throughput or lower the "
                 "device demand (smaller model / larger host)"))
    return diags
