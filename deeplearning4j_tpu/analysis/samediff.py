"""SameDiff graph linter — static analysis of recorded op graphs (E15x/W15x).

``autodiff.samediff.SameDiff`` records ``_Node`` op graphs whose creation
order IS topological order; that makes the graph statically checkable the
same way layer configs are: propagate shapes node-by-node with pure
shape rules (no ``jax.eval_shape``, no trace), and report structural
problems — dangling placeholders, variables no loss depends on, loss
names that do not exist — as structured diagnostics before the first
compile.

Codes: ``E151`` undefined input name, ``E152`` shape conflict, ``E153``
bad loss variable, ``W151`` dangling placeholder, ``W152`` unused
trainable variable, ``W153`` training config with no loss marked.

Everything here is duck-typed off the recorded graph data (``_nodes`` /
``_placeholders`` / ``_variables`` / ``_constants`` / ``_loss_variables``
/ ``training_config``) and imports no jax — the pass runs with jax
blocked (pinned by the pure-static subprocess test). Ops without a shape
rule simply propagate "unknown": structural lints still apply, shape
lints go as far as the rules reach (the same graceful degradation the
reference's -1 dims give its ``summary()``).

Entry points: ``sd.validate()`` and ``analyze(sd)``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.analysis.diagnostics import (Diagnostic, Severity,
                                                     ValidationReport)

#: A static shape: tuple with None for unknown dims, or None when the
#: whole rank is unknown.
Shape = Optional[Tuple[Optional[int], ...]]


def analyze_samediff(sd, batch_size: int = 1) -> ValidationReport:
    report = ValidationReport(subject="SameDiff")
    nodes = list(getattr(sd, "_nodes", ()))
    placeholders = dict(getattr(sd, "_placeholders", {}) or {})
    variables = dict(getattr(sd, "_variables", {}) or {})
    constants = dict(getattr(sd, "_constants", {}) or {})
    loss_vars = list(getattr(sd, "_loss_variables", ()) or ())

    env: Dict[str, Shape] = {}
    for name, arr in list(variables.items()) + list(constants.items()):
        shape = getattr(arr, "shape", None)
        env[name] = tuple(int(d) for d in shape) if shape is not None else None
    for name, (shape, _dtype) in placeholders.items():
        env[name] = _normalize_ph_shape(shape, batch_size)

    consumed = set()
    produced = set()
    for node in nodes:
        loc = f"op '{node.outputs[0]}' ({node.op})" if node.outputs \
            else f"op ({node.op})"
        in_shapes: List[Shape] = []
        missing = False
        for ref in node.inputs:
            consumed.add(ref)
            if ref not in env:
                missing = True
                report.add(Diagnostic(
                    "DL4J-E151", Severity.ERROR, loc,
                    f"consumes '{ref}' but no variable, constant, "
                    f"placeholder, or earlier op output defines it",
                    fix_hint="define the input first (creation order is "
                             "execution order) or fix the name"))
            else:
                in_shapes.append(env[ref])
        if missing:
            for out in node.outputs:
                env[out] = None
                produced.add(out)
            continue
        out_shapes, err = _infer(node.op, in_shapes,
                                 dict(getattr(node, "attrs", {}) or {}))
        if err is not None:
            report.add(Diagnostic(
                "DL4J-E152", Severity.ERROR, loc, err,
                fix_hint="fix the operand shapes named in the message"))
        for i, out in enumerate(node.outputs):
            env[out] = out_shapes[i] if out_shapes and i < len(out_shapes) \
                else None
            produced.add(out)

    # W151: a placeholder nothing consumes still must be fed on every
    # output()/fit() call — almost always a leftover from refactoring
    if nodes:
        for name in placeholders:
            if name not in consumed:
                report.add(Diagnostic(
                    "DL4J-W151", Severity.WARNING, f"placeholder '{name}'",
                    "no recorded op consumes this placeholder (every "
                    "execution still requires feeding it)",
                    fix_hint="remove the placeholder or wire it into the "
                             "graph"))

    # E153 / W152 / W153: training-side structure
    known = set(env)
    for name in loss_vars:
        if name not in known:
            report.add(Diagnostic(
                "DL4J-E153", Severity.ERROR, f"loss '{name}'",
                f"setLossVariables names '{name}' but the graph has no "
                f"such variable",
                fix_hint="pass the op's output name (or the SDVariable) "
                         "to setLossVariables"))
    if loss_vars and variables:
        reachable = _ancestors(nodes, [n for n in loss_vars if n in known])
        for name in variables:
            if name not in reachable:
                report.add(Diagnostic(
                    "DL4J-W152", Severity.WARNING, f"variable '{name}'",
                    "no loss variable depends on this trainable variable "
                    "— its gradient is identically zero and the updater "
                    "still allocates state for it",
                    fix_hint="wire it into the loss, convertToConstants() "
                             "it, or drop it"))
    if getattr(sd, "training_config", None) is not None and not loss_vars:
        report.add(Diagnostic(
            "DL4J-W153", Severity.WARNING, "config",
            "a TrainingConfig is set but no loss variables are marked — "
            "fit() will raise 'call setLossVariables first'",
            fix_hint="call setLossVariables(<loss op output>) before fit"))
    return report


def _normalize_ph_shape(shape, batch_size) -> Shape:
    """Only the LEADING None/-1 dim is the batch substitution; any other
    unknown dim (sequence length, free spatial size) stays unknown —
    guessing there would fabricate shape conflicts."""
    if shape is None:
        return None
    out = []
    for i, d in enumerate(shape):
        if d is None or int(d) == -1:
            out.append(int(batch_size) if i == 0 and batch_size else None)
        else:
            out.append(int(d))
    return tuple(out)


def _ancestors(nodes, roots) -> set:
    producers = {}
    for node in nodes:
        for out in node.outputs:
            producers[out] = node
    seen, stack = set(), list(roots)
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        node = producers.get(name)
        if node is not None:
            stack.extend(node.inputs)
    return seen


# ------------------------------------------------------------- shape rules

def _infer(op: str, in_shapes: List[Shape], attrs: Dict):
    """-> (list of output shapes, error message or None). Unknown ops and
    unknown operand shapes degrade to ([None], None)."""
    rule = _SHAPE_RULES.get(op)
    if rule is None:
        if op in _PASSTHROUGH_OPS:
            return [in_shapes[0] if in_shapes else None], None
        return [None], None
    try:
        return rule(in_shapes, attrs)
    except _ShapeConflict as e:
        return [None], str(e)
    except Exception:
        return [None], None            # a rule must never crash the lint


class _ShapeConflict(ValueError):
    pass


def _broadcast(a: Shape, b: Shape, op: str) -> Shape:
    if a is None or b is None:
        return None
    out = []
    for da, db in zip(((None,) * max(0, len(b) - len(a)) + tuple(a)),
                      ((None,) * max(0, len(a) - len(b)) + tuple(b))):
        if da is None or db is None:
            out.append(da if db is None else db if da is None else None)
        elif da == db or db == 1:
            out.append(da)
        elif da == 1:
            out.append(db)
        else:
            raise _ShapeConflict(
                f"{op}: operand shapes {_fmt(a)} and {_fmt(b)} do not "
                f"broadcast (dims {da} vs {db})")
    return tuple(out)


def _fmt(s: Shape) -> str:
    if s is None:
        return "<unknown>"
    return "[" + ", ".join("?" if d is None else str(d) for d in s) + "]"


def _rule_binary(ins, attrs):
    a = ins[0] if len(ins) > 0 else None
    b = ins[1] if len(ins) > 1 else None
    return [_broadcast(a, b, "elementwise")], None


def _rule_matmul(ins, attrs):
    a, b = (ins + [None, None])[:2]
    if a is None or b is None or len(a) < 2 or len(b) < 2:
        return [None], None
    ta = bool(attrs.get("transpose_a"))
    tb = bool(attrs.get("transpose_b"))
    m, k = (a[-1], a[-2]) if ta else (a[-2], a[-1])
    k2, n = (b[-1], b[-2]) if tb else (b[-2], b[-1])
    if k is not None and k2 is not None and k != k2:
        raise _ShapeConflict(
            f"matmul: contracting dims disagree — {_fmt(a)}"
            f"{' (transposed)' if ta else ''} x {_fmt(b)}"
            f"{' (transposed)' if tb else ''} contracts {k} against {k2}")
    batch = _broadcast(a[:-2], b[:-2], "matmul batch dims")
    return [(tuple(batch) if batch else ()) + (m, n)], None


def _rule_xw_plus_b(ins, attrs):
    x, w = (ins + [None, None, None])[:2]
    b = ins[2] if len(ins) > 2 else None
    if x is not None and w is not None and len(x) >= 2 and len(w) == 2 \
            and x[-1] is not None and w[0] is not None and x[-1] != w[0]:
        raise _ShapeConflict(
            f"xw_plus_b: x features {_fmt(x)} do not match W rows {_fmt(w)}")
    if w is not None and b is not None and len(w) == 2 and len(b) == 1 \
            and None not in (w[1], b[0]) and w[1] != b[0]:
        raise _ShapeConflict(
            f"xw_plus_b: bias {_fmt(b)} does not match W cols {_fmt(w)}")
    if x is None or w is None or len(w) != 2:
        return [None], None
    return [tuple(x[:-1]) + (w[1],)], None


def _rule_reduce(ins, attrs):
    x = ins[0] if ins else None
    if x is None:
        return [None], None
    axis = attrs.get("axis")
    keep = bool(attrs.get("keepdims"))
    if axis is None:
        return [((1,) * len(x)) if keep else ()], None
    axes = [axis] if isinstance(axis, int) else list(axis)
    axes = [a % len(x) for a in axes]
    if keep:
        return [tuple(1 if i in axes else d for i, d in enumerate(x))], None
    return [tuple(d for i, d in enumerate(x) if i not in axes)], None


def _rule_reshape(ins, attrs):
    x = ins[0] if ins else None
    shape = attrs.get("shape")
    if shape is None:
        return [None], None
    shape = tuple(int(d) for d in shape)
    if x is not None and None not in x and -1 not in shape:
        n_in, n_out = 1, 1
        for d in x:
            n_in *= d
        for d in shape:
            n_out *= d
        if n_in != n_out:
            raise _ShapeConflict(
                f"reshape: cannot reshape {_fmt(x)} ({n_in} elements) to "
                f"{list(shape)} ({n_out} elements)")
    return [tuple(None if d == -1 else d for d in shape)], None


def _rule_transpose(ins, attrs):
    x = ins[0] if ins else None
    if x is None:
        return [None], None
    perm = attrs.get("perm")
    if not perm:
        return [tuple(reversed(x))], None
    if len(perm) != len(x):
        raise _ShapeConflict(
            f"transpose: perm {list(perm)} does not match rank of {_fmt(x)}")
    return [tuple(x[p] for p in perm)], None


def _rule_loss(ins, attrs):
    a = ins[0] if len(ins) > 0 else None
    b = ins[1] if len(ins) > 1 else None
    if a is not None and b is not None:
        _broadcast(a, b, "loss labels/predictions")
    return [()], None


#: ops whose output shape is their first input's (activations, casts,
#: dropout, normalizers over a known axis)
_PASSTHROUGH_OPS = frozenset({
    "neg", "abs", "exp", "log", "sqrt", "square", "tanh", "sigmoid",
    "relu", "gelu", "swish", "softmax", "log_softmax", "cast", "dropout",
    "sign", "floor", "ceil", "round", "erf", "softplus", "elu", "selu",
    "hard_sigmoid", "leaky_relu", "relu6", "cube", "rsqrt", "reciprocal",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "identity",
    "layer_norm", "batchnorm_sd", "bias_add", "std", "variance",
})

_SHAPE_RULES = {
    "add": _rule_binary, "subtract": _rule_binary, "multiply": _rule_binary,
    "divide": _rule_binary, "pow": _rule_binary, "maximum": _rule_binary,
    "minimum": _rule_binary, "greater": _rule_binary, "less": _rule_binary,
    "greater_equal": _rule_binary, "less_equal": _rule_binary,
    "equals": _rule_binary, "not_equals": _rule_binary,
    "squared_difference": _rule_binary, "floordiv": _rule_binary,
    "floormod": _rule_binary, "atan2": _rule_binary,
    "matmul": _rule_matmul,
    "xw_plus_b": _rule_xw_plus_b, "relu_layer": _rule_xw_plus_b,
    "reduce_sum": _rule_reduce, "reduce_mean": _rule_reduce,
    "reduce_max": _rule_reduce, "reduce_min": _rule_reduce,
    "reduce_prod": _rule_reduce, "reduce_norm2": _rule_reduce,
    "argmax": _rule_reduce, "argmin": _rule_reduce,
    "reshape": _rule_reshape,
    "transpose": _rule_transpose,
    "mean_sqerr_loss": _rule_loss, "softmax_cross_entropy_loss": _rule_loss,
    "sigmoid_cross_entropy_loss": _rule_loss, "absolute_difference_loss":
    _rule_loss, "cosine_distance_loss": _rule_loss, "hinge_loss": _rule_loss,
    "huber_loss": _rule_loss, "log_loss": _rule_loss,
    "sparse_softmax_cross_entropy_loss": _rule_loss,
}
