"""Whole-program static cost model (E12x/W12x) — jax-free.

Every open ROADMAP item needs a cost oracle before the code runs:
pipeline scheduling needs per-stage time, elastic shrink needs "will the
survivors OOM?", the serving fleet needs "how many replicas for this
QPS/SLO?", and the tuner burns real measurements on candidates a model
could reject statically.  This module is that oracle, built on the
:mod:`analysis.graphir` facts every model kind lowers to:

1. **Liveness-aware HBM planning** (:func:`memory_plan`) — a pass over
   the IR's producer/consumer edges computing the true training-step
   high-water mark: params, grads, fp32 masters, updater state
   (ZeRO-aware via the MeshSpec plan), live activations held for the
   backward pass, megastep ``[K, B, ...]`` staging, prefetch depth —
   replacing E104/E111's params-only accounting with lifetime
   accounting.  Conventions (pinned analytically by test against a
   hand-computed MLP):

   - params + grads at the policy's COMPUTE dtype; fp32 masters appear
     only when compute is low-precision;
   - updater state is ``updater_state_factor x param-elements x 4``
     bytes (state lives on the fp32 masters), divided by the declared
     ZeRO plan's divisor;
   - every produced activation (the input placeholder included — the
     first layer's dW needs it) is held for backward at the compute
     dtype, batch dim sharded over the data axis;
   - megastep staging is ``K x input bytes`` when K > 1; prefetch adds
     ``depth x input bytes``.

2. **Roofline step-time / MFU estimation** (:func:`step_time`) — per-op
   ``max(flops / peak_flops, bytes / hbm_bw)`` (train factor 3x for
   fwd+bwd), plus gradient-collective time from
   ``distribution.collective_payload_estimates`` over the chip's ICI
   bandwidth, rolled up into predicted step time, per-stage time under
   a declared pipeline, and predicted MFU with the binding resource
   named (compute / hbm / comms).

3. **Planner / capacity entry points** — ``analyze(cost=CostSpec(...))``
   / ``conf.validate(cost=...)`` / CLI ``--cost --chip tpu-v4``, the
   :func:`plan` report, and :func:`plan_pruner` (the tune/ seam:
   statically dominated candidates are pruned before measurement).

Codes (documented in :mod:`analysis.diagnostics`): ``E120`` step-peak
HBM overflow (names the dominating liveness component), ``E121``
serving-bucket peak overflow, ``E122`` capacity shortfall (names the
minimal replica count), ``W120`` remat opportunity, ``W121`` comms-bound
step, ``W122`` predicted MFU below target.

Warning gates are deliberate: ``W121`` needs a DECLARED batch size (the
per-device batch is unknowable otherwise), ``W122`` a declared
``mfu_target``, ``E121`` declared buckets, ``E122`` a declared ``qps``
or ``p99_ms`` — so ``--cost --chip tpu-v4`` alone judges exactly what
it can know: the HBM plan.

No jax import anywhere (pinned by the jax-blocked subprocess test).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis import distribution as _dist
from deeplearning4j_tpu.analysis import graphir as _gir
from deeplearning4j_tpu.analysis.chipspec import ChipSpec
from deeplearning4j_tpu.analysis.diagnostics import Diagnostic, Severity
from deeplearning4j_tpu.analysis.distribution import (MeshSpec, _fmt_bytes,
                                                      dtype_bytes,
                                                      updater_state_factor)
from deeplearning4j_tpu.nn.precision import LOW_PRECISION, PrecisionPolicy

#: W120 fires only when the step peak is at least this fraction of the
#: chip's HBM — a remat hint far from the budget is noise.
REMAT_BUDGET_FRACTION = 0.5
#: W121 fires when predicted collective time exceeds this fraction of
#: the predicted step time.
COMMS_BOUND_FRACTION = 0.5


class CostSpec:
    """Declarative input to the cost model (the ``analyze(cost=...)`` /
    CLI ``--cost`` surface).

    :param chip: a :class:`ChipSpec`, registry name, or dict
        (default ``"tpu-v4"``).
    :param qps: declared fleet load — enables the E122 capacity check.
    :param p99_ms: declared latency SLO — enables the E122 latency check.
    :param replicas: declared replica count for the capacity check
        (default 1 when qps is declared).
    :param mfu_target: declared MFU floor — enables W122.
    :param buckets: serving batch buckets — enables E121.
    :param steps_per_dispatch: megastep K (staging bytes scale with it).
    :param prefetch: host->device prefetch depth (staged input copies).
    :param precision: policy override for prediction (e.g. ``"bf16"``) —
        the tune/ pruner varies this per candidate plan.
    """

    def __init__(self, chip="tpu-v4", qps: Optional[float] = None,
                 p99_ms: Optional[float] = None,
                 replicas: Optional[int] = None,
                 mfu_target: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 steps_per_dispatch: int = 1, prefetch: int = 2,
                 precision=None):
        self.chip = ChipSpec.coerce(chip)
        self.qps = None if qps is None else float(qps)
        self.p99_ms = None if p99_ms is None else float(p99_ms)
        self.replicas = None if replicas is None else int(replicas)
        self.mfu_target = None if mfu_target is None else float(mfu_target)
        self.buckets = tuple(int(b) for b in buckets) if buckets else None
        self.steps_per_dispatch = max(int(steps_per_dispatch), 1)
        self.prefetch = max(int(prefetch), 0)
        self.precision = precision

    @staticmethod
    def coerce(obj) -> Optional["CostSpec"]:
        """CostSpec | True (all defaults) | chip name string | dict."""
        if obj is None or isinstance(obj, CostSpec):
            return obj
        if obj is True:
            return CostSpec()
        if isinstance(obj, str):
            return CostSpec(chip=obj)
        if isinstance(obj, dict):
            return CostSpec(**obj)
        raise TypeError(f"cannot interpret {obj!r} as a cost declaration "
                        "(use CostSpec, True, a chip name, or a dict)")

    def __repr__(self):
        return f"CostSpec(chip={self.chip.name!r})"


# ------------------------------------------------------------- lowering

def lower(target, batch_size: int = 1) -> _gir.GraphIR:
    """Any model kind -> GraphIR: an IR passes through; SameDiff-shaped
    objects, graph configs, and sequential configs take their
    lowerings.  ``model.conf``-bearing wrappers unwrap first."""
    if isinstance(target, _gir.GraphIR):
        return target
    target = getattr(target, "conf", target)
    if hasattr(target, "_nodes") and hasattr(target, "_placeholders"):
        return _gir.from_samediff(target, batch_size=batch_size)
    if hasattr(target, "graph_inputs") and hasattr(target, "nodes"):
        return _gir.from_graph(target, batch_size=batch_size)
    if hasattr(target, "layers"):
        return _gir.from_multilayer(target, batch_size=batch_size)
    raise TypeError(f"cannot lower {type(target).__name__} to a GraphIR "
                    "for cost analysis")


def _resolve_policy(ir: _gir.GraphIR, policy, cost: CostSpec
                    ) -> PrecisionPolicy:
    if cost.precision is not None:
        pol = PrecisionPolicy.coerce(cost.precision)
        if pol is not None:
            return pol
    pol = PrecisionPolicy.coerce(policy)
    if pol is not None:
        return pol
    implied = PrecisionPolicy.from_config_dtype(
        _gir._dominant_param_dtype(ir))
    return implied if implied is not None else PrecisionPolicy()


# ---------------------------------------------------------- memory plan

class MemoryPlan:
    """Per-device training-step HBM high-water mark, by liveness
    component. ``components`` maps name -> bytes; the peak is their sum
    (every component is live simultaneously at the end of the forward
    pass, where the backward begins)."""

    def __init__(self, components: Dict[str, float], chip: ChipSpec):
        self.components = dict(components)
        self.chip = chip

    @property
    def peak_bytes(self) -> float:
        return sum(self.components.values())

    def dominating(self) -> Tuple[str, float]:
        name = max(self.components, key=lambda k: self.components[k])
        return name, self.components[name]

    def format(self) -> str:
        parts = ", ".join(f"{k}: {_fmt_bytes(v)}"
                          for k, v in sorted(self.components.items(),
                                             key=lambda kv: -kv[1]) if v)
        return (f"step-peak HBM {_fmt_bytes(self.peak_bytes)}/device "
                f"of {self.chip.hbm_gb:g} GiB ({parts})")


def _input_bytes(ir: _gir.GraphIR, itemsize: int, data_width: int) -> float:
    total = 0.0
    for t in ir.placeholders():
        if t.size_known():
            total += _dist._prod(t.shape) * itemsize
    return total / max(data_width, 1)


def _activation_bytes(ir: _gir.GraphIR, itemsize: int,
                      data_width: int) -> float:
    """Backward-liveness activation bytes per device: every produced
    activation plus the input placeholders, held until its consumer's
    gradient — for a training step that is ALL of them at the fwd/bwd
    boundary. Batch dim shards over the data axis."""
    total = 0.0
    for t in ir.tensors.values():
        if t.kind not in ("activation", "placeholder"):
            continue
        if not t.size_known():
            continue
        total += _dist._prod(t.shape) * itemsize
    return total / max(data_width, 1)


def _forward_liveness_peak(ir: _gir.GraphIR, itemsize: int) -> float:
    """Inference-mode high-water mark over the op schedule: at op ``i``
    the live set is every activation/placeholder produced at or before
    ``i`` whose last consumer is at or after ``i``.  Returns TOTAL bytes
    (not per-device) at the IR's own batch size."""
    spans = []
    for t in ir.tensors.values():
        if t.kind not in ("activation", "placeholder") \
                or not t.size_known():
            continue
        start = t.producer if t.producer is not None else 0
        end = max(t.consumers) if t.consumers else start
        spans.append((start, end, _dist._prod(t.shape) * itemsize))
    peak = 0.0
    for i in range(len(ir.ops) or 1):
        live = sum(b for s, e, b in spans if s <= i <= e)
        peak = max(peak, live)
    if not ir.ops:
        peak = sum(b for _s, _e, b in spans)
    return peak


def memory_plan(target, cost=None, mesh=None, batch_size: Optional[int] = None,
                policy=None) -> MemoryPlan:
    """The liveness-aware training-step HBM plan for one device."""
    cost = CostSpec.coerce(cost) or CostSpec()
    mesh = MeshSpec.coerce(mesh) or MeshSpec({})
    batch = int(batch_size or 1)
    ir = lower(target, batch_size=batch)
    pol = _resolve_policy(ir, policy, cost)
    compute_bytes = dtype_bytes(pol.compute)
    low = pol.compute in LOW_PRECISION
    data_width = mesh.size(mesh.data_axis)

    entries = _gir._ir_entries(ir)
    facts = _dist._param_facts(entries, mesh, compute_bytes)
    factor = updater_state_factor(ir.updater)
    params = grads = masters = updater = 0.0
    for f in facts:
        params += f.bytes_per_device
        grads += f.bytes_per_device
        elems = f.bytes_per_device / compute_bytes
        if low:
            masters += elems * 4
        updater += elems * 4 * factor / _dist._zero_state_divisor(f, mesh)

    acts = _activation_bytes(ir, compute_bytes, data_width)
    inp = _input_bytes(ir, compute_bytes, data_width)
    k = cost.steps_per_dispatch
    staging = k * inp if k > 1 else 0.0
    prefetch = cost.prefetch * inp
    return MemoryPlan({
        "params": params, "grads": grads, "fp32 masters": masters,
        "updater state": updater, "live activations": acts,
        "megastep staging": staging, "prefetch": prefetch,
    }, cost.chip)


def serving_peak_bytes(target, cost=None, mesh=None, policy=None,
                       buckets: Optional[Sequence[int]] = None) -> float:
    """Serving-mode per-device peak: replicated params plus the largest
    bucket's forward-liveness activation high-water mark."""
    cost = CostSpec.coerce(cost) or CostSpec()
    mesh = MeshSpec.coerce(mesh) or MeshSpec({})
    buckets = tuple(buckets or cost.buckets or (1,))
    ir = lower(target, batch_size=1)
    pol = _resolve_policy(ir, policy, cost)
    compute_bytes = dtype_bytes(pol.compute)
    data_width = mesh.size(mesh.data_axis)
    facts = _dist._param_facts(_gir._ir_entries(ir), mesh, compute_bytes)
    params = sum(f.bytes_per_device for f in facts)
    act_peak = _forward_liveness_peak(ir, compute_bytes) / max(
        ir.batch_size, 1)
    return params + act_peak * max(buckets) / max(data_width, 1)


# -------------------------------------------------------------- roofline

class StepTimeEstimate:
    """Predicted training-step time on one chip, with the binding
    resource named and a per-stage breakdown under a declared
    pipeline."""

    def __init__(self, compute_s: float, hbm_s: float, roofline_s: float,
                 collective_s: float, mfu: float, chip: ChipSpec,
                 per_stage: Optional[List[float]] = None):
        self.compute_s = compute_s      # pure-FLOP lower bound
        self.hbm_s = hbm_s              # pure-bandwidth lower bound
        self.roofline_s = roofline_s    # sum of per-op max()
        self.collective_s = collective_s
        self.mfu = mfu
        self.chip = chip
        self.per_stage = per_stage

    @property
    def step_s(self) -> float:
        return self.roofline_s + self.collective_s

    @property
    def bound(self) -> str:
        terms = {"compute": self.compute_s, "hbm bandwidth": self.hbm_s,
                 "collectives": self.collective_s}
        return max(terms, key=lambda k: terms[k])

    def format(self) -> str:
        stages = ""
        if self.per_stage:
            stages = " (per stage: %s)" % ", ".join(
                f"{s * 1e3:.2f} ms" for s in self.per_stage)
        return (f"predicted step {self.step_s * 1e3:.3f} ms on "
                f"{self.chip.name} (roofline {self.roofline_s * 1e3:.3f} "
                f"ms + collectives {self.collective_s * 1e3:.3f} ms), "
                f"MFU {self.mfu:.3f}, {self.bound}-bound{stages}")


def _per_op_costs(ir: _gir.GraphIR, itemsize: int, batch: int,
                  data_width: int) -> List[Tuple[int, float, float]]:
    """[(op index, flops per device, bytes per device)] for one forward
    pass.  Sequential/graph lowerings carry per-example FLOPs (scale by
    batch); SameDiff lowerings already include the batch dim."""
    per_example = ir.subject != "SameDiff"
    out = []
    for op in ir.ops:
        flops = float(op.flops)
        if per_example:
            flops *= batch
        flops /= max(data_width, 1)
        bytes_ = 0.0
        for ref in tuple(op.inputs) + tuple(op.outputs):
            t = ir.tensors.get(ref)
            if t is None or not t.size_known():
                continue
            b = _dist._prod(t.shape) * itemsize
            if t.kind in ("activation", "placeholder"):
                b /= max(data_width, 1)     # batch dim sharded
            bytes_ += b
        out.append((op.index, flops, bytes_))
    return out


def step_time(target, cost=None, mesh=None, batch_size: Optional[int] = None,
              policy=None, train: bool = True) -> StepTimeEstimate:
    """Roofline step-time estimate: per-op max(flops/peak, bytes/bw)
    (x3 for fwd+bwd when training) plus gradient-collective time over
    the chip's ICI bandwidth."""
    cost = CostSpec.coerce(cost) or CostSpec()
    mesh = MeshSpec.coerce(mesh) or MeshSpec({})
    batch = int(batch_size or 1)
    ir = lower(target, batch_size=batch)
    pol = _resolve_policy(ir, policy, cost)
    compute_bytes = dtype_bytes(pol.compute)
    chip = cost.chip
    peak = chip.peak_for(pol.compute)
    bw = chip.hbm_gbps * 1e9
    data_width = mesh.size(mesh.data_axis)
    factor = 3.0 if train else 1.0

    costs = _per_op_costs(ir, compute_bytes, batch, data_width)
    compute_s = sum(f for _i, f, _b in costs) * factor / peak
    hbm_s = sum(b for _i, _f, b in costs) * factor / bw
    roofline_s = sum(max(f * factor / peak, b * factor / bw)
                     for _i, f, b in costs)

    collective_s = 0.0
    if train and data_width > 1:
        facts = _dist._param_facts(_gir._ir_entries(ir), mesh,
                                   compute_bytes)
        payload = sum(_dist.collective_payload_estimates(
            facts, mesh).values())
        collective_s = payload / (chip.ici_gbps * 1e9)

    per_stage = None
    stages = _dist._stage_assignment(mesh, len(ir.ops))
    if stages is not None and ir.ops:
        per_stage = [0.0] * mesh.pipeline.stages
        for i, f, b in costs:
            per_stage[stages[i]] += max(f * factor / peak,
                                        b * factor / bw)

    step_s = roofline_s + collective_s
    total_flops = sum(f for _i, f, _b in costs) * factor
    mfu = total_flops / (step_s * peak) if step_s > 0 else 0.0
    return StepTimeEstimate(compute_s, hbm_s, roofline_s, collective_s,
                            mfu, chip, per_stage=per_stage)


# ------------------------------------------------------ capacity planner

def capacity(target, cost, mesh=None, policy=None) -> Dict[str, float]:
    """Serving capacity facts for the E122 check: per-request latency at
    the largest bucket, per-replica QPS, and (when qps is declared) the
    minimal replica count that sustains it."""
    cost = CostSpec.coerce(cost) or CostSpec()
    bucket = max(cost.buckets) if cost.buckets else 1
    est = step_time(target, cost=cost, mesh=mesh, batch_size=bucket,
                    policy=policy, train=False)
    latency_s = est.step_s
    per_replica_qps = bucket / latency_s if latency_s > 0 else float("inf")
    out = {"bucket": bucket, "latency_ms": latency_s * 1e3,
           "per_replica_qps": per_replica_qps}
    if cost.qps is not None:
        out["min_replicas"] = max(
            1, int(math.ceil(cost.qps / per_replica_qps))
            if per_replica_qps > 0 else 10 ** 9)
    return out


# ---------------------------------------------------------------- lints

def lint_cost(target, cost, mesh=None, batch_size: Optional[int] = None,
              policy=None) -> List[Diagnostic]:
    """The E12x/W12x family over one model. Gating: E120/W120 always run
    (the HBM plan needs no extra declaration); W121 needs a declared
    batch size, W122 a declared mfu_target, E121 declared buckets, E122
    a declared qps or p99_ms."""
    cost = CostSpec.coerce(cost)
    if cost is None:
        return []
    diags: List[Diagnostic] = []
    chip = cost.chip
    budget = chip.hbm_bytes

    mem = memory_plan(target, cost=cost, mesh=mesh, batch_size=batch_size,
                      policy=policy)
    dom_name, dom_bytes = mem.dominating()
    if mem.peak_bytes > budget:
        diags.append(Diagnostic(
            "DL4J-E120", Severity.ERROR, "cost model",
            f"training step-peak HBM {_fmt_bytes(mem.peak_bytes)}/device "
            f"exceeds {chip.name}'s {chip.hbm_gb:g} GiB — the dominating "
            f"liveness component is {dom_name} "
            f"({_fmt_bytes(dom_bytes)}); full plan: {mem.format()}",
            fix_hint="shard params over a model axis, declare ZeRO "
                     "(zero=True), drop steps_per_dispatch/prefetch, or "
                     "rematerialize activations"))
    elif dom_name == "live activations" \
            and mem.peak_bytes >= REMAT_BUDGET_FRACTION * budget:
        diags.append(Diagnostic(
            "DL4J-W120", Severity.WARNING, "cost model",
            f"rematerialization opportunity: live backward activations "
            f"({_fmt_bytes(dom_bytes)}) dominate the "
            f"{_fmt_bytes(mem.peak_bytes)} step peak, which sits at "
            f"{mem.peak_bytes / budget:.0%} of {chip.name}'s "
            f"{chip.hbm_gb:g} GiB — recomputing activations in the "
            f"backward pass trades cheap FLOPs for the dominating term",
            fix_hint="enable activation rematerialization (or shrink the "
                     "batch) before scaling further"))

    est = step_time(target, cost=cost, mesh=mesh, batch_size=batch_size,
                    policy=policy, train=True)
    if batch_size is not None and est.step_s > 0 \
            and est.collective_s > COMMS_BOUND_FRACTION * est.step_s:
        diags.append(Diagnostic(
            "DL4J-W121", Severity.WARNING, "cost model",
            f"comms-bound step: predicted gradient-collective time "
            f"{est.collective_s * 1e3:.3f} ms is "
            f"{est.collective_s / est.step_s:.0%} of the "
            f"{est.step_s * 1e3:.3f} ms predicted step over "
            f"{chip.name}'s {chip.ici_gbps:g} GB/s ICI — scaling the "
            f"data axis further buys little",
            fix_hint="raise the per-device batch, accumulate gradients "
                     "(steps_per_dispatch), or allreduce in bf16"))
    if cost.mfu_target is not None and est.mfu < cost.mfu_target:
        diags.append(Diagnostic(
            "DL4J-W122", Severity.WARNING, "cost model",
            f"predicted MFU {est.mfu:.3f} is below the declared target "
            f"{cost.mfu_target:g} on {chip.name} — the binding resource "
            f"is {est.bound} ({est.format()})",
            fix_hint="raise the batch, fuse epilogues / switch to bf16 "
                     "compute, or lower the target for this chip"))

    if cost.buckets:
        peak = serving_peak_bytes(target, cost=cost, mesh=mesh,
                                  policy=policy)
        if peak > budget:
            diags.append(Diagnostic(
                "DL4J-E121", Severity.ERROR, "cost model",
                f"serving-bucket peak HBM {_fmt_bytes(peak)}/device "
                f"(params + bucket {max(cost.buckets)}'s forward "
                f"liveness peak) exceeds {chip.name}'s "
                f"{chip.hbm_gb:g} GiB at peak coalesced load",
                fix_hint="cap the bucket ladder, shard params over a "
                         "model axis, or serve on a bigger chip"))

    if cost.qps is not None or cost.p99_ms is not None:
        cap = capacity(target, cost, mesh=mesh, policy=policy)
        if cost.p99_ms is not None and cap["latency_ms"] > cost.p99_ms:
            diags.append(Diagnostic(
                "DL4J-E122", Severity.ERROR, "cost model",
                f"capacity: predicted per-request latency "
                f"{cap['latency_ms']:.3f} ms at bucket {cap['bucket']} "
                f"already exceeds the {cost.p99_ms:g} ms p99 budget on "
                f"an IDLE {chip.name} replica — no replica count fixes "
                f"latency",
                fix_hint="serve smaller buckets, a faster chip, or a "
                         "smaller model"))
        if cost.qps is not None:
            need = cap["min_replicas"]
            have = cost.replicas if cost.replicas is not None else 1
            if need > have:
                diags.append(Diagnostic(
                    "DL4J-E122", Severity.ERROR, "cost model",
                    f"capacity shortfall: {have} replica(s) sustain "
                    f"~{cap['per_replica_qps'] * have:.1f} QPS at bucket "
                    f"{cap['bucket']} but {cost.qps:g} QPS is declared "
                    f"— the minimal replica count is {need}",
                    fix_hint=f"deploy >= {need} replicas (or serve "
                             f"larger buckets to raise per-replica "
                             f"throughput)"))
    return diags


# --------------------------------------------------------------- planner

class CostReport:
    """The :func:`plan` bundle: memory plan + step estimate + capacity +
    the E12x/W12x diagnostics, with a human ``format()``."""

    def __init__(self, memory: MemoryPlan, step: StepTimeEstimate,
                 cap: Optional[Dict[str, float]],
                 diagnostics: List[Diagnostic]):
        self.memory = memory
        self.step = step
        self.capacity = cap
        self.diagnostics = diagnostics

    def format(self) -> str:
        lines = [self.memory.format(), self.step.format()]
        if self.capacity is not None:
            c = self.capacity
            line = (f"capacity: bucket {c['bucket']} at "
                    f"{c['latency_ms']:.3f} ms -> "
                    f"{c['per_replica_qps']:.1f} QPS/replica")
            if "min_replicas" in c:
                line += f", minimal replicas {c['min_replicas']}"
            lines.append(line)
        for d in self.diagnostics:
            lines.append(d.format())
        return "\n".join(lines)


def plan(target, cost=None, mesh=None, batch_size: Optional[int] = None,
         policy=None) -> CostReport:
    """One-stop planner: the full cost picture for a model on a chip."""
    cost = CostSpec.coerce(cost) or CostSpec()
    mem = memory_plan(target, cost=cost, mesh=mesh, batch_size=batch_size,
                      policy=policy)
    est = step_time(target, cost=cost, mesh=mesh, batch_size=batch_size,
                    policy=policy, train=True)
    cap = capacity(target, cost, mesh=mesh, policy=policy) \
        if (cost.qps is not None or cost.p99_ms is not None
            or cost.buckets) else None
    diags = lint_cost(target, cost, mesh=mesh, batch_size=batch_size,
                      policy=policy)
    return CostReport(mem, est, cap, diags)


# --------------------------------------------------------- tune/ pruning

def plan_pruner(conf, batch_size: Optional[int], cost, mesh=None,
                policy=None, bound: float = 3.0):
    """Build the tune/ static-domination pruner: a callable mapping a
    :class:`tune.TuningPlan` to a prune REASON string (or None to keep
    it).  A candidate is dominated when its predicted step peak OOMs the
    chip or its predicted step time exceeds the DEFAULT plan's
    prediction x ``bound``.  The caller (tune.driver) guarantees the
    incumbent default plan is never offered for pruning."""
    cost = CostSpec.coerce(cost) or CostSpec()

    def spec_for(tuning_plan) -> CostSpec:
        return CostSpec(
            chip=cost.chip, steps_per_dispatch=getattr(
                tuning_plan, "steps_per_dispatch", 1) or 1,
            prefetch=getattr(tuning_plan, "prefetch", 0) or 0,
            precision=getattr(tuning_plan, "precision", None))

    base = step_time(
        conf, cost=CostSpec(chip=cost.chip, steps_per_dispatch=1,
                            prefetch=0),
        mesh=mesh, batch_size=batch_size, policy=policy)

    def pruner(tuning_plan) -> Optional[str]:
        c = spec_for(tuning_plan)
        mem = memory_plan(conf, cost=c, mesh=mesh, batch_size=batch_size,
                          policy=policy)
        if mem.peak_bytes > c.chip.hbm_bytes:
            dom, dom_b = mem.dominating()
            return (f"predicted OOM on {c.chip.name}: "
                    f"{_fmt_bytes(mem.peak_bytes)}/device of "
                    f"{c.chip.hbm_gb:g} GiB ({dom} {_fmt_bytes(dom_b)} "
                    f"dominates)")
        est = step_time(conf, cost=c, mesh=mesh, batch_size=batch_size,
                        policy=policy)
        if base.step_s > 0 and est.step_s > base.step_s * bound:
            return (f"predicted step {est.step_s * 1e3:.3f} ms > "
                    f"{bound:g}x the default plan's "
                    f"{base.step_s * 1e3:.3f} ms")
        return None

    return pruner
