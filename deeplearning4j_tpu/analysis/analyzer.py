"""Static model analyzer — ahead-of-compile shape/dtype inference and
graph diagnostics.

Walks a ``MultiLayerConfiguration`` / ``ComputationGraphConfiguration``
(or their builders, or a built network) WITHOUT touching jax: InputTypes
propagate layer-by-layer / vertex-by-vertex through the same pure
``output_type`` / ``expected_nin`` hooks the build path uses, and every
finding comes back as a structured :class:`Diagnostic` instead of an
opaque XLA trace error three layers deep.

Entry points: :func:`analyze` (any config/builder/network),
``conf.validate()`` / ``model.validate()`` (thin wrappers), and the
``python -m deeplearning4j_tpu.analysis`` CLI.

No jax at module scope — nn.config is jax-free and everything else
(preprocessor selection, layer classes) is resolved lazily off the
objects being analyzed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis import distribution as _dist
from deeplearning4j_tpu.analysis import layout as _layout
from deeplearning4j_tpu.analysis import numerics as _numerics
from deeplearning4j_tpu.analysis.diagnostics import (Diagnostic, Severity,
                                                     ValidationReport)
from deeplearning4j_tpu.analysis.distribution import MeshSpec

#: Loss functions that assume unbounded/regression outputs — pairing one
#: with softmax collapses the gradient signal (ref: DL4J's
#: OutputLayerUtil.validateOutputLayer warning of the same shape).
_REGRESSION_LOSSES = {"mse", "l2", "l1", "mae", "squaredloss", "huber"}


def analyze(target, batch_size: Optional[int] = None,
            data_devices: Optional[int] = None, mesh=None, sharding=None,
            pipeline=None, hbm_gb: Optional[float] = None, zero=None,
            input_pipeline=None, policy=None, data_range=None,
            cost=None, profile=None,
            suppress=None, severity_overrides=None) -> ValidationReport:
    """Analyze a configuration, builder, network, or SameDiff graph.

    ``batch_size``/``data_devices`` feed the W103 mesh-divisibility lint
    (both optional — pass the planned global batch and the size of the
    ``parallel/`` data axis when known). ``mesh`` (a
    :class:`~deeplearning4j_tpu.analysis.distribution.MeshSpec`, an
    ``{axis: size}`` dict, a ``"data=8,model=2"`` string, or a runtime
    ``DeviceMesh``) switches on the E1xx/W10x distribution lints;
    ``sharding`` (``ShardingRule`` or {regex: spec}), ``pipeline``
    (``PipelineSpec``/stage count), ``hbm_gb``, and ``zero`` (a ZeRO
    updater-state-sharding declaration: ``True``, an axis name, a
    dict, or a runtime ``distributed.zero.ZeroPlan`` — E104 then
    counts updater state at 1/data-axis and W109 stays quiet) refine
    them.
    ``input_pipeline`` (an
    :class:`~deeplearning4j_tpu.analysis.pipeline.InputPipelineSpec`,
    dict, or ``"workers=8,batch=256,decode_ms=1.3"`` string) switches on
    the W108 can-this-host-feed-this-chip check.
    ``policy`` (a :class:`~deeplearning4j_tpu.nn.precision.
    PrecisionPolicy` or a dtype string like ``"bf16"``) and
    ``data_range`` (a :class:`~deeplearning4j_tpu.analysis.numerics.
    DataRangeSpec`, ``"0..255"``, or ``(lo, hi)``) refine the E3xx/W30x
    numerics lints — with neither, the pass still runs under the policy
    implied by the config's ``dataType`` (or the network's attached
    ``setPrecisionPolicy``).
    ``cost`` (a :class:`~deeplearning4j_tpu.analysis.cost.CostSpec`,
    ``True``, a chip name like ``"tpu-v4"``, or a dict) switches on the
    E12x/W12x static cost-model lints: liveness-aware step-peak HBM,
    roofline step-time/MFU, serving-bucket peak, and fleet capacity.
    ``profile`` (a ``profiler.devicetime.DeviceTimeTable``, a list of
    ``{"layer": ..., "device_ms": ...}`` rows, or a JSON trace path)
    makes the W105 pipeline-balance lint judge on MEASURED per-stage
    device time instead of the FLOP model (needs ``mesh=`` with a
    pipeline declared).
    ``suppress``/``severity_overrides`` shape the report per code
    (:meth:`ValidationReport.apply_config`).
    """
    conf = getattr(target, "conf", target)
    mesh_spec = _mesh_spec(mesh, sharding, pipeline, hbm_gb, zero)
    if profile is not None and mesh_spec is None:
        raise ValueError("the measured-profile W105 lint (profile=) needs "
                         "a mesh declaration — pass mesh=... as well")
    if hasattr(conf, "_nodes") and hasattr(conf, "_placeholders"):
        if input_pipeline is not None:
            raise ValueError(
                "the input-pipeline lint (input_pipeline=) applies to "
                "layer configurations, not SameDiff graphs")
        from deeplearning4j_tpu.analysis.samediff import analyze_samediff
        report = analyze_samediff(conf, batch_size=batch_size or 1)
        report.extend(_samediff_lints(conf, batch_size, data_devices,
                                      mesh_spec, policy, data_range,
                                      profile=profile))
    elif hasattr(conf, "graph_inputs") and hasattr(conf, "nodes"):
        report = _analyze_graph(conf, batch_size, data_devices, mesh_spec,
                                profile=profile)
    elif hasattr(conf, "layers") and hasattr(conf, "base"):
        report = _analyze_multilayer(conf, batch_size, data_devices,
                                     mesh_spec, profile=profile)
    else:
        raise TypeError(f"cannot analyze {type(target).__name__}: expected a "
                        "MultiLayerConfiguration, ComputationGraph"
                        "Configuration, one of their builders, a network, "
                        "or a SameDiff graph")
    if input_pipeline is not None:
        from deeplearning4j_tpu.analysis.pipeline import lint_input_pipeline
        report.extend(lint_input_pipeline(conf, input_pipeline))
    if hasattr(conf, "layers") or hasattr(conf, "graph_inputs"):
        report.extend(_numerics.lint_numerics(
            conf, policy=policy, data_range=data_range,
            model=target if target is not conf else None))
    if cost is not None:
        from deeplearning4j_tpu.analysis import cost as _cost
        report.extend(_cost.lint_cost(conf, cost, mesh=mesh_spec,
                                      batch_size=batch_size, policy=policy))
        # The liveness plan counts params + grads + masters + updater
        # state exactly (ZeRO-aware) against the DECLARED chip's HBM, so
        # the params-only-era heuristics are subsumed: E104's budget
        # check and W109's replicated-state advice would double-report
        # (against a different, default budget) what E120 already
        # decides — its message names updater state when it dominates.
        report.diagnostics = [d for d in report.diagnostics
                              if d.code not in ("DL4J-E104", "DL4J-W109")]
    if target is not conf:                       # a network: add model-level
        report.extend(_model_checks(target))
    for holder in (target, conf):       # importer-attached findings (E16x)
        imported = getattr(holder, "import_report", None)
        if imported is not None:
            report.extend(imported.diagnostics)
            break
    return report.apply_config(suppress, severity_overrides)


def _samediff_lints(sd, batch_size, data_devices, mesh_spec, policy,
                    data_range, profile=None) -> List[Diagnostic]:
    """Full lint parity for recorded graphs: lower the SameDiff to the
    analysis IR (:mod:`~deeplearning4j_tpu.analysis.graphir`) and run the
    same layout/distribution/numerics families native configs get, plus
    the W162 frozen-weight check."""
    from deeplearning4j_tpu.analysis import graphir as _gir
    from deeplearning4j_tpu.analysis import imports as _imports
    ir = _gir.from_samediff(sd, batch_size=batch_size or 1)
    diags: List[Diagnostic] = []
    diags.extend(_gir.lint_ir_layout(
        ir, batch_size,
        data_devices if mesh_spec is None else None))
    if mesh_spec is not None:
        diags.extend(_gir.lint_ir_distribution(ir, mesh_spec, batch_size,
                                               profile=profile))
    diags.extend(_gir.lint_ir_numerics(ir, policy=policy,
                                       data_range=data_range))
    diags.extend(_imports.lint_frozen_constants(sd))
    return diags


def _mesh_spec(mesh, sharding, pipeline, hbm_gb,
               zero=None) -> Optional[MeshSpec]:
    spec = MeshSpec.coerce(mesh)
    if spec is None:
        if sharding is not None or pipeline is not None \
                or hbm_gb is not None or zero is not None:
            raise ValueError("sharding/pipeline/hbm_gb/zero lints need a "
                             "mesh declaration — pass mesh=... as well")
        return None
    if sharding is not None or pipeline is not None or hbm_gb is not None \
            or zero is not None:
        spec = MeshSpec(
            spec.axes, data_axis=spec.data_axis,
            sharding=sharding if sharding is not None else spec.sharding,
            pipeline=pipeline if pipeline is not None else spec.pipeline,
            hbm_gb=hbm_gb if hbm_gb is not None else spec.hbm_gb,
            devices=spec.devices,   # keep the E102 axes-vs-devices lint
            zero=zero if zero is not None else spec.zero)
    return spec


def _model_checks(net) -> List[Diagnostic]:
    """Network-level findings: frozen-layer/updater pairing (W003) and any
    recompile-churn diagnostics the runtime detector accumulated for this
    model (W201)."""
    from deeplearning4j_tpu.analysis.churn import get_churn_detector
    diags: List[Diagnostic] = []
    frozen = getattr(net, "_frozen_layers", None)
    updater = getattr(getattr(net.conf, "base", None), "updater", None)
    if frozen and updater is not None and _updater_is_stateful(updater):
        diags.append(Diagnostic(
            "DL4J-W003", Severity.WARNING,
            f"layers {sorted(frozen)}",
            f"frozen layers are trained with a stateful updater "
            f"({type(updater).__name__}) — moment/state buffers are "
            f"allocated and carried for params that never update",
            fix_hint="use Sgd/NoOp for fully-frozen fine-tuning, or drop "
                     "the frozen prefix via TransferLearningHelper so no "
                     "updater state is allocated for it"))
    diags.extend(get_churn_detector().diagnostics_for(net))
    return diags


def _updater_is_stateful(updater) -> bool:
    """Stateful = the class overrides IUpdater.init_state (Adam & family);
    Sgd/NoOp inherit the empty base implementation."""
    base = None
    for cls in type(updater).__mro__:
        if cls.__name__ == "IUpdater":
            base = cls
            break
    if base is None:
        return False
    return type(updater).init_state is not base.init_state


# --------------------------------------------------------------- multilayer
def _layer_loc(i: int, layer) -> str:
    cls = type(layer).__name__
    name = getattr(layer, "name", None)
    if name and name != cls:
        return f"layer {i} ({cls} '{name}')"
    return f"layer {i} ({cls})"


def _analyze_multilayer(conf, batch_size, data_devices,
                        mesh: Optional[MeshSpec] = None,
                        profile=None) -> ValidationReport:
    report = ValidationReport(subject="MultiLayerConfiguration")
    layers = list(conf.layers)
    preprocessors = dict(getattr(conf, "preprocessors", {}) or {})

    _check_duplicate_names(
        [( _layer_loc(i, l), getattr(l, "name", None), type(l).__name__)
         for i, l in enumerate(layers)], report)

    if not layers:
        report.add(Diagnostic("DL4J-E008", Severity.ERROR, "config",
                              "configuration has no layers",
                              fix_hint="add at least one layer ending in an "
                                       "output/loss layer"))
        return report

    last = layers[-1]
    if not hasattr(last, "compute_loss"):
        report.add(Diagnostic(
            "DL4J-E008", Severity.ERROR, _layer_loc(len(layers) - 1, last),
            f"last layer {type(last).__name__} is not an output/loss layer "
            f"— fit() has no loss to optimize",
            fix_hint="end the network with OutputLayer / RnnOutputLayer / "
                     "LossLayer (or a subclass)"))
    for i, layer in enumerate(layers):
        if hasattr(layer, "compute_loss"):
            report.extend(_pairing_lints(layer, _layer_loc(i, layer)))

    _check_tbptt(conf, layers, report)

    if getattr(conf, "input_type", None) is None:
        _analyze_without_input_type(layers, preprocessors, report)
    else:
        _propagate_multilayer(conf, layers, preprocessors, report)

    located = [(_layer_loc(i, l), l) for i, l in enumerate(layers)]
    layout_fmt = getattr(conf.base, "compute_layout", "NCHW")
    report.extend(_layout.lint_layers(located, compute_layout=layout_fmt))
    report.extend(_layout.lint_conv_stack(located,
                                          compute_layout=layout_fmt))
    report.extend(_layout.lint_dtype(
        getattr(conf.base, "dtype", None)))
    if mesh is not None:
        report.extend(_dist.lint_multilayer(conf, mesh, batch_size,
                                            profile=profile))
    else:
        report.extend(_layout.lint_batch_mesh(batch_size, data_devices))
    return report


def _check_duplicate_names(entries: Sequence[Tuple[str, Optional[str], str]],
                           report: ValidationReport,
                           explicit_only: bool = True) -> None:
    """E004 over (location, name, class_name) triples. For sequential nets
    only explicitly-set names count (the default name IS the class name,
    which legitimately repeats); graph callers pass explicit_only=False."""
    seen: Dict[str, str] = {}
    for loc, name, cls in entries:
        if not name:
            continue
        if explicit_only and name == cls:
            continue
        if name in seen:
            report.add(Diagnostic(
                "DL4J-E004", Severity.ERROR, loc,
                f"name '{name}' already used at {seen[name]}",
                fix_hint="give every layer/vertex a unique name"))
        else:
            seen[name] = loc


def _check_tbptt(conf, layers, report: ValidationReport) -> None:
    bp = str(getattr(conf, "backprop_type", "standard") or "standard").lower()
    if bp not in ("tbptt", "truncatedbptt", "truncated_bptt"):
        return
    if any(getattr(l, "input_kind", None) == "rnn" for l in layers):
        return
    report.add(Diagnostic(
        "DL4J-W002", Severity.WARNING, "config",
        "backpropType is truncated BPTT but the network has no recurrent "
        "layers — the time-segmentation is a no-op (or will fail on "
        "non-sequence input)",
        fix_hint="drop backpropType('tbptt') or add recurrent layers "
                 "(LSTM/GRU/SimpleRnn/...)"))


def _pairing_lints(layer, loc: str) -> List[Diagnostic]:
    """W001: loss/activation pairings that silently cripple training."""
    act = str(getattr(layer, "activation", "") or "").lower()
    loss = str(getattr(layer, "loss_fn", "") or "").lower()
    n_out = getattr(layer, "nOut", None)
    diags = []
    if act == "softmax" and loss in _REGRESSION_LOSSES:
        diags.append(Diagnostic(
            "DL4J-W001", Severity.WARNING, loc,
            f"softmax activation paired with regression loss '{loss}' — "
            f"gradients through softmax+{loss} are tiny and training "
            f"crawls",
            fix_hint="use lossFunction='mcxent' with softmax, or switch "
                     "the activation to identity for a regression head"))
    if act == "sigmoid" and loss == "mcxent" and (n_out or 0) > 1:
        diags.append(Diagnostic(
            "DL4J-W001", Severity.WARNING, loc,
            f"sigmoid activation with multiclass cross-entropy over "
            f"nOut={n_out} — rows are not a distribution, so mcxent is "
            f"miscalibrated",
            fix_hint="use softmax+mcxent for 1-of-N classification, or "
                     "sigmoid+xent for independent multi-label targets"))
    return diags


def _analyze_without_input_type(layers, preprocessors,
                                report: ValidationReport) -> None:
    """No ``setInputType``: propagation never ran, so check the things
    that must then be explicit — E005 (cnn->dense with no flatten) and
    E001 (weight layers whose nIn is unresolvable)."""
    for i in range(1, len(layers)):
        prev, cur = layers[i - 1], layers[i]
        if (getattr(prev, "input_kind", None) == "cnn"
                and getattr(cur, "input_kind", None) == "ff"
                and i not in preprocessors):
            report.add(Diagnostic(
                "DL4J-E005", Severity.ERROR, _layer_loc(i, cur),
                f"{type(cur).__name__} consumes the 4-D feature map of "
                f"{type(prev).__name__} with no CnnToFeedForward flatten "
                f"in between",
                fix_hint="call setInputType(InputType.convolutional(...)) "
                         "so the preprocessor is inserted automatically"))
    for i, layer in enumerate(layers):
        if getattr(layer, "has_params", False) and \
                getattr(layer, "nIn", None) is None:
            report.add(Diagnostic(
                "DL4J-E001", Severity.ERROR, _layer_loc(i, layer),
                f"{type(layer).__name__}.nIn is unset and cannot be "
                f"inferred because the configuration declares no InputType",
                fix_hint="set nIn explicitly or call setInputType(...) on "
                         "the builder"))


def _propagate_multilayer(conf, layers, preprocessors,
                          report: ValidationReport) -> None:
    from deeplearning4j_tpu.nn import preprocessors as pp
    cur = conf.input_type
    for i, layer in enumerate(layers):
        loc = _layer_loc(i, layer)
        pre = preprocessors.get(i)
        if pre is None:
            try:
                pre = pp.preprocessor_for(cur, layer)
            except ValueError as e:
                report.add(Diagnostic(
                    "DL4J-E005", Severity.ERROR, loc, str(e),
                    fix_hint="declare the input as InputType."
                             "convolutionalFlat(h, w, c) (or insert the "
                             "preprocessor explicitly)"))
                return
        if pre is not None:
            cur = pre.output_type(cur)
        diag, cur = _step_layer(layer, cur, loc)
        if diag is not None:
            report.add(diag)
        if cur is None:
            return


def _step_layer(layer, it, loc: str):
    """Check one layer against its propagated InputType and return
    (diagnostic_or_None, output_type_or_None). A None output type stops
    propagation (shapes downstream would be garbage)."""
    try:
        expected = layer.expected_nin(it) \
            if hasattr(layer, "expected_nin") else None
    except Exception as e:
        return Diagnostic(
            "DL4J-E007", Severity.ERROR, loc,
            f"shape inference failed: {e}",
            fix_hint="fix the layer geometry named in the message"), None
    declared = getattr(layer, "nIn", None)
    if declared is not None and expected is not None \
            and int(declared) != int(expected):
        return Diagnostic(
            "DL4J-E001", Severity.ERROR, loc,
            f"declared nIn={declared} but the upstream layer produces "
            f"{expected} ({it.kind} input {it.dims})",
            fix_hint=f"set nIn={expected} or leave nIn unset so "
                     f"propagation fills it in"), None
    try:
        out = layer.output_type(it)
    except Exception as e:
        return Diagnostic(
            "DL4J-E007", Severity.ERROR, loc,
            f"output shape inference failed: {e}",
            fix_hint="set nOut (and check kernel/stride/padding geometry)"
        ), None
    bad = _invalid_dims(out)
    if bad:
        return Diagnostic(
            "DL4J-E007", Severity.ERROR, loc,
            f"output type {out!r} has non-positive/unset dims {bad}",
            fix_hint="set nOut, and check that kernels/strides fit the "
                     "spatial input (no dimension may shrink below 1)"), None
    return None, out


def _invalid_dims(it) -> Dict[str, Any]:
    bad = {}
    for k, v in it.dims.items():
        if k == "timesteps":        # -1 = variable length, legal
            continue
        if v is None or (isinstance(v, (int, float)) and v <= 0):
            bad[k] = v
    return bad


# -------------------------------------------------------------------- graph
def _node_loc(node) -> str:
    return f"'{node.name}' ({type(node.obj).__name__})"


def _analyze_graph(conf, batch_size, data_devices,
                   mesh: Optional[MeshSpec] = None,
                   profile=None) -> ValidationReport:
    report = ValidationReport(subject="ComputationGraphConfiguration")
    nodes = list(conf.nodes)
    inputs = list(conf.graph_inputs)
    outputs = list(conf.graph_outputs)
    input_types = dict(getattr(conf, "input_types", {}) or {})
    preprocessors = dict(getattr(conf, "preprocessors", {}) or {})

    _check_duplicate_names(
        [(_node_loc(n), n.name, None) for n in nodes] +
        [(f"graph input '{i}'", i, None) for i in inputs],
        report, explicit_only=False)

    defined = set(inputs) | {n.name for n in nodes}
    structurally_sound = True
    for node in nodes:
        for ref in node.inputs:
            if ref not in defined:
                structurally_sound = False
                report.add(Diagnostic(
                    "DL4J-E003", Severity.ERROR, _node_loc(node),
                    f"references undefined input '{ref}'",
                    fix_hint="add the missing layer/vertex or fix the "
                             "input name"))
    node_names = {n.name for n in nodes}
    for out in outputs:
        if out not in node_names:
            structurally_sound = False
            report.add(Diagnostic(
                "DL4J-E003", Severity.ERROR, f"graph output '{out}'",
                "output references an undefined node",
                fix_hint="setOutputs(...) must name existing layers"))
    if not outputs:
        report.add(Diagnostic(
            "DL4J-E008", Severity.ERROR, "config",
            "graph declares no outputs",
            fix_hint="call setOutputs(...) with at least one output layer"))

    topo = _graph_toposort(nodes, inputs, defined, report)
    if topo is None:
        structurally_sound = False

    if structurally_sound:
        _check_reachability(nodes, outputs, report)

    by_name = {n.name: n for n in nodes}
    for out in outputs:
        node = by_name.get(out)
        if node is not None and (node.kind != "layer"
                                 or not hasattr(node.obj, "compute_loss")):
            report.add(Diagnostic(
                "DL4J-E008", Severity.ERROR, _node_loc(node),
                "graph output is not an output/loss layer — fit() has no "
                "loss to optimize at this head",
                fix_hint="route the output through OutputLayer / LossLayer"))
    for node in nodes:
        if node.kind == "layer" and hasattr(node.obj, "compute_loss"):
            report.extend(_pairing_lints(node.obj, _node_loc(node)))

    if structurally_sound and topo is not None and inputs and \
            all(i in input_types for i in inputs):
        _propagate_graph(topo, input_types, preprocessors, report)

    located = [(_node_loc(n), n.obj) for n in nodes if n.kind == "layer"]
    layout_fmt = getattr(conf.base, "compute_layout", "NCHW")
    report.extend(_layout.lint_layers(located, compute_layout=layout_fmt))
    report.extend(_layout.lint_conv_stack(located,
                                          compute_layout=layout_fmt))
    report.extend(_layout.lint_dtype(getattr(conf.base, "dtype", None)))
    if mesh is not None:
        report.extend(_dist.lint_graph(conf, mesh, batch_size,
                                       profile=profile))
    else:
        report.extend(_layout.lint_batch_mesh(batch_size, data_devices))
    return report


def _graph_toposort(nodes, inputs, defined, report: ValidationReport):
    """Kahn's algorithm; returns topological order or None after adding an
    E002 when the leftover nodes form a cycle (all their refs exist but
    none can ever become ready)."""
    order, seen = [], set(inputs)
    remaining = [n for n in nodes if all(r in defined for r in n.inputs)]
    progressed = True
    while remaining and progressed:
        progressed = False
        for n in list(remaining):
            if all(r in seen for r in n.inputs):
                order.append(n)
                seen.add(n.name)
                remaining.remove(n)
                progressed = True
    if remaining:
        cyc = sorted(n.name for n in remaining)
        report.add(Diagnostic(
            "DL4J-E002", Severity.ERROR, ", ".join(cyc),
            f"dependency cycle through {len(cyc)} node(s): {cyc}",
            fix_hint="break the cycle — a feedback connection must go "
                     "through a recurrent layer's state, not a graph edge"))
        return None
    return order


def _check_reachability(nodes, outputs, report: ValidationReport) -> None:
    """E003 (warning flavor): nodes no output depends on still execute
    every step — and their params would train on zero gradient."""
    by_name = {n.name: n for n in nodes}
    needed, stack = set(), [o for o in outputs if o in by_name]
    while stack:
        name = stack.pop()
        if name in needed:
            continue
        needed.add(name)
        stack.extend(r for r in by_name[name].inputs if r in by_name)
    for node in nodes:
        if node.name not in needed:
            report.add(Diagnostic(
                "DL4J-E003", Severity.WARNING, _node_loc(node),
                "dangling vertex: no graph output depends on it (it still "
                "executes every step, and its params get no gradient)",
                fix_hint="wire it toward an output or remove it"))


def _propagate_graph(topo, input_types, preprocessors,
                     report: ValidationReport) -> None:
    from deeplearning4j_tpu.nn import preprocessors as pp
    types = dict(input_types)
    for node in topo:
        loc = _node_loc(node)
        in_types = []
        for ref in node.inputs:
            t = types.get(ref)
            if t is None:           # upstream already failed; stop here
                return
            in_types.append(t)
        if node.kind == "layer":
            it = in_types[0]
            pre = preprocessors.get(node.name)
            if pre is None:
                try:
                    pre = pp.preprocessor_for(it, node.obj)
                except ValueError as e:
                    report.add(Diagnostic("DL4J-E005", Severity.ERROR, loc,
                                          str(e)))
                    return
            if pre is not None:
                it = pre.output_type(it)
            diag, out = _step_layer(node.obj, it, loc)
            if diag is not None:
                report.add(diag)
            if out is None:
                return
            types[node.name] = out
        else:
            diag = _vertex_shape_conflicts(node, in_types, loc)
            if diag is not None:
                report.add(diag)
                return
            try:
                types[node.name] = node.obj.output_type(*in_types)
            except Exception as e:
                report.add(Diagnostic(
                    "DL4J-E007", Severity.ERROR, loc,
                    f"vertex output shape inference failed: {e}"))
                return


def _vertex_shape_conflicts(node, in_types, loc: str) -> Optional[Diagnostic]:
    """E006 for the multi-input vertices (merge/elementwise/stack/dot)."""
    if len(in_types) < 2:
        return None
    cls = type(node.obj).__name__
    kinds = {t.kind for t in in_types}
    if len(kinds) > 1:
        return Diagnostic(
            "DL4J-E006", Severity.ERROR, loc,
            f"{cls} mixes input kinds {sorted(kinds)}: "
            f"{[repr(t) for t in in_types]}",
            fix_hint="insert preprocessors so every branch produces the "
                     "same kind before merging")
    first = in_types[0]
    if cls in ("ElementWiseVertex", "StackVertex", "DotProductVertex"):
        for t in in_types[1:]:
            if t != first:
                return Diagnostic(
                    "DL4J-E006", Severity.ERROR, loc,
                    f"{cls} needs identical input shapes, got "
                    f"{[repr(t) for t in in_types]}",
                    fix_hint="match the branch shapes (1x1 conv / dense "
                             "projection on the smaller branch is the "
                             "usual fix)")
    elif cls == "MergeVertex":
        if first.kind == "cnn":
            hw = {(t.height, t.width) for t in in_types}
            if len(hw) > 1:
                return Diagnostic(
                    "DL4J-E006", Severity.ERROR, loc,
                    f"MergeVertex concatenates channels but spatial dims "
                    f"differ across branches: {sorted(hw)}",
                    fix_hint="align strides/padding so every branch "
                             "reaches the merge at the same HxW")
        elif first.kind == "rnn":
            ts = {t.dims.get("timesteps", -1) for t in in_types}
            if len(ts - {-1}) > 1:
                return Diagnostic(
                    "DL4J-E006", Severity.ERROR, loc,
                    f"MergeVertex branches disagree on sequence length: "
                    f"{sorted(ts)}",
                    fix_hint="crop/pad the sequences to one length before "
                             "merging")
    return None
