"""Recompile-churn detector — the runtime half of the linter.

Every distinct (shape, dtype, weak-type) signature a dispatch site sees
costs one full XLA compile. A training loop whose batch shapes drift
(ragged final batches, per-epoch bucketing, weak-typed python scalars
promoted differently between calls) silently recompiles over and over —
on a real TPU each recompile is seconds of wall clock and the symptom is
just "training is slow".

The networks' ``_fit_one``/``_fit_mega`` paths and the native runtime's
compile cache report fingerprints here; the detector counts distinct
signatures per site into the process-wide profiler registry
(``dl4j_recompiles_total{site=...}``) and emits a ``DL4J-W201``
diagnostic (plus one python warning) the first time a site crosses the
threshold. ``model.validate()`` folds any findings for that model into
its report.

No jax imports — fingerprints are built from duck-typed ``.shape`` /
``.dtype`` / ``.weak_type`` attributes so the module stays pure-static.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.diagnostics import Diagnostic, Severity

def _default_threshold() -> int:
    """Read at detector construction (NOT module import — the package is
    imported as a side effect of importing any network class, long before
    a script gets the chance to set the knob)."""
    return int(os.environ.get("DL4J_TPU_RECOMPILE_CHURN_THRESHOLD", "8"))


def array_fingerprint(*arrays) -> Tuple:
    """Jit-cache-equivalent signature of a positional argument list:
    (shape, dtype, weak_type) per array, None passed through. Two calls
    with equal fingerprints hit the same compiled program; a new
    fingerprint is a recompile."""
    out = []
    for a in arrays:
        if a is None:
            out.append(None)
        elif isinstance(a, (list, tuple)):
            out.append(array_fingerprint(*a))
        else:
            out.append((tuple(getattr(a, "shape", ())),
                        str(getattr(a, "dtype", type(a).__name__)),
                        bool(getattr(a, "weak_type", False))))
    return tuple(out)


class RecompileChurnDetector:
    """Counts distinct jit signatures per dispatch site.

    ``record(site, fingerprint, owner=...)`` is the hot-path call: one
    lock + set lookup when the signature was already seen. ``owner``
    scopes the threshold bookkeeping (two models sharing a site string
    do not pool their signatures); the metrics label stays the coarse
    ``site`` name.
    """

    def __init__(self, threshold: int = None, registry=None):
        from deeplearning4j_tpu.profiler.metrics import get_registry
        self.threshold = _default_threshold() if threshold is None \
            else int(threshold)
        self._counter = (registry or get_registry()).counter(
            "dl4j_recompiles_total",
            "Distinct jit signatures compiled per dispatch site (a value "
            "that keeps growing during steady-state training is churn)",
            labelnames=("site",))
        # instrumented (PR-8 adoption sweep): record() sits on every fit
        # dispatch — the lock itself is only taken per NEW signature, but
        # contention here is exactly the churn the detector exists to see
        from deeplearning4j_tpu.profiler.locks import InstrumentedLock
        self._lock = InstrumentedLock("churn_detector")
        self._seen: Dict[Tuple[str, int], Set] = {}
        self._flagged: Set[Tuple[str, int]] = set()
        self._diags: List[Tuple[Optional[int], Diagnostic]] = []

    def record(self, site: str, fingerprint, owner=None) -> Optional[Diagnostic]:
        """Report one dispatch signature; returns the W201 diagnostic the
        first time ``site`` (scoped to ``owner``) crosses the threshold."""
        key = (site, id(owner) if owner is not None else 0)
        # lock-free fast path for the per-iteration hot loop: a GIL-safe
        # dict/set read suffices once the signature has been seen (the
        # steady-state case — the lock is only taken per NEW signature)
        seen = self._seen.get(key)
        if seen is not None and fingerprint in seen:
            return None
        with self._lock:
            seen = self._seen.get(key)
            if seen is None:
                seen = self._seen[key] = set()
            if fingerprint in seen:
                return None
            seen.add(fingerprint)
            n = len(seen)
            crossed = n > self.threshold and key not in self._flagged
            if crossed:
                self._flagged.add(key)
        self._counter.labels(site=site).inc()
        if not crossed:
            return None
        diag = Diagnostic(
            "DL4J-W201", Severity.WARNING, site,
            f"{n} distinct jit signatures compiled at this site "
            f"(threshold {self.threshold}) — shifting batch shapes/dtypes "
            f"are forcing repeated XLA recompiles",
            fix_hint="pad or bucket batches to a fixed shape (e.g. drop/pad "
                     "the ragged final batch), pin input dtypes, and avoid "
                     "weak-typed python scalars in the step inputs")
        with self._lock:
            self._diags.append((key[1] or None, diag))
        warnings.warn(f"{diag.code} [{site}]: {diag.message}",
                      RuntimeWarning, stacklevel=2)
        return diag

    def signature_count(self, site: str, owner=None) -> int:
        key = (site, id(owner) if owner is not None else 0)
        with self._lock:
            return len(self._seen.get(key, ()))

    def diagnostics_for(self, owner=None) -> List[Diagnostic]:
        """Findings scoped to ``owner`` (plus unscoped sites like the
        native compile cache when ``owner`` is None)."""
        oid = None if owner is None else id(owner)
        with self._lock:
            return [d for o, d in self._diags
                    if o == oid or (owner is not None and o is None)]

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()
            self._flagged.clear()
            self._diags.clear()


_DETECTOR: Optional[RecompileChurnDetector] = None
_DETECTOR_LOCK = threading.Lock()


def get_churn_detector() -> RecompileChurnDetector:
    """Process-wide detector the dispatch seams report into."""
    global _DETECTOR
    if _DETECTOR is None:
        with _DETECTOR_LOCK:
            if _DETECTOR is None:
                _DETECTOR = RecompileChurnDetector()
    return _DETECTOR
