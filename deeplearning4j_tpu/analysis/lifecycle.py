"""Lifecycle-plan lint: is the canary observation actually observable?

The lifecycle driver's promote/rollback verdicts are only as good as
the evidence its judge can collect during the observation window. Two
configurations silently produce evidence-free verdicts, and both are
statically decidable from the plan alone (no jax — same contract as
the rest of ``analysis``):

- ``DL4J-W113``: the judge's burn-rate lookback
  (``observation_window``) is shorter than the SLO spec's FAST window.
  ``SLOEngine.burn_over`` references the newest sample at least
  window-seconds old; a lookback that cannot contain one fast-window
  reference reads a burn of ~0 on a fleet that is actively burning —
  the canary promotes blind.
- ``DL4J-W114``: the canary fraction is below routing resolution for
  the expected per-tick traffic — ``fraction x requests_per_tick``
  rounds to zero canary-routed requests (the credit accumulator never
  crosses 1.0 within a tick), so the "canary" metrics the judge reads
  are pure incumbent. Also fired when the per-tick canary volume
  cannot fill even the smallest batch bucket (the canary only ever
  measures the padded-out fringe).

Entry point: :func:`lint_lifecycle` (what ``python -m
deeplearning4j_tpu.lifecycle`` and the driver's ``validate()`` call).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from deeplearning4j_tpu.analysis.diagnostics import (Diagnostic, Severity,
                                                     ValidationReport)


def lint_lifecycle(observation_window: float,
                   canary_fraction: float,
                   slo_windows: Optional[Sequence[float]] = None,
                   requests_per_tick: Optional[float] = None,
                   buckets: Optional[Sequence[int]] = None,
                   subject: str = "lifecycle") -> ValidationReport:
    """Lint one lifecycle plan. ``slo_windows`` is the (fast, slow)
    pair from the :class:`~deeplearning4j_tpu.profiler.slo.SLOSpec`
    the judge consults; ``requests_per_tick`` the expected unpinned
    request volume per observation tick; ``buckets`` the serving
    bucket ladder of the canary's server."""
    diags: List[Diagnostic] = []
    if slo_windows:
        fast = float(min(slo_windows))
        if float(observation_window) < fast:
            diags.append(Diagnostic(
                "DL4J-W113", Severity.WARNING, subject,
                f"observation_window {observation_window:g}s is shorter "
                f"than the SLO fast window {fast:g}s — burn_over() "
                "cannot reference a sample one fast-window old, so "
                "every canary verdict reads ~0 burn",
                fix_hint="raise observation_window to at least the "
                         "fast window (or shrink the SLOSpec's "
                         "windows for the canary judge)"))
    if requests_per_tick is not None:
        expected = float(canary_fraction) * float(requests_per_tick)
        if expected < 1.0:
            diags.append(Diagnostic(
                "DL4J-W114", Severity.WARNING, subject,
                f"canary_fraction {canary_fraction:g} x "
                f"{requests_per_tick:g} requests/tick = {expected:.2f} "
                "canary-routed requests per observation tick — the "
                "judge is measuring the incumbent, not the canary",
                fix_hint="raise the fraction, lengthen the tick, or "
                         "drive more traffic during observation"))
        elif buckets:
            smallest = min(int(b) for b in buckets)
            if expected < smallest:
                diags.append(Diagnostic(
                    "DL4J-W114", Severity.WARNING, subject,
                    f"~{expected:.1f} canary requests/tick cannot fill "
                    f"the smallest batch bucket ({smallest}) — every "
                    "canary batch is mostly padding, so its latency "
                    "signal is the bucket's, not the model's",
                    fix_hint="raise the fraction or accept the padded "
                             "signal (occupancy shows up in "
                             "batch_occupancy_mean)"))
    return ValidationReport(diags, subject=subject)
