"""CLI: lint zoo models / user modules ahead of any compile.

Usage::

    python -m deeplearning4j_tpu.analysis --zoo            # every zoo model
    python -m deeplearning4j_tpu.analysis LeNet ResNet50   # named zoo models
    python -m deeplearning4j_tpu.analysis my.module        # module attrs
    python -m deeplearning4j_tpu.analysis my.module:build  # one attribute
    python -m deeplearning4j_tpu.analysis --samediff my.module:sd
    python -m deeplearning4j_tpu.analysis --onnx model.onnx
    python -m deeplearning4j_tpu.analysis --zoo --mesh data=8 --cost \\
        --chip tpu-v4                      # E12x/W12x cost model

A module target is scanned for ZooModel subclasses, configurations, and
networks; a ``module:attr`` target names one such object (callables are
called with no args first). Exit status is 0 only when every target is
clean — warnings count as failures unless ``--warnings-ok``.

Building zoo configs imports the layer stack (and therefore jax), but no
program is traced or compiled — the analysis itself stays static.
"""

from __future__ import annotations

import argparse
import importlib
import sys
from typing import List, Tuple

from deeplearning4j_tpu.analysis.analyzer import analyze
from deeplearning4j_tpu.analysis.diagnostics import (ValidationReport,
                                                     _normalize_severity,
                                                     normalize_code)


def _zoo_registry():
    from deeplearning4j_tpu.models import zoo
    return zoo.ZOO_MODELS


def _coerce_target(name: str, obj) -> List[Tuple[str, object]]:
    """Turn one resolved object into [(label, analyzable)] pairs."""
    if isinstance(obj, type):
        from deeplearning4j_tpu.models.zoo import ZooModel
        if issubclass(obj, ZooModel):
            return [(name, obj().conf_builder())]
        obj = obj()
    if callable(obj) and not hasattr(obj, "conf") \
            and not hasattr(obj, "layers") and not hasattr(obj, "nodes"):
        obj = obj()
    return [(name, obj)]


def _resolve(target: str) -> List[Tuple[str, object]]:
    registry = _zoo_registry()
    if target in registry:
        return _coerce_target(target, registry[target])
    mod_name, _, attr = target.partition(":")
    try:
        module = importlib.import_module(mod_name)
    except ImportError:
        # maybe a dotted attribute path: pkg.mod.Attr
        if not attr and "." in target:
            mod_name, _, attr = target.rpartition(".")
            module = importlib.import_module(mod_name)
        else:
            raise
    if attr:
        return _coerce_target(target, getattr(module, attr))
    from deeplearning4j_tpu.models.zoo import ZooModel
    found = []
    for aname in sorted(vars(module)):
        obj = vars(module)[aname]
        if isinstance(obj, type) and issubclass(obj, ZooModel) \
                and obj is not ZooModel \
                and obj.__module__ == module.__name__:
            found.extend(_coerce_target(f"{target}:{aname}", obj))
        elif hasattr(obj, "layers") and hasattr(obj, "base") \
                or hasattr(obj, "nodes") and hasattr(obj, "graph_inputs"):
            found.extend(_coerce_target(f"{target}:{aname}", obj))
    if not found:
        raise SystemExit(f"no zoo models or configurations found in "
                         f"{target!r}")
    return found


def _resolve_onnx(path: str):
    """An .onnx target: SameDiff when every op imports, otherwise the
    jax-free E161 pre-scan report (importing would just raise)."""
    from deeplearning4j_tpu.analysis import imports as _imp
    from deeplearning4j_tpu.modelimport import onnx_proto as op_
    try:
        model = op_.load_model(path)
    except (OSError, ValueError) as e:
        raise SystemExit(f"--onnx {path}: {e}")
    pre = _imp.lint_onnx_model(model)
    if any(d.code == "DL4J-E161" for d in pre.diagnostics):
        return pre
    from deeplearning4j_tpu.modelimport.onnx import OnnxGraphImport
    return OnnxGraphImport.importOnnxModel(model)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="Static model linter: shape/dtype propagation, graph "
                    "diagnostics, and TPU layout lints — no compile, no "
                    "device.")
    ap.add_argument("targets", nargs="*",
                    help="zoo model name (e.g. LeNet), module, or "
                         "module:attr")
    ap.add_argument("--zoo", action="store_true",
                    help="lint every model-zoo architecture")
    ap.add_argument("--samediff", action="append", default=[],
                    metavar="MODULE:ATTR",
                    help="lint a recorded SameDiff graph: module:attr "
                         "naming a SameDiff (or a no-arg callable "
                         "returning one) — runs the full layout/"
                         "distribution/numerics parity passes plus any "
                         "attached import_report (repeatable)")
    ap.add_argument("--onnx", action="append", default=[], metavar="PATH",
                    help="lint an .onnx file: the jax-free E16x/W16x "
                         "pre-scan, then (when every op imports) the "
                         "full analyzer over the imported graph "
                         "(repeatable)")
    ap.add_argument("--concurrency", metavar="PATH_OR_MODULE",
                    action="append", default=[],
                    help="run the E2xx/W21x thread-safety lints over a "
                         "source file, directory, or importable module "
                         "name (pure AST — nothing is imported or "
                         "executed; repeatable)")
    ap.add_argument("--batch-size", type=int, default=None,
                    help="planned global batch size (enables the W103 "
                         "mesh-divisibility lint, or E101 with --mesh)")
    ap.add_argument("--devices", type=int, default=None,
                    help="data-parallel mesh axis size for W103")
    ap.add_argument("--mesh", default=None, metavar="AXES",
                    help="declared device mesh, e.g. 'data=8' or "
                         "'data=4,model=2' — enables the E1xx/W10x "
                         "distribution lints")
    ap.add_argument("--zero", action="store_true",
                    help="declare ZeRO updater-state sharding over the "
                         "data axis (ISSUE 15): E104 counts optimizer "
                         "state at 1/data-axis and W109 stays quiet")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget in GiB for the E104 "
                         "parameter-footprint check (default 16)")
    ap.add_argument("--policy", default=None, metavar="POLICY",
                    help="precision policy for the E3xx/W30x numerics "
                         "lints: a compute dtype ('bf16', 'fp16', "
                         "'fp32') or 'compute=fp16,params=fp32,"
                         "loss_scale=32768' (loss_scale=dynamic + "
                         "loss_scale_init=/growth_interval=/... for the "
                         "grow/backoff automaton) — without it the pass "
                         "runs under each config's own dataType")
    ap.add_argument("--data-range", default=None, metavar="LO..HI",
                    help="declared input value range for the range-"
                         "dependent numerics lints (E303/W303), e.g. "
                         "'0..255' or '-1..1,normalized'")
    ap.add_argument("--pipeline", default=None, metavar="SPEC",
                    help="declared input pipeline for the W108 can-this-"
                         "host-feed-this-chip check, e.g. 'workers=8,"
                         "batch=256,decode_ms=1.3,h2d_mbps=6.2,hw=224"
                         "[,dtype=uint8][,mfu=0.3][,device_img_s=2184]'")
    ap.add_argument("--cost", action="store_true",
                    help="run the E12x/W12x whole-program cost model: "
                         "liveness-based step-peak HBM plan, roofline "
                         "step-time/MFU estimate, capacity planner "
                         "(default chip tpu-v4; supersedes the params-"
                         "only E104/W109 heuristics)")
    ap.add_argument("--chip", default=None, metavar="NAME",
                    help="chip to cost against (tpu-v3, tpu-v4, tpu-v5e, "
                         "cpu) — implies --cost")
    ap.add_argument("--qps", type=float, default=None,
                    help="target aggregate serving QPS for the E122 "
                         "capacity check — implies --cost")
    ap.add_argument("--p99-ms", type=float, default=None,
                    help="target p99 latency budget in ms for E122 — "
                         "implies --cost")
    ap.add_argument("--profile", default=None, metavar="PATH",
                    help="measured per-stage device-time profile (JSON "
                         "from profiler/devicetime.py) — W105 stage "
                         "imbalance is judged on measured time instead "
                         "of the FLOP model (needs --mesh)")
    ap.add_argument("--stages", type=int, default=None, metavar="N",
                    help="declare an N-stage pipeline split for the "
                         "per-stage lints (needs --mesh)")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="CODES",
                    help="suppress diagnostic codes (comma-separated or "
                         "repeated), e.g. --suppress W101,DL4J-W107 — the "
                         "'# dl4j: noqa=W101' equivalent for the CLI")
    ap.add_argument("--severity", action="append", default=[],
                    metavar="CODE=LEVEL",
                    help="override a code's severity, e.g. --severity "
                         "W104=error or --severity E101=warning "
                         "(levels: info, warning, error; repeatable)")
    ap.add_argument("--warnings-ok", action="store_true",
                    help="exit 0 even when warnings (W-codes) were found")
    args = ap.parse_args(argv)

    # validate the per-code config up front — a typo'd code must be a
    # clean usage error, not a traceback halfway through a --zoo run
    try:
        suppress = [normalize_code(c) for chunk in args.suppress
                    for c in chunk.split(",") if c]
    except ValueError as e:
        ap.error(f"--suppress: {e}")
    overrides = {}
    for spec in args.severity:
        code, eq, level = spec.partition("=")
        if not eq or not code or not level:
            ap.error(f"--severity expects CODE=LEVEL, got {spec!r}")
        try:
            overrides[normalize_code(code)] = _normalize_severity(level)
        except ValueError as e:
            ap.error(f"--severity: {e}")
    if args.hbm_gb is not None and not args.mesh:
        ap.error("--hbm-gb needs a mesh declaration: pass --mesh as well")
    if args.zero and not args.mesh:
        ap.error("--zero needs a mesh declaration: pass --mesh as well")
    if args.profile and not args.mesh:
        ap.error("--profile needs a mesh declaration: pass --mesh as well")
    if args.stages is not None and not args.mesh:
        ap.error("--stages needs a mesh declaration: pass --mesh as well")
    cost_spec = None
    if args.cost or args.chip or args.qps is not None \
            or args.p99_ms is not None:
        from deeplearning4j_tpu.analysis.cost import CostSpec
        try:
            cost_spec = CostSpec(chip=args.chip or "tpu-v4", qps=args.qps,
                                 p99_ms=args.p99_ms)
        except ValueError as e:
            ap.error(f"--chip: {e}")
    profile_spec = None
    if args.profile:
        from deeplearning4j_tpu.analysis.distribution import StageProfile
        try:
            profile_spec = StageProfile.coerce(args.profile)
        except (OSError, ValueError) as e:
            ap.error(f"--profile: {e}")
    policy_spec = None
    if args.policy:
        from deeplearning4j_tpu.nn.precision import PrecisionPolicy
        try:
            if "=" in args.policy:
                kv = {}
                for part in args.policy.split(","):
                    k, eq, v = part.partition("=")
                    if not eq:
                        raise ValueError(f"expected key=value, got {part!r}")
                    k = k.strip()
                    if k == "loss_scale":
                        # 'dynamic' = the grow/backoff automaton; any
                        # other spelling must be a static float
                        v = v.strip()
                        kv[k] = v if v.lower() == "dynamic" else float(v)
                    elif k in ("loss_scale_init", "growth_factor",
                               "backoff_factor", "min_loss_scale",
                               "max_loss_scale"):
                        kv[k] = float(v)
                    elif k == "growth_interval":
                        kv[k] = int(v)
                    elif k in ("compute", "params"):
                        kv[k] = v.strip()
                    else:
                        raise ValueError(f"unknown policy key {k!r}")
                policy_spec = PrecisionPolicy(**kv)
            else:
                policy_spec = PrecisionPolicy.coerce(args.policy)
        except (ValueError, TypeError) as e:
            ap.error(f"--policy: {e}")
    range_spec = None
    if args.data_range:
        from deeplearning4j_tpu.analysis.numerics import DataRangeSpec
        try:
            range_spec = DataRangeSpec.parse(args.data_range)
        except ValueError as e:
            ap.error(f"--data-range: {e}")
    pipeline_spec = None
    if args.pipeline:
        from deeplearning4j_tpu.analysis.pipeline import InputPipelineSpec
        try:
            pipeline_spec = InputPipelineSpec.parse(args.pipeline)
        except ValueError as e:
            ap.error(f"--pipeline: {e}")

    if args.concurrency:
        if args.targets or args.zoo:
            ap.error("--concurrency lints source, not models: pass either "
                     "--concurrency targets or model targets, not both")
        # source-level lints: resolved without importing the target (and
        # without importing the model/zoo stack at all)
        from deeplearning4j_tpu.analysis.concurrency import \
            analyze_concurrency
        failed = 0
        for target in args.concurrency:
            try:
                report = analyze_concurrency(target, suppress=suppress,
                                             severity_overrides=overrides)
            except FileNotFoundError as e:
                ap.error(f"--concurrency: {e}")
            print(report.format())
            if not report.ok(warnings_as_errors=not args.warnings_ok):
                failed += 1
        return 1 if failed else 0

    targets: List[Tuple[str, object]] = []
    if args.zoo:
        targets.extend((name, cls().conf_builder())
                       for name, cls in _zoo_registry().items())
    for t in args.targets:
        targets.extend(_resolve(t))
    for t in args.samediff:
        targets.extend(_resolve(t))
    for path in args.onnx:
        targets.append((path, _resolve_onnx(path)))
    if not targets:
        ap.print_usage()
        print("nothing to lint: pass --zoo and/or target names")
        return 2

    failed = 0
    total = ValidationReport()
    for name, obj in targets:
        if isinstance(obj, ValidationReport):   # unimportable .onnx: the
            report = obj.apply_config(suppress, overrides)   # pre-scan IS
        else:                                                # the report
            report = analyze(obj, batch_size=args.batch_size,
                             data_devices=args.devices, mesh=args.mesh,
                             pipeline=args.stages,
                             hbm_gb=args.hbm_gb,
                             zero=True if args.zero else None,
                             input_pipeline=pipeline_spec,
                             policy=policy_spec, data_range=range_spec,
                             cost=cost_spec, profile=profile_spec,
                             suppress=suppress,
                             severity_overrides=overrides)
        report.subject = name
        total.extend(report.diagnostics)
        print(report.format())
        if not report.ok(warnings_as_errors=not args.warnings_ok):
            failed += 1
    print(f"\n{len(targets)} model(s) linted: {len(targets) - failed} clean, "
          f"{failed} with findings ({len(total.errors())} error(s), "
          f"{len(total.warnings())} warning(s))")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
