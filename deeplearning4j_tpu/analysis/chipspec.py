"""Chip capability registry for the static cost model (jax-free).

A :class:`ChipSpec` is the hardware half of the cost model's inputs: the
peak matmul throughput, HBM capacity and bandwidth, and interconnect
bandwidth that :mod:`analysis.cost` roofs its predictions against.  The
registry carries the published numbers for the TPU generations the repo
targets plus a deliberately small ``cpu`` entry for tests; everything is
plain Python so the module imports (and lints) with jax blocked.

Numbers are per-chip (not per-board) and intentionally round — the cost
model is a planning oracle, not a benchmark.  ``peak_flops`` is the
bf16/low-precision MXU peak; :meth:`ChipSpec.peak_for` halves it for
fp32 compute, matching how the MXU is scheduled.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Union


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip hardware capabilities used by the roofline/liveness model.

    :param name: registry key (``"tpu-v4"``) or a free-form label for
        custom specs.
    :param peak_flops: bf16 matmul peak, FLOP/s per chip.
    :param hbm_gb: HBM capacity per chip in GiB.
    :param hbm_gbps: HBM bandwidth, GB/s per chip.
    :param ici_gbps: inter-chip interconnect bandwidth, GB/s per chip
        (the divisor for gradient-collective bytes).
    :param host_gbps: host <-> chip (PCIe/DCN) bandwidth, GB/s — used
        for prefetch/staging feasibility, not the step-time roofline.
    """

    name: str
    peak_flops: float
    hbm_gb: float
    hbm_gbps: float
    ici_gbps: float
    host_gbps: float = 16.0

    def peak_for(self, dtype: str = "bf16") -> float:
        """MXU peak for a compute dtype: fp32 runs at half the bf16 rate."""
        d = (dtype or "bf16").lower()
        if d in ("float32", "fp32", "f32"):
            return self.peak_flops / 2.0
        return self.peak_flops

    @property
    def hbm_bytes(self) -> float:
        return self.hbm_gb * (1 << 30)

    def with_hbm_gb(self, hbm_gb: float) -> "ChipSpec":
        return replace(self, hbm_gb=hbm_gb)

    @classmethod
    def coerce(cls, obj: Union["ChipSpec", str, Dict, None],
               default: str = "tpu-v4") -> "ChipSpec":
        """Accept a ChipSpec, a registry name, a dict of fields, or None
        (-> the default chip).  Unknown names raise with the known list.
        """
        if obj is None:
            return CHIP_REGISTRY[default]
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, str):
            key = obj.lower()
            if key not in CHIP_REGISTRY:
                raise ValueError(
                    "unknown chip %r — known chips: %s"
                    % (obj, ", ".join(sorted(CHIP_REGISTRY))))
            return CHIP_REGISTRY[key]
        if isinstance(obj, dict):
            d = dict(obj)
            d.setdefault("name", "custom")
            return cls(**d)
        raise TypeError("cannot coerce %r to a ChipSpec" % (obj,))


#: Published per-chip numbers (bf16 peak / HBM GiB / HBM GB/s / ICI GB/s).
CHIP_REGISTRY: Dict[str, ChipSpec] = {
    "tpu-v3": ChipSpec("tpu-v3", peak_flops=123e12, hbm_gb=16.0,
                       hbm_gbps=900.0, ici_gbps=100.0),
    "tpu-v4": ChipSpec("tpu-v4", peak_flops=275e12, hbm_gb=32.0,
                       hbm_gbps=1228.0, ici_gbps=300.0),
    "tpu-v5e": ChipSpec("tpu-v5e", peak_flops=197e12, hbm_gb=16.0,
                        hbm_gbps=819.0, ici_gbps=200.0),
    # Test/dev stand-in: small enough that fixtures can overflow it.
    "cpu": ChipSpec("cpu", peak_flops=0.5e12, hbm_gb=4.0,
                    hbm_gbps=50.0, ici_gbps=10.0, host_gbps=8.0),
}


def chip_names() -> tuple:
    return tuple(sorted(CHIP_REGISTRY))
