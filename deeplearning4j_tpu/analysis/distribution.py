"""Distribution analyzer — static sharding/mesh/pipeline lints (E1xx/W10x).

The costliest misconfigurations on a multi-chip mesh are *distribution*
mistakes — a batch that does not divide the data axis, a sharding rule
naming an axis the mesh lacks, a replicated giant that eats HBM on every
device, a pipeline whose slowest stage gates every tick. All of them are
statically decidable from the model config plus the mesh declaration
(the GSPMD/weight-update-sharding observation: sharding is a property of
shapes and axis sizes, not of runtime state), so this pass runs them
ahead of any compile and with NO jax import — the declarations here are
plain-data mirrors of the ``parallel/`` runtime objects
(:class:`MeshSpec` ~ ``parallel.mesh.DeviceMesh``, sharding-rule dicts ~
``parallel.mesh.ShardingRule``, :class:`PipelineSpec` ~
``parallel.pipeline``).

Codes (documented in :mod:`analysis.diagnostics`):

- ``E101`` batch not divisible by the data axis
- ``E102`` named mesh axis absent / sized differently than declared
- ``E103`` pipeline stage boundary splits a weight-tied pair
- ``E104`` per-device parameter footprint exceeds the HBM budget
- ``W104`` replicated parameter tensor above threshold with a model axis idle
- ``W105`` pipeline stage FLOP imbalance beyond tolerance
- ``W106`` sub-MXU per-device shard after splitting
- ``W107`` per-layer gradient-collective bytes per step above threshold
- ``W109`` data-parallel mesh with fully-replicated optimizer state
  above threshold and no ZeRO plan declared (ISSUE 15: declare
  ``zero=`` — the runtime mirror is ``distributed.zero.ZeroPlan``)

Entry points: ``analyze(conf, mesh=...)`` / ``conf.validate(mesh=...)``
(the lints run from :mod:`analysis.analyzer`), and the CLI's ``--mesh``
flag. The per-layer shape/FLOP facts come from the jax-free declared-
shape hooks on the layer configs (``Layer.param_shapes()``).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis.diagnostics import Diagnostic, Severity
from deeplearning4j_tpu.analysis.layout import MXU_LANES, MXU_SUBLANES

#: W104 only flags tensors at least this large (bytes) — small replicated
#: params are the normal, correct layout.
REPLICATED_BYTES_THRESHOLD = 16 * 1024 * 1024
#: W107 threshold on one layer's estimated per-step gradient allreduce
#: payload (ring allreduce sends ~2(N-1)/N of the tensor per device).
COLLECTIVE_BYTES_THRESHOLD = 1024 ** 3
#: Default E104 per-device HBM budget (GiB) — a TPUv4-ish chip. Params
#: only; the message reminds that optimizer state multiplies it.
DEFAULT_HBM_GB = 16.0
#: W109 only fires when the replicated per-device optimizer state
#: exceeds this (small state is the normal, correct layout).
OPT_REPLICATED_BYTES_THRESHOLD = 64 * 1024 * 1024

#: Per-updater optimizer-state size factor (state bytes = factor x param
#: bytes) — the jax-free mirror of ``train.updaters`` ``init_state``
#: shapes, keyed by config class name.
UPDATER_STATE_FACTORS = {
    "Sgd": 0, "NoOp": 0,
    "Nesterovs": 1, "RmsProp": 1, "AdaGrad": 1,
    "Adam": 2, "AdamW": 2, "Nadam": 2, "AdaMax": 2, "AdaDelta": 2,
    "AMSGrad": 3,
}


def updater_state_factor(updater) -> int:
    """Optimizer-state bytes per parameter byte for an updater config
    (instance, class, or name string). Unknown stateful updaters
    default to 2 (the Adam-family shape); stateless to 0."""
    if updater is None:
        return 0
    name = updater if isinstance(updater, str) \
        else type(updater).__name__ if not isinstance(updater, type) \
        else updater.__name__
    if name in UPDATER_STATE_FACTORS:
        return UPDATER_STATE_FACTORS[name]
    return 2 if getattr(updater, "has_state", True) else 0

_DTYPE_BYTES = {"float64": 8, "double": 8, "f64": 8,
                "float32": 4, "float": 4, "f32": 4,
                "bfloat16": 2, "bf16": 2,
                "float16": 2, "half": 2, "f16": 2,
                "int8": 1, "uint8": 1}


def dtype_bytes(dtype) -> int:
    return _DTYPE_BYTES.get(str(dtype or "float32").lower(), 4)


def _prod(shape: Sequence[int]) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


class PipelineSpec:
    """Static declaration of a GPipe-style pipeline split (the jax-free
    mirror of ``parallel.pipeline``): ``stages`` contiguous stages over
    the layer list, either evenly split or at explicit ``boundaries``
    (stage-start layer indices, first must be 0), sharded over mesh axis
    ``axis``."""

    def __init__(self, stages: int, axis: str = "pipe",
                 boundaries: Optional[Sequence[int]] = None,
                 flop_tolerance: float = 0.25):
        self.stages = int(stages)
        self.axis = axis
        self.boundaries = list(boundaries) if boundaries is not None else None
        self.flop_tolerance = float(flop_tolerance)

    @staticmethod
    def coerce(obj) -> Optional["PipelineSpec"]:
        if obj is None or isinstance(obj, PipelineSpec):
            return obj
        if isinstance(obj, int):
            return PipelineSpec(obj)
        if isinstance(obj, dict):
            return PipelineSpec(**obj)
        raise TypeError(f"cannot interpret {obj!r} as a pipeline spec "
                        "(use PipelineSpec, an int stage count, or a dict)")

    def stage_of(self, n_layers: int) -> List[int]:
        """Stage index per layer. Raises ValueError on bad boundaries."""
        if self.stages < 1:
            raise ValueError(f"pipeline stages must be >= 1, got {self.stages}")
        if self.boundaries is not None:
            b = list(self.boundaries)
            if len(b) != self.stages or b != sorted(b) or (b and b[0] != 0) \
                    or len(set(b)) != len(b) or (b and b[-1] >= max(n_layers, 1)):
                raise ValueError(
                    f"pipeline boundaries {b} must be {self.stages} strictly "
                    f"increasing stage-start indices beginning at 0 and "
                    f"below {n_layers}")
            out, stage = [], 0
            for i in range(n_layers):
                while stage + 1 < len(b) and i >= b[stage + 1]:
                    stage += 1
                out.append(stage)
            return out
        per = max(1, -(-n_layers // self.stages))       # ceil
        return [min(i // per, self.stages - 1) for i in range(n_layers)]


class StageProfile:
    """A measured per-layer device-time profile for the W105 stage-balance
    lint (the ROADMAP carry: judge imbalance on MEASURED time when a
    profile exists, FLOP model only as fallback).

    ``rows``: forward-order ``{"layer": name, "device_ms": float}`` dicts
    — exactly what :class:`profiler.devicetime.LayerTime.as_dict` emits
    and what ``DeviceTimeTable`` rows serialize to.  ``source`` names
    where the numbers came from (a trace path, ``"measured"``, ...) and
    is quoted in the diagnostic message.
    """

    def __init__(self, rows: Sequence[Dict], source: str = "measured"):
        self.rows = [dict(r) for r in rows]
        self.source = str(source)

    @staticmethod
    def coerce(obj) -> Optional["StageProfile"]:
        """StageProfile | DeviceTimeTable (duck-typed ``.rows``) | a list
        of row dicts | {"rows": [...]} | a JSON trace file path."""
        if obj is None or isinstance(obj, StageProfile):
            return obj
        if isinstance(obj, str):
            if not os.path.exists(obj):
                raise ValueError(f"profile file {obj!r} does not exist")
            with open(obj) as fh:
                data = json.load(fh)
            if isinstance(data, dict):
                return StageProfile(data.get("rows", []),
                                    source=data.get("source", obj))
            return StageProfile(data, source=obj)
        rows = getattr(obj, "rows", None)
        if rows is not None and not isinstance(obj, dict):
            rows = [r.as_dict() if hasattr(r, "as_dict") else dict(r)
                    for r in rows]
            return StageProfile(rows,
                                source=getattr(obj, "source", "measured"))
        if isinstance(obj, dict):
            return StageProfile(obj.get("rows", []),
                                source=obj.get("source", "measured"))
        if isinstance(obj, (list, tuple)):
            return StageProfile(obj)
        raise TypeError(f"cannot interpret {obj!r} as a device-time "
                        "profile (use profiler.devicetime.DeviceTimeTable, "
                        "a list of row dicts, or a JSON trace path)")

    def time_per_entry(self, entries) -> Optional[List[float]]:
        """Measured device-ms per ``(loc, layer, it, out)`` entry — name
        match against the devicetime layer-naming convention
        (``name or cls.lower()_{i}``) first, positional fallback when the
        row count matches, else None (caller falls back to FLOPs)."""
        by_name: Dict[str, float] = {}
        for r in self.rows:
            name = r.get("layer")
            ms = r.get("device_ms")
            if name is not None and ms is not None:
                by_name[str(name)] = by_name.get(str(name), 0.0) + float(ms)
        out: List[Optional[float]] = []
        for i, (_loc, layer, _it, _o) in enumerate(entries):
            lname = getattr(layer, "name", None) \
                or f"{type(layer).__name__.lower()}_{i}"
            out.append(by_name.get(str(lname)))
        if all(v is not None for v in out) and out:
            return [float(v) for v in out]
        if len(self.rows) == len(entries):
            try:
                return [float(r.get("device_ms", 0.0)) for r in self.rows]
            except (TypeError, ValueError):
                return None
        return None


class MeshSpec:
    """Jax-free device-mesh declaration for the static pass.

    ``axes``: ordered {name: size} (the ``parallel.mesh.DeviceMesh``
    convention: ``data``/``model``/``seq``/``pipe``). ``sharding``: a
    ``parallel.mesh.ShardingRule``-shaped declaration — {param-name-regex:
    partition-spec-tuple} (or a ShardingRule instance; entries may be an
    axis name, ``None``, or a tuple of axis names per dim). ``pipeline``:
    a :class:`PipelineSpec`. ``hbm_gb``: per-device parameter budget for
    E104 (``None`` disables). ``devices``: the physical device count,
    when known — declares the axes-product-vs-hardware consistency
    check (E102), which the elastic shrink revalidation relies on."""

    def __init__(self, axes: Dict[str, int], data_axis: str = "data",
                 sharding=None, pipeline=None, hbm_gb: float = DEFAULT_HBM_GB,
                 devices: Optional[int] = None, zero=None):
        self.axes = {str(k): int(v) for k, v in dict(axes).items()}
        for name, size in self.axes.items():
            if size < 1:
                raise ValueError(f"mesh axis {name!r} has size {size}")
        self.data_axis = data_axis
        self.sharding = sharding
        self.pipeline = PipelineSpec.coerce(pipeline)
        self.hbm_gb = hbm_gb
        # ZeRO declaration (ISSUE 15): the jax-free mirror of
        # ``distributed.zero.ZeroPlan`` — {"axis": ..., "min_bytes": ...}.
        # When declared, E104 counts updater state at 1/axis-size and
        # W109 stays quiet.
        self.zero = self._coerce_zero(zero)
        # optional PHYSICAL device count: when declared (DeviceMesh.spec()
        # does, and the elastic shrink revalidation does), _lint_axes
        # checks the axes product against it (E102) — a mesh declaration
        # that no longer matches the surviving hardware is exactly the
        # misconfiguration an elastic resume must catch before replicating
        self.devices = None if devices is None else int(devices)

    def _coerce_zero(self, zero) -> Optional[Dict[str, Any]]:
        if zero is None or zero is False:
            return None
        if zero is True:
            return {"axis": self.data_axis, "min_bytes": 65536}
        if isinstance(zero, str):
            return {"axis": zero, "min_bytes": 65536}
        if isinstance(zero, dict):
            return {"axis": str(zero.get("axis", self.data_axis)),
                    "min_bytes": int(zero.get("min_bytes", 65536))}
        # duck-typed runtime ZeroPlan (never imported: stays jax-free)
        axis = getattr(zero, "axis", None)
        if axis is not None:
            return {"axis": str(axis),
                    "min_bytes": int(getattr(zero, "min_bytes", 65536))}
        raise TypeError(f"cannot interpret {zero!r} as a ZeRO declaration "
                        "(use True, an axis name, or a dict)")

    @staticmethod
    def parse(text: str) -> "MeshSpec":
        """``"data=8,model=2"`` -> MeshSpec (the CLI ``--mesh`` syntax)."""
        axes: Dict[str, int] = {}
        for part in str(text).split(","):
            part = part.strip()
            if not part:
                continue
            name, eq, size = part.partition("=")
            if not eq or not name.strip():
                raise ValueError(f"bad mesh axis {part!r}: expected "
                                 f"name=size[,name=size...]")
            try:
                axes[name.strip()] = int(size)
            except ValueError:
                raise ValueError(f"bad mesh axis size in {part!r}") from None
        if not axes:
            raise ValueError(f"empty mesh declaration {text!r}")
        return MeshSpec(axes)

    @staticmethod
    def coerce(obj) -> Optional["MeshSpec"]:
        """MeshSpec | axes dict | "data=8,..." string | a runtime
        ``DeviceMesh`` (duck-typed via its jax Mesh's ``.shape`` mapping,
        so this module still never imports jax)."""
        if obj is None or isinstance(obj, MeshSpec):
            return obj
        if isinstance(obj, str):
            return MeshSpec.parse(obj)
        if isinstance(obj, dict):
            return MeshSpec(obj)
        inner = getattr(obj, "mesh", None)
        shape = getattr(inner, "shape", None) or getattr(obj, "shape", None)
        if shape is not None and hasattr(shape, "items"):
            return MeshSpec(dict(shape))
        raise TypeError(f"cannot interpret {obj!r} as a mesh declaration "
                        "(use MeshSpec, {axis: size}, 'data=8,model=2', or "
                        "a parallel.mesh.DeviceMesh)")

    def size(self, axis: str, default: int = 1) -> int:
        return self.axes.get(axis, default)

    def model_axes(self) -> List[str]:
        """Axes a parameter tensor could shard over (size > 1): excludes
        the data axis (shards the batch), the declared pipeline axis
        (shards by stage assignment, not by spec), and ``seq`` (sequence
        parallelism shards activations — params stay replicated)."""
        skip = {self.data_axis, "seq"}
        if self.pipeline is not None:
            skip.add(self.pipeline.axis)
        else:
            skip.add("pipe")
        return [a for a, n in self.axes.items() if a not in skip and n > 1]

    def __repr__(self):
        body = ", ".join(f"{k}={v}" for k, v in self.axes.items())
        return f"MeshSpec({body})"


# ----------------------------------------------------------- sharding rules

def _normalize_rules(sharding) -> List[Tuple[Any, Tuple]]:
    """-> [(compiled regex, spec tuple)]. Accepts a
    ``parallel.mesh.ShardingRule`` (has ``.rules``), a {pattern: spec}
    dict, an already-normalized list, or None."""
    if sharding is None:
        return []
    rules = getattr(sharding, "rules", sharding)
    if isinstance(rules, dict):
        rules = [(re.compile(k), tuple(v)) for k, v in rules.items()]
    out = []
    for pat, spec in rules:
        if isinstance(pat, str):
            pat = re.compile(pat)
        out.append((pat, tuple(spec)))
    return out


def _spec_for(rules, name: str, ndim: int) -> Tuple:
    """Partition spec for one named param, padded to ``ndim`` (missing
    trailing dims replicate — jax PartitionSpec semantics)."""
    for pat, spec in rules:
        if pat.search(name):
            spec = tuple(spec)[:ndim]
            return spec + (None,) * (ndim - len(spec))
    return (None,) * ndim


def _dim_axes(entry) -> Tuple[str, ...]:
    """One spec entry -> the tuple of axis names it shards over."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def _spec_axes(spec) -> List[str]:
    return [a for entry in spec for a in _dim_axes(entry)]


def _shard_divisor(entry, mesh: MeshSpec) -> int:
    div = 1
    for a in _dim_axes(entry):
        div *= mesh.size(a)
    return div


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB"):
        if abs(n) < 1024:
            return f"{n:.0f} {unit}"
        n /= 1024
    return f"{n:.2f} GiB" if n >= 100 else f"{n:.1f} GiB"


# ------------------------------------------------------------- layer facts

class _ParamFact:
    """One parameter tensor's static facts under the mesh. ``idx`` is the
    owning entry's position (the pipeline stage assignment keys off it)."""

    __slots__ = ("idx", "location", "name", "shape", "spec", "bytes_total",
                 "bytes_per_device")

    def __init__(self, idx, location, name, shape, spec, itemsize, mesh):
        self.idx = idx
        self.location = location
        self.name = name
        self.shape = tuple(int(d) for d in shape)
        self.spec = spec
        self.bytes_total = _prod(self.shape) * itemsize
        div = 1
        for entry in spec:
            div *= _shard_divisor(entry, mesh)
        self.bytes_per_device = self.bytes_total / max(div, 1)


def _param_facts(entries, mesh: MeshSpec, itemsize: int) -> List[_ParamFact]:
    rules = _normalize_rules(mesh.sharding)
    facts = []
    for idx, (loc, layer, _it, _out) in enumerate(entries):
        shapes = getattr(layer, "param_shapes", lambda: {})()
        lname = getattr(layer, "name", None) or type(layer).__name__
        qualified = getattr(layer, "qualified_params", False)
        for pname, shape in shapes.items():
            if not shape or any(not d or d < 0 for d in shape):
                continue                       # unresolved nIn/nOut: skip
            # graphir's fact bundles carry already-qualified tensor names
            # (the sharding regexes must see the graph's own names)
            full = pname if qualified else f"{lname}/{pname}"
            spec = _spec_for(rules, full, len(shape))
            facts.append(_ParamFact(idx, loc, full, shape, spec, itemsize,
                                    mesh))
    return facts


def _stage_assignment(mesh: MeshSpec, n_entries: int) -> Optional[List[int]]:
    """Stage index per entry when a VALID pipeline is declared (axis
    present, sized to the stage count, boundaries well-formed) — else
    None. Invalid declarations are _lint_axes/_lint_pipeline's E102."""
    pipe = mesh.pipeline
    if pipe is None or mesh.size(pipe.axis) != pipe.stages:
        return None
    try:
        return pipe.stage_of(n_entries)
    except ValueError:
        return None


def _approx_flops(layer, it, out_it) -> int:
    """Per-example forward FLOP estimate from declared shapes: 2*W for
    every matmul-bearing weight, times spatial positions for conv output
    maps, times timesteps for recurrent input.  Attention layers add
    their score/context matmuls (2 x T^2 x E MACs each) — without that
    term a transformer stage's FLOPs read as just its projections and
    the W105 stage-balance lint undercounts it (the PR-4 carried
    follow-up; same for conv-LSTM, whose gate convs now come from
    ``ConvLSTM2D.param_shapes``)."""
    hook = getattr(layer, "approx_flops", None)
    if hook is not None:     # declared-fact hook (graphir's IR entries)
        try:
            return int(hook())
        except Exception:
            return 0
    shapes = getattr(layer, "param_shapes", lambda: {})()
    w = sum(_prod(s) for s in shapes.values() if len(s) >= 2)
    mult = 1
    if out_it is not None and getattr(out_it, "kind", None) == "cnn":
        mult = max(int(out_it.dims.get("height", 1)), 1) * \
            max(int(out_it.dims.get("width", 1)), 1)
    elif it is not None and getattr(it, "kind", None) == "rnn":
        t = int(it.dims.get("timesteps", -1) or -1)
        mult = t if t > 0 else 1
    flops = 2 * w * mult
    flops += _attention_flops(layer, it)
    return flops


def _attention_flops(layer, it) -> int:
    """Score + context matmul FLOPs for attention layers: QK^T is
    T_q x T_k x E MACs, attn x V the same again — 2 FLOPs per MAC.
    Needs a statically-declared timestep count; degrades to 0 (the old
    undercount) when T is unknown."""
    n_heads = getattr(layer, "n_heads", None)
    if not n_heads:
        return 0
    if it is None or getattr(it, "kind", None) != "rnn":
        return 0
    t_k = int(it.dims.get("timesteps", -1) or -1)
    if t_k <= 0:
        return 0
    head_size = getattr(layer, "head_size", None)
    e = int(n_heads) * int(head_size) if head_size \
        else int(getattr(layer, "nIn", 0) or 0)
    if not e:
        return 0
    # LearnedSelfAttention queries from n_queries learned vectors;
    # RecurrentAttention queries once per output step (T_q = T_k)
    t_q = int(getattr(layer, "n_queries", 0) or 0) or t_k
    return 2 * 2 * t_q * t_k * e


def _propagate_types(conf):
    """Best-effort InputType per layer for the sequential config: (input,
    output) pairs, None where propagation is impossible or fails (the
    structural analyzer already reported that as its own diagnostic)."""
    layers = list(conf.layers)
    out: List[Tuple] = [(None, None)] * len(layers)
    cur = getattr(conf, "input_type", None)
    if cur is None:
        return out
    preprocessors = dict(getattr(conf, "preprocessors", {}) or {})
    try:
        from deeplearning4j_tpu.nn import preprocessors as pp
    except ImportError:      # jax-blocked environment: skip type refinement
        return out
    for i, layer in enumerate(layers):
        if cur is None:
            break
        try:
            pre = preprocessors.get(i)
            if pre is None:
                pre = pp.preprocessor_for(cur, layer)
            if pre is not None:
                cur = pre.output_type(cur)
            nxt = layer.output_type(cur)
        except Exception:
            out[i] = (cur, None)
            break
        out[i] = (cur, nxt)
        cur = nxt
    return out


# -------------------------------------------------------------- the checks

def lint_multilayer(conf, mesh: MeshSpec, batch_size: Optional[int],
                    profile=None) -> List[Diagnostic]:
    from deeplearning4j_tpu.analysis.analyzer import _layer_loc
    layers = list(conf.layers)
    types = _propagate_types(conf)
    entries = [(_layer_loc(i, l), l, types[i][0], types[i][1])
               for i, l in enumerate(layers)]
    diags = lint_entries(entries, mesh, batch_size,
                         getattr(getattr(conf, "base", None), "dtype", None),
                         updater=getattr(getattr(conf, "base", None),
                                         "updater", None))
    diags.extend(_lint_pipeline(entries, mesh, profile=profile))
    return diags


def lint_graph(conf, mesh: MeshSpec, batch_size: Optional[int],
               profile=None) -> List[Diagnostic]:
    """Graph configs get every per-tensor/mesh check. InputTypes
    propagate through vertices (PR-4 carried follow-up), so the
    type-dependent checks (W105 stage balance from real per-layer FLOPs,
    W106 geometry, W107 collectives) see the same facts the sequential
    path does; the pipeline pass runs over the topological layer order —
    the one linearization a DAG stage split could use."""
    from deeplearning4j_tpu.analysis.analyzer import _node_loc
    types = _propagate_graph_types(conf)
    entries = []
    for n in _graph_layer_order(conf):
        it, out = types.get(n.name, (None, None))
        entries.append((_node_loc(n), n.obj, it, out))
    diags = lint_entries(entries, mesh, batch_size,
                         getattr(getattr(conf, "base", None), "dtype", None),
                         updater=getattr(getattr(conf, "base", None),
                                         "updater", None))
    diags.extend(_lint_pipeline(entries, mesh, profile=profile))
    return diags


def _graph_layer_order(conf) -> List:
    """Layer nodes in topological order (declaration order breaks ties /
    cycles — the structural analyzer owns reporting those)."""
    return [n for n in _graph_order_all(conf, list(conf.nodes))
            if n.kind == "layer"]


def _propagate_graph_types(conf) -> Dict[str, Tuple]:
    """Best-effort (in_type, out_type) per graph node, propagated through
    layer nodes AND vertices in topological order. Unknown inputs or a
    failing hook stop that path only — downstream nodes get (None, None)
    and the checks degrade exactly as they always did."""
    out: Dict[str, Tuple] = {}
    input_types = dict(getattr(conf, "input_types", {}) or {})
    if not input_types:
        return out
    try:
        from deeplearning4j_tpu.nn import preprocessors as pp
    except ImportError:      # jax-blocked environment: skip refinement
        return out
    preprocessors = dict(getattr(conf, "preprocessors", {}) or {})
    types = dict(input_types)
    nodes = list(conf.nodes)
    for n in _graph_order_all(conf, nodes):
        in_types = [types.get(r) for r in n.inputs]
        if any(t is None for t in in_types) or not in_types:
            continue
        try:
            if n.kind == "layer":
                it = in_types[0]
                pre = preprocessors.get(n.name)
                if pre is None:
                    pre = pp.preprocessor_for(it, n.obj)
                if pre is not None:
                    it = pre.output_type(it)
                nxt = n.obj.output_type(it)
                out[n.name] = (it, nxt)
                types[n.name] = nxt
            else:
                types[n.name] = n.obj.output_type(*in_types)
        except Exception:
            continue          # structural analyzer reports this path
    return out


def _graph_order_all(conf, nodes) -> List:
    """All nodes (layers + vertices) topologically, same tie-breaking as
    :func:`_graph_layer_order`."""
    seen = set(getattr(conf, "graph_inputs", ()) or ())
    names = {n.name for n in nodes}
    order, remaining = [], list(nodes)
    progressed = True
    while remaining and progressed:
        progressed = False
        for n in list(remaining):
            if all(r in seen or r not in names for r in n.inputs):
                order.append(n)
                seen.add(n.name)
                remaining.remove(n)
                progressed = True
    order.extend(remaining)
    return order


def lint_entries(entries, mesh: MeshSpec, batch_size: Optional[int],
                 dtype, updater=None) -> List[Diagnostic]:
    """Mesh-wide checks over ``(location, layer, in_type, out_type)``
    entries — shared by the sequential and graph paths. ``updater``
    (the config's IUpdater, when known) feeds the optimizer-state
    accounting: the ZeRO-aware E104 and the W109 replicated-state
    warning."""
    diags: List[Diagnostic] = []
    diags.extend(_lint_batch(mesh, batch_size))
    diags.extend(_lint_axes(mesh))
    facts = _param_facts(entries, mesh, dtype_bytes(dtype))
    diags.extend(_lint_hbm(facts, mesh,
                           _stage_assignment(mesh, len(entries)),
                           updater=updater))
    diags.extend(_lint_replicated(facts, mesh))
    diags.extend(_lint_opt_replication(facts, mesh, updater,
                                       _stage_assignment(mesh,
                                                         len(entries))))
    diags.extend(_lint_shard_geometry(facts, mesh))
    diags.extend(_lint_collectives(facts, mesh))
    return diags


def _lint_batch(mesh: MeshSpec, batch_size) -> List[Diagnostic]:
    n = mesh.size(mesh.data_axis)
    if not batch_size or n <= 1 or batch_size % n == 0:
        return []
    return [Diagnostic(
        "DL4J-E101", Severity.ERROR, "mesh",
        f"global batch {batch_size} does not divide the "
        f"'{mesh.data_axis}' axis ({n} devices) — per-device batches "
        f"would be ragged and the sharded dispatch will pad or fail",
        fix_hint=f"use a global batch that is a multiple of {n} "
                 f"(e.g. {((batch_size // n) + 1) * n})")]


def _lint_axes(mesh: MeshSpec) -> List[Diagnostic]:
    diags = []
    if mesh.devices is not None:
        product = 1
        for n in mesh.axes.values():
            product *= n
        if product != mesh.devices:
            diags.append(Diagnostic(
                "DL4J-E102", Severity.ERROR, "mesh",
                f"mesh axes {dict(mesh.axes)} multiply to {product} "
                f"device(s) but {mesh.devices} are declared — the mesh "
                f"cannot be built on this device set",
                fix_hint="resize an axis so the product matches the "
                         "physical device count (after an elastic shrink, "
                         "the data axis must equal the survivor count)"))
    missing = []
    for _pat, spec in _normalize_rules(mesh.sharding):
        missing.extend(a for a in _spec_axes(spec) if a not in mesh.axes)
    for axis in sorted(set(missing)):
        diags.append(Diagnostic(
            "DL4J-E102", Severity.ERROR, "sharding rules",
            f"partition spec names mesh axis '{axis}' but the declared "
            f"mesh has axes {sorted(mesh.axes)} — placement would fail at "
            f"the first device_put",
            fix_hint=f"add '{axis}' to the mesh (DeviceMesh.create / "
                     f"--mesh {axis}=N) or fix the rule's axis name"))
    pipe = mesh.pipeline
    if pipe is not None:
        if pipe.axis not in mesh.axes:
            diags.append(Diagnostic(
                "DL4J-E102", Severity.ERROR, "pipeline",
                f"pipeline declares mesh axis '{pipe.axis}' but the mesh "
                f"has axes {sorted(mesh.axes)}",
                fix_hint=f"declare the axis (--mesh {pipe.axis}="
                         f"{pipe.stages}) or drop the pipeline spec"))
        elif mesh.size(pipe.axis) != pipe.stages:
            diags.append(Diagnostic(
                "DL4J-E102", Severity.ERROR, "pipeline",
                f"pipeline declares {pipe.stages} stages but mesh axis "
                f"'{pipe.axis}' has size {mesh.size(pipe.axis)} — one "
                f"device per stage is the parallel/pipeline contract",
                fix_hint="make the stage count equal the pipe-axis size"))
    return diags


def _lint_pipeline(entries, mesh: MeshSpec, profile=None) -> List[Diagnostic]:
    pipe = mesh.pipeline
    if pipe is None or pipe.axis not in mesh.axes \
            or mesh.size(pipe.axis) != pipe.stages:
        return []                     # E102 already covers the mismatch
    diags = []
    try:
        stage_of = pipe.stage_of(len(entries))
    except ValueError as e:
        return [Diagnostic("DL4J-E102", Severity.ERROR, "pipeline", str(e),
                           fix_hint="fix the stage boundaries")]
    # E103: weight-tied pairs must live on one stage (a tie across stages
    # means the 'shared' tensor is two tensors on two devices, kept in
    # sync only by luck)
    groups: Dict[str, List[Tuple[int, str]]] = {}
    for i, (loc, layer, _it, _out) in enumerate(entries):
        tie = getattr(layer, "tied_with", None)
        if tie:
            groups.setdefault(str(tie), []).append((i, loc))
    for tie, members in sorted(groups.items()):
        stages = {stage_of[i] for i, _ in members}
        if len(stages) > 1:
            locs = ", ".join(loc for _, loc in members)
            diags.append(Diagnostic(
                "DL4J-E103", Severity.ERROR, locs,
                f"weight-tie group '{tie}' is split across pipeline "
                f"stages {sorted(stages)} — tied parameters on different "
                f"stages are physically distinct tensors and silently "
                f"diverge",
                fix_hint="move the stage boundary so every layer of the "
                         "tie group lands on one stage (or break the tie)"))
    # W105: stage balance — the pipeline advances at the slowest stage's
    # pace, so imbalance is pure bubble on every other device. MEASURED
    # per-layer device time (analyze(profile=...) / --profile) when a
    # profile maps onto the layers, the FLOP model as fallback — the
    # message names which source judged it.
    measured = None
    if profile is not None:
        prof = StageProfile.coerce(profile)
        measured = prof.time_per_entry(entries)
    if measured is not None:
        cost = [0.0] * pipe.stages
        for i in range(len(entries)):
            cost[stage_of[i]] += measured[i]
        unit, src = "device-ms/step", \
            f"measured per-stage device time (source: {prof.source})"
        fmt = [f"stage {s}: {c:.2f}" for s, c in enumerate(cost)]
    else:
        cost = [0.0] * pipe.stages
        for i, (_loc, layer, it, out) in enumerate(entries):
            cost[stage_of[i]] += _approx_flops(layer, it, out)
        unit, src = "GFLOP/example", "the static FLOP model"
        fmt = [f"stage {s}: {c / 1e9:.2f}" for s, c in enumerate(cost)]
    total = sum(cost)
    if total > 0:
        mean = total / pipe.stages
        worst = max(range(pipe.stages), key=lambda s: cost[s])
        if cost[worst] > mean * (1.0 + pipe.flop_tolerance):
            diags.append(Diagnostic(
                "DL4J-W105", Severity.WARNING, "pipeline",
                f"stage imbalance (judged on {src}): stage {worst} "
                f"carries {cost[worst] / mean:.2f}x the mean "
                f"({unit}: {', '.join(fmt)}) — every lighter stage idles "
                f"the difference each tick",
                fix_hint="move the stage boundaries toward an even "
                         "split (boundaries=[...]), not an even layer "
                         "count"))
    return diags


def _zero_state_divisor(f: "_ParamFact", mesh: MeshSpec) -> int:
    """How many ways the declared ZeRO plan splits this param's updater
    state — the static mirror of ``ZeroPlan.state_spec``: the data-axis
    size when the tensor is big enough and has a free dim the axis
    divides, else 1 (state keeps the param's sharding)."""
    zero = mesh.zero
    if zero is None:
        return 1
    n = mesh.size(zero["axis"])
    if n <= 1 or f.bytes_total < zero["min_bytes"]:
        return 1
    spec = tuple(f.spec) + (None,) * (len(f.shape) - len(f.spec))
    if zero["axis"] in _spec_axes(spec):
        # the param spec already shards over the ZeRO axis (FSDP-style):
        # bytes_per_device is already divided by it — dividing again
        # would under-count E104's state bytes n-fold
        return 1
    for dim, entry in zip(f.shape, spec):
        if entry is None and dim >= n and dim % n == 0:
            return n
    return 1


def _opt_bytes_per_device(f: "_ParamFact", mesh: MeshSpec,
                          factor: int) -> float:
    return f.bytes_per_device * factor / _zero_state_divisor(f, mesh)


def _lint_hbm(facts, mesh: MeshSpec,
              stages: Optional[List[int]] = None,
              updater=None) -> List[Diagnostic]:
    if mesh.hbm_gb is None or not facts:
        return []
    budget = float(mesh.hbm_gb) * 1024 ** 3
    # E104 counts updater state only under a declared ZeRO plan (ISSUE
    # 15): each state tensor at 1/data-axis of its replicated size. The
    # no-ZeRO replicated-optimizer hazard is W109's, keeping E104's
    # params-only baseline stable for existing budgets.
    factor = updater_state_factor(updater) if mesh.zero is not None else 0

    def per_device(f):
        return f.bytes_per_device + _opt_bytes_per_device(f, mesh, factor)

    if stages is not None:
        # pipeline: a device holds only its own stage's layers — budget
        # the heaviest stage, not the whole model
        per_stage: Dict[int, float] = {}
        for f in facts:
            per_stage[stages[f.idx]] = per_stage.get(stages[f.idx], 0.0) \
                + per_device(f)
        worst = max(per_stage, key=per_stage.get)
        total = per_stage[worst]
        location = f"pipeline stage {worst}"
        facts = [f for f in facts if stages[f.idx] == worst]
    else:
        total = sum(per_device(f) for f in facts)
        location = "mesh"
    if total <= budget:
        return []
    top = sorted(facts, key=lambda f: -f.bytes_per_device)[:3]
    biggest = "; ".join(f"{f.name} {f.shape} {_fmt_bytes(f.bytes_per_device)}"
                        f"/device" for f in top)
    if factor:
        accounting = (f"params + ZeRO-sharded updater state over "
                      f"{mesh.size(mesh.zero['axis'])} "
                      f"'{mesh.zero['axis']}' shards")
    else:
        accounting = "params only — optimizer state multiplies this 2-3x"
    return [Diagnostic(
        "DL4J-E104", Severity.ERROR, location,
        f"per-device parameter footprint {_fmt_bytes(total)} exceeds the "
        f"{mesh.hbm_gb:g} GiB HBM budget ({accounting}). "
        f"Biggest shards: {biggest}",
        fix_hint="shard the large tensors over a model axis (ShardingRule"
                 "), raise the budget (--hbm-gb), or shrink the model")]


def _lint_opt_replication(facts, mesh: MeshSpec, updater,
                          stages: Optional[List[int]] = None
                          ) -> List[Diagnostic]:
    """W109: a data-parallel mesh training with fully-replicated
    optimizer state above threshold and NO ZeRO plan declared — every
    extra replica burns ``factor x params`` HBM that cross-replica
    weight-update sharding would reclaim (PAPERS.md). Stage-aware like
    E104: under a pipeline, a device replicates only its own stage's
    state."""
    if mesh.zero is not None or not facts:
        return []
    n = mesh.size(mesh.data_axis)
    if n <= 1:
        return []
    factor = updater_state_factor(updater)
    if factor < 1:
        return []
    if stages is not None:
        per_stage: Dict[int, float] = {}
        for f in facts:
            per_stage[stages[f.idx]] = per_stage.get(stages[f.idx], 0.0) \
                + f.bytes_per_device
        opt_bytes = max(per_stage.values()) * factor
    else:
        opt_bytes = sum(f.bytes_per_device for f in facts) * factor
    if opt_bytes <= OPT_REPLICATED_BYTES_THRESHOLD:
        return []
    return [Diagnostic(
        "DL4J-W109", Severity.WARNING, "mesh",
        f"fully-replicated optimizer state: "
        f"{type(updater).__name__ if updater is not None else 'the updater'}"
        f" keeps {_fmt_bytes(opt_bytes)} of state on EVERY of the {n} "
        f"'{mesh.data_axis}' replicas — sharding it across the data axis "
        f"(ZeRO-style cross-replica weight-update sharding) cuts that to "
        f"~{_fmt_bytes(opt_bytes / n)} per device with identical math",
        fix_hint="declare zero= on the mesh (MeshSpec(zero=True)) and "
                 "train with ShardedTrainingPlan(mesh, "
                 "zero=ZeroPlan()) — distributed.zero")]


def _lint_replicated(facts, mesh: MeshSpec) -> List[Diagnostic]:
    model_axes = mesh.model_axes()
    if not model_axes:
        return []
    diags = []
    for f in facts:
        if f.bytes_total < REPLICATED_BYTES_THRESHOLD:
            continue
        if any(a in mesh.axes and mesh.size(a) > 1
               for a in _spec_axes(f.spec)):
            continue                   # sharded over something real
        diags.append(Diagnostic(
            "DL4J-W104", Severity.WARNING, f.location,
            f"parameter {f.name} {f.shape} ({_fmt_bytes(f.bytes_total)}) "
            f"is replicated on every device although the mesh declares "
            f"model axes {model_axes} — each replica burns the full "
            f"tensor (and its updater state) in HBM",
            fix_hint="add a ShardingRule entry partitioning it over "
                     f"'{model_axes[0]}' (GSPMD-style weight-update "
                     "sharding: see PAPERS.md cross-replica sharding)"))
    return diags


def _lint_shard_geometry(facts, mesh: MeshSpec) -> List[Diagnostic]:
    diags = []
    for f in facts:
        if len(f.shape) < 2:
            continue
        for dim_idx, entry in enumerate(f.spec):
            axes = [a for a in _dim_axes(entry) if mesh.size(a) > 1]
            if not axes:
                continue
            div = _shard_divisor(entry, mesh)
            dim = f.shape[dim_idx]
            minor = dim_idx == len(f.shape) - 1
            tile = MXU_LANES if minor else MXU_SUBLANES
            per_dev = dim / div
            if dim % div != 0:
                diags.append(Diagnostic(
                    "DL4J-W106", Severity.WARNING, f.location,
                    f"{f.name} dim {dim_idx} ({dim}) does not divide its "
                    f"shard factor {div} over {axes} — GSPMD pads every "
                    f"shard to {-(-dim // div)}",
                    fix_hint=f"pick a dim that is a multiple of {div}"))
            elif dim >= tile and per_dev < tile:
                kind = "lane" if minor else "sublane"
                diags.append(Diagnostic(
                    "DL4J-W106", Severity.WARNING, f.location,
                    f"{f.name} dim {dim_idx} ({dim}) shards over {axes} "
                    f"to {per_dev:.0f}/device — below one "
                    f"{MXU_SUBLANES}x{MXU_LANES} MXU tile in the {kind} "
                    f"dim, so every device pads back up to {tile} and "
                    f"most of each MAC is dead",
                    fix_hint=f"shard a larger dim, or keep per-device "
                             f"extent >= {tile} (dim >= {tile * div} "
                             f"here)"))
    return diags


def collective_payload_estimates(facts, mesh: MeshSpec) -> Dict[str, float]:
    """The W107 scaling model: per-layer estimated gradient-allreduce
    payload in bytes per device per step — ring allreduce moves
    ~``2(N-1)/N`` of each per-device gradient shard over the data axis.
    Returns {} on a 1-wide data axis (no gradient collective at all)."""
    n = mesh.size(mesh.data_axis)
    if n <= 1:
        return {}
    ring = 2.0 * (n - 1) / n
    per_layer: Dict[str, float] = {}
    for f in facts:
        per_layer[f.location] = per_layer.get(f.location, 0.0) \
            + f.bytes_per_device
    return {loc: b * ring for loc, b in per_layer.items()}


def estimate_gradient_collectives(conf, mesh) -> Dict[str, float]:
    """Public entry for the collective-volume characterization
    (``benchmarks/probe_collectives.py``): the SAME per-layer estimate
    the W107 lint thresholds, for a sequential configuration under any
    mesh declaration. Jax-free — the measured counterpart comes from
    the compiled HLO (``distributed.gspmd.hlo_collective_bytes``)."""
    from deeplearning4j_tpu.analysis.analyzer import _layer_loc
    mesh = MeshSpec.coerce(mesh)
    entries = [(_layer_loc(i, l), l, None, None)
               for i, l in enumerate(conf.layers)]
    facts = _param_facts(entries, mesh, dtype_bytes(
        getattr(getattr(conf, "base", None), "dtype", None)))
    return collective_payload_estimates(facts, mesh)


def _lint_collectives(facts, mesh: MeshSpec) -> List[Diagnostic]:
    """Per-layer gradient-allreduce estimate from the SHARDED facts: the
    gradient carries the parameter's sharding, so model-sharding a tensor
    shrinks its allreduce payload — following W104/W107's own fix hint
    clears the warning."""
    diags = []
    n = mesh.size(mesh.data_axis)
    for loc, payload in collective_payload_estimates(facts, mesh).items():
        if payload > COLLECTIVE_BYTES_THRESHOLD:
            diags.append(Diagnostic(
                "DL4J-W107", Severity.WARNING, loc,
                f"estimated gradient allreduce for this layer moves "
                f"{_fmt_bytes(payload)} per device per step (ring "
                f"allreduce of its {_fmt_bytes(payload * n / (2.0 * (n - 1)))}"
                f" per-device grad shard over {n} '{mesh.data_axis}' "
                f"devices) — likely the step's communication bottleneck",
                fix_hint="shard the tensor over a model axis, keep grads "
                         "in bf16 for the allreduce, or shrink the layer"))
    return diags
