"""Serving-config lint: batch buckets x mesh x HBM — validate a
deployment BEFORE it compiles or takes traffic.

The model server pads coalesced batches to a fixed bucket ladder and
AOT-compiles every bucket on the serving mesh. This module makes the
three ways that configuration goes wrong statically checkable (no jax —
same contract as the rest of ``analysis``):

- ``DL4J-E110``: a bucket does not divide the mesh's data axis — the
  sharded dispatch cannot place it and the first request fails at
  ``device_put``, after warmup already burned the compiles.
- ``DL4J-E111``: per-device HBM estimate (replicated params + the
  largest bucket's activation working set) exceeds the budget — the
  server OOMs under exactly the biggest coalesced batch, i.e. at peak
  load.
- ``DL4J-W110``: a pathological bucket ladder (duplicates, or more
  buckets than :data:`BUCKET_COUNT_THRESHOLD`) — every bucket x shape
  is one compiled program held in the executable cache, and warmup
  time scales with the product.
- ``DL4J-W111``: a registry roll planned onto a version without warmed
  buckets — the first post-roll request at an unwarmed (bucket, shape)
  XLA-compiles under live traffic, exactly the cold-start the zero-drop
  hot-swap exists to avoid.
- ``DL4J-W112``: a serving/registry warmup running WITHOUT a persistent
  compile cache (no ``DL4J_TPU_COMPILE_CACHE_DIR`` /
  ``nn.compilecache.configure()`` directory, or an unwritable one) —
  every fresh process, rollout, and hot-swap staging pays full XLA
  compile where a populated cache would deserialize from disk. Checked
  only when the lint runs on behalf of an actual ``warmup()``
  (``check_cache=True``): a pure-static ``validate()`` stays silent so
  config linting is environment-independent.

Entry points: :func:`lint_serving` (what ``ModelServer.validate()`` /
``warmup(strict=True)`` call) — accepts a network, or a bare
configuration, plus the bucket ladder and an optional mesh / HBM
budget — and :func:`lint_registry_roll` (what
``ModelRegistry.validate_roll()`` / ``roll(strict=True)`` call),
duck-typed over server objects so it stays jax-free.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.analysis.diagnostics import (Diagnostic, Severity,
                                                     ValidationReport)
from deeplearning4j_tpu.analysis.distribution import (MeshSpec, _fmt_bytes,
                                                      _param_facts,
                                                      _propagate_types,
                                                      _prod, dtype_bytes)

#: W110 fires past this many buckets: each bucket x input shape is one
#: XLA program (compile seconds at warmup, executable-cache HBM after).
BUCKET_COUNT_THRESHOLD = 8


def _entries(model_or_conf):
    """(location, layer) pairs from a network, a sequential config, or a
    graph config — mirrors distribution's entry building, duck-typed."""
    conf = getattr(model_or_conf, "conf", model_or_conf)
    if hasattr(conf, "layers"):
        from deeplearning4j_tpu.analysis.analyzer import _layer_loc
        return conf, [(_layer_loc(i, l), l, None, None)
                      for i, l in enumerate(conf.layers)]
    if hasattr(conf, "nodes"):
        from deeplearning4j_tpu.analysis.analyzer import _node_loc
        return conf, [(_node_loc(n), n.obj, None, None)
                      for n in conf.nodes if n.kind == "layer"]
    return conf, []


def _activation_bytes_per_example(conf, shapes, itemsize: int) -> float:
    """Per-example forward working-set estimate: the summed declared
    layer output sizes (InputType propagation) when available, else the
    raw feature size — deliberately coarse, this is a budget lint, not
    an allocator."""
    total = 0
    try:
        for _in_t, out_t in _propagate_types(conf):
            if out_t is None:
                continue
            dims = [int(v) for v in getattr(out_t, "dims", {}).values()
                    if isinstance(v, (int, float)) and v > 0]
            if dims:
                total += _prod(dims)
    except Exception:
        total = 0
    if total == 0 and shapes:
        total = max(_prod([int(d) for d in s]) for s in shapes if s)
    return float(total) * itemsize


def lint_compile_cache(context: str = "serving warmup") -> List[Diagnostic]:
    """The DL4J-W112 check: is a persistent compile cache configured and
    writable? jax-free (``nn.compilecache``'s config half imports no
    accelerator stack)."""
    from deeplearning4j_tpu.nn.compilecache import ENV_DIR, cache_dir_status
    directory, writable = cache_dir_status()
    if directory is None:
        return [Diagnostic(
            "DL4J-W112", Severity.WARNING, context,
            "no persistent compile cache is configured — every fresh "
            "process, rollout, and hot-swap staging pays full XLA "
            "compile for programs an earlier run already compiled",
            fix_hint=f"set {ENV_DIR}=/path/shared/by/your/fleet (or call "
                     "nn.compilecache.configure(dir)) so warmup "
                     "deserializes previously-seen (model, bucket, mesh, "
                     "policy) programs from disk")]
    if not writable:
        return [Diagnostic(
            "DL4J-W112", Severity.WARNING, context,
            f"persistent compile cache directory {directory!r} is not "
            "writable — warmup can neither populate nor refresh it, so "
            "rollouts on new (model, bucket, mesh, policy) tuples still "
            "pay full compile",
            fix_hint="fix the directory permissions (or point "
                     f"{ENV_DIR} at a writable path)")]
    return []


def lint_serving(model_or_conf, buckets: Sequence[int], mesh=None,
                 shapes: Optional[Iterable[Sequence[int]]] = None,
                 hbm_gb: Optional[float] = None, input_dtype=None,
                 check_cache: bool = False,
                 extra: Iterable[Diagnostic] = ()) -> ValidationReport:
    """Static serving-config report for ``buckets`` on ``mesh``.

    ``mesh`` coerces like everywhere else (MeshSpec, dict, string, or a
    runtime DeviceMesh); ``shapes`` are per-request feature shapes (the
    ``warmup()`` argument) for the activation estimate; ``hbm_gb``
    enables E111 (None skips it — CPU tests have no HBM to budget);
    ``extra`` folds pre-existing diagnostics (the server's W201 churn
    findings) into the report; ``check_cache=True`` (the warmup path)
    adds the DL4J-W112 persistent-compile-cache check."""
    spec = MeshSpec.coerce(mesh) if mesh is not None else None
    buckets = [int(b) for b in buckets]
    diags: List[Diagnostic] = list(extra)
    if check_cache:
        diags.extend(lint_compile_cache())

    data_width = spec.size(spec.data_axis) if spec is not None else 1
    if data_width > 1:
        for b in buckets:
            if b % data_width != 0:
                diags.append(Diagnostic(
                    "DL4J-E110", Severity.ERROR, "serving buckets",
                    f"bucket {b} does not divide the '{spec.data_axis}' "
                    f"axis ({data_width} devices) — the sharded dispatch "
                    "cannot place it and the first request at this bucket "
                    "fails AFTER warmup compiled it",
                    fix_hint=f"use bucket sizes that are multiples of "
                             f"{data_width} (ModelServer.buckets() derives "
                             "a correct ladder from the mesh)"))

    if len(set(buckets)) != len(buckets):
        diags.append(Diagnostic(
            "DL4J-W110", Severity.WARNING, "serving buckets",
            f"duplicate bucket sizes in {sorted(buckets)} — each entry "
            "costs one warmup compile per input shape for the same "
            "program",
            fix_hint="deduplicate the bucket ladder"))
    elif len(buckets) > BUCKET_COUNT_THRESHOLD:
        diags.append(Diagnostic(
            "DL4J-W110", Severity.WARNING, "serving buckets",
            f"{len(buckets)} buckets (threshold "
            f"{BUCKET_COUNT_THRESHOLD}) — every bucket x input shape is "
            "one compiled program: warmup time and executable-cache "
            "footprint scale with the product",
            fix_hint="coarsen the ladder (power-of-two steps from the "
                     "mesh data width to batch_limit is the default)"))

    if hbm_gb is not None and buckets:
        conf, entries = _entries(model_or_conf)
        itemsize = dtype_bytes(input_dtype
                               if input_dtype is not None
                               else getattr(getattr(conf, "base", None),
                                            "dtype", None))
        pspec = spec if spec is not None else MeshSpec({"data": 1})
        facts = _param_facts(entries, pspec, itemsize)
        param_bytes = sum(f.bytes_per_device for f in facts)
        act = _activation_bytes_per_example(conf, shapes or (), itemsize)
        biggest = max(buckets)
        act_bytes = act * biggest / max(data_width, 1)
        budget = float(hbm_gb) * 1024 ** 3
        if param_bytes + act_bytes > budget:
            diags.append(Diagnostic(
                "DL4J-E111", Severity.ERROR, "serving memory",
                f"per-device serving footprint "
                f"{_fmt_bytes(param_bytes + act_bytes)} (params "
                f"{_fmt_bytes(param_bytes)} + bucket-{biggest} activations "
                f"~{_fmt_bytes(act_bytes)}) exceeds the {hbm_gb:g} GiB HBM "
                "budget — the server OOMs at peak coalesced load",
                fix_hint="lower batch_limit (the largest bucket), shard "
                         "the model over a model axis, or raise hbm_gb"))

    return ValidationReport(diags, subject="serving config")


def lint_registry_roll(model_name: str, target, active=None
                       ) -> ValidationReport:
    """Pre-roll lint for a multi-model registry version swap: ``target``
    (and optionally the currently ``active`` version) are server-like
    objects exposing ``_warmed`` / ``_warm_shapes`` / ``buckets()`` —
    duck-typed, so the check needs no jax and runs before any traffic
    moves.

    - ``DL4J-W111`` when the target was never warmed at all, or when
      shapes the active version serves warm are missing from the
      target's warmed set (those requests compile under live load right
      after the roll).
    """
    diags: List[Diagnostic] = []
    loc = f"registry roll -> {model_name}"
    warmed = bool(getattr(target, "_warmed", False))
    t_shapes = [tuple(s) for s in getattr(target, "_warm_shapes", [])]
    if not warmed:
        diags.append(Diagnostic(
            "DL4J-W111", Severity.WARNING, loc,
            "roll planned onto a version with NO warmed buckets — every "
            "post-roll request XLA-compiles under live traffic (the "
            "cold-start a zero-drop hot-swap must not pay)",
            fix_hint="warmup([...]) the new version on the serving mesh "
                     "BEFORE roll() (ModelRegistry.load does this when "
                     "shapes are known)"))
    elif active is not None:
        a_shapes = [tuple(s) for s in getattr(active, "_warm_shapes", [])]
        missing = [s for s in a_shapes if s not in t_shapes]
        if missing:
            diags.append(Diagnostic(
                "DL4J-W111", Severity.WARNING, loc,
                f"active version serves warmed shapes {missing} the roll "
                "target never compiled — those requests hit cold XLA "
                "compiles (or shape rejection) right after the swap",
                fix_hint="warm the target with the active version's full "
                         "shape set before rolling"))
    return ValidationReport(diags, subject="registry roll")
