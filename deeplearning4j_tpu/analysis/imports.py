"""Import-time lints (E16x/W16x) — what a model loses crossing the border.

The Keras/ONNX/TF importers translate a foreign graph into SameDiff (or
a native config).  Translation is lossy in documented, statically
decidable ways, and a service admitting user-supplied models must report
those losses BEFORE the first compile — the TensorFlow-Serving posture
(PAPERS.md): reject or warn at admission, not at dispatch.  Codes:

- ``E161`` unmapped op — the importer has no builder (the import raises;
  :func:`lint_onnx_model` pre-scans so ALL unmapped ops surface at once
  instead of one raise at a time).
- ``E162`` unhonored semantics — an attribute the builder silently
  approximates (``ceil_mode`` pools, ``SAME_LOWER`` asymmetric padding).
- ``E163`` lossy narrowing — fp64 initializers demote to fp32 (x64 is
  disabled) and int64 values past the int32 range truncate.
- ``W161`` dynamic-dim placeholder — a non-batch unknown dim means one
  fresh XLA compile per distinct runtime shape (recompile churn).
- ``W162`` frozen variable — a source-graph variable imported as a
  constant while a TrainingConfig exists: ``fit()`` never updates it.
- ``W163`` const-folding overflow — folding constant subgraphs at import
  produced nonfinite floats or values past the target integer range.

Split of responsibilities: this module is **jax-free** (pinned by the
jax-blocked subprocess test) and owns the decision logic; the importers
call in with whatever they have (proto objects, folded arrays, the
finished SameDiff) and attach the resulting
:class:`~deeplearning4j_tpu.analysis.diagnostics.ValidationReport` to
the returned model as ``import_report``, which ``analyze()`` /
``sd.validate()`` then merge into the full report.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.analysis.diagnostics import (Diagnostic, Severity,
                                                     ValidationReport)
from deeplearning4j_tpu.analysis.graphir import (ONNX_DTYPE_NAMES,
                                                 WEIGHT_POSITIONS)

_INT32_MAX = 2 ** 31 - 1
_INT32_MIN = -(2 ** 31)

#: ops ``modelimport.onnx._BUILDERS`` maps (plus ``Constant``, which the
#: importer handles inline).  A jax-free mirror so the E161 pre-scan runs
#: without importing the importer; pinned against the live registry by
#: test (test_onnximport: supported-op parity).
SUPPORTED_ONNX_OPS = frozenset({
    "Constant",
    # _SIMPLE_OPS
    "Add", "Sub", "Mul", "Div", "Pow", "Max", "Min", "Neg", "Abs", "Exp",
    "Log", "Sqrt", "Reciprocal", "Floor", "Ceil", "Round", "Sign", "Relu",
    "Sigmoid", "Tanh", "Erf", "Softplus", "Softsign", "Selu", "Identity",
    "MatMul", "Sin", "Cos", "Where", "Equal", "Greater", "GreaterOrEqual",
    "Less", "LessOrEqual", "Not", "And", "Or", "GlobalAveragePool",
    "GlobalMaxPool", "Shape", "Size",
    # decorated builders
    "Gemm", "Softmax", "LogSoftmax", "LeakyRelu", "Elu", "HardSigmoid",
    "Gelu", "Clip", "Transpose", "Reshape", "Flatten", "Concat", "Squeeze",
    "Unsqueeze", "Gather", "Slice", "Cast", "Conv", "BatchNormalization",
    "Pad", "Expand", "Split", "Dropout",
    # pools + reductions
    "MaxPool", "AveragePool", "ReduceMean", "ReduceSum", "ReduceMax",
    "ReduceMin", "ReduceProd",
})

#: dtype names that lossy-narrow under jax with x64 disabled
_NARROWED = {"float64": "float32", "int64": "int32", "uint64": "uint32"}


def _attr_of(node, name):
    """NodeProto attr value by name, None when absent (duck-typed off the
    onnx_proto NodeProto: ``attrs`` dict of objects with ``.value``)."""
    a = (getattr(node, "attrs", {}) or {}).get(name)
    if a is None:
        return None
    v = getattr(a, "value", a)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


def lint_onnx_model(model, supported_ops: Optional[Iterable[str]] = None
                    ) -> ValidationReport:
    """Pre-import scan of a parsed ONNX ModelProto: E161/E162/E163/W161.

    Runs before (and independently of) the actual import — a jax-less
    admission controller can reject a model without ever building it.
    ``supported_ops`` defaults to :data:`SUPPORTED_ONNX_OPS`; the
    importer passes its live ``_BUILDERS`` registry."""
    report = ValidationReport(subject="ONNX import")
    supported = set(supported_ops) if supported_ops is not None \
        else SUPPORTED_ONNX_OPS
    g = getattr(model, "graph", model)
    if g is None:
        return report

    for node in getattr(g, "nodes", ()) or ():
        op = node.op_type
        loc = f"node '{node.name or node.outputs[0]}' ({op})"
        if op not in supported:
            report.add(Diagnostic(
                "DL4J-E161", Severity.ERROR, loc,
                f"unmapped ONNX op '{op}' — the importer has no builder "
                f"for it and importOnnxModel will raise",
                fix_hint="add a builder to modelimport.onnx._BUILDERS or "
                         "export the model without this op"))
            continue
        report.extend(_onnx_node_semantics(op, node, loc))

    init_names = set()
    for t in getattr(g, "initializers", ()) or ():
        init_names.add(t.name)
        report.extend(lint_narrowed_array(
            t.array, f"initializer '{t.name}'",
            dtype_name=ONNX_DTYPE_NAMES.get(
                getattr(t, "data_type", None))))
    for vi in getattr(g, "inputs", ()) or ():
        if vi.name in init_names:
            continue
        report.extend(lint_placeholder_shape(
            getattr(vi, "shape", None), f"graph input '{vi.name}'"))
        elem = ONNX_DTYPE_NAMES.get(getattr(vi, "elem_type", None))
        if elem in _NARROWED:
            report.add(Diagnostic(
                "DL4J-E163", Severity.ERROR, f"graph input '{vi.name}'",
                f"input dtype {elem} narrows to {_NARROWED[elem]} at "
                f"import (x64 is disabled) — values past the narrow "
                f"range truncate silently at feed time",
                fix_hint=f"export the model with {_NARROWED[elem]} "
                         f"inputs (or re-quantize the feed)"))
    return report


def _onnx_node_semantics(op: str, node, loc: str) -> List[Diagnostic]:
    """E162: attributes the builders silently approximate."""
    diags: List[Diagnostic] = []
    if op in ("MaxPool", "AveragePool") and _attr_of(node, "ceil_mode"):
        diags.append(Diagnostic(
            "DL4J-E162", Severity.ERROR, loc,
            f"{op} ceil_mode=1 is not honored — the builder always "
            f"floor-divides the output size, so the last partial window "
            f"is dropped and shapes downstream shift",
            fix_hint="re-export with ceil_mode=0 (add explicit padding "
                     "to keep the output size)"))
    if op in ("Conv", "MaxPool", "AveragePool") and \
            _attr_of(node, "auto_pad") == "SAME_LOWER":
        diags.append(Diagnostic(
            "DL4J-E162", Severity.ERROR, loc,
            f"{op} auto_pad=SAME_LOWER imports as SAME_UPPER — odd "
            f"padding lands on the opposite edge, shifting every output "
            f"by one for even kernels",
            fix_hint="re-export with explicit pads (or SAME_UPPER if the "
                     "off-by-one is acceptable)"))
    if op == "Pad":
        mode = _attr_of(node, "mode")
        if mode and str(mode) not in ("constant",):
            diags.append(Diagnostic(
                "DL4J-E162", Severity.ERROR, loc,
                f"Pad mode '{mode}' is not honored (constant-mode "
                f"padding only)",
                fix_hint="re-export with constant padding"))
    return diags


def lint_placeholder_shape(shape, loc: str) -> List[Diagnostic]:
    """W161: unknown non-batch dims force one compile per runtime shape."""
    if shape is None:
        return [Diagnostic(
            "DL4J-W161", Severity.WARNING, loc,
            "input has no static shape at all — every distinct shape fed "
            "at runtime compiles a fresh XLA executable",
            fix_hint="export with a static shape (batch may stay "
                     "dynamic), or serve through fixed bucket shapes")]
    dyn = [i for i, d in enumerate(shape)
           if i > 0 and (d is None or (isinstance(d, int) and d <= 0)
                         or isinstance(d, str))]
    if not dyn:
        return []
    return [Diagnostic(
        "DL4J-W161", Severity.WARNING, loc,
        f"non-batch dimension(s) {dyn} of shape "
        f"{[d if d else '?' for d in shape]} are dynamic — each distinct "
        f"value fed at runtime compiles a fresh XLA executable "
        f"(recompile churn)",
        fix_hint="fix the free dims at export time, or pad inputs to a "
                 "bucket ladder before feeding")]


def lint_narrowed_array(arr, loc: str,
                        dtype_name: Optional[str] = None
                        ) -> List[Diagnostic]:
    """E163 for one source array: fp64 always loses mantissa; int64 only
    matters when values actually exceed the int32 range (shape constants
    stay clean)."""
    dt = dtype_name or str(getattr(arr, "dtype", ""))
    if dt in ("float64", "double"):
        return [Diagnostic(
            "DL4J-E163", Severity.ERROR, loc,
            "float64 weights narrow to float32 at import (x64 is "
            "disabled) — the extra mantissa the exporter preserved is "
            "silently dropped",
            fix_hint="export weights as float32 (no TPU kernel runs fp64 "
                     "natively anyway), or accept the rounding and "
                     "suppress this code")]
    if dt in ("int64", "uint64"):
        try:
            a = np.asarray(arr)
            if a.size and (int(a.max(initial=0)) > _INT32_MAX
                           or int(a.min(initial=0)) < _INT32_MIN):
                return [Diagnostic(
                    "DL4J-E163", Severity.ERROR, loc,
                    f"{dt} values exceed the int32 range and truncate at "
                    f"import (x64 is disabled) — indices/ids above 2**31 "
                    f"wrap to garbage",
                    fix_hint="remap the id space below 2**31 or split "
                             "the embedding table")]
        except Exception:
            return []
    return []


def fold_overflow_diags(op: str, name: str,
                        arrays: Sequence) -> List[Diagnostic]:
    """W163 for one const-folded node's outputs: nonfinite floats (the
    fold overflowed) or integer values past the int32 range (they would
    truncate the moment a consumer lands on device)."""
    diags: List[Diagnostic] = []
    for arr in arrays:
        try:
            a = np.asarray(arr)
        except Exception:
            continue
        kind = getattr(a.dtype, "kind", "")
        if kind == "f" and a.size and not bool(np.isfinite(a).all()):
            diags.append(Diagnostic(
                "DL4J-W163", Severity.WARNING, f"folded '{name}' ({op})",
                "import-time const folding produced nonfinite values — "
                "the constant subgraph overflows before the model ever "
                "runs",
                fix_hint="check the exporter's constant arithmetic "
                         "(scale factors, epsilon placement)"))
            break
        if kind in ("i", "u") and a.dtype.itemsize > 4 and a.size and \
                (int(a.max(initial=0)) > _INT32_MAX
                 or int(a.min(initial=0)) < _INT32_MIN):
            diags.append(Diagnostic(
                "DL4J-W163", Severity.WARNING, f"folded '{name}' ({op})",
                "import-time const folding produced int64 values past "
                "the int32 range — they truncate when a consumer "
                "materializes them on device",
                fix_hint="keep the constant below 2**31 (shape math "
                         "rarely needs more)"))
            break
    return diags


def lint_frozen_constants(sd) -> List[Diagnostic]:
    """W162 at validate time: weight-position constants (imported frozen
    weights) while a TrainingConfig is attached — ``fit()`` will train
    around them without ever updating them.  Clean without a training
    config: serving a frozen import is the normal case."""
    if getattr(sd, "training_config", None) is None:
        return []
    constants = dict(getattr(sd, "_constants", {}) or {})
    if not constants:
        return []
    frozen = []
    for node in getattr(sd, "_nodes", ()) or ():
        for pos in WEIGHT_POSITIONS.get(node.op, ()):
            if pos < len(node.inputs) and node.inputs[pos] in constants:
                frozen.append((node.inputs[pos], node))
    diags: List[Diagnostic] = []
    seen = set()
    for name, node in frozen:
        if name in seen:
            continue
        seen.add(name)
        diags.append(Diagnostic(
            "DL4J-W162", Severity.WARNING,
            f"constant '{name}' (op '{node.outputs[0]}' ({node.op}))",
            "weight imported as a constant while a TrainingConfig is "
            "attached — fit() computes no gradient for it and it stays "
            "frozen at its imported value",
            fix_hint="convert it to a variable (sd.convertToVariables / "
                     "re-import with trainable weights) or drop the "
                     "TrainingConfig if this model only serves"))
    return diags


def samediff_import_report(sd) -> ValidationReport:
    """The graph-side import findings every importer shares, computed
    from the finished SameDiff: W161 on the recorded placeholders.
    Importers extend this with their format-specific findings."""
    report = ValidationReport(subject="import")
    # a placeholder nothing consumes cannot trigger a recompile — TF's
    # lowered-while graphs ship dummy 'unused_control_flow_input' feeds
    consumed = set()
    for node in getattr(sd, "_nodes", []) or []:
        consumed.update(node.inputs)
    for name, (shape, _dtype) in dict(
            getattr(sd, "_placeholders", {}) or {}).items():
        if consumed and name not in consumed:
            continue
        report.extend(lint_placeholder_shape(shape,
                                              f"placeholder '{name}'"))
    return report
