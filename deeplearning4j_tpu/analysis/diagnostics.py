"""Diagnostic model for the static analyzer.

Reference parity: the pre-init validation DL4J scatters through
``MultiLayerConfiguration.Builder.build`` / ``ComputationGraphConfiguration
.validate`` (nIn/nOut checks, duplicate-name checks, dangling-vertex
checks) — unified here into one structured diagnostic stream the way
TVM's relay type-checker and TensorFlow's pre-session graph validation
report: every finding is a ``Diagnostic(code, severity, location,
message, fix_hint)`` instead of whichever exception happens to fire
first deep inside a trace.

IMPORTANT: this module (like the whole ``analysis`` package) must not
import jax at module scope — the linter runs ahead of any compile and is
usable from environments where no accelerator stack is importable
(verified by ``tests/test_analysis.py``).
"""

from __future__ import annotations

import enum
from typing import Iterable, List, Optional


class Severity(enum.IntEnum):
    """Ordered so reports can sort most-severe first."""

    INFO = 0
    WARNING = 1
    ERROR = 2


#: The documented diagnostic codes (the README table is generated from
#: the same source). E### = configuration errors (init(strict=True)
#: raises), W0## = training-semantics warnings, W1## = TPU layout lints,
#: W2## = runtime recompile-churn findings.
DIAGNOSTIC_CODES = {
    "DL4J-E001": "nIn mismatch: a layer's declared nIn disagrees with the "
                 "propagated input size (or nIn is unresolvable because no "
                 "InputType was set)",
    "DL4J-E002": "cycle: the computation graph contains a dependency cycle",
    "DL4J-E003": "dangling/unreachable vertex: a node references an "
                 "undefined input, or does not lie on any input->output "
                 "path",
    "DL4J-E004": "duplicate name: two layers/vertices share an explicit "
                 "name",
    "DL4J-E005": "missing CNN->Dense preprocessor: a 4-D feature map feeds "
                 "a dense layer with no flatten step in between",
    "DL4J-E006": "merge-shape conflict: Merge/ElementWise vertex inputs "
                 "have incompatible shapes or kinds",
    "DL4J-E007": "shape inference failure: missing nOut, spatial underflow "
                 "(kernel larger than input), or an invalid layer geometry",
    "DL4J-E008": "missing loss head: the last layer / a graph output is "
                 "not an output or loss layer, so fit() cannot compute a "
                 "loss",
    "DL4J-W001": "loss/activation pairing: softmax with a regression loss, "
                 "or sigmoid with a multiclass cross-entropy",
    "DL4J-W002": "TBPTT configured on a network with no recurrent layers",
    "DL4J-W003": "frozen layers with a stateful updater (updater state is "
                 "allocated and carried for params that never update)",
    "DL4J-W101": "MXU padding waste: a matmul lane dim is far from the "
                 "next multiple of 128 (tiles pad to 8x128 on the MXU)",
    "DL4J-W102": "non-TPU-native dtype: float64/float16 force emulation or "
                 "silent f32 upcasts on TPU",
    "DL4J-W103": "batch size does not divide the data-parallel mesh axis, "
                 "so per-device batches would be ragged",
    "DL4J-W201": "recompile churn: one dispatch site compiled more than N "
                 "distinct jit signatures (shifting shapes/dtypes)",
    # E1xx/W10x distribution lints (analysis/distribution.py): statically
    # decidable from config + mesh declaration alone, before any compile.
    "DL4J-E101": "batch/mesh mismatch: the global batch size does not "
                 "divide the declared data-parallel mesh axis",
    "DL4J-E102": "mesh axis mismatch: a sharding rule or parallel "
                 "declaration names a mesh axis that is absent (or sized "
                 "differently than the declaration requires)",
    "DL4J-E103": "pipeline tie split: a pipeline stage boundary separates "
                 "two weight-tied layers onto different stages",
    "DL4J-E104": "HBM budget exceeded: the per-device parameter footprint "
                 "(shards + replicated tensors) exceeds the configured "
                 "per-device HBM budget",
    "DL4J-W104": "replicated giant: a large parameter tensor is fully "
                 "replicated although the mesh declares a non-trivial "
                 "model axis it could shard over",
    "DL4J-W105": "pipeline imbalance: per-stage FLOP estimates differ "
                 "beyond tolerance, so the slowest stage gates every tick",
    "DL4J-W106": "sub-MXU shard: a sharding rule splits a parameter's "
                 "lane dim below one 8x128 MXU tile per device (or leaves "
                 "it non-divisible, forcing padding)",
    "DL4J-W107": "collective volume: a single layer's estimated gradient "
                 "allreduce payload per step exceeds the threshold",
    "DL4J-W108": "input pipeline cannot feed the chip: the declared "
                 "pipeline's decode- or H2D-bound img/s (workers x "
                 "per-core decode rate, bandwidth / image bytes) is below "
                 "the model's estimated device img/s — the accelerator "
                 "idles regardless of stage overlap",
    "DL4J-W109": "replicated optimizer state: a data-parallel mesh trains "
                 "with the full updater state (Adam moments etc.) "
                 "replicated on every replica above the size threshold "
                 "and no ZeRO plan declared — cross-replica weight-update "
                 "sharding (distributed.zero.ZeroPlan) cuts per-device "
                 "optimizer HBM ~n_data x with identical math",
    # E11x/W11x serving-config lints (analysis/serving.py): validate the
    # bucket ladder x mesh x HBM budget before warmup burns the compiles.
    "DL4J-E110": "serving bucket/mesh mismatch: a batch bucket does not "
                 "divide the serving mesh's data axis, so the sharded "
                 "dispatch cannot place it",
    "DL4J-E111": "serving HBM budget exceeded: replicated params plus the "
                 "largest bucket's activation estimate exceed the "
                 "per-device budget (OOM at peak coalesced load)",
    "DL4J-W110": "serving bucket ladder: duplicate buckets or more buckets "
                 "than the threshold — each bucket x input shape is one "
                 "compiled program (warmup time, executable-cache HBM)",
    "DL4J-W111": "registry roll without warmed buckets: the hot-swap "
                 "target version was never warmed (or misses shapes the "
                 "active version serves warm), so post-roll traffic "
                 "XLA-compiles under live load",
    "DL4J-W112": "serving warmup without a persistent compile cache: no "
                 "DL4J_TPU_COMPILE_CACHE_DIR / compilecache.configure() "
                 "directory is set (or the directory is unwritable), so "
                 "every fresh process, rollout, and hot-swap staging pays "
                 "full XLA compile instead of a disk hit",
    "DL4J-W113": "lifecycle observation window shorter than the SLO fast "
                 "window: the canary judge's burn-rate lookback cannot "
                 "contain even one fast-window reference sample, so every "
                 "canary verdict reads a burn of ~0 and promotes blind",
    "DL4J-W114": "canary fraction below routing resolution: fraction x "
                 "expected-requests-per-tick rounds to zero canary-routed "
                 "requests per observation tick (or the fraction is so "
                 "small the smallest batch bucket never fills), so the "
                 "observation window measures the incumbent, not the "
                 "canary",
    # E12x/W12x static cost-model lints (analysis/cost.py): liveness-aware
    # HBM planning, roofline step-time/MFU prediction, fleet capacity.
    "DL4J-E120": "training step-peak HBM overflow: the liveness-aware "
                 "high-water mark (params + grads + fp32 masters + updater "
                 "state + live backward activations + megastep staging + "
                 "prefetch) exceeds the chip's per-device HBM — the "
                 "message names the dominating liveness component, which "
                 "params-only accounting (E104) would have missed",
    "DL4J-E121": "serving-bucket peak HBM overflow: replicated params plus "
                 "the largest bucket's liveness-aware activation peak "
                 "exceed the chip's per-device HBM at peak coalesced load",
    "DL4J-E122": "fleet capacity shortfall: at the predicted per-replica "
                 "throughput the declared replica count cannot sustain the "
                 "declared QPS (or the predicted per-request latency "
                 "already exceeds the p99 budget on an idle replica) — the "
                 "message names the minimal replica count that can",
    "DL4J-W120": "rematerialization opportunity: live backward activations "
                 "dominate the step-peak HBM high-water mark and the peak "
                 "sits near the chip's budget — recomputing activations "
                 "in the backward pass trades cheap FLOPs for the "
                 "dominating memory term",
    "DL4J-W121": "comms-bound step: predicted gradient-collective time "
                 "over the declared ICI bandwidth exceeds half the "
                 "predicted step time, so scaling the data axis further "
                 "buys little — larger per-device batch, gradient "
                 "accumulation, or precision-reduced collectives move the "
                 "roofline",
    "DL4J-W122": "predicted MFU below target: the roofline step-time "
                 "estimate puts model FLOP utilization under the declared "
                 "mfu_target on the declared chip — the message names the "
                 "binding resource (compute, HBM bandwidth, or "
                 "collectives)",
    # E2xx/W21x concurrency lints (analysis/concurrency.py): AST-level
    # thread-safety analysis of the framework's own (or user) source.
    "DL4J-E201": "unguarded cross-thread mutation: an attribute (or a "
                 "module global shared via threading.Thread(target=fn)) "
                 "is assigned/mutated outside any lock, so other threads "
                 "can observe or clobber intermediate state",
    "DL4J-E202": "unguarded read-modify-write: `self.x += 1` (or an "
                 "equivalent read-then-assign, incl. on module globals) "
                 "on shared state outside any lock — two racing writers "
                 "lose one update (the lost-increment class)",
    "DL4J-E203": "lock-order cycle: the static lock-acquisition graph "
                 "contains a cycle, so two threads taking the locks in "
                 "opposite orders deadlock",
    "DL4J-W210": "wall clock in deadline arithmetic: time.time() (which "
                 "NTP can step) feeds timeout/deadline math — use "
                 "time.monotonic() for durations",
    "DL4J-W211": "Condition.wait() outside a predicate loop: spurious "
                 "wakeups / stolen notifications return with the "
                 "condition still false",
    "DL4J-W212": "unjoined worker thread: a stored thread is started but "
                 "no close/drain path joins it, racing shutdown against "
                 "its last writes",
    "DL4J-W213": "double-checked/lazy initialization race: `if self.x is "
                 "None: self.x = ...` without holding a lock (or without "
                 "re-checking under it) lets two threads both initialize",
    "DL4J-E299": "unparseable source: the concurrency analyzer could not "
                 "parse this file, so none of its classes were checked — "
                 "a distinct code so suppressing a real finding family "
                 "never hides a syntax error",
    # E3xx/W30x numerics & precision lints (analysis/numerics.py):
    # dtype-flow + dynamic-range analysis under a PrecisionPolicy and an
    # optional DataRangeSpec input declaration, before any compile.
    "DL4J-E301": "precision-policy conflict: a low-precision stateful "
                 "updater without fp32 master params (moments overflow "
                 "or round to nothing), or a per-layer dtype override "
                 "contradicting the declared policy",
    "DL4J-E302": "precision-unsafe accumulation: softmax / large-axis "
                 "mean-variance reductions / a loss head accumulating "
                 "in the low-precision compute dtype with no fp32 "
                 "island",
    "DL4J-E303": "dynamic-range overflow: float16 compute without loss "
                 "scaling, or a declared input range whose gradient / "
                 "second-moment magnitude estimate exceeds what the "
                 "dtype x updater combination tolerates (the raw-pixel "
                 "Adam-overflow class)",
    "DL4J-W301": "redundant cast churn: a non-island fp32 override "
                 "sandwiched between low-precision layers bounces "
                 "activations dtype->fp32->dtype at both boundaries "
                 "every step",
    "DL4J-W302": "loss-scaling misconfiguration: a scale where the "
                 "compute dtype does not need one (bf16/fp32), a scale "
                 "< 1, or one large enough to overflow the scaled loss "
                 "itself",
    "DL4J-W303": "unnormalized input: a declared [0, 255]-style range "
                 "with no normalizer attached and no normalization "
                 "layer first in the net",
    # E15x/W15x SameDiff graph lints (analysis/samediff.py).
    "DL4J-E151": "undefined graph input: an op node consumes a name no "
                 "variable, constant, placeholder, or node output defines",
    "DL4J-E152": "graph shape conflict: static shape propagation over the "
                 "recorded op graph found incompatible operand shapes",
    "DL4J-E153": "bad loss variable: setLossVariables names a variable "
                 "that does not exist in the graph",
    "DL4J-W151": "dangling placeholder: a placeholder no recorded op "
                 "consumes (every output() still requires feeding it)",
    "DL4J-W152": "unused variable: a trainable variable no loss output "
                 "depends on (it gets zero gradient every step)",
    "DL4J-W153": "no training op: a TrainingConfig is set but no loss "
                 "variables are marked, so fit() has nothing to minimize",
    # E16x/W16x import-time lints (analysis/imports.py, emitted by the
    # Keras/ONNX/TF importers into the returned model's import_report).
    "DL4J-E161": "unmapped import op: the source graph uses an op the "
                 "importer has no builder for — the import raises (or "
                 "the pre-scan reports every such op up front)",
    "DL4J-E162": "unhonored import semantics: an attribute/opset detail "
                 "the builder cannot reproduce exactly (ceil_mode pools, "
                 "SAME_LOWER asymmetric padding, ...) — results will "
                 "differ from the source framework",
    "DL4J-E163": "lossy import narrowing: an initializer or input dtype "
                 "is narrowed at import (fp64 weights -> fp32, int64 "
                 "indices -> int32) and large values would truncate",
    "DL4J-W161": "dynamic-dim placeholder: a non-batch dimension is "
                 "unknown at import, so every distinct shape fed at "
                 "runtime compiles a fresh executable (recompile churn)",
    "DL4J-W162": "frozen variable: a source-graph variable imported as a "
                 "constant while a TrainingConfig exists — fit() will "
                 "never update it",
    "DL4J-W163": "import const-folding overflow: folding constant "
                 "subgraphs at import produced nonfinite floats or "
                 "values past the target integer range",
}


def normalize_code(code: str) -> str:
    """Accept both spellings everywhere codes are configured:
    ``"W101"``/``"w101"`` and the full ``"DL4J-W101"``."""
    code = str(code).strip().upper()
    if not code.startswith("DL4J-"):
        code = "DL4J-" + code
    if code not in DIAGNOSTIC_CODES:
        raise ValueError(f"unknown diagnostic code {code!r} (documented: "
                         f"{', '.join(sorted(DIAGNOSTIC_CODES))})")
    return code


def _normalize_severity(value) -> "Severity":
    if isinstance(value, Severity):
        return value
    try:
        return Severity[str(value).strip().upper()]
    except KeyError:
        raise ValueError(f"unknown severity {value!r} (use one of "
                         f"{[s.name.lower() for s in Severity]})") from None


class Diagnostic:
    """One structured finding from the analyzer or the churn detector."""

    __slots__ = ("code", "severity", "location", "message", "fix_hint")

    def __init__(self, code: str, severity: Severity, location: str,
                 message: str, fix_hint: Optional[str] = None):
        if code not in DIAGNOSTIC_CODES:
            raise ValueError(f"undocumented diagnostic code {code!r}")
        self.code = code
        self.severity = Severity(severity)
        self.location = location
        self.message = message
        self.fix_hint = fix_hint

    def format(self) -> str:
        line = (f"{self.code} {self.severity.name.lower():<7} "
                f"[{self.location}] {self.message}")
        if self.fix_hint:
            line += f"\n    fix: {self.fix_hint}"
        return line

    def __repr__(self):
        return (f"Diagnostic({self.code}, {self.severity.name}, "
                f"{self.location!r}, {self.message!r})")


class ValidationReport:
    """Ordered collection of diagnostics with severity accessors."""

    def __init__(self, diagnostics: Iterable[Diagnostic] = (),
                 subject: str = ""):
        self.subject = subject
        self.diagnostics: List[Diagnostic] = list(diagnostics)

    def add(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def extend(self, diags: Iterable[Diagnostic]) -> None:
        self.diagnostics.extend(diags)

    def apply_config(self, suppress: Iterable[str] = None,
                     severity_overrides=None) -> "ValidationReport":
        """Per-code report shaping (the flake8-noqa equivalent for model
        lints): drop every diagnostic whose code is in ``suppress``, and
        re-grade codes named in ``severity_overrides`` ({code: severity},
        severity as a :class:`Severity` or its name). Codes accept both
        the short (``"W101"``) and full (``"DL4J-W101"``) spelling.
        Mutates and returns the report (so ``validate(...)`` chains)."""
        if suppress:
            if isinstance(suppress, str):
                suppress = [suppress]
            dropped = {normalize_code(c) for c in suppress}
            self.diagnostics = [d for d in self.diagnostics
                                if d.code not in dropped]
        if severity_overrides:
            remap = {normalize_code(c): _normalize_severity(s)
                     for c, s in dict(severity_overrides).items()}
            for d in self.diagnostics:
                if d.code in remap:
                    d.severity = remap[d.code]
        return self

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.ERROR]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.WARNING]

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def ok(self, warnings_as_errors: bool = False) -> bool:
        if self.errors():
            return False
        return not (warnings_as_errors and self.warnings())

    def raise_if_errors(self) -> "ValidationReport":
        if self.errors():
            raise ModelValidationError(self)
        return self

    def format(self) -> str:
        head = self.subject or "model"
        if not self.diagnostics:
            return f"{head}: clean (0 errors, 0 warnings)"
        lines = [f"{head}: {len(self.errors())} error(s), "
                 f"{len(self.warnings())} warning(s)"]
        for d in sorted(self.diagnostics, key=lambda d: -int(d.severity)):
            lines.append("  " + d.format().replace("\n", "\n  "))
        return "\n".join(lines)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __str__(self):
        return self.format()

    def __repr__(self):
        return (f"ValidationReport({self.subject!r}, "
                f"errors={len(self.errors())}, "
                f"warnings={len(self.warnings())})")


class ModelValidationError(ValueError):
    """Raised by ``init(strict=True)`` / ``raise_if_errors`` on E-codes."""

    def __init__(self, report: ValidationReport):
        self.report = report
        super().__init__(report.format())
