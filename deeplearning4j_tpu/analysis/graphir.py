"""Analysis IR — typed tensor/op facts for lint parity across model kinds.

Native configs get ~40 codes across five analyzer families because every
pass reads declared facts (``param_shapes``, ``output_type``, dtype
hooks).  Recorded SameDiff graphs — including everything the Keras/ONNX/
TF importers produce — only carried the structural E15x pass, because no
equivalent fact surface existed.  This module is that surface: a jax-free
IR of

- :class:`TensorFact` — shape, dtype, kind (param / const / placeholder /
  activation), producer and consumer edges, weight-position flag;
- :class:`OpFact` — op name, operands, attrs, and a per-op FLOP estimate;

with two lowerings.  :func:`from_samediff` walks a recorded ``_Node``
graph, extending the E15x shape rules with rules for the importers'
namespaced ops (``onnx.Conv``, ``tf.MatMul``, ...) and per-op **dtype**
rules; unknown ops degrade gracefully to unknown facts, never to a
crash.  :func:`from_multilayer` lowers a native sequential config to the
same facts (the parity adapter: tests pin that both lowerings agree with
the distribution pass's own accounting).

The lint drivers at the bottom run the existing families over the IR —
layout (W101/W102/W103), distribution (E101/E102/E104/W104–W107 via
``distribution.lint_entries`` over per-op fact bundles), numerics
(E301–E303/W301–W303 via dtype-flow over IR edges) — so ``sd.validate
(mesh=..., policy=..., data_range=...)`` emits the same codes a native
config would.

No jax import anywhere in this module (pinned by the jax-blocked
subprocess test): array facts are duck-typed off ``.shape``/``.dtype``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.analysis import distribution as _dist
from deeplearning4j_tpu.analysis import layout as _layout
from deeplearning4j_tpu.analysis.diagnostics import Diagnostic, Severity
from deeplearning4j_tpu.analysis.numerics import (
    REDUCTION_AXIS_THRESHOLD, SOFTMAX_AXIS_THRESHOLD,
    UNNORMALIZED_THRESHOLD, _SATURATING, _SQUARING_UPDATERS, DataRangeSpec,
    _lint_loss_scaling)
from deeplearning4j_tpu.analysis.samediff import (Shape, _infer,
                                                  _normalize_ph_shape)
from deeplearning4j_tpu.nn.precision import (DTYPE_MAX, LOW_PRECISION,
                                             PrecisionPolicy)

#: tensor kinds — ``param`` is trainable (SameDiff ``_variables`` / native
#: layer params), ``const`` covers initializers/frozen weights.
KINDS = ("param", "const", "placeholder", "activation")

#: operand positions that hold weights, per op: the classifier that makes
#: an IMPORTED graph's frozen initializers count as parameters for the
#: layout/distribution accounting (and feed the W162 frozen-variable
#: lint).  Index is into the recorded node's input list.
WEIGHT_POSITIONS: Dict[str, Tuple[int, ...]] = {
    "matmul": (1,), "xw_plus_b": (1, 2), "relu_layer": (1, 2),
    "onnx.MatMul": (1,), "onnx.Gemm": (1, 2), "onnx.Conv": (1, 2),
    "onnx.BatchNormalization": (1, 2, 3, 4),
    "tf.MatMul": (1,), "tf.Conv2D": (1,), "tf.DepthwiseConv2dNative": (1,),
    "tf.BiasAdd": (1,), "tf.FusedBatchNormV3": (1, 2, 3, 4),
}

#: conv-family ops: their weight lane dims get the conv-aware W101 text.
CONV_OPS = frozenset({"onnx.Conv", "tf.Conv2D", "tf.DepthwiseConv2dNative"})

_NORMALIZING_OPS = frozenset({
    "layer_norm", "batchnorm_sd", "onnx.BatchNormalization",
    "tf.FusedBatchNormV3", "tf.FusedBatchNorm",
})

_SOFTMAX_OPS = frozenset({"softmax", "log_softmax", "onnx.Softmax",
                          "onnx.LogSoftmax", "tf.Softmax"})

_REDUCTION_OPS = frozenset({
    "reduce_sum", "reduce_mean", "onnx.ReduceSum", "onnx.ReduceMean",
    "tf.Sum", "tf.Mean",
})

_LOSS_OPS = frozenset({
    "mean_sqerr_loss", "softmax_cross_entropy_loss",
    "sigmoid_cross_entropy_loss", "absolute_difference_loss",
    "cosine_distance_loss", "hinge_loss", "huber_loss", "log_loss",
    "sparse_softmax_cross_entropy_loss",
})

_CAST_OPS = frozenset({"cast", "onnx.Cast", "tf.Cast"})

#: activations recorded under their op name whose output magnitude
#: saturates to ~1 (mirrors numerics._SATURATING for the conf pass)
_SATURATING_OPS = frozenset(
    {n for n in _SATURATING} |
    {"onnx.Sigmoid", "onnx.Tanh", "onnx.Softmax", "onnx.HardSigmoid",
     "tf.Sigmoid", "tf.Tanh", "tf.Softmax"})

#: ONNX TensorProto data-type codes -> dtype names (local copy so this
#: module never imports modelimport; pinned against onnx_proto by test)
ONNX_DTYPE_NAMES = {
    1: "float32", 2: "uint8", 3: "int8", 4: "uint16", 5: "int16",
    6: "int32", 7: "int64", 9: "bool", 10: "float16", 11: "float64",
    12: "uint32", 13: "uint64", 16: "bfloat16",
}


class TensorFact:
    """Static facts about one graph tensor."""

    __slots__ = ("name", "shape", "dtype", "kind", "producer", "consumers",
                 "weight_of")

    def __init__(self, name: str, shape: Shape, dtype: Optional[str],
                 kind: str, producer: Optional[int] = None):
        self.name = name
        self.shape = shape
        self.dtype = dtype
        self.kind = kind
        self.producer = producer      # op index, None for graph inputs
        self.consumers: List[int] = []
        #: op index this tensor is a weight operand of (param-vs-activation
        #: classification for consts: frozen imported weights land here)
        self.weight_of: Optional[int] = None

    @property
    def is_weight(self) -> bool:
        return self.kind == "param" or self.weight_of is not None

    def size_known(self) -> bool:
        return self.shape is not None and None not in self.shape

    def __repr__(self):
        return (f"TensorFact({self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, kind={self.kind})")


class OpFact:
    """Static facts about one graph op."""

    __slots__ = ("index", "op", "name", "inputs", "outputs", "attrs",
                 "flops")

    def __init__(self, index: int, op: str, name: str,
                 inputs: Tuple[str, ...], outputs: Tuple[str, ...],
                 attrs: Dict[str, Any], flops: int = 0):
        self.index = index
        self.op = op
        self.name = name
        self.inputs = tuple(inputs)
        self.outputs = tuple(outputs)
        self.attrs = dict(attrs or {})
        self.flops = int(flops)

    @property
    def location(self) -> str:
        return f"op '{self.name}' ({self.op})"

    def __repr__(self):
        return f"OpFact({self.index}, {self.op!r}, {self.name!r})"


class GraphIR:
    """The lowered graph: tensor facts + op facts + training context."""

    __slots__ = ("tensors", "ops", "subject", "batch_size", "updater",
                 "loss_variables")

    def __init__(self, subject: str, batch_size: int = 1):
        self.tensors: Dict[str, TensorFact] = {}
        self.ops: List[OpFact] = []
        self.subject = subject
        self.batch_size = int(batch_size or 1)
        self.updater = None           # the TrainingConfig's updater, if any
        self.loss_variables: List[str] = []

    def weights(self) -> List[TensorFact]:
        """Params + weight-position consts, in definition order."""
        return [t for t in self.tensors.values() if t.is_weight]

    def placeholders(self) -> List[TensorFact]:
        return [t for t in self.tensors.values()
                if t.kind == "placeholder"]

    def total_flops(self) -> int:
        return sum(op.flops for op in self.ops)


# ------------------------------------------------------- shape/dtype rules

def _attr_params(attrs: Dict) -> Dict:
    return dict(attrs.get("params") or {})


def _conv_spatial(dim, k, stride, pad_lo, pad_hi, dilation):
    if dim is None:
        return None
    eff = (k - 1) * dilation + 1
    return max((dim + pad_lo + pad_hi - eff) // stride + 1, 0)


def _rule_onnx_conv(ins: List[Shape], attrs: Dict):
    x, w = (list(ins) + [None, None])[:2]
    if w is None or x is None or len(x) < 3:
        return [None]
    p = _attr_params(attrs)
    n_spatial = len(x) - 2
    out = [x[0], w[0]]
    if str(p.get("auto_pad", "NOTSET")).startswith("SAME"):
        strides = list(p.get("strides") or [1] * n_spatial)
        for i in range(n_spatial):
            d = x[2 + i]
            out.append(None if d is None
                       else -(-d // strides[i]))      # ceil-div
        return [tuple(out)]
    kernel = list(p.get("kernel_shape") or
                  (list(w[2:]) if len(w) > 2 else []))
    if len(kernel) != n_spatial:
        return [tuple(out) + (None,) * n_spatial]
    strides = list(p.get("strides") or [1] * n_spatial)
    dil = list(p.get("dilations") or [1] * n_spatial)
    pads = list(p.get("pads") or [0] * (2 * n_spatial))
    for i in range(n_spatial):
        out.append(_conv_spatial(x[2 + i], kernel[i], strides[i],
                                 pads[i], pads[n_spatial + i], dil[i]))
    return [tuple(out)]


def _rule_onnx_pool(ins: List[Shape], attrs: Dict):
    x = ins[0] if ins else None
    if x is None or len(x) < 3:
        return [None]
    p = _attr_params(attrs)
    kernel = list(p.get("kernel_shape") or [])
    n_spatial = len(x) - 2
    out = [x[0], x[1]]
    if len(kernel) != n_spatial:
        return [tuple(out) + (None,) * n_spatial]
    strides = list(p.get("strides") or [1] * n_spatial)
    pads = list(p.get("pads") or [0] * (2 * n_spatial))
    for i in range(n_spatial):
        out.append(_conv_spatial(x[2 + i], kernel[i], strides[i],
                                 pads[i], pads[n_spatial + i], 1))
    return [tuple(out)]


def _rule_onnx_global_pool(ins: List[Shape], attrs: Dict):
    x = ins[0] if ins else None
    if x is None or len(x) < 3:
        return [None]
    return [tuple(x[:2]) + (1,) * (len(x) - 2)]


def _rule_onnx_gemm(ins: List[Shape], attrs: Dict):
    a, b = (list(ins) + [None, None])[:2]
    if a is None or b is None or len(a) != 2 or len(b) != 2:
        return [None]
    p = _attr_params(attrs)
    m = a[1] if p.get("transA") else a[0]
    n = b[0] if p.get("transB") else b[1]
    return [(m, n)]


def _rule_onnx_flatten(ins: List[Shape], attrs: Dict):
    x = ins[0] if ins else None
    if x is None:
        return [None]
    axis = int(_attr_params(attrs).get("axis", 1)) % (len(x) + 1)

    def prod(dims):
        r = 1
        for d in dims:
            if d is None:
                return None
            r *= d
        return r
    return [(prod(x[:axis]), prod(x[axis:]))]


def _rule_onnx_reshape(ins: List[Shape], attrs: Dict):
    shape = _attr_params(attrs).get("shape")
    if shape is None:
        return [None]
    return [tuple(None if int(d) in (-1, 0) else int(d) for d in shape)]


def _rule_onnx_transpose(ins: List[Shape], attrs: Dict):
    x = ins[0] if ins else None
    if x is None:
        return [None]
    perm = _attr_params(attrs).get("perm")
    if not perm:
        return [tuple(reversed(x))]
    if len(perm) != len(x):
        return [None]
    return [tuple(x[p] for p in perm)]


def _rule_onnx_concat(ins: List[Shape], attrs: Dict):
    known = [s for s in ins if s is not None]
    if not known or any(s is None for s in ins):
        return [None]
    axis = int(_attr_params(attrs).get("axis", 0)) % len(known[0])
    out = list(known[0])
    total = 0
    for s in known:
        if s[axis] is None:
            total = None
            break
        total += s[axis]
    out[axis] = total
    return [tuple(out)]


def _rule_onnx_reduce(ins: List[Shape], attrs: Dict):
    x = ins[0] if ins else None
    if x is None:
        return [None]
    p = _attr_params(attrs)
    axes = p.get("axes")
    keep = bool(p.get("keepdims", 1))
    if axes is None:
        return [((1,) * len(x)) if keep else ()]
    axes = [int(a) % len(x) for a in axes]
    if keep:
        return [tuple(1 if i in axes else d for i, d in enumerate(x))]
    return [tuple(d for i, d in enumerate(x) if i not in axes)]


def _rule_binary(ins: List[Shape], attrs: Dict):
    out, _err = _infer("add", list(ins[:2]), {})
    return out


def _rule_passthrough(ins: List[Shape], attrs: Dict):
    return [ins[0] if ins else None]


def _rule_tf_matmul(ins: List[Shape], attrs: Dict):
    p = _attr_params(attrs)
    out, _err = _infer("matmul", list(ins[:2]),
                       {"transpose_a": p.get("transpose_a"),
                        "transpose_b": p.get("transpose_b")})
    return out


def _rule_tf_conv2d(ins: List[Shape], attrs: Dict):
    # TF convs import NHWC-only; W is (kh, kw, C, M)
    x, w = (list(ins) + [None, None])[:2]
    if x is None or w is None or len(x) != 4 or len(w) != 4:
        return [None]
    p = _attr_params(attrs)
    strides = p.get("strides") or (1, 1)
    if isinstance(strides, (list, tuple)) and len(strides) == 4:
        strides = strides[1:3]
    same = str(p.get("padding", "SAME")).upper().startswith("SAME")
    out_sp = []
    for i in range(2):
        d = x[1 + i]
        if d is None:
            out_sp.append(None)
        elif same:
            out_sp.append(-(-d // strides[i]))
        else:
            out_sp.append(_conv_spatial(d, w[i], strides[i], 0, 0, 1))
    return [(x[0], out_sp[0], out_sp[1], w[3])]


_IMPORT_SHAPE_RULES = {
    "onnx.Conv": _rule_onnx_conv,
    "onnx.MaxPool": _rule_onnx_pool, "onnx.AveragePool": _rule_onnx_pool,
    "onnx.GlobalAveragePool": _rule_onnx_global_pool,
    "onnx.GlobalMaxPool": _rule_onnx_global_pool,
    "onnx.Gemm": _rule_onnx_gemm,
    "onnx.Flatten": _rule_onnx_flatten,
    "onnx.Reshape": _rule_onnx_reshape,
    "onnx.Transpose": _rule_onnx_transpose,
    "onnx.Concat": _rule_onnx_concat,
    "onnx.ReduceSum": _rule_onnx_reduce, "onnx.ReduceMean": _rule_onnx_reduce,
    "onnx.ReduceMax": _rule_onnx_reduce, "onnx.ReduceMin": _rule_onnx_reduce,
    "tf.MatMul": _rule_tf_matmul,
    "tf.Conv2D": _rule_tf_conv2d,
}

_IMPORT_PASSTHROUGH = frozenset({
    "onnx.Relu", "onnx.LeakyRelu", "onnx.Elu", "onnx.Sigmoid", "onnx.Tanh",
    "onnx.Softmax", "onnx.LogSoftmax", "onnx.HardSigmoid", "onnx.Gelu",
    "onnx.Clip", "onnx.Cast", "onnx.Identity", "onnx.Dropout", "onnx.Erf",
    "onnx.Sqrt", "onnx.Exp", "onnx.Log", "onnx.Neg", "onnx.Abs",
    "onnx.BatchNormalization",
    "tf.Relu", "tf.Relu6", "tf.Sigmoid", "tf.Tanh", "tf.Softmax",
    "tf.Identity", "tf.BiasAdd", "tf.Cast", "tf.FusedBatchNormV3",
    "tf.LeakyRelu", "tf.Elu", "tf.Sqrt", "tf.Exp", "tf.Log", "tf.Neg",
    "tf.Abs", "tf.Rsqrt",
})

_IMPORT_BINARY = frozenset({
    "onnx.Add", "onnx.Sub", "onnx.Mul", "onnx.Div", "onnx.Pow",
    "onnx.Min", "onnx.Max", "onnx.Greater", "onnx.Less", "onnx.Equal",
    "tf.Add", "tf.AddV2", "tf.Sub", "tf.Mul", "tf.RealDiv", "tf.Maximum",
    "tf.Minimum", "tf.Pow", "tf.Greater", "tf.Less", "tf.Equal",
    "tf.SquaredDifference",
})


def infer_shapes(op: str, in_shapes: List[Shape], attrs: Dict,
                 n_out: int = 1) -> List[Shape]:
    """Shape rule dispatch: native rules (analysis.samediff) for native
    ops, the import tables for namespaced ops, unknown degrades to
    ``[None] * n_out`` — never raises."""
    try:
        if "." in op:
            rule = _IMPORT_SHAPE_RULES.get(op)
            if rule is not None:
                out = rule(in_shapes, attrs)
            elif op in _IMPORT_PASSTHROUGH:
                out = _rule_passthrough(in_shapes, attrs)
            elif op in _IMPORT_BINARY:
                out = _rule_binary(in_shapes, attrs)
            else:
                out = [None]
        else:
            out, _err = _infer(op, list(in_shapes), attrs)
    except Exception:
        out = [None]
    out = list(out or [None])
    if len(out) < n_out:
        out += [out[0]] * (n_out - len(out))
    return out[:n_out]


_BOOL_OPS = frozenset({"greater", "less", "greater_equal", "less_equal",
                       "equals", "not_equals", "onnx.Greater", "onnx.Less",
                       "onnx.Equal", "tf.Greater", "tf.Less", "tf.Equal"})
_INDEX_OPS = frozenset({"argmax", "argmin", "onnx.ArgMax", "onnx.ArgMin",
                        "tf.ArgMax", "tf.ArgMin"})

_FLOAT_ORDER = ("float64", "float32", "bfloat16", "float16")


def infer_dtype(op: str, in_dtypes: List[Optional[str]],
                attrs: Dict) -> Optional[str]:
    """Per-op dtype rule (the PR-11 follow-up): casts read their target,
    comparisons produce bool, arg-reductions produce int32, everything
    else promotes across its known operand dtypes; unknown stays
    unknown."""
    if op in _CAST_OPS:
        if op == "cast":
            return attrs.get("dtype")
        p = _attr_params(attrs)
        if op == "onnx.Cast":
            return ONNX_DTYPE_NAMES.get(int(p.get("to", -1)))
        target = p.get("dtype") or p.get("DstT")
        return str(target) if target is not None else None
    if op in _BOOL_OPS:
        return "bool"
    if op in _INDEX_OPS:
        return "int32"
    known = [d for d in in_dtypes if d]
    if not known:
        return None
    floats = [d for d in known if d in _FLOAT_ORDER]
    if floats:
        for name in _FLOAT_ORDER:     # widest float present wins
            if name in floats:
                return name
    return known[0]


def _op_flops(op: str, in_shapes: List[Shape], out_shape: Shape,
              attrs: Dict) -> int:
    """Per-op FLOP estimate: 2 FLOPs per MAC for the matmul/conv family,
    0 for everything else (the same dominant-term model the native
    distribution pass uses)."""
    def prod(dims):
        r = 1
        for d in dims or ():
            if d is None or d <= 0:
                return 0
            r *= d
        return r

    try:
        if op in ("matmul", "onnx.MatMul", "onnx.Gemm", "tf.MatMul",
                  "xw_plus_b", "relu_layer"):
            a, b = (list(in_shapes) + [None, None])[:2]
            if a is None or b is None or len(a) < 2 or len(b) < 2:
                return 0
            k = a[-1] or b[-2] or b[-1] or 0
            return 2 * prod(out_shape) * int(k)
        if op in CONV_OPS:
            w = in_shapes[1] if len(in_shapes) > 1 else None
            if w is None or out_shape is None:
                return 0
            # per output element: one MAC per kernel element x in-channels
            per_out = prod(w[1:]) if op == "onnx.Conv" else prod(w[:3])
            return 2 * prod(out_shape) * per_out
    except Exception:
        return 0
    return 0


# ------------------------------------------------------------- lowerings

def _arr_shape(arr) -> Shape:
    shape = getattr(arr, "shape", None)
    return tuple(int(d) for d in shape) if shape is not None else None


def _arr_dtype(arr) -> Optional[str]:
    dt = getattr(arr, "dtype", None)
    return str(dt) if dt is not None else None


def from_samediff(sd, batch_size: int = 1) -> GraphIR:
    """Lower a recorded SameDiff graph (native or imported) to the IR.

    Creation order is execution order (the ``_record_fn`` contract), so
    one forward walk resolves every fact.  Unknown ops produce unknown
    shapes/dtypes; nothing here raises on a malformed graph — the E15x
    structural pass owns error reporting."""
    ir = GraphIR(subject="SameDiff", batch_size=batch_size)
    tc = getattr(sd, "training_config", None)
    ir.updater = getattr(tc, "updater", None) if tc is not None else None
    ir.loss_variables = list(getattr(sd, "_loss_variables", ()) or ())

    for name, arr in dict(getattr(sd, "_variables", {}) or {}).items():
        ir.tensors[name] = TensorFact(name, _arr_shape(arr),
                                      _arr_dtype(arr), "param")
    for name, arr in dict(getattr(sd, "_constants", {}) or {}).items():
        ir.tensors[name] = TensorFact(name, _arr_shape(arr),
                                      _arr_dtype(arr), "const")
    for name, (shape, dtype) in dict(
            getattr(sd, "_placeholders", {}) or {}).items():
        try:
            dt = np.dtype(dtype).name if dtype is not None else None
        except Exception:
            dt = str(dtype) if dtype is not None else None
        ir.tensors[name] = TensorFact(
            name, _normalize_ph_shape(shape, batch_size), dt, "placeholder")

    for idx, node in enumerate(getattr(sd, "_nodes", ()) or ()):
        attrs = dict(getattr(node, "attrs", {}) or {})
        in_shapes: List[Shape] = []
        in_dtypes: List[Optional[str]] = []
        for pos, ref in enumerate(node.inputs):
            t = ir.tensors.get(ref)
            if t is None:       # E151 territory — degrade, don't crash
                in_shapes.append(None)
                in_dtypes.append(None)
                continue
            t.consumers.append(idx)
            if t.kind in ("param", "const") and \
                    pos in WEIGHT_POSITIONS.get(node.op, ()) and \
                    t.weight_of is None:
                t.weight_of = idx
            in_shapes.append(t.shape)
            in_dtypes.append(t.dtype)
        out_shapes = infer_shapes(node.op, in_shapes, attrs,
                                  n_out=len(node.outputs))
        out_dtype = infer_dtype(node.op, in_dtypes, attrs)
        for i, out in enumerate(node.outputs):
            ir.tensors[out] = TensorFact(
                out, out_shapes[i] if i < len(out_shapes) else None,
                out_dtype, "activation", producer=idx)
        name = node.outputs[0] if node.outputs else f"#{idx}"
        ir.ops.append(OpFact(
            idx, node.op, name, tuple(node.inputs), tuple(node.outputs),
            attrs, flops=_op_flops(node.op, in_shapes,
                                   out_shapes[0] if out_shapes else None,
                                   attrs)))
    return ir


def _type_shape(it, batch_size: int) -> Shape:
    """``(batch,) + positive declared dims`` from an InputType, None when
    the type (or any dim) is unknown — the activation-byte fact the cost
    model's liveness pass reads."""
    if it is None:
        return None
    dims = [int(v) for v in getattr(it, "dims", {}).values()
            if isinstance(v, (int, float)) and v > 0]
    if not dims:
        return None
    return (int(batch_size),) + tuple(dims)


def from_multilayer(conf, batch_size: int = 1) -> GraphIR:
    """Lower a native sequential config to the same facts — the parity
    adapter: param names/shapes match ``distribution._param_facts`` and
    per-layer FLOPs match ``distribution._approx_flops``, pinned by
    test."""
    ir = GraphIR(subject="MultiLayerConfiguration", batch_size=batch_size)
    base = getattr(conf, "base", None)
    ir.updater = getattr(base, "updater", None)
    dtype = getattr(base, "dtype", None)
    dt = str(dtype) if dtype is not None else "float32"
    types = _dist._propagate_types(conf)
    prev_out = "input"
    it0 = getattr(conf, "input_type", None)
    ir.tensors["input"] = TensorFact(
        "input", _type_shape(it0, batch_size), dt, "placeholder")
    seen_names: Dict[str, int] = {}
    for idx, layer in enumerate(getattr(conf, "layers", ()) or ()):
        lname = getattr(layer, "name", None) or type(layer).__name__
        # repeated default-named layers must not collide in the tensor
        # dict (the liveness/byte accounting would silently drop them) —
        # disambiguate with the layer index, matching nothing less
        # specific than the class-name prefix sharding regexes target
        if lname in seen_names:
            lname = f"{lname}_{idx}"
        seen_names[lname] = idx
        shapes = getattr(layer, "param_shapes", lambda: {})()
        pnames = []
        for pname, shape in (shapes or {}).items():
            if not shape or any(not d or d < 0 for d in shape):
                continue
            full = f"{lname}/{pname}"
            t = TensorFact(full, tuple(int(d) for d in shape), dt, "param")
            t.weight_of = idx
            t.consumers.append(idx)
            ir.tensors[full] = t
            pnames.append(full)
        out_name = f"{lname}:act"
        it, out_it = types[idx]
        ir.tensors[out_name] = TensorFact(out_name,
                                          _type_shape(out_it, batch_size),
                                          dt, "activation", producer=idx)
        ir.tensors[prev_out].consumers.append(idx)
        ir.ops.append(OpFact(
            idx, type(layer).__name__, lname,
            tuple([prev_out] + pnames), (out_name,), {},
            flops=_dist._approx_flops(layer, it, out_it)))
        prev_out = out_name
    return ir


def from_graph(conf, batch_size: int = 1) -> GraphIR:
    """Lower a ComputationGraphConfiguration to the IR — layer nodes AND
    vertices become ops in topological order, so the cost model's
    liveness pass sees the same producer/consumer edges the sequential
    lowering gives (vertices carry no params and zero FLOPs; their
    output shapes stay unknown and the liveness pass degrades to the
    layer-activation facts)."""
    ir = GraphIR(subject="ComputationGraphConfiguration",
                 batch_size=batch_size)
    base = getattr(conf, "base", None)
    ir.updater = getattr(base, "updater", None)
    dtype = getattr(base, "dtype", None)
    dt = str(dtype) if dtype is not None else "float32"
    input_types = dict(getattr(conf, "input_types", {}) or {})
    for gi in getattr(conf, "graph_inputs", ()) or ():
        ir.tensors[gi] = TensorFact(
            gi, _type_shape(input_types.get(gi), batch_size), dt,
            "placeholder")
    types = _dist._propagate_graph_types(conf)
    nodes = _dist._graph_order_all(conf, list(getattr(conf, "nodes", ())))
    act_of = {}                      # node name -> its activation tensor
    for idx, n in enumerate(nodes):
        in_refs = []
        for r in n.inputs:
            ref = r if r in ir.tensors and r not in act_of else \
                act_of.get(r, f"{r}:act")
            in_refs.append(ref)
            t = ir.tensors.get(ref)
            if t is not None:
                t.consumers.append(idx)
        pnames = []
        flops = 0
        if getattr(n, "kind", None) == "layer":
            lname = getattr(n, "name", None) or type(n.obj).__name__
            for pname, shape in (getattr(n.obj, "param_shapes",
                                         lambda: {})() or {}).items():
                if not shape or any(not d or d < 0 for d in shape):
                    continue
                full = f"{lname}/{pname}"
                t = TensorFact(full, tuple(int(d) for d in shape), dt,
                               "param")
                t.weight_of = idx
                t.consumers.append(idx)
                ir.tensors[full] = t
                pnames.append(full)
            it, out_it = types.get(n.name, (None, None))
            flops = _dist._approx_flops(n.obj, it, out_it)
        else:
            out_it = None
        out_name = f"{n.name}:act"
        ir.tensors[out_name] = TensorFact(out_name,
                                          _type_shape(out_it, batch_size),
                                          dt, "activation", producer=idx)
        act_of[n.name] = out_name
        ir.ops.append(OpFact(
            idx, type(n.obj).__name__, n.name,
            tuple(in_refs + pnames), (out_name,), {}, flops=flops))
    return ir


# ---------------------------------------------------------- lint drivers

def lint_ir_layout(ir: GraphIR, batch_size: Optional[int] = None,
                   data_devices: Optional[int] = None) -> List[Diagnostic]:
    """W101/W102/W103 over IR facts: weight lane dims against the MXU
    tile grid, non-native tensor dtypes (once per distinct dtype), batch
    vs. data-mesh divisibility."""
    diags: List[Diagnostic] = []
    for t in ir.weights():
        if t.shape is None or not t.shape:
            continue
        conv = False
        loc = f"tensor '{t.name}'"
        if t.weight_of is not None and t.weight_of < len(ir.ops):
            op = ir.ops[t.weight_of]
            conv = op.op in CONV_OPS
            loc = f"tensor '{t.name}' ({op.location})"
        dims = [d for d in (t.shape[-1],) if d is not None] if not conv \
            else [d for d in t.shape[:2] if d is not None]
        for d in dims:
            diag = _layout.lint_lane_dim(int(d), loc, conv=conv)
            if diag is not None:
                diags.append(diag)
    seen_dtypes = set()
    for t in ir.tensors.values():
        if t.dtype is None or t.dtype in seen_dtypes:
            continue
        found = _layout.lint_dtype(t.dtype, f"tensor '{t.name}'")
        if found:
            seen_dtypes.add(t.dtype)
            diags.extend(found)
    diags.extend(_layout.lint_batch_mesh(batch_size, data_devices,
                                         location="graph"))
    return diags


class _IRLayerFacts:
    """Declared-fact adapter: one op's weight tensors presented through
    the ``param_shapes()`` / ``name`` / ``tied_with`` / ``approx_flops``
    hooks the distribution pass reads — IR facts ride the existing
    ``lint_entries`` / ``_lint_pipeline`` machinery unchanged."""

    #: the IR tensor names are already the graph's own names — no layer
    #: prefix (sharding regexes must see the recorded names)
    qualified_params = True

    def __init__(self, name: str, params: Dict[str, Tuple[int, ...]],
                 flops: int):
        self._params = params
        self.name = name
        self.tied_with = None
        self._flops = int(flops)

    def param_shapes(self):
        return dict(self._params)

    def approx_flops(self):
        return self._flops


def _ir_entries(ir: GraphIR):
    """(location, facts, None, None) entries: one per op owning weight
    tensors (plus a trailing bundle for unconsumed params), FLOPs from
    the IR op facts."""
    by_op: Dict[int, Dict[str, Tuple[int, ...]]] = {}
    orphans: Dict[str, Tuple[int, ...]] = {}
    for t in ir.weights():
        if t.shape is None or None in t.shape or not t.shape:
            continue
        if t.weight_of is not None:
            by_op.setdefault(t.weight_of, {})[t.name] = t.shape
        else:
            orphans[t.name] = t.shape
    entries = []
    for op in ir.ops:
        params = by_op.get(op.index)
        if params is None and op.flops <= 0:
            continue
        facts = _IRLayerFacts(op.location, params or {}, op.flops)
        entries.append((op.location, facts, None, None))
    if orphans:
        entries.append(("unconsumed parameters",
                        _IRLayerFacts("unconsumed parameters", orphans, 0),
                        None, None))
    return entries


def _dominant_param_dtype(ir: GraphIR) -> Optional[str]:
    counts: Dict[str, int] = {}
    for t in ir.weights():
        if t.dtype:
            counts[t.dtype] = counts.get(t.dtype, 0) + 1
    if not counts:
        return None
    return max(counts.items(), key=lambda kv: kv[1])[0]


def lint_ir_distribution(ir: GraphIR, mesh, batch_size: Optional[int],
                         profile=None) -> List[Diagnostic]:
    """E101/E102/E104/W104–W107 (+E103/W105 under a declared pipeline)
    over IR param facts — the codes native configs get from
    ``distribution.lint_multilayer``, driven by the same machinery."""
    entries = _ir_entries(ir)
    diags = _dist.lint_entries(entries, mesh, batch_size,
                               _dominant_param_dtype(ir),
                               updater=ir.updater)
    diags.extend(_dist._lint_pipeline(entries, mesh, profile=profile))
    return diags


def _resolve_ir_policy(ir: GraphIR, policy) -> PrecisionPolicy:
    pol = PrecisionPolicy.coerce(policy)
    if pol is not None:
        return pol
    implied = PrecisionPolicy.from_config_dtype(_dominant_param_dtype(ir))
    return implied if implied is not None else PrecisionPolicy()


def _axis_len(shape: Shape, axis) -> Optional[int]:
    if shape is None:
        return None
    try:
        return shape[int(axis) % len(shape)]
    except Exception:
        return None


def _updater_name(updater) -> str:
    return type(updater).__name__ if updater is not None else ""


def lint_ir_numerics(ir: GraphIR, policy=None,
                     data_range=None) -> List[Diagnostic]:
    """E301–E303/W301–W303 via dtype-flow over IR edges — the numerics
    codes native configs get, decided from tensor dtypes, op kinds, and
    the declared policy/range."""
    pol = _resolve_ir_policy(ir, policy)
    rng = DataRangeSpec.coerce(data_range)
    diags: List[Diagnostic] = []
    upd = _updater_name(ir.updater)
    compute = pol.compute

    # E301: trainable params stored low-precision + a squaring updater —
    # the moments live in a dtype that cannot hold their dynamic range
    if upd in _SQUARING_UPDATERS:
        low_params = [t for t in ir.weights()
                      if t.kind == "param" and t.dtype in LOW_PRECISION]
        if pol.params in LOW_PRECISION or low_params:
            where = low_params[0].name if low_params else "policy"
            dt = low_params[0].dtype if low_params else pol.params
            diags.append(Diagnostic(
                "DL4J-E301", Severity.ERROR, f"'{where}'",
                f"trainable parameters live in {dt} while {upd} keeps "
                f"squared-gradient state — the moments round to zero or "
                f"overflow in a low-precision dtype",
                fix_hint="keep fp32 master params (params='float32' in "
                         "the PrecisionPolicy) and cast per-op instead"))

    # E302: softmax / large reductions / loss heads accumulating low
    if compute in LOW_PRECISION:
        for op in ir.ops:
            in_t = ir.tensors.get(op.inputs[0]) if op.inputs else None
            in_shape = in_t.shape if in_t is not None else None
            if op.op in _SOFTMAX_OPS:
                axis = _attr_params(op.attrs).get(
                    "axis", op.attrs.get("axis", -1))
                n = _axis_len(in_shape, axis if axis is not None else -1)
                if n is not None and n >= SOFTMAX_AXIS_THRESHOLD:
                    diags.append(Diagnostic(
                        "DL4J-E302", Severity.ERROR, op.location,
                        f"softmax over {n} elements accumulates in "
                        f"{compute} — the exponential sum loses the "
                        f"distribution's tail below {compute}'s mantissa",
                        fix_hint="compute the softmax in float32 (cast in "
                                 "/ cast out) or keep the policy's fp32 "
                                 "loss island"))
            elif op.op in _REDUCTION_OPS:
                p = _attr_params(op.attrs)
                axes = p.get("axes", p.get("axis",
                                           op.attrs.get("axis")))
                if axes is None and in_shape is not None \
                        and None not in in_shape:
                    n = 1
                    for d in in_shape:
                        n *= d
                else:
                    first = axes[0] if isinstance(axes, (list, tuple)) \
                        and axes else axes
                    n = _axis_len(in_shape, first) \
                        if first is not None else None
                if n is not None and n >= REDUCTION_AXIS_THRESHOLD:
                    diags.append(Diagnostic(
                        "DL4J-E302", Severity.ERROR, op.location,
                        f"reduction over {n} elements accumulates in "
                        f"{compute} — mean/variance over that many "
                        f"low-mantissa terms drifts",
                        fix_hint="accumulate in float32 (cast before the "
                                 "reduction)"))
            elif op.op in _LOSS_OPS and in_t is not None \
                    and in_t.dtype in LOW_PRECISION:
                diags.append(Diagnostic(
                    "DL4J-E302", Severity.ERROR, op.location,
                    f"loss accumulates in {in_t.dtype} — the loss head "
                    f"is the one reduction that must stay fp32",
                    fix_hint="cast predictions to float32 before the "
                             "loss op"))

    # E303: fp16 without loss scaling; declared-range overflow
    if compute == "float16" and pol.numeric_loss_scale() is None:
        diags.append(Diagnostic(
            "DL4J-E303", Severity.ERROR, "policy",
            "float16 compute with no loss scaling — small gradients "
            "underflow to zero below 2**-24 and training silently "
            "stalls",
            fix_hint="set loss_scale (2**15 static, or 'dynamic') on "
                     "the PrecisionPolicy, or use bfloat16"))
    if rng is not None:
        mag = rng.max_abs
        for op in ir.ops:
            if op.op in _SATURATING_OPS:
                mag = 1.0
            elif op.op in _NORMALIZING_OPS:
                mag = 3.0
        params_dt = pol.params
        if upd in _SQUARING_UPDATERS and \
                mag * mag > DTYPE_MAX.get(params_dt, float("inf")):
            diags.append(Diagnostic(
                "DL4J-E303", Severity.ERROR, "config",
                f"declared input range [{rng.lo:g}, {rng.hi:g}] drives "
                f"squared-gradient magnitude ~{mag * mag:.3g} past "
                f"{params_dt}'s max — {upd}'s second moment overflows "
                f"and every update zeroes",
                fix_hint="normalize the input (attach a scaler or "
                         "declare normalized=True) or keep fp32 "
                         "updater state"))
        scale = pol.numeric_loss_scale()
        if scale is not None and mag * scale > pol.compute_max():
            diags.append(Diagnostic(
                "DL4J-E303", Severity.ERROR, "policy",
                f"loss scale {scale:g} x activation magnitude ~{mag:g} "
                f"overflows {compute}",
                fix_hint="lower the loss scale or normalize the input"))

    # W301: explicit cast sandwich low -> fp32 -> same low dtype
    for op in ir.ops:
        if op.op not in _CAST_OPS or not op.outputs:
            continue
        src = ir.tensors.get(op.inputs[0]) if op.inputs else None
        out = ir.tensors.get(op.outputs[0])
        if src is None or out is None or src.dtype not in LOW_PRECISION \
                or out.dtype != "float32":
            continue
        for c in out.consumers:
            nxt = ir.ops[c]
            nxt_out = ir.tensors.get(nxt.outputs[0]) if nxt.outputs \
                else None
            if nxt.op in _CAST_OPS and nxt_out is not None \
                    and nxt_out.dtype == src.dtype:
                diags.append(Diagnostic(
                    "DL4J-W301", Severity.WARNING, op.location,
                    f"cast churn: {src.dtype} -> float32 -> {src.dtype} "
                    f"with no fp32 compute in between — both casts are "
                    f"pure memory traffic",
                    fix_hint="drop the round trip (stay in "
                             f"{src.dtype})"))
                break

    diags.extend(_lint_loss_scaling(pol))

    # W303: unnormalized declared range with no normalizer at the frontier
    if rng is not None and not rng.normalized \
            and rng.max_abs > UNNORMALIZED_THRESHOLD:
        normalized_first = False
        for ph in ir.placeholders():
            for c in ph.consumers:
                if ir.ops[c].op in _NORMALIZING_OPS:
                    normalized_first = True
        if not normalized_first and ir.ops:
            diags.append(Diagnostic(
                "DL4J-W303", Severity.WARNING, "graph",
                f"declared input range [{rng.lo:g}, {rng.hi:g}] is "
                f"unnormalized and no normalization op consumes the "
                f"placeholder — raw-pixel-scale inputs cost "
                f"{rng.max_abs:g}x dynamic-range headroom in every "
                f"activation (the PR-4 Adam-overflow class)",
                fix_hint="normalize before the graph (or declare "
                         "DataRangeSpec(..., normalized=True) if a "
                         "normalizer is attached upstream)"))
    return diags
